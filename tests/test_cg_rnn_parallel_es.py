"""Round-3 additions: ComputationGraph stateful RNN inference + TBPTT
(ref: ComputationGraph.rnnTimeStep :1569 / doTruncatedBPTT :1476) and
EarlyStoppingParallelTrainer (ref: parallelism/EarlyStoppingParallelTrainer.java)."""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.nn.conf.graph_conf import GraphBuilder
from deeplearning4j_tpu.nn.conf.layers import (
    DenseLayer, GravesLSTM, OutputLayer, RnnOutputLayer)
from deeplearning4j_tpu.nn.conf.network import GlobalConf
from deeplearning4j_tpu.nn.graph import ComputationGraph

V = 12


def _char_graph(tbptt=False):
    g = GlobalConf(seed=5, learning_rate=0.1, updater="rmsprop",
                   weight_init="xavier")
    b = (GraphBuilder(g)
         .add_inputs("in")
         .add_layer("lstm1", GravesLSTM(n_in=V, n_out=16, activation="tanh"),
                    "in")
         .add_layer("lstm2", GravesLSTM(n_in=16, n_out=16, activation="tanh"),
                    "lstm1")
         .add_layer("out", RnnOutputLayer(n_in=16, n_out=V,
                                          activation="softmax",
                                          loss="mcxent"), "lstm2")
         .set_outputs("out"))
    if tbptt:
        b.backprop_type("truncatedbptt")
        b.t_bptt_forward_length(4).t_bptt_backward_length(4)
    return ComputationGraph(b.build()).init()


def _seq_batch(n=4, t=12, seed=0):
    rng = np.random.default_rng(seed)
    eye = np.eye(V, dtype=np.float32)
    x = eye[rng.integers(0, V, (n, t))]
    y = eye[rng.integers(0, V, (n, t))]
    return x, y


def test_cg_rnn_time_step_matches_full_forward():
    """Feeding a sequence chunk-by-chunk through rnn_time_step must equal
    the one-shot forward — state carriage is exact."""
    net = _char_graph()
    x, _ = _seq_batch(t=8, seed=1)
    (full,) = net.output(x)

    net.rnn_clear_previous_state()
    outs = []
    for t0 in range(0, 8, 2):
        (o,) = net.rnn_time_step(x[:, t0:t0 + 2])
        outs.append(np.asarray(o))
    stepped = np.concatenate(outs, axis=1)
    np.testing.assert_allclose(stepped, np.asarray(full), rtol=2e-4,
                               atol=2e-5)
    # clearing state resets generation
    net.rnn_clear_previous_state()
    (again,) = net.rnn_time_step(x[:, :2])
    np.testing.assert_allclose(np.asarray(again), stepped[:, :2], rtol=2e-4,
                               atol=2e-5)


def test_cg_char_rnn_generates_with_carried_state():
    """Token-by-token autoregressive sampling off a CG char-RNN — the CG
    analog of models/charrnn.sample_text."""
    net = _char_graph()
    eye = np.eye(V, dtype=np.float32)
    net.rnn_clear_previous_state()
    tok = 3
    generated = [tok]
    for _ in range(10):
        (o,) = net.rnn_time_step(eye[np.asarray([tok])][None])
        probs = np.asarray(o)[0, -1]
        assert probs.shape == (V,)
        assert abs(probs.sum() - 1.0) < 1e-4
        tok = int(np.argmax(probs))
        generated.append(tok)
    assert len(generated) == 11
    # the carried state must actually influence the distribution: same
    # input token twice in a row gives different outputs (state moved)
    net.rnn_clear_previous_state()
    (o1,) = net.rnn_time_step(eye[np.asarray([2])][None])
    (o2,) = net.rnn_time_step(eye[np.asarray([2])][None])
    assert not np.allclose(np.asarray(o1), np.asarray(o2))


def test_cg_tbptt_training_carries_and_learns():
    net = _char_graph(tbptt=True)
    x, y = _seq_batch(n=8, t=12, seed=2)
    mds = MultiDataSet([x], [y])
    it0 = net.iteration
    net.fit(mds)
    # 12 timesteps / fwd_length 4 → 3 TBPTT segments = 3 iterations
    assert net.iteration - it0 == 3
    s0 = float(net.score(mds))
    for _ in range(15):
        net.fit(mds)
    assert float(net.score(mds)) < s0


def test_cg_tbptt_state_cleared_between_batches():
    """MLN-parity semantics: the carry is reset at the START of each
    TBPTT batch (MultiLayerNetwork._fit_tbptt), so two fits of the same
    batch from the same params see identical data regardless of the
    state the previous batch left behind."""
    net = _char_graph(tbptt=True)
    x, y = _seq_batch(n=4, t=8, seed=3)
    ref = net.clone()
    net.fit(MultiDataSet([x], [y]))
    first_scores = float(net.score())
    # leftover carry exists after the batch (stateful generation can
    # continue, ref rnnTimeStep-after-fit), but must NOT leak into the
    # next fit: a fresh clone fitting the same batch scores identically
    assert any("rnn_state" in s for s in net.net_state.values())
    net.fit(MultiDataSet([x], [y]))           # stale carry present
    ref.fit(MultiDataSet([x], [y]))
    ref.fit(MultiDataSet([x], [y]))           # no stale carry ever
    np.testing.assert_allclose(float(net.score()), float(ref.score()),
                               rtol=1e-6)
    net.rnn_clear_previous_state()
    assert all("rnn_state" not in s for s in net.net_state.values())
    assert first_scores == first_scores  # silence lint (score sampled)


# ---------------------------------------------------------------------------
# EarlyStoppingParallelTrainer
# ---------------------------------------------------------------------------

def _iris_like(seed=0):
    # one fixed ground-truth w for train AND eval sets; x varies by seed
    w = np.random.default_rng(42).normal(size=(4, 3))
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(64, 4)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[np.argmax(x @ w, axis=1)]
    return ListDataSetIterator([DataSet(x[i:i + 32], y[i:i + 32])
                                for i in (0, 32)])


def _mlp():
    conf = (NeuralNetConfigurationBuilder()
            .seed(1).learning_rate(0.1).updater("adam")
            .list()
            .layer(DenseLayer(n_in=4, n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .build())
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    return MultiLayerNetwork(conf).init()


def NeuralNetConfigurationBuilder():
    from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
    return NeuralNetConfiguration.builder()


def test_early_stopping_parallel_trainer_score_improvement():
    from deeplearning4j_tpu.nn.earlystopping import (
        DataSetLossCalculator, EarlyStoppingConfiguration,
        MaxEpochsTerminationCondition,
        ScoreImprovementEpochTerminationCondition)
    from deeplearning4j_tpu.parallel import make_mesh
    from deeplearning4j_tpu.parallel.earlystopping import (
        EarlyStoppingParallelTrainer)

    data = _iris_like()
    net = _mlp()
    cfg = EarlyStoppingConfiguration(
        score_calculator=DataSetLossCalculator(_iris_like(seed=1)),
        epoch_termination_conditions=[
            ScoreImprovementEpochTerminationCondition(
                max_epochs_without_improvement=2),
            MaxEpochsTerminationCondition(30)],
        save_last_model=True)
    trainer = EarlyStoppingParallelTrainer(cfg, net, data,
                                           mesh=make_mesh())
    res = trainer.fit()
    assert res.termination_reason == "EpochTerminationCondition"
    assert res.best_model is not None
    assert res.best_model_score < math_inf()
    assert res.score_vs_epoch  # scores were tracked during mesh training
    # the trained mesh model must actually have learned something
    assert res.best_model_score < 1.2


def math_inf():
    import math
    return math.inf


def test_early_stopping_graph_trainer():
    """(ref: trainer/EarlyStoppingGraphTrainer.java) — the CG engine
    drives the same early-stopping loop."""
    from deeplearning4j_tpu.nn.earlystopping import (
        DataSetLossCalculator, EarlyStoppingConfiguration,
        EarlyStoppingGraphTrainer, MaxEpochsTerminationCondition)
    from deeplearning4j_tpu.nn.conf.graph_conf import GraphBuilder
    from deeplearning4j_tpu.nn.conf.network import GlobalConf

    g = GlobalConf(seed=2, learning_rate=0.1, updater="adam")
    conf = (GraphBuilder(g).add_inputs("in")
            .add_layer("d", DenseLayer(n_in=4, n_out=8, activation="tanh"),
                       "in")
            .add_layer("out", OutputLayer(n_in=8, n_out=3,
                                          activation="softmax",
                                          loss="mcxent"), "d")
            .set_outputs("out").build())
    net = ComputationGraph(conf).init()
    data = _iris_like()
    cfg = EarlyStoppingConfiguration(
        score_calculator=DataSetLossCalculator(_iris_like(seed=1)),
        epoch_termination_conditions=[MaxEpochsTerminationCondition(4)])
    res = EarlyStoppingGraphTrainer(cfg, net, data).fit()
    assert res.total_epochs == 4
    assert res.best_model is not None


def test_mln_rnn_activate_using_stored_state():
    """(ref: MultiLayerNetwork.rnnActivateUsingStoredState :1955)"""
    from deeplearning4j_tpu.models.charrnn import char_rnn
    net = char_rnn(vocab_size=8, hidden=8, layers=1)
    net.init()
    eye = np.eye(8, dtype=np.float32)
    x1 = eye[np.random.default_rng(0).integers(0, 8, (2, 3))]
    x2 = eye[np.random.default_rng(1).integers(0, 8, (2, 3))]

    net.rnn_clear_previous_state()
    acts = net.rnn_activate_using_stored_state(x1, store_last_for_tbptt=True)
    assert len(acts) == len(net.layers)
    assert any("rnn_state" in s for s in net.net_state)
    # continuing from stored state must equal rnn_time_step over the
    # concatenated sequence
    out_b = np.asarray(net.rnn_activate_using_stored_state(x2)[-1])
    net.rnn_clear_previous_state()
    full = np.asarray(net.rnn_time_step(np.concatenate([x1, x2], axis=1)))
    np.testing.assert_allclose(out_b, full[:, 3:], rtol=2e-4, atol=1e-5)
    # without store_last_for_tbptt the state must NOT advance
    net.rnn_clear_previous_state()
    a1 = np.asarray(net.rnn_activate_using_stored_state(x1)[-1])
    a2 = np.asarray(net.rnn_activate_using_stored_state(x1)[-1])
    np.testing.assert_array_equal(a1, a2)


def test_profiler_listener_produces_trace(tmp_path):
    """SURVEY §5: jax.profiler/XPlane integration as a TrainingListener —
    a trace directory with profile artifacts appears after the
    configured iteration window."""
    from deeplearning4j_tpu.nn.listeners import ProfilerListener

    net = _mlp()
    lst = ProfilerListener(tmp_path / "traces", frequency=2,
                           trace_iterations=1)
    net.set_listeners(lst)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(8, 4)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)]
    for _ in range(5):
        net.fit(x, y)
    lst.close()
    assert lst.trace_dirs, "a trace window should have been captured"
    import os
    produced = []
    for d in lst.trace_dirs:
        for root, _, files in os.walk(d):
            produced.extend(files)
    assert produced, f"no profiler artifacts under {lst.trace_dirs}"
    assert any("xplane" in f or f.endswith(".json.gz") or "trace" in f
               for f in produced), produced


def test_parallel_wrapper_computation_graph():
    """ParallelWrapper drives a ComputationGraph (tuple-shaped step args,
    MultiDataSet path) — the layout the ResNet-50 DP bench uses."""
    from deeplearning4j_tpu.datasets.dataset import MultiDataSet
    from deeplearning4j_tpu.nn.conf.graph_conf import (
        ElementWiseVertex, GraphBuilder)
    from deeplearning4j_tpu.nn.conf.network import GlobalConf
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.parallel import ParallelWrapper, make_mesh

    g = GlobalConf(seed=3, learning_rate=0.1, updater="adam")
    conf = (GraphBuilder(g)
            .add_inputs("in")
            .add_layer("d1", DenseLayer(n_in=4, n_out=8, activation="relu"),
                       "in")
            .add_layer("d2", DenseLayer(n_in=4, n_out=8, activation="tanh"),
                       "in")
            .add_vertex("add", ElementWiseVertex(op="add"), "d1", "d2")
            .add_layer("out", OutputLayer(n_in=8, n_out=3,
                                          activation="softmax",
                                          loss="mcxent"), "add")
            .set_outputs("out")
            .build())
    net = ComputationGraph(conf).init()
    w = np.random.default_rng(42).normal(size=(4, 3))
    rng = np.random.default_rng(5)
    x = rng.normal(size=(32, 4)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[np.argmax(x @ w, axis=1)]
    mds = MultiDataSet([x], [y])
    data = ListDataSetIterator([mds])
    s0 = float(net.score(mds))
    pw = ParallelWrapper(net, make_mesh())
    for _ in range(25):
        pw.fit(data)
    assert float(net.score(mds)) < s0
    # DataSet is auto-normalized to MultiDataSet for graph models too
    pw.fit(ListDataSetIterator([DataSet(x, y)]))


def test_early_stopping_parallel_trainer_iteration_condition():
    from deeplearning4j_tpu.nn.earlystopping import (
        DataSetLossCalculator, EarlyStoppingConfiguration,
        MaxScoreIterationTerminationCondition)
    from deeplearning4j_tpu.parallel.earlystopping import (
        EarlyStoppingParallelTrainer)

    data = _iris_like()
    net = _mlp()
    cfg = EarlyStoppingConfiguration(
        score_calculator=DataSetLossCalculator(_iris_like(seed=1)),
        iteration_termination_conditions=[
            MaxScoreIterationTerminationCondition(1e-12)])  # fires instantly
    res = EarlyStoppingParallelTrainer(cfg, net, data).fit()
    assert res.termination_reason == "IterationTerminationCondition"
    assert res.total_epochs == 1
