"""Elastic distributed runtime tests (deeplearning4j_tpu/distributed/ —
docs/DISTRIBUTED.md): coordinator protocol units (leases, generation
fencing, breaker re-admission, snapshot relay), in-process thread-worker
clusters (parity vs a single-host twin, fault-injected preemption,
zombie eviction + resync, absorption of a joiner), checkpoint restore
across process counts, and conf plumbing."""

import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.distributed import (
    Coordinator, DistSession, WorkerEvictedError, shard_bounds)
from deeplearning4j_tpu.nn.conf.graph_conf import GraphBuilder
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.conf.network import (
    GlobalConf, MultiLayerConfiguration, NeuralNetConfiguration)
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.resilience import faults


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


# ----------------------------------------------------------------------
# Coordinator protocol units (injected clock — no real waiting)
# ----------------------------------------------------------------------
class Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _form(co, ids):
    for w in ids:
        assert co.join(w)["admitted"]
    out = {}
    for w in ids:
        out[w] = co.sync_done(w)
    return out


def test_formation_assigns_ranks_in_join_order():
    clk = Clock()
    co = Coordinator(expected=3, lease_ms=1000, clock=clk)
    placements = _form(co, ["wa", "wb", "wc"])
    assert co.generation == 1
    assert placements["wc"]["world"] == 3
    ranks = {w: co.placement(w)["rank"] for w in ("wa", "wb", "wc")}
    assert ranks == {"wa": 0, "wb": 1, "wc": 2}


def test_lease_suspect_then_recover():
    clk = Clock()
    co = Coordinator(expected=2, lease_ms=1000, suspect_grace_ms=1000,
                     clock=clk)
    _form(co, ["wa", "wb"])
    clk.t = 1.5     # wb misses its lease
    co.heartbeat("wa")
    assert co.placement("wb")["state"] == "suspect"
    assert co.generation == 1            # suspicion alone never rolls
    co.heartbeat("wb")                   # recovery
    assert co.placement("wb")["state"] == "active"
    assert co.generation == 1


def test_lease_death_rolls_generation_and_reranks():
    clk = Clock()
    co = Coordinator(expected=2, lease_ms=1000, suspect_grace_ms=500,
                     clock=clk)
    _form(co, ["wa", "wb"])
    clk.t = 0.9
    co.heartbeat("wa")          # wa's lease renewed to 1.9
    clk.t = 1.6     # wb: lease (1.0) + grace (0.5) both lapsed
    co.heartbeat("wa")
    assert co.generation == 2
    p = co.placement("wa")
    assert (p["world"], p["rank"]) == (1, 0)
    assert co.placement("wb")["state"] == "dead"


def test_generation_fencing_rejects_stale_generation():
    clk = Clock()
    co = Coordinator(expected=2, lease_ms=1000, clock=clk)
    _form(co, ["wa", "wb"])
    co.leave("wb")              # roll to generation 2
    resp = co.allreduce("wa", generation=1, step=1, weight=1.0,
                        vec=np.ones(3, np.float32))
    assert resp.get("rolled") and resp["generation"] == 2
    # nothing was merged: the correct-generation barrier still completes
    ok = co.allreduce("wa", generation=2, step=1, weight=2.0,
                      vec=np.full(3, 5.0, np.float32))
    assert ok["step"] == 1
    np.testing.assert_allclose(ok["vec"], 5.0)


def test_step_fencing_rejects_desynced_steps():
    clk = Clock()
    co = Coordinator(expected=1, lease_ms=1000, clock=clk)
    _form(co, ["wa"])
    co.allreduce("wa", 1, 1, 1.0, np.zeros(2, np.float32))
    stale = co.allreduce("wa", 1, 1, 1.0, np.zeros(2, np.float32))
    assert stale.get("stale_step") and stale["committed"] == 1
    ahead = co.allreduce("wa", 1, 5, 1.0, np.zeros(2, np.float32))
    assert ahead.get("stale_step")


def test_fresh_coordinator_adopts_checkpoint_resumed_step():
    clk = Clock()
    co = Coordinator(expected=1, lease_ms=1000, clock=clk)
    _form(co, ["wa"])
    # a cluster restarted from a checkpoint at iteration 6 submits 7
    ok = co.allreduce("wa", 1, 7, 1.0, np.ones(2, np.float32))
    assert ok["step"] == 7 and co.step == 7


def test_weighted_reduce_in_rank_order():
    clk = Clock()
    co = Coordinator(expected=2, lease_ms=1000, clock=clk)
    _form(co, ["wa", "wb"])
    out = {}

    def contribute(w, weight, val):
        out[w] = co.allreduce(w, 1, 1, weight,
                              np.full(2, val, np.float32))

    t1 = threading.Thread(target=contribute, args=("wa", 3.0, 1.0))
    t1.start()
    time.sleep(0.05)
    contribute("wb", 1.0, 5.0)
    t1.join(30)
    expect = (3.0 * 1.0 + 1.0 * 5.0) / 4.0
    np.testing.assert_allclose(out["wa"]["vec"], expect)
    np.testing.assert_allclose(out["wb"]["vec"], expect)
    assert out["wa"]["weight"] == 4.0


def test_breaker_refuses_flapping_worker_then_readmits():
    clk = Clock()
    co = Coordinator(expected=2, lease_ms=100, suspect_grace_ms=100,
                     breaker={"min_calls": 2, "window": 4,
                              "cooldown_s": 5.0},
                     clock=clk)
    _form(co, ["wa", "wb"])
    for _ in range(2):          # wb dies twice in quick succession
        clk.t += 0.3
        co.heartbeat("wa")      # sweep: wb lease+grace lapsed -> dead
        assert co.placement("wb")["state"] == "dead"
        resp = co.join("wb")    # respawn rejoins...
        if resp["admitted"]:
            co.sync_done("wb")
    refused = co.join("wb")
    assert not refused["admitted"]
    assert refused["reason"] == "breaker_open"
    assert refused["retry_after_s"] > 0
    clk.t += 10.0               # cooldown passes: probe admitted
    again = co.join("wb")
    assert again["admitted"], again


def test_snapshot_relay_activates_joiner_atomically():
    clk = Clock()
    co = Coordinator(expected=1, lease_ms=1000, clock=clk)
    _form(co, ["wa"])
    co.allreduce("wa", 1, 1, 1.0, np.zeros(2, np.float32))
    resp = co.join("wb")
    assert resp["admitted"] and resp["await_snapshot"]
    assert co.get_snapshot("wb", min_step=1) is None   # nothing yet
    # rank 0 is asked to upload on its next barrier
    nxt = co.allreduce("wa", 1, 2, 1.0, np.zeros(2, np.float32))
    assert nxt["upload_state"]
    co.put_snapshot("wa", 2, np.arange(4, dtype=np.float32),
                    None, {"epoch": 0, "iteration_in_epoch": 2})
    # the upload activated the joiner and rolled — committed step frozen
    assert co.generation == 2
    assert co.placement("wb")["state"] == "active"
    snap = co.get_snapshot("wb", min_step=1)
    assert snap["step"] == 2
    np.testing.assert_allclose(snap["params"], np.arange(4))
    # both now barrier step 3 together
    done = {}
    t = threading.Thread(target=lambda: done.setdefault(
        "wa", co.allreduce("wa", 2, 3, 1.0, np.ones(2, np.float32))))
    t.start()
    done["wb"] = co.allreduce("wb", 2, 3, 1.0, np.ones(2, np.float32))
    t.join(30)
    assert done["wa"]["step"] == done["wb"]["step"] == 3


def test_shard_bounds_cover_every_row_once():
    for n in (1, 7, 16, 33):
        for world in (1, 2, 3, 5):
            spans = [shard_bounds(n, world, r) for r in range(world)]
            rows = [i for lo, hi in spans for i in range(lo, hi)]
            assert rows == list(range(n)), (n, world, spans)


# ----------------------------------------------------------------------
# Thread-worker clusters (in-process: one jax runtime, N sessions)
# ----------------------------------------------------------------------
def _mln_conf(dist=True, **dist_kw):
    b = (NeuralNetConfiguration.builder().seed(99).learning_rate(0.05)
         .updater("adam"))
    if dist:
        b.distributed(processes=dist_kw.pop("processes", 2), **dist_kw)
    return (b.list()
            .layer(DenseLayer(n_in=4, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax",
                               loss="mcxent"))
            .build())


def _batches(n=8, rows=16, seed=7):
    rng = np.random.default_rng(seed)
    return [DataSet(rng.normal(size=(rows, 4)).astype(np.float32),
                    np.eye(3, dtype=np.float32)[rng.integers(0, 3, rows)])
            for _ in range(n)]


def _run_cluster(co, n, batches, epochs=1, make_net=None, extra=(),
                 slow_s=0.0, ckpt_dirs=None):
    """N worker threads (own model each) against one coordinator;
    returns ({worker: final params}, [(worker, exc type)] for died)."""
    results, died = {}, []

    def make_default():
        return MultiLayerNetwork(_mln_conf()).init()

    class SlowIter(ListDataSetIterator):
        def next(self):
            if slow_s:
                time.sleep(slow_s)
            return super().next()

    def work(wid, delay=0.0):
        try:
            if delay:
                time.sleep(delay)
            net = (make_net or make_default)()
            if ckpt_dirs and wid in ckpt_dirs:
                from deeplearning4j_tpu.nn.checkpoint import (
                    CheckpointListener)
                net.add_listener(CheckpointListener(
                    ckpt_dirs[wid], save_every_n_iterations=2))
            sess = DistSession(co, wid, heartbeat_ms=60)
            sess.connect()
            net._dist_session = sess
            net.fit(SlowIter(list(batches)), epochs=epochs)
            results[wid] = np.asarray(net.params())
            sess.close()
        except BaseException as e:  # noqa: BLE001 — chaos kills ride here
            died.append((wid, type(e).__name__))

    threads = [threading.Thread(target=work, args=(f"w{i}",))
               for i in range(n)]
    threads += [threading.Thread(target=work, args=(wid, delay))
                for wid, delay in extra]
    for t in threads:
        t.start()
    for t in threads:
        t.join(180)
        assert not t.is_alive(), "cluster worker thread hung"
    return results, died


def test_thread_cluster_matches_single_host_mln():
    ref = MultiLayerNetwork(_mln_conf(dist=False)).init()
    ref.fit(ListDataSetIterator(_batches()), epochs=2)
    ref_p = np.asarray(ref.params())
    co = Coordinator(expected=2, lease_ms=800)
    results, died = _run_cluster(co, 2, _batches(), epochs=2)
    assert not died, died
    np.testing.assert_array_equal(results["w0"], results["w1"])
    np.testing.assert_allclose(results["w0"], ref_p, atol=1e-6)
    assert co.status()["step"] == 16


def test_thread_cluster_matches_single_host_cg():
    def g(dist):
        gc = GlobalConf(seed=7, learning_rate=0.05, updater="sgd")
        if dist:
            gc.dist_enabled = True
            gc.dist_processes = 2
        return gc

    def conf(dist):
        return (GraphBuilder(g(dist))
                .add_inputs("in")
                .add_layer("d", DenseLayer(n_in=4, n_out=8,
                                           activation="tanh"), "in")
                .add_layer("out", OutputLayer(n_in=8, n_out=3,
                                              activation="softmax",
                                              loss="mcxent"), "d")
                .set_outputs("out")
                .build())

    mds = [MultiDataSet([b.features], [b.labels])
           for b in _batches(6)]
    ref = ComputationGraph(conf(False)).init()
    for m in mds:
        ref.fit(m)
    ref_p = np.asarray(ref.params())

    co = Coordinator(expected=2, lease_ms=800)
    results, errs = {}, []

    def work(wid):
        try:
            net = ComputationGraph(conf(True)).init()
            sess = DistSession(co, wid, heartbeat_ms=60)
            sess.connect()
            net._dist_session = sess
            for m in mds:
                net.fit(m)
            results[wid] = np.asarray(net.params())
            sess.close()
        except BaseException as e:  # noqa: BLE001
            errs.append((wid, repr(e)))

    ts = [threading.Thread(target=work, args=(f"w{i}",))
          for i in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(180)
    assert not errs, errs
    np.testing.assert_array_equal(results["w0"], results["w1"])
    np.testing.assert_allclose(results["w0"], ref_p, atol=1e-6)


def test_elastic_kill_midepoch_and_absorb_joiner():
    """The headline elastic path, in-process: a fault-injected worker
    kill mid-epoch (dist.worker, mode=kill) shrinks the cluster to one
    survivor which finishes the SAME run; a replacement worker joining
    mid-stream absorbs the survivors' in-memory snapshot.  Every
    finisher matches the uninterrupted single-host twin ≤1e-6."""
    ref = MultiLayerNetwork(_mln_conf(dist=False)).init()
    ref.fit(ListDataSetIterator(_batches(10)), epochs=1)
    ref_p = np.asarray(ref.params())

    faults.arm({"site": "dist.worker", "mode": "kill", "on_call": 6,
                "max_injections": 1})
    co = Coordinator(expected=2, lease_ms=300)
    results, died = _run_cluster(
        co, 2, _batches(10), slow_s=0.05,
        extra=[("w9", 1.0)])      # the replacement joins ~step 4-8
    assert [k for k, e in died if e == "ThreadKill"], died
    assert len(died) == 1, died
    survivors = set(results)
    assert len(survivors) == 2, results   # one original + the joiner
    assert "w9" in survivors
    for wid, p in results.items():
        np.testing.assert_allclose(p, ref_p, atol=1e-6, err_msg=wid)
    st = co.status()
    assert st["step"] == 10
    assert st["generation"] >= 3   # formation + death + absorption


def test_heartbeat_kill_makes_zombie_that_resyncs():
    """dist.heartbeat kill: the step loop survives but the lease lapses
    — the coordinator evicts the zombie, it re-admits through the
    breaker, resyncs from the survivors' snapshot, and finishes with
    full parity (no lost or doubled steps)."""
    ref = MultiLayerNetwork(_mln_conf(dist=False)).init()
    ref.fit(ListDataSetIterator(_batches(10)), epochs=1)
    ref_p = np.asarray(ref.params())

    faults.arm({"site": "dist.heartbeat", "mode": "kill", "on_call": 3,
                "max_injections": 1})
    co = Coordinator(expected=2, lease_ms=250,
                     breaker={"cooldown_s": 0.1})
    results, died = _run_cluster(co, 2, _batches(10), slow_s=0.05)
    assert not died, died
    assert set(results) == {"w0", "w1"}
    for wid, p in results.items():
        np.testing.assert_allclose(p, ref_p, atol=1e-6, err_msg=wid)
    reg_status = co.status()
    assert reg_status["step"] == 10
    assert reg_status["generation"] >= 3   # eviction + re-absorption


def test_checkpoint_restore_across_process_counts(tmp_path):
    """A checkpointed 2-worker run resumed by a 1-worker cluster (fresh
    coordinator): the manifest's replay-skip + the coordinator's
    step-adoption continue the run to single-host parity — checkpoints
    are portable across world sizes."""
    batches = _batches(8)
    ref = MultiLayerNetwork(_mln_conf(dist=False)).init()
    ref.fit(ListDataSetIterator(list(batches)), epochs=2)
    ref_p = np.asarray(ref.params())

    def make_net():
        conf = _mln_conf()
        conf.global_conf.ft_resume = True
        return MultiLayerNetwork(conf).init()

    dirs = {"w0": str(tmp_path / "w0"), "w1": str(tmp_path / "w1")}
    co = Coordinator(expected=2, lease_ms=800)
    results, died = _run_cluster(co, 2, batches, epochs=1,
                                 make_net=make_net, ckpt_dirs=dirs)
    assert not died, died

    # restart as a 1-worker cluster from w0's checkpoints, epochs=2:
    # epoch 0 replay-skips, epoch 1 trains at world=1
    def make_resumed():
        conf = _mln_conf(processes=1)
        conf.global_conf.ft_resume = True
        conf.global_conf.ft_checkpoint_dir = dirs["w0"]
        return MultiLayerNetwork(conf).init()

    co2 = Coordinator(expected=1, lease_ms=800)
    results2, died2 = _run_cluster(co2, 1, batches, epochs=2,
                                   make_net=make_resumed)
    assert not died2, died2
    np.testing.assert_allclose(results2["w0"], ref_p, atol=1e-6)
    # the manifest recorded the cluster placement it was written under
    from deeplearning4j_tpu.nn.checkpoint import read_manifest
    entries = read_manifest(dirs["w0"])
    assert entries and entries[-1].get("dist", {}).get("world") == 2


# ----------------------------------------------------------------------
# Conf plumbing
# ----------------------------------------------------------------------
def test_dist_conf_inert_without_coordinator():
    """conf.distributed() with no coordinator reachable degrades to
    plain single-process fit — byte-identical params."""
    plain = MultiLayerNetwork(_mln_conf(dist=False)).init()
    plain.fit(ListDataSetIterator(_batches(4)), epochs=1)
    dist = MultiLayerNetwork(_mln_conf()).init()
    dist.fit(ListDataSetIterator(_batches(4)), epochs=1)
    np.testing.assert_array_equal(np.asarray(plain.params()),
                                  np.asarray(dist.params()))


def test_dist_conf_serde_roundtrip():
    conf = _mln_conf(processes=4, coordinator="http://10.0.0.1:4711",
                     heartbeat_ms=125.0, lease_ms=999.0)
    doc = conf.to_dict()
    back = MultiLayerConfiguration.from_dict(doc)
    g = back.global_conf
    assert g.dist_enabled and g.dist_processes == 4
    assert g.dist_coordinator == "http://10.0.0.1:4711"
    assert g.dist_heartbeat_ms == 125.0 and g.dist_lease_ms == 999.0
    # legacy configs (no dist fields) still load with inert defaults
    legacy = dict(doc)
    legacy["global"] = {k: v for k, v in doc["global"].items()
                       if not k.startswith("dist_")}
    g2 = MultiLayerConfiguration.from_dict(legacy).global_conf
    assert not g2.dist_enabled and g2.dist_processes == 0


def test_dist_metrics_families_registered():
    from deeplearning4j_tpu import monitor
    snap = monitor.get_registry().snapshot()
    for fam in ("dl4j_dist_generation", "dl4j_dist_members",
                "dl4j_dist_generation_rolls_total",
                "dl4j_dist_allreduce_total",
                "dl4j_dist_allreduce_seconds",
                "dl4j_dist_evictions_total", "dl4j_dist_rejoins_total",
                "dl4j_dist_snapshot_transfers_total"):
        assert fam in snap, fam
