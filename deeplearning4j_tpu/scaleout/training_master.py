"""TrainingMaster / TrainingWorker SPI
(ref: spark/api/TrainingMaster.java, TrainingWorker.java,
TrainingHook.java, WorkerConfiguration.java,
spark/api/worker/NetBroadcastTuple.java).

The SPI shape is preserved — a pluggable strategy object that owns how a
front-end's ``fit`` distributes work — but the worker boundary is a host
thread/process driving device computation instead of a Spark executor
JVM."""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Optional

import numpy as np


@dataclasses.dataclass
class WorkerConfiguration:
    """(ref: spark/api/WorkerConfiguration.java)"""

    is_graph_network: bool = False
    batch_size_per_worker: int = 32
    averaging_frequency: int = 5
    prefetch_num_batches: int = 2
    collect_training_stats: bool = False


@dataclasses.dataclass
class NetBroadcastTuple:
    """Everything a worker needs to reconstruct the model: the conf JSON,
    the flat parameter vector, and the flat updater-state vector
    (ref: spark/api/worker/NetBroadcastTuple.java — the broadcast's
    payload; flat-vector parity is the checkpoint-format contract)."""

    conf_json: str
    params: np.ndarray
    updater_state: Optional[np.ndarray]
    is_graph: bool = False
    iteration: int = 0  # driver step count — keeps Adam bias correction
    #                     aligned across re-broadcasts


class TrainingHook:
    """(ref: spark/api/TrainingHook.java — pre/post update callbacks;
    the parameter-server edition wires push/pull in here,
    ref: dl4j-spark-parameterserver/.../ParameterServerTrainingHook.java)"""

    def pre_update(self, minibatch, model) -> None:  # pragma: no cover
        pass

    def post_update(self, minibatch, model) -> None:  # pragma: no cover
        pass


class TrainingWorker:
    """Executor-side logic (ref: spark/api/TrainingWorker.java): build the
    net from the broadcast, process minibatches, emit a result."""

    def get_initial_model(self, broadcast: NetBroadcastTuple):
        raise NotImplementedError

    def process_minibatch(self, dataset, model) -> None:
        raise NotImplementedError

    def get_final_result(self, model) -> Any:
        raise NotImplementedError


class TrainingMaster:
    """(ref: spark/api/TrainingMaster.java) — the distributed-training
    strategy SPI.  Concrete: ParameterAveragingTrainingMaster."""

    def __init__(self):
        self.hooks: List[TrainingHook] = []

    # -- hook management (ref: TrainingMaster.addHook/removeHook) ----------
    def add_hook(self, hook: TrainingHook) -> None:
        self.hooks.append(hook)

    def remove_hook(self, hook: TrainingHook) -> None:
        self.hooks.remove(hook)

    # -- main entry points --------------------------------------------------
    def execute_training(self, front_end, data) -> None:
        raise NotImplementedError

    # -- reproducibility (ref: TrainingMaster.toJson/fromJson) -------------
    def _config_dict(self) -> Dict[str, Any]:
        raise NotImplementedError

    def to_json(self) -> str:
        d = {"type": type(self).__name__}
        d.update(self._config_dict())
        return json.dumps(d, indent=2)

    @staticmethod
    def from_json(s: str) -> "TrainingMaster":
        d = json.loads(s)
        kind = d.pop("type")
        from deeplearning4j_tpu.scaleout.param_averaging import (
            ParameterAveragingTrainingMaster)
        registry = {
            "ParameterAveragingTrainingMaster":
                ParameterAveragingTrainingMaster,
        }
        return registry[kind](**d)
