"""Multi-host / multi-slice training over DCN
(replaces the reference's Spark cluster tier end-to-end: driver↔executor
broadcast + treeAggregate, ref:
spark/impl/paramavg/ParameterAveragingTrainingMaster.java:867 — and the
Aeron parameter server, ref: §2.5 — with ONE mechanism: a jax.distributed
process group whose global mesh spans slices, XLA inserting ICI
collectives within a slice and DCN collectives across slices inside the
same compiled step).

Usage on each host of the cluster::

    from deeplearning4j_tpu.scaleout.multislice import (
        initialize_distributed, global_mesh)
    initialize_distributed()          # reads coordinator from env
    mesh = global_mesh(MeshConfig(data=-1, fsdp=8))
    ParallelWrapper(net, mesh).fit(iterator)

Per the scaling-book recipe: keep 'fsdp'/'model'/'seq' axes within a
slice (ICI) and put only the 'data' axis across slices so the only
cross-slice traffic is the gradient all-reduce, which overlaps with the
backward pass.  Single-process runs work unchanged (the mesh is just the
local devices)."""

from __future__ import annotations

import os
from typing import Optional, Sequence

import jax

from deeplearning4j_tpu.parallel.mesh import AXES, MeshConfig, make_mesh

_initialized = False


def supports_multiprocess_mesh() -> bool:
    """Whether THIS backend can run cross-process computations inside
    one compiled program.  The jax CPU backend cannot ("Multiprocess
    computations aren't implemented on the CPU backend") — on CPU the
    elastic runtime's coordinator barrier (``distributed/``) is the
    data plane instead, and joining ``jax.distributed`` would only
    manufacture a global mesh no program can execute on.
    ``DL4J_DIST_FORCE_JAX=1`` overrides (future jax versions)."""
    if os.environ.get("DL4J_DIST_FORCE_JAX") == "1":
        return True
    try:
        return jax.default_backend() != "cpu"
    except Exception:
        return False


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> bool:
    """Join the jax.distributed process group.  Arguments default to the
    standard env vars (JAX_COORDINATOR_ADDRESS / NUM_PROCESSES /
    PROCESS_ID, also honoring TPU pod metadata when present).  Returns
    True if a multi-process group was joined, False for single-process
    (no coordinator configured, or a backend that cannot execute
    multi-process computations — the elastic runtime then uses its
    coordinator-level collectives) — callers need no special-casing
    either way."""
    global _initialized
    if _initialized:
        return jax.process_count() > 1
    coordinator_address = (coordinator_address
                           or os.environ.get("JAX_COORDINATOR_ADDRESS"))
    if coordinator_address is None:
        return False  # single-process: local devices only
    if not supports_multiprocess_mesh():
        return False  # CPU backend: a joined group would be unusable
    kwargs = {"coordinator_address": coordinator_address}
    if num_processes is None and "NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["NUM_PROCESSES"])
    if process_id is None and "PROCESS_ID" in os.environ:
        process_id = int(os.environ["PROCESS_ID"])
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)
    _initialized = True
    return jax.process_count() > 1


def global_mesh(config: Optional[MeshConfig] = None,
                devices: Optional[Sequence] = None):
    """Mesh over ALL processes' devices (jax.devices() is global after
    initialize_distributed).  The 'data' axis is laid out across slices
    (slowest-varying) so intra-slice axes ride ICI."""
    return make_mesh(config, devices=devices)


def process_local_batch_slice(global_batch: int) -> slice:
    """Which rows of a globally-sharded batch this process should feed —
    hosts feed disjoint shards; jax.make_array_from_process_local_data
    assembles the global array."""
    per = global_batch // jax.process_count()
    start = per * jax.process_index()
    return slice(start, start + per)
