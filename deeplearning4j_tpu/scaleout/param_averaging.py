"""ParameterAveragingTrainingMaster — the reference's one concrete
distributed-training strategy, rebuilt for host-driven TPU workers
(ref: spark/impl/paramavg/ParameterAveragingTrainingMaster.java: split
sizing :346-352, doIteration :702-721, processResults + treeAggregate
:860-905; worker loop ref: spark/api/worker/ExecuteWorkerFlatMap.java:29-124,
ParameterAveragingTrainingWorker.java).

Semantics preserved:
* data is split into "splits" of ``num_workers × batch_size_per_worker ×
  averaging_frequency`` examples;
* each split is repartitioned across workers, every worker rebuilds the
  model from the broadcast (conf JSON + flat params + flat updater
  state), fits its partition's minibatches locally;
* results are tree-aggregated (param sum + optional updater-state sum at
  configurable ``aggregation_depth``), divided by worker count, applied
  to the driver model, and re-broadcast with the next split.

On a single host the workers are a thread pool (the reference's
local[N] Spark mode, which is exactly how its own test suite exercises
this code — SURVEY.md §4); each worker drives the same jitted step.  For
true pod-scale the per-step-psum path (parallel/ParallelWrapper over an
ICI/DCN mesh) is both faster and mathematically stronger; this master
exists for reference-parity semantics (averaging every N steps) and for
transports where collectives are unavailable."""

from __future__ import annotations

import math
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.scaleout.stats import TrainingStats
from deeplearning4j_tpu.scaleout.training_master import (
    NetBroadcastTuple, TrainingMaster, TrainingWorker, WorkerConfiguration)


class ParameterAveragingTrainingWorker(TrainingWorker):
    """(ref: spark/impl/paramavg/ParameterAveragingTrainingWorker.java)"""

    def __init__(self, config: WorkerConfiguration, hooks):
        self.config = config
        self.hooks = hooks

    def get_initial_model(self, broadcast: NetBroadcastTuple):
        if broadcast.is_graph:
            from deeplearning4j_tpu.nn.conf.graph_conf import (
                ComputationGraphConfiguration)
            from deeplearning4j_tpu.nn.graph import ComputationGraph
            net = ComputationGraph(
                ComputationGraphConfiguration.from_json(
                    broadcast.conf_json)).init()
        else:
            from deeplearning4j_tpu.nn.conf.network import (
                MultiLayerConfiguration)
            from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
            net = MultiLayerNetwork(
                MultiLayerConfiguration.from_json(broadcast.conf_json)).init()
        net.set_params(broadcast.params)
        if broadcast.updater_state is not None and len(broadcast.updater_state):
            net.set_updater_state_flat(broadcast.updater_state)
        net.iteration = broadcast.iteration
        return net

    def process_minibatch(self, dataset: DataSet, model) -> None:
        for h in self.hooks:
            h.pre_update(dataset, model)
        model.fit(dataset)
        for h in self.hooks:
            h.post_update(dataset, model)

    def get_final_result(self, model) -> Dict[str, Any]:
        return {
            "params": np.asarray(model.params()),
            "updater_state": np.asarray(model.updater_state_flat()),
            "score": float(model.score()),
            "count": 1,
        }


class ParameterAveragingTrainingMaster(TrainingMaster):
    def __init__(self, num_workers: int = 2,
                 batch_size_per_worker: int = 32,
                 averaging_frequency: int = 5,
                 aggregation_depth: int = 2,
                 average_updater_state: bool = True,
                 prefetch_num_batches: int = 2,
                 collect_training_stats: bool = False,
                 repartition: str = "balanced"):
        super().__init__()
        if averaging_frequency <= 0:
            raise ValueError("averaging_frequency must be >= 1")
        self.num_workers = num_workers
        self.batch_size_per_worker = batch_size_per_worker
        self.averaging_frequency = averaging_frequency
        self.aggregation_depth = max(2, aggregation_depth)
        self.average_updater_state = average_updater_state
        self.prefetch_num_batches = prefetch_num_batches
        self.collect_training_stats = collect_training_stats
        self.repartition = repartition
        self.stats: Optional[TrainingStats] = (
            TrainingStats() if collect_training_stats else None)

    # -- config record (ref: TrainingMaster.toJson) -------------------------
    def _config_dict(self) -> Dict[str, Any]:
        return {
            "num_workers": self.num_workers,
            "batch_size_per_worker": self.batch_size_per_worker,
            "averaging_frequency": self.averaging_frequency,
            "aggregation_depth": self.aggregation_depth,
            "average_updater_state": self.average_updater_state,
            "prefetch_num_batches": self.prefetch_num_batches,
            "collect_training_stats": self.collect_training_stats,
            "repartition": self.repartition,
        }

    # -- data plumbing ------------------------------------------------------
    def _collect(self, data) -> List[DataSet]:
        """Accept list[DataSet], a DataSetIterator, or one DataSet; break
        into per-worker minibatches of batch_size_per_worker."""
        datasets: List[DataSet] = []
        if isinstance(data, DataSet):
            datasets = [data]
        elif hasattr(data, "has_next"):
            data.reset()
            while data.has_next():
                datasets.append(data.next())
        else:
            datasets = list(data)
        out: List[DataSet] = []
        b = self.batch_size_per_worker
        for ds in datasets:
            n = ds.num_examples()
            if n <= b:
                out.append(ds)
                continue
            for s in range(0, n, b):
                out.append(ds.get_range(s, min(s + b, n)))
        return out

    def _partition(self, batches: List[DataSet]) -> List[List[DataSet]]:
        """Balanced round-robin repartition
        (ref: spark/util/SparkUtils.repartitionBalanceIfRequired)."""
        parts: List[List[DataSet]] = [[] for _ in range(self.num_workers)]
        for i, ds in enumerate(batches):
            parts[i % self.num_workers].append(ds)
        return parts

    # -- aggregation --------------------------------------------------------
    def _tree_aggregate(self, results: List[Dict[str, Any]]) -> Dict[str, Any]:
        """Pairwise (depth-grouped) reduction of param/updater sums —
        the treeAggregate analog (ref:
        ParameterAveragingTrainingMaster.java:860-867,
        aggregator/ParameterAveragingElementAddFunction.java)."""

        def combine(a: Dict[str, Any], b: Dict[str, Any]) -> Dict[str, Any]:
            return {
                "params": a["params"] + b["params"],
                "updater_state": (a["updater_state"] + b["updater_state"]
                                  if a["updater_state"] is not None
                                  and b["updater_state"] is not None else None),
                "score": a["score"] + b["score"],
                "count": a["count"] + b["count"],
            }

        level = list(results)
        d = self.aggregation_depth
        while len(level) > 1:
            nxt = []
            for i in range(0, len(level), d):
                group = level[i:i + d]
                acc = group[0]
                for g in group[1:]:
                    acc = combine(acc, g)
                nxt.append(acc)
            level = nxt
        return level[0]

    # -- the distributed loop ----------------------------------------------
    def execute_training(self, front_end, data) -> None:
        model = front_end.network
        if model.net_params is None:
            model.init()
        batches = self._collect(data)
        if not batches:
            return
        split_size = self.num_workers * self.averaging_frequency
        n_splits = math.ceil(len(batches) / split_size)
        stats = self.stats
        worker = ParameterAveragingTrainingWorker(
            WorkerConfiguration(
                is_graph_network=front_end.is_graph,
                batch_size_per_worker=self.batch_size_per_worker,
                averaging_frequency=self.averaging_frequency,
                prefetch_num_batches=self.prefetch_num_batches,
                collect_training_stats=self.collect_training_stats),
            self.hooks)

        for si in range(n_splits):
            split = batches[si * split_size:(si + 1) * split_size]
            # broadcast (ref: doIteration :702-721)
            if stats:
                with stats.time("broadcast"):
                    broadcast = self._make_broadcast(front_end, model)
            else:
                broadcast = self._make_broadcast(front_end, model)
            parts = self._partition(split)

            def run_worker(wid_part):
                wid, part = wid_part
                if not part:
                    return None
                t = stats.time("worker_fit", f"worker-{wid}") if stats else None
                if t:
                    t.__enter__()
                try:
                    net = worker.get_initial_model(broadcast)
                    for ds in part:
                        worker.process_minibatch(ds, net)
                    return worker.get_final_result(net)
                finally:
                    if t:
                        t.__exit__(None, None, None)

            with ThreadPoolExecutor(max_workers=self.num_workers) as ex:
                results = [r for r in ex.map(run_worker, enumerate(parts))
                           if r is not None]
            if not results:
                continue
            # aggregate + apply (ref: processResults :860-905)
            if stats:
                with stats.time("aggregate"):
                    agg = self._tree_aggregate(results)
            else:
                agg = self._tree_aggregate(results)
            c = agg["count"]
            model.set_params(agg["params"] / c)
            if self.average_updater_state and agg["updater_state"] is not None:
                model.set_updater_state_flat(agg["updater_state"] / c)
            model._score = agg["score"] / c
            # driver iteration advances by the local steps each worker took
            # (ceil over workers keeps Adam bias correction monotone)
            model.iteration += max(len(p) for p in parts)
            for lst in getattr(model, "listeners", []):
                lst.iteration_done(model, model.iteration)

    def _make_broadcast(self, front_end, model) -> NetBroadcastTuple:
        ups = np.asarray(model.updater_state_flat())
        return NetBroadcastTuple(
            conf_json=model.conf.to_json(),
            params=np.asarray(model.params()),
            updater_state=ups if ups.size else None,
            is_graph=front_end.is_graph,
            iteration=int(model.iteration))
