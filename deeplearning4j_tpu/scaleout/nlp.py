"""Distributed NLP: parallel vocab construction + cluster Word2Vec
(ref: dl4j-spark-nlp/.../spark/text/functions/TextPipeline.java — map
sentences → tokens → per-partition word counts → reduce; spark/models/
embeddings/word2vec/Word2Vec.java; dl4j-spark-nlp-java8/.../SparkWord2Vec.java).

The reference counts words with Spark accumulators across partitions and
then trains with its parameter-averaging loop.  Here the corpus is
partitioned across a worker pool for counting (the TextPipeline role),
the vocab/Huffman build is shared, and training runs through the fused
XLA skip-gram kernels — batched device steps replace the reference's
per-executor Aggregate ops."""

from __future__ import annotations

from collections import Counter
from concurrent.futures import ThreadPoolExecutor
from typing import Iterable, List, Optional

from deeplearning4j_tpu.scaleout.data import repartition_balanced
from deeplearning4j_tpu.text.tokenization import (
    DefaultTokenizerFactory, TokenizerFactory)


class TextPipeline:
    """Distributed token counting (ref: spark/text/functions/
    TextPipeline.java — buildVocabCache: tokenize, filter stopwords,
    accumulate counts, filter minWordFrequency)."""

    def __init__(self, sentences: Iterable[str],
                 tokenizer_factory: Optional[TokenizerFactory] = None,
                 stop_words: Optional[Iterable[str]] = None,
                 min_word_frequency: int = 1,
                 num_partitions: int = 4):
        self.sentences = list(sentences)
        self.tf = tokenizer_factory or DefaultTokenizerFactory()
        self.stop_words = set(stop_words or [])
        self.min_word_frequency = min_word_frequency
        self.num_partitions = num_partitions

    def _count_partition(self, part: List[str]) -> Counter:
        c: Counter = Counter()
        for sentence in part:
            for tok in self.tf.create(sentence).get_tokens():
                if tok and tok not in self.stop_words:
                    c[tok] += 1
        return c

    def build_word_counts(self) -> Counter:
        parts = repartition_balanced(self.sentences, self.num_partitions)
        with ThreadPoolExecutor(max_workers=self.num_partitions) as ex:
            counters = list(ex.map(self._count_partition, parts))
        total: Counter = Counter()
        for c in counters:
            total.update(c)
        return total

    def build_vocab_cache(self):
        """→ AbstractCache with Huffman codes, ready for training."""
        from deeplearning4j_tpu.text.sequence import SequenceElement
        from deeplearning4j_tpu.text.vocab import AbstractCache, Huffman
        counts = self.build_word_counts()
        cache = AbstractCache()
        for word, n in counts.items():
            if n >= self.min_word_frequency:
                cache.add_token(SequenceElement(word, frequency=float(n)))
        cache.build_index()
        Huffman(cache.vocab_words()).build()
        return cache


class ClusterWord2Vec:
    """Word2Vec with distributed vocab build
    (ref: spark/models/embeddings/word2vec/Word2Vec.java — the Spark
    front-end wraps the same training core behind an RDD<String> input)."""

    def __init__(self, layer_size: int = 100, window: int = 5,
                 min_word_frequency: int = 1, negative: int = 5,
                 use_hierarchic_softmax: bool = True, seed: int = 42,
                 num_partitions: int = 4, iterations: int = 1,
                 learning_rate: float = 0.025,
                 tokenizer_factory: Optional[TokenizerFactory] = None,
                 stop_words: Optional[Iterable[str]] = None):
        self.layer_size = layer_size
        self.window = window
        self.min_word_frequency = min_word_frequency
        self.negative = negative
        self.use_hierarchic_softmax = use_hierarchic_softmax
        self.seed = seed
        self.num_partitions = num_partitions
        self.iterations = iterations
        self.learning_rate = learning_rate
        self.tokenizer_factory = tokenizer_factory
        self.stop_words = stop_words
        self.model = None

    def fit(self, sentences: Iterable[str]):
        from deeplearning4j_tpu.embeddings.word2vec import Word2Vec
        from deeplearning4j_tpu.text.sentence_iterators import (
            CollectionSentenceIterator)
        sentences = list(sentences)
        pipeline = TextPipeline(
            sentences, self.tokenizer_factory, self.stop_words,
            self.min_word_frequency, self.num_partitions)
        vocab = pipeline.build_vocab_cache()
        builder = (Word2Vec.Builder()
                   .iterate(CollectionSentenceIterator(sentences)))
        builder.conf.layer_size = self.layer_size
        builder.conf.window = self.window
        builder.conf.min_word_frequency = self.min_word_frequency
        builder.conf.negative = self.negative
        builder.conf.use_hierarchic_softmax = self.use_hierarchic_softmax
        builder.conf.seed = self.seed
        builder.conf.iterations = self.iterations
        builder.conf.learning_rate = self.learning_rate
        if self.tokenizer_factory is not None:
            builder.tokenizer_factory(self.tokenizer_factory)
        if self.stop_words:
            builder.stop_words(self.stop_words)
        w2v = builder.build()
        w2v.vocab = vocab  # pre-built distributed vocab
        w2v.fit()
        self.model = w2v
        return w2v
