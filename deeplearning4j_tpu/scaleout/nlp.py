"""Distributed NLP: parallel vocab construction + cluster Word2Vec
(ref: dl4j-spark-nlp/.../spark/text/functions/TextPipeline.java — map
sentences → tokens → per-partition word counts → reduce; spark/models/
embeddings/word2vec/Word2Vec.java; dl4j-spark-nlp-java8/.../SparkWord2Vec.java).

The reference counts words with Spark accumulators across partitions and
then trains with its parameter-averaging loop.  Here the corpus is
partitioned across a worker pool for counting (the TextPipeline role),
the vocab/Huffman build is shared, and training runs through the fused
XLA skip-gram kernels — batched device steps replace the reference's
per-executor Aggregate ops."""

from __future__ import annotations

from collections import Counter
from concurrent.futures import ThreadPoolExecutor
from typing import Iterable, List, Optional

from deeplearning4j_tpu.scaleout.data import repartition_balanced
from deeplearning4j_tpu.text.tokenization import (
    DefaultTokenizerFactory, TokenizerFactory)


class TextPipeline:
    """Distributed token counting (ref: spark/text/functions/
    TextPipeline.java — buildVocabCache: tokenize, filter stopwords,
    accumulate counts, filter minWordFrequency)."""

    def __init__(self, sentences: Iterable[str],
                 tokenizer_factory: Optional[TokenizerFactory] = None,
                 stop_words: Optional[Iterable[str]] = None,
                 min_word_frequency: int = 1,
                 num_partitions: int = 4):
        self.sentences = list(sentences)
        self.tf = tokenizer_factory or DefaultTokenizerFactory()
        self.stop_words = set(stop_words or [])
        self.min_word_frequency = min_word_frequency
        self.num_partitions = num_partitions

    def _count_partition(self, part: List[str]) -> Counter:
        c: Counter = Counter()
        for sentence in part:
            for tok in self.tf.create(sentence).get_tokens():
                if tok and tok not in self.stop_words:
                    c[tok] += 1
        return c

    def build_partition_counts(self):
        """(partitions, per-partition token counters) from ONE balanced
        split and ONE tokenization pass.  Returning the partitions with
        their counters makes the alignment explicit — callers that also
        train per shard reuse these exact partitions instead of relying
        on a second repartition call happening to agree."""
        parts = repartition_balanced(self.sentences, self.num_partitions)
        with ThreadPoolExecutor(max_workers=self.num_partitions) as ex:
            return parts, list(ex.map(self._count_partition, parts))

    def build_word_counts(self) -> Counter:
        total: Counter = Counter()
        for c in self.build_partition_counts()[1]:
            total.update(c)
        return total

    def build_vocab_cache(self, counts: Optional[Counter] = None):
        """→ AbstractCache with Huffman codes, ready for training.
        Pass pre-computed ``counts`` to skip re-tokenizing (e.g. from
        build_partition_counts when per-shard weights are also needed)."""
        from deeplearning4j_tpu.text.sequence import SequenceElement
        from deeplearning4j_tpu.text.vocab import AbstractCache, Huffman
        if counts is None:
            counts = self.build_word_counts()
        cache = AbstractCache()
        for word, n in counts.items():
            if n >= self.min_word_frequency:
                cache.add_token(SequenceElement(word, frequency=float(n)))
        cache.build_index()
        Huffman(cache.vocab_words()).build()
        return cache


def _build_local_w2v(vocab, sentences, layer_size, window,
                     min_word_frequency, negative, use_hierarchic_softmax,
                     seed, iterations, learning_rate, tokenizer_factory,
                     stop_words, epochs=1):
    """A single-process Word2Vec over a corpus (shard) with a PRE-BUILT
    shared vocab — the per-executor training core of the distributed
    tier (ref: spark/models/embeddings/word2vec/Word2Vec.java:55 — each
    executor trains the same vocab on its partition)."""
    from deeplearning4j_tpu.embeddings.word2vec import Word2Vec
    from deeplearning4j_tpu.text.sentence_iterators import (
        CollectionSentenceIterator)
    builder = (Word2Vec.Builder()
               .iterate(CollectionSentenceIterator(list(sentences))))
    c = builder.conf
    c.layer_size = layer_size
    c.window = window
    c.min_word_frequency = min_word_frequency
    c.negative = negative
    c.use_hierarchic_softmax = use_hierarchic_softmax
    c.seed = seed
    c.iterations = iterations
    c.learning_rate = learning_rate
    c.epochs = epochs
    if tokenizer_factory is not None:
        builder.tokenizer_factory(tokenizer_factory)
    if stop_words:
        builder.stop_words(stop_words)
    w2v = builder.build()
    w2v.vocab = vocab
    return w2v


def _check_aggregation(mode: str) -> str:
    if mode not in ("sum", "average"):
        raise ValueError(f"aggregation must be 'sum' or 'average', "
                         f"got {mode!r}")
    return mode


def _aggregation_weights(weights, aggregation):
    """Per-shard delta scale: 'average' keeps the token-share weights
    (the reference's parameter-averaging semantics); 'sum' (default)
    applies every shard's delta in full — for DISJOINT shards this is
    first-order gradient ACCUMULATION, so one round moves the shared
    weights about one full corpus epoch instead of one shard-epoch
    (measured on a community-separation task at P=4/6 rounds:
    sum margin +1.72 vs average margin -0.15).  The trade: summed
    steps are ~P-times larger, the large-batch analog — lower the
    learning rate if training turns unstable."""
    import numpy as np
    if aggregation == "sum":
        return np.ones(len(weights), np.float64)
    return np.asarray(weights, np.float64)


def _run_averaging_rounds(replicas, weights, lookup_table, rounds,
                          syncs_per_round: int = 1):
    """The delta-aggregation core shared by DistributedWord2Vec and
    DistributedSequenceVectors: each sync, every replica trains one
    pass over its (sub-)shard from the CURRENT shared weights, then the
    shared weights absorb the weight_i-scaled deltas (callers pass
    token-share weights for 'average' mode or ones for 'sum' — see
    _aggregation_weights).  Mutates and finalizes ``lookup_table`` in
    place.

    ``syncs_per_round=M > 1`` synchronizes after every 1/M of each
    shard (the reference Spark tier's averaging-frequency knob).  It
    reduces within-round replica divergence/staleness; it does NOT
    change average-mode's 1/P per-round data efficiency (the average of
    chunk deltas still moves the weights ~one chunk-epoch per sync) —
    use ``aggregation='sum'`` for sequential-SGD-like data efficiency
    (see _aggregation_weights)."""
    import numpy as np
    import jax.numpy as jnp
    syn0 = np.array(lookup_table.syn0, np.float32)
    syn1 = np.array(lookup_table.syn1, np.float32)
    syn1neg = np.array(lookup_table.syn1neg, np.float32)
    M = max(1, int(syncs_per_round))
    chunked = [_replica_chunks(r, M) for r in replicas]
    for _round in range(rounds):
        for m in range(M):
            # replicas whose chunk m is non-empty, with their weights
            live = [(r, chunks[m], w) for r, chunks, w in
                    zip(replicas, chunked, weights) if chunks[m]]
            if not live:
                continue
            with ThreadPoolExecutor(max_workers=len(live)) as ex:
                deltas = list(ex.map(
                    lambda rc: _shard_round(rc[0], syn0, syn1, syn1neg,
                                            source=rc[1]),
                    live))
            for (d0, d1, d1n), (_, _, w) in zip(deltas, live):
                syn0 += w * d0
                syn1 += w * d1
                syn1neg += w * d1n
    lookup_table.syn0 = jnp.asarray(syn0)
    lookup_table.syn1 = jnp.asarray(syn1)
    lookup_table.syn1neg = jnp.asarray(syn1neg)


def _replica_chunks(replica, m):
    """Split a replica's sequence source into m balanced chunks (a
    round-robin interleave, repartition_balanced) — chunk k is trained
    at sync k of every round."""
    src = list(replica._sequence_source or [])
    if m <= 1:
        return [src]
    return repartition_balanced(src, m)


def _shard_round(w2v, syn0, syn1, syn1neg, source=None):
    """One parameter-averaging sync on one shard (or the ``source``
    sub-shard chunk): seed the replica with the shared weights, train
    one epoch over it, return the weight deltas.  build_vocab() keeps
    pre-seeded weights (reset only when syn0 is None), so setting them
    first makes fit() resume — the executor-side step of the
    reference's training loop."""
    import jax.numpy as jnp
    prev_source = w2v._sequence_source
    if source is not None:
        w2v._sequence_source = source
    try:
        w2v.build_vocab()
        lt = w2v.lookup_table
        lt.syn0 = jnp.asarray(syn0)
        lt.syn1 = jnp.asarray(syn1)
        lt.syn1neg = jnp.asarray(syn1neg)
        w2v.fit()
    finally:
        w2v._sequence_source = prev_source
    import numpy as np
    return (np.asarray(lt.syn0) - syn0,
            np.asarray(lt.syn1) - syn1,
            np.asarray(lt.syn1neg) - syn1neg)


class DistributedWord2Vec:
    """Word2Vec trained ACROSS corpus shards with periodic parameter
    averaging — the reference's Spark training tier
    (ref: spark/models/embeddings/word2vec/Word2Vec.java:55 — executors
    train on partitions, the driver aggregates;
    dl4j-spark-nlp-java8/.../SparkWord2Vec.java, SparkSequenceVectors.java).

    Spark executors become a worker pool: each round (= one collective
    pass), every worker trains a replica on its shard starting from the
    shared weights, and the shared weights absorb the workers' deltas —
    by default SUMMED (``aggregation="sum"``: gradient-accumulation
    semantics over disjoint shards, sequential-SGD-like data
    efficiency), or token-share-weight AVERAGED
    (``aggregation="average"``: the reference
    ParameterAveragingTrainingMaster semantics, ~1/P the per-round
    movement — see _aggregation_weights).  Training itself runs the
    fused XLA skip-gram kernels inside every worker.

    For multi-host training, the same sync structure runs over the TCP
    parameter server (scaleout/paramserver.py): each process trains its
    shard, pushes its (mode-scaled) delta, barriers on the server's
    push count, then pulls the aggregated state
    (:meth:`fit_process_shard`).
    """

    def __init__(self, layer_size: int = 32, window: int = 5,
                 min_word_frequency: int = 1, negative: float = 5,
                 use_hierarchic_softmax: bool = True, seed: int = 42,
                 num_partitions: int = 4, iterations: int = 1,
                 epochs: int = 1, learning_rate: float = 0.025,
                 tokenizer_factory: Optional[TokenizerFactory] = None,
                 stop_words: Optional[Iterable[str]] = None,
                 syncs_per_round: int = 1, aggregation: str = "sum"):
        self.layer_size = layer_size
        self.window = window
        self.min_word_frequency = min_word_frequency
        self.negative = negative
        self.use_hierarchic_softmax = use_hierarchic_softmax
        self.seed = seed
        self.num_partitions = num_partitions
        self.iterations = iterations
        self.epochs = epochs
        self.learning_rate = learning_rate
        self.tokenizer_factory = tokenizer_factory
        self.stop_words = stop_words
        self.syncs_per_round = syncs_per_round
        self.aggregation = _check_aggregation(aggregation)
        self.model = None

    # -- shared plumbing ----------------------------------------------------
    def _vocab_and_shards(self, sentences: List[str],
                          keep_empty: bool = False,
                          num_partitions: Optional[int] = None):
        """Distributed vocab build + balanced corpus shards with
        per-shard token weights (one tokenization pass: the vocab counts
        ARE the per-partition counters).  ``keep_empty=True`` preserves
        the shard↔index alignment (one shard per PROCESS, weight 0 for
        an empty shard) — required by fit_process_shard, where dropping
        a shard would misalign every process_id behind it."""
        import numpy as np
        P = num_partitions or self.num_partitions
        pipeline = TextPipeline(
            sentences, self.tokenizer_factory, self.stop_words,
            self.min_word_frequency, P)
        shards, part_counts = pipeline.build_partition_counts()
        total_counts: Counter = Counter()
        for c in part_counts:
            total_counts.update(c)
        vocab = pipeline.build_vocab_cache(total_counts)
        counts = [sum(c.values()) for c in part_counts]
        if not keep_empty:
            counts = [n for s, n in zip(shards, counts) if s]
            shards = [s for s in shards if s]
        total = float(sum(counts)) or 1.0
        weights = np.asarray(counts, np.float64) / total
        return vocab, shards, weights

    def _seed_model(self, vocab, sentences):
        """Shared-weight holder (also the returned query model)."""
        w2v = _build_local_w2v(
            vocab, sentences, self.layer_size, self.window,
            self.min_word_frequency, self.negative,
            self.use_hierarchic_softmax, self.seed, self.iterations,
            self.learning_rate, self.tokenizer_factory, self.stop_words)
        w2v.build_vocab()
        return w2v

    # -- single-host worker-pool mode ---------------------------------------
    def fit(self, sentences: Iterable[str]):
        """Train over a thread worker pool (the local[n] analog of the
        Spark executors; BaseSparkTest.java uses local masters the same
        way).  Returns the trained queryable Word2Vec model."""
        import numpy as np
        sentences = list(sentences)
        vocab, shards, weights = self._vocab_and_shards(sentences)
        if not shards:
            raise ValueError("DistributedWord2Vec.fit: corpus has no "
                             "non-empty sentences")
        shared = self._seed_model(vocab, sentences)
        replicas = [
            _build_local_w2v(
                vocab, shard, self.layer_size, self.window,
                self.min_word_frequency, self.negative,
                self.use_hierarchic_softmax, self.seed + 13 * (i + 1),
                self.iterations, self.learning_rate,
                self.tokenizer_factory, self.stop_words)
            for i, shard in enumerate(shards)]
        _run_averaging_rounds(
            replicas, _aggregation_weights(weights, self.aggregation),
            shared.lookup_table, self.epochs, self.syncs_per_round)
        self.model = shared
        return shared

    # -- multi-process mode over the parameter server -----------------------
    @staticmethod
    def _pack(syn0, syn1, syn1neg):
        import numpy as np
        return np.concatenate([np.ravel(syn0), np.ravel(syn1),
                               np.ravel(syn1neg)]).astype(np.float32)

    @staticmethod
    def _unpack(flat, shapes):
        import numpy as np
        out, off = [], 0
        for sh in shapes:
            n = int(np.prod(sh))
            out.append(flat[off:off + n].reshape(sh))
            off += n
        return out

    def fit_process_shard(self, sentences: Iterable[str], *,
                          process_id: int, num_processes: int,
                          server_host: str, server_port: int,
                          poll_interval: float = 0.05,
                          timeout: float = 300.0):
        """One PROCESS's side of multi-host training: every process gets
        the full corpus (so the shared vocab is identical), trains only
        shard ``process_id``, and synchronizes every sync (M =
        ``syncs_per_round`` per round) through the parameter server
        with a TWO-phase barrier — (1) push the shard delta (scaled by
        the token-share weight in ``aggregation="average"`` mode, full
        in the default ``"sum"`` mode — see _aggregation_weights) and
        wait for all peers' pushes, then pull the aggregated state;
        (2) ack the pull and wait for all peers' acks before the next
        push, so no fast peer can contaminate weights a slow peer has
        not pulled.  Returns the queryable model holding the final
        shared weights."""
        import time
        import numpy as np
        import jax.numpy as jnp
        from deeplearning4j_tpu.scaleout.paramserver import (
            ParameterServerClient)
        sentences = list(sentences)
        vocab, shards, weights = self._vocab_and_shards(
            sentences, keep_empty=True, num_partitions=num_processes)
        shared = self._seed_model(vocab, sentences)
        lt = shared.lookup_table
        shapes = [np.asarray(a).shape for a in (lt.syn0, lt.syn1,
                                                lt.syn1neg)]
        shard = shards[process_id]   # may be empty: zero-delta rounds,
        # still participates in every barrier
        replica = _build_local_w2v(
            vocab, shard, self.layer_size, self.window,
            self.min_word_frequency, self.negative,
            self.use_hierarchic_softmax, self.seed + 13 * (process_id + 1),
            self.iterations, self.learning_rate, self.tokenizer_factory,
            self.stop_words) if shard else None
        M = max(1, int(self.syncs_per_round))
        chunks = _replica_chunks(replica, M) if replica is not None \
            else [[] for _ in range(M)]

        def wait_until(cond, what):
            deadline = time.time() + timeout
            while not cond():
                if time.time() > deadline:
                    raise TimeoutError(f"{what} not reached within "
                                       f"{timeout}s")
                time.sleep(poll_interval)

        client = ParameterServerClient(server_host, server_port)
        try:
            # round-0 barrier: every process must pull the seed before
            # ANY round-1 push lands (the server applies pushes
            # immediately, so an unguarded seed pull could read a fast
            # peer's round-1 delta)
            current = client.get_nd_array()
            client.increment_counter("pulled:0")
            wait_until(
                lambda: client.read_counter("pulled:0") >= num_processes,
                "seed barrier")
            sync_no = 0
            for rnd in range(1, self.epochs + 1):
                for m in range(M):
                    sync_no += 1
                    syn0, syn1, syn1neg = self._unpack(current, shapes)
                    if replica is not None and chunks[m]:
                        d0, d1, d1n = _shard_round(
                            replica, syn0, syn1, syn1neg,
                            source=chunks[m])
                        scale = (1.0 if self.aggregation == "sum"
                                 else float(weights[process_id]))
                        delta = scale * self._pack(d0, d1, d1n)
                    else:
                        delta = np.zeros_like(current)
                    # phase 1: everyone pushes, then pulls the
                    # aggregated state
                    client.push_nd_array(delta)
                    wait_until(
                        lambda n=sync_no: client.push_count()
                        >= n * num_processes,
                        f"sync {sync_no} push barrier")
                    current = client.get_nd_array()
                    # phase 2: everyone acks the pull before any later
                    # push may land (prevents fast-peer contamination)
                    client.increment_counter(f"pulled:{sync_no}")
                    wait_until(
                        lambda n=sync_no: client.read_counter(
                            f"pulled:{n}") >= num_processes,
                        f"sync {sync_no} pull barrier")
        finally:
            client.close()
        syn0, syn1, syn1neg = self._unpack(current, shapes)
        lt.syn0 = jnp.asarray(syn0)
        lt.syn1 = jnp.asarray(syn1)
        lt.syn1neg = jnp.asarray(syn1neg)
        self.model = shared
        return shared


class DistributedSequenceVectors:
    """Generic SequenceVectors trained across SEQUENCE shards with
    per-round parameter averaging — the reference's
    SparkSequenceVectors / SparkParagraphVectors tier
    (ref: dl4j-spark-nlp-java8/.../SparkSequenceVectors.java — executors
    train the shared vocab on sequence partitions and the driver
    aggregates; SparkParagraphVectors is the same engine with
    ``train_sequences=True``).

    Works for any Sequence stream — DeepWalk walks, labeled paragraph
    sequences, token sequences — using the same round structure as
    :class:`DistributedWord2Vec`: each round every worker trains a
    replica of the shared weights on its shard, and the shared weights
    absorb the workers' deltas (summed by default, element-count-weight
    averaged in ``aggregation="average"`` reference-compat mode).

    Aggregation modes (``aggregation=``):

    * ``"sum"`` (default) — every shard's delta applies in full; for
      disjoint shards this is first-order gradient ACCUMULATION, so one
      round moves the shared weights about one full corpus epoch
      (sequential-SGD-like data efficiency; steps are ~P× larger — the
      large-batch analog — so lower the learning rate if unstable).
    * ``"average"`` — the reference's parameter-averaging semantics
      (token-share-weighted mean of deltas).  One round then moves the
      weights only about ONE shard-epoch, i.e. ≈ 1/num_partitions of a
      single-process epoch — budget ``epochs ≈ num_partitions ×
      single-process epochs`` (measured: P=4 needs 4×6 rounds to match
      P=1 at 6 epochs on a community-separation task; sum mode matches
      in 6).

    ``syncs_per_round=M`` synchronizes after every 1/M of each shard
    (the Spark tier's averaging-frequency knob) — it reduces replica
    divergence within a round; it does NOT change average-mode's 1/P
    data-efficiency factor."""

    def __init__(self, configuration=None, num_partitions: int = 4,
                 epochs: Optional[int] = None, seed_offset: int = 13,
                 syncs_per_round: int = 1, aggregation: str = "sum"):
        """``epochs`` is the number of averaging ROUNDS (one collective
        pass over the corpus each); when omitted it follows
        ``configuration.epochs`` so a VectorsConfiguration(epochs=N) is
        honored rather than silently reduced to one round."""
        from deeplearning4j_tpu.embeddings.sequencevectors import (
            VectorsConfiguration)
        self.conf = configuration or VectorsConfiguration()
        self.num_partitions = num_partitions
        self.epochs = epochs if epochs is not None else self.conf.epochs
        self.seed_offset = seed_offset
        self.syncs_per_round = syncs_per_round
        self.aggregation = _check_aggregation(aggregation)
        self.model = None

    def _replica(self, vocab, shard, seed):
        import dataclasses as _dc
        from deeplearning4j_tpu.embeddings.sequencevectors import (
            SequenceVectors)
        conf = _dc.replace(self.conf, seed=seed, epochs=1)
        sv = SequenceVectors(conf, vocab=vocab)
        sv._sequence_source = list(shard)
        return sv

    def fit(self, sequences) -> "object":
        """``sequences``: a list/iterable of
        :class:`~deeplearning4j_tpu.text.sequence.Sequence`.  Returns
        the trained queryable SequenceVectors holding the averaged
        weights."""
        import numpy as np
        from deeplearning4j_tpu.embeddings.sequencevectors import (
            SequenceVectors)
        from deeplearning4j_tpu.text.vocab import VocabConstructor

        sequences = list(sequences)
        if not sequences:
            raise ValueError(
                "DistributedSequenceVectors.fit: no sequences")
        ctor = VocabConstructor(
            min_element_frequency=self.conf.min_word_frequency,
            build_huffman=True)
        ctor.add_source(sequences)
        vocab = ctor.build_joint_vocabulary()

        shards = repartition_balanced(sequences, self.num_partitions)
        shards = [s for s in shards if s]
        counts = [sum(seq.size() for seq in s) for s in shards]
        total = float(sum(counts)) or 1.0
        weights = np.asarray(counts, np.float64) / total

        shared = SequenceVectors(self.conf, vocab=vocab)
        shared._sequence_source = sequences
        shared.build_vocab()
        replicas = [
            self._replica(vocab, shard,
                          self.conf.seed + self.seed_offset * (i + 1))
            for i, shard in enumerate(shards)]
        _run_averaging_rounds(
            replicas, _aggregation_weights(weights, self.aggregation),
            shared.lookup_table, self.epochs, self.syncs_per_round)
        self.model = shared
        return shared


class ClusterWord2Vec:
    """Word2Vec with distributed vocab build
    (ref: spark/models/embeddings/word2vec/Word2Vec.java — the Spark
    front-end wraps the same training core behind an RDD<String> input)."""

    def __init__(self, layer_size: int = 100, window: int = 5,
                 min_word_frequency: int = 1, negative: int = 5,
                 use_hierarchic_softmax: bool = True, seed: int = 42,
                 num_partitions: int = 4, iterations: int = 1,
                 learning_rate: float = 0.025,
                 tokenizer_factory: Optional[TokenizerFactory] = None,
                 stop_words: Optional[Iterable[str]] = None):
        self.layer_size = layer_size
        self.window = window
        self.min_word_frequency = min_word_frequency
        self.negative = negative
        self.use_hierarchic_softmax = use_hierarchic_softmax
        self.seed = seed
        self.num_partitions = num_partitions
        self.iterations = iterations
        self.learning_rate = learning_rate
        self.tokenizer_factory = tokenizer_factory
        self.stop_words = stop_words
        self.model = None

    def fit(self, sentences: Iterable[str]):
        """Distributed vocab build AND distributed training (round-4
        verdict: the training tier used to delegate to a local fit).
        ``num_partitions > 1`` trains shards over a worker pool with
        per-round parameter averaging via :class:`DistributedWord2Vec`;
        a single partition keeps the plain local path."""
        sentences = list(sentences)
        if self.num_partitions > 1:
            dist = DistributedWord2Vec(
                layer_size=self.layer_size, window=self.window,
                min_word_frequency=self.min_word_frequency,
                negative=self.negative,
                use_hierarchic_softmax=self.use_hierarchic_softmax,
                seed=self.seed, num_partitions=self.num_partitions,
                iterations=self.iterations, epochs=1,
                learning_rate=self.learning_rate,
                tokenizer_factory=self.tokenizer_factory,
                stop_words=self.stop_words)
            self.model = dist.fit(sentences)
            return self.model
        pipeline = TextPipeline(
            sentences, self.tokenizer_factory, self.stop_words,
            self.min_word_frequency, self.num_partitions)
        vocab = pipeline.build_vocab_cache()
        w2v = _build_local_w2v(
            vocab, sentences, self.layer_size, self.window,
            self.min_word_frequency, self.negative,
            self.use_hierarchic_softmax, self.seed, self.iterations,
            self.learning_rate, self.tokenizer_factory, self.stop_words)
        w2v.fit()
        self.model = w2v
        return w2v
