"""Asynchronous parameter server
(ref: deeplearning4j-scaleout-parallelwrapper-parameter-server —
parallelism/parameterserver/ParameterServerTrainer.java:15,33-74,
ParameterServerTrainerContext.java; external nd4j-parameter-server with
its Aeron UDP transport).

The reference's third communication tier: workers train local replicas
and asynchronously push updates to / pull parameters from a server node
over UDP.  Rebuilt here as a length-prefixed TCP protocol (no Aeron in
this image; the update semantics, not the wire library, are the
capability).  Server-side accumulation is additive — workers push
*deltas* (new − pulled), the Hogwild-style async-SGD scheme the
parameter-averaging literature calls "asynchronous update push".

On-mesh training should prefer the per-step psum path
(parallel/ParallelWrapper); this tier exists for asynchronous,
loosely-coupled workers — e.g. hosts feeding independent TPU slices
without a shared mesh.

Wire format: 1-byte op ('P' push, 'G' get, 'N' push count, 'C' increment
named counter, 'R' read named counter, 'Q' quit) + u32 little-endian
payload length + payload (float32 array bytes for P, a UTF-8 counter
name for C/R).  Responses: u32 length + payload; N/C/R answer with the
count/value in the length field.
"""

from __future__ import annotations

import logging
import socket
import struct
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional

import numpy as np

log = logging.getLogger(__name__)

_HDR = struct.Struct("<cI")
_LEN = struct.Struct("<I")


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


class ParameterServerNode:
    """Server holding the canonical flat parameter vector
    (ref: external nd4j ParameterServerNode consumed at
    ParameterServerTrainer.java:15)."""

    def __init__(self, initial_params: np.ndarray, host: str = "127.0.0.1",
                 port: int = 0):
        self.params = np.array(initial_params, np.float32, copy=True)
        self.counters: dict = {}
        self._lock = threading.Lock()
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(32)
        self.host, self.port = self._srv.getsockname()
        self.updates_received = 0
        self._running = True
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    # -- server loop --------------------------------------------------------
    def _serve(self) -> None:
        # crash handler (DL4J208): an unexpected accept-loop error must
        # be LOUD — a silently-dead acceptor looks alive to clients and
        # strands every connect until timeout
        try:
            while self._running:
                try:
                    conn, _ = self._srv.accept()
                except OSError:
                    break
                threading.Thread(target=self._handle, args=(conn,),
                                 daemon=True).start()
        except Exception:
            log.exception("parameter-server accept loop died")

    def _handle(self, conn: socket.socket) -> None:
        try:
            while True:
                hdr = _recv_exact(conn, _HDR.size)
                op, n = _HDR.unpack(hdr)
                if op == b"P":  # push delta
                    payload = _recv_exact(conn, n)
                    delta = np.frombuffer(payload, np.float32)
                    with self._lock:
                        if delta.shape != self.params.shape:
                            conn.sendall(_LEN.pack(0))
                            continue
                        self.params += delta
                        self.updates_received += 1
                    conn.sendall(_LEN.pack(1))
                elif op == b"G":  # pull
                    with self._lock:
                        payload = self.params.tobytes()
                    conn.sendall(_LEN.pack(len(payload)) + payload)
                elif op == b"N":  # push count — lets loosely-coupled
                    # workers build a sync barrier ("wait until all P
                    # peers pushed round r") on top of async pushes
                    with self._lock:
                        count = self.updates_received
                    conn.sendall(_LEN.pack(count))
                elif op == b"C":  # increment named counter → new value
                    key = _recv_exact(conn, n).decode()
                    with self._lock:
                        self.counters[key] = self.counters.get(key, 0) + 1
                        val = self.counters[key]
                    conn.sendall(_LEN.pack(val))
                elif op == b"R":  # read named counter
                    key = _recv_exact(conn, n).decode()
                    with self._lock:
                        val = self.counters.get(key, 0)
                    conn.sendall(_LEN.pack(val))
                elif op == b"Q":
                    break
                else:
                    break
        except (ConnectionError, OSError):
            pass
        except Exception:
            # crash handler (DL4J208): a malformed frame (struct/decode
            # error) must not silently kill the handler thread
            log.exception("parameter-server handler died on a "
                          "malformed frame")
        finally:
            conn.close()

    def shutdown(self) -> None:
        self._running = False
        try:
            self._srv.close()
        except OSError:
            pass


class ParameterServerClient:
    """(ref: org.nd4j.parameterserver.client.ParameterServerClient —
    pushNDArray / getArray surface)"""

    def __init__(self, host: str, port: int):
        self._sock = socket.create_connection((host, port))
        self._lock = threading.Lock()

    def push_nd_array(self, delta: np.ndarray) -> bool:
        payload = np.ascontiguousarray(delta, np.float32).tobytes()
        with self._lock:
            self._sock.sendall(_HDR.pack(b"P", len(payload)))
            self._sock.sendall(payload)
            (ok,) = _LEN.unpack(_recv_exact(self._sock, _LEN.size))
        return bool(ok)

    def get_nd_array(self) -> np.ndarray:
        with self._lock:
            self._sock.sendall(_HDR.pack(b"G", 0))
            (n,) = _LEN.unpack(_recv_exact(self._sock, _LEN.size))
            payload = _recv_exact(self._sock, n)
        return np.frombuffer(payload, np.float32).copy()

    def push_count(self) -> int:
        """Total pushes the server has accepted (sync-barrier primitive)."""
        with self._lock:
            self._sock.sendall(_HDR.pack(b"N", 0))
            (count,) = _LEN.unpack(_recv_exact(self._sock, _LEN.size))
        return int(count)

    def increment_counter(self, key: str) -> int:
        """Atomically bump a named server-side counter; returns the new
        value (the ack half of a two-phase barrier)."""
        payload = key.encode()
        with self._lock:
            self._sock.sendall(_HDR.pack(b"C", len(payload)) + payload)
            (val,) = _LEN.unpack(_recv_exact(self._sock, _LEN.size))
        return int(val)

    def read_counter(self, key: str) -> int:
        payload = key.encode()
        with self._lock:
            self._sock.sendall(_HDR.pack(b"R", len(payload)) + payload)
            (val,) = _LEN.unpack(_recv_exact(self._sock, _LEN.size))
        return int(val)

    def close(self) -> None:
        try:
            self._sock.sendall(_HDR.pack(b"Q", 0))
        except OSError:
            pass
        self._sock.close()


class ParameterServerTrainer:
    """Async-SGD trainer: N workers pull → local fit → push delta
    (ref: parallelism/parameterserver/ParameterServerTrainer.java:33-74 —
    feedDataSet trains then pushes/pulls through the client)."""

    def __init__(self, model, num_workers: int = 2,
                 node: Optional[ParameterServerNode] = None):
        if model.net_params is None:
            model.init()
        self.model = model
        self.num_workers = num_workers
        self._own_node = node is None
        self.node = node or ParameterServerNode(np.asarray(model.params()))

    def fit(self, iterator, epochs: int = 1):
        conf_json = self.model.conf.to_json()
        from deeplearning4j_tpu.nn.conf.network import MultiLayerConfiguration
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        # collect batches, round-robin to workers
        batches = []
        for _ in range(epochs):
            iterator.reset()
            while iterator.has_next():
                batches.append(iterator.next())
        parts: List[List] = [[] for _ in range(self.num_workers)]
        for i, b in enumerate(batches):
            parts[i % self.num_workers].append(b)

        def worker(part):
            if not part:
                return
            client = ParameterServerClient(self.node.host, self.node.port)
            net = MultiLayerNetwork(
                MultiLayerConfiguration.from_json(conf_json)).init()
            try:
                for ds in part:
                    pulled = client.get_nd_array()
                    net.set_params(pulled)
                    net.fit(ds)
                    delta = np.asarray(net.params()) - pulled
                    client.push_nd_array(delta)
            finally:
                client.close()

        with ThreadPoolExecutor(max_workers=self.num_workers) as ex:
            list(ex.map(worker, parts))
        self.model.set_params(self.node.params)
        if self._own_node:
            self.node.shutdown()
        return self.model
