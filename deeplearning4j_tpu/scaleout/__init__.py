"""Cluster-scale training — the reference's `deeplearning4j-scaleout/spark`
tier rebuilt TPU-natively (SURVEY.md §2.6).

The reference distributes by shipping (conf, params, updater-state) to
Spark executors, fitting locally per partition, and tree-aggregating the
resulting parameters back to the driver
(ref: spark/impl/paramavg/ParameterAveragingTrainingMaster.java).  Here
the same TrainingMaster SPI exists, but the unit of distribution is a
*host process driving a TPU slice*: workers run the jitted train step,
and the aggregation is either host-staged tree averaging (reference
parity, works across any transport) or — the recommended path — one
`psum` over the mesh inside the compiled step (parallel/ParallelWrapper),
with DCN-spanning meshes via `jax.distributed` for pod scale
(scaleout.multislice)."""

from deeplearning4j_tpu.scaleout.training_master import (
    NetBroadcastTuple, TrainingHook, TrainingMaster, TrainingWorker,
    WorkerConfiguration)
from deeplearning4j_tpu.scaleout.param_averaging import (
    ParameterAveragingTrainingMaster)
from deeplearning4j_tpu.scaleout.frontends import (
    ClusterComputationGraph, ClusterDl4jMultiLayer)
from deeplearning4j_tpu.scaleout.stats import TrainingStats
from deeplearning4j_tpu.scaleout.time_source import (
    NTPTimeSource, SystemClockTimeSource, TimeSourceProvider)

__all__ = [
    "NetBroadcastTuple", "TrainingHook", "TrainingMaster", "TrainingWorker",
    "WorkerConfiguration", "ParameterAveragingTrainingMaster",
    "ClusterComputationGraph", "ClusterDl4jMultiLayer", "TrainingStats",
    "NTPTimeSource", "SystemClockTimeSource", "TimeSourceProvider",
]
