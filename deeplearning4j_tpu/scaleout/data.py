"""Distributed data plumbing
(ref: dl4j-spark/.../spark/data/ — BatchAndExportDataSetsFunction,
DataSetExportFunction, PathSparkDataSetIterator; spark/util/SparkUtils
repartitioning; spark/iterator/PortableDataStreamDataSetIterator).

The reference persists RDD<DataSet> partitions to distributed storage
and re-reads them by path on executors.  Here DataSets export to ``.npz``
files (features/labels/masks) and stream back through a path-backed
iterator — the same decoupling of ETL from training, feeding the async
device-prefetch pipeline."""

from __future__ import annotations

import os
from pathlib import Path
from typing import Iterable, List, Optional, Sequence, Union

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import DataSetIterator


def export_dataset(ds: DataSet, path: Union[str, Path]) -> None:
    """(ref: spark/data/DataSetExportFunction.java).  Write is atomic
    (temp file + rename) so streaming consumers never observe a
    half-written archive."""
    arrays = {"features": ds.features, "labels": ds.labels}
    if ds.features_mask is not None:
        arrays["features_mask"] = ds.features_mask
    if ds.labels_mask is not None:
        arrays["labels_mask"] = ds.labels_mask
    path = Path(path)
    if path.suffix != ".npz":
        path = path.with_suffix(path.suffix + ".npz")
    tmp = path.with_name(path.name + ".tmp")
    with open(tmp, "wb") as f:
        np.savez(f, **arrays)
    os.replace(tmp, path)


def load_dataset(path: Union[str, Path]) -> DataSet:
    with np.load(path) as z:
        return DataSet(z["features"], z["labels"],
                       z["features_mask"] if "features_mask" in z else None,
                       z["labels_mask"] if "labels_mask" in z else None)


def batch_and_export(datasets: Iterable[DataSet], out_dir: Union[str, Path],
                     batch_size: int) -> List[str]:
    """Rebatch to exactly ``batch_size`` then export each minibatch
    (ref: spark/data/BatchAndExportDataSetsFunction.java — used to fix up
    partition batch sizes before training)."""
    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    paths: List[str] = []
    buf: List[DataSet] = []
    count = 0

    def flush(final: bool) -> None:
        nonlocal buf, count
        if not buf:
            return
        merged = DataSet.merge(buf)
        buf = []
        full = merged.num_examples() // batch_size * batch_size
        for b in merged.get_range(0, full).batch_by(batch_size):
            p = out_dir / f"dataset_{count}.npz"
            export_dataset(b, p)
            paths.append(str(p))
            count += 1
        rest = merged.get_range(full, merged.num_examples())
        if rest.num_examples():
            if final:
                p = out_dir / f"dataset_{count}.npz"
                export_dataset(rest, p)
                paths.append(str(p))
                count += 1
            else:
                buf = [rest]

    for ds in datasets:
        buf.append(ds)
        if sum(d.num_examples() for d in buf) >= batch_size:
            flush(final=False)
    flush(final=True)
    return paths


class PathDataSetIterator(DataSetIterator):
    """Streams DataSets from exported files
    (ref: spark/iterator/PathSparkDataSetIterator.java).  With
    ``prefetch=True`` file reads run ahead on the native threaded
    prefetcher (native/dl4j_io.cc), decoding on the consumer thread."""

    def __init__(self, paths: Sequence[Union[str, Path]],
                 prefetch: bool = False, prefetch_capacity: int = 4):
        self.paths = [str(p) for p in paths]
        self.prefetch = prefetch
        self.prefetch_capacity = prefetch_capacity
        self._stream = None
        self._i = 0

    @staticmethod
    def from_dir(directory: Union[str, Path]) -> "PathDataSetIterator":
        files = sorted(Path(directory).glob("*.npz"),
                       key=lambda p: (len(p.name), p.name))
        return PathDataSetIterator(files)

    def has_next(self) -> bool:
        return self._i < len(self.paths)

    def next(self) -> DataSet:
        if self.prefetch:
            if self._stream is None:
                from deeplearning4j_tpu.native import NativeFilePrefetcher
                from deeplearning4j_tpu.native.io import load_npz_dataset_bytes
                self._decode = load_npz_dataset_bytes
                self._stream = iter(NativeFilePrefetcher(
                    self.paths[self._i:], capacity=self.prefetch_capacity))
            path, blob = next(self._stream)
            if not blob:  # native reader signals failure with empty blob
                raise FileNotFoundError(f"unreadable dataset file: {path}")
            self._i += 1
            return self._decode(blob)
        ds = load_dataset(self.paths[self._i])
        self._i += 1
        return ds

    def reset(self) -> None:
        self._i = 0
        self._stream = None


def repartition_balanced(items: Sequence, n_partitions: int) -> List[List]:
    """Equal-count round-robin split
    (ref: spark/util/SparkUtils.repartitionBalanceIfRequired,
    spark/impl/common/repartition/BalancedPartitioner.java)."""
    parts: List[List] = [[] for _ in range(n_partitions)]
    for i, x in enumerate(items):
        parts[i % n_partitions].append(x)
    return parts
