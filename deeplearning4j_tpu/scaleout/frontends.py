"""Cluster front-ends wrapping MultiLayerNetwork / ComputationGraph
(ref: spark/impl/multilayer/SparkDl4jMultiLayer.java:202-282,
spark/impl/graph/SparkComputationGraph.java).

``fit`` delegates to the TrainingMaster (ref: SparkDl4jMultiLayer.fit
:212-216 → trainingMaster.executeTraining); ``evaluate``/
``calculate_score`` fan out over worker partitions and merge —
the reference's distributed-eval path
(ref: spark/impl/multilayer/evaluation/, spark/impl/common/score/)."""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.scaleout.training_master import TrainingMaster


class _BaseClusterFrontEnd:
    is_graph = False

    def __init__(self, network, training_master: TrainingMaster):
        self.network = network
        self.training_master = training_master

    # -- training -----------------------------------------------------------
    def fit(self, data, epochs: int = 1):
        for _ in range(epochs):
            self.training_master.execute_training(self, data)
        return self.network

    # -- distributed eval / scoring ----------------------------------------
    def _partitions(self, data, batch: int) -> List[DataSet]:
        if isinstance(data, DataSet):
            return data.batch_by(batch)
        if hasattr(data, "has_next"):
            data.reset()
            out = []
            while data.has_next():
                out.append(data.next())
            return out
        return list(data)

    def calculate_score(self, data, average: bool = True,
                        batch: int = 64) -> float:
        """(ref: SparkDl4jMultiLayer.calculateScore — sum/avg of per-
        example scores across the RDD)"""
        parts = self._partitions(data, batch)
        n_workers = getattr(self.training_master, "num_workers", 4)

        def score_part(ds):
            return float(self.network.score(ds)) * ds.num_examples()

        with ThreadPoolExecutor(max_workers=n_workers) as ex:
            totals = list(ex.map(score_part, parts))
        n = sum(p.num_examples() for p in parts)
        s = sum(totals)
        return s / n if average and n else s

    def evaluate(self, data, batch: int = 64):
        """Distributed evaluation: per-partition Evaluations merged
        (ref: spark/impl/multilayer/evaluation/EvaluationRunner)."""
        from deeplearning4j_tpu.nn.evaluation import Evaluation
        parts = self._partitions(data, batch)
        n_workers = getattr(self.training_master, "num_workers", 4)

        def eval_part(ds):
            ev = Evaluation()
            out = np.asarray(self.network.output(ds.features))
            ev.eval(ds.labels, out, mask=ds.labels_mask)
            return ev

        with ThreadPoolExecutor(max_workers=n_workers) as ex:
            evals = list(ex.map(eval_part, parts))
        merged = Evaluation()
        for ev in evals:
            merged.merge(ev)
        return merged

    # -- stats passthrough --------------------------------------------------
    def get_training_stats(self):
        return getattr(self.training_master, "stats", None)


class ClusterDl4jMultiLayer(_BaseClusterFrontEnd):
    """(ref: spark/impl/multilayer/SparkDl4jMultiLayer.java)"""

    is_graph = False

    def __init__(self, conf_or_net, training_master: TrainingMaster):
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        if isinstance(conf_or_net, MultiLayerNetwork):
            net = conf_or_net
        else:
            net = MultiLayerNetwork(conf_or_net)
        if net.net_params is None:
            net.init()
        super().__init__(net, training_master)


class ClusterComputationGraph(_BaseClusterFrontEnd):
    """(ref: spark/impl/graph/SparkComputationGraph.java)"""

    is_graph = True

    def __init__(self, conf_or_net, training_master: TrainingMaster):
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        if isinstance(conf_or_net, ComputationGraph):
            net = conf_or_net
        else:
            net = ComputationGraph(conf_or_net)
        if net.net_params is None:
            net.init()
        super().__init__(net, training_master)
