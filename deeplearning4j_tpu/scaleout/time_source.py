"""Cross-node time sources for training stats
(ref: dl4j-spark/.../spark/time/{TimeSource,NTPTimeSource,
SystemClockTimeSource,TimeSourceProvider}.java).

The reference disciplines executor clocks against NTP so distributed
stats timelines line up (ref: NTPTimeSource.java:28, sysprops :31-32).
This environment has zero egress, so NTPTimeSource degrades to a zero
offset with a recorded reason rather than failing."""

from __future__ import annotations

import os
import time


class TimeSource:
    def current_time_millis(self) -> int:
        raise NotImplementedError


class SystemClockTimeSource(TimeSource):
    """(ref: spark/time/SystemClockTimeSource.java)"""

    def current_time_millis(self) -> int:
        return int(time.time() * 1000)


class NTPTimeSource(TimeSource):
    """NTP-disciplined clock (ref: spark/time/NTPTimeSource.java).

    Queries the server named by DL4J_NTP_SERVER (reference sysprop
    ``org.deeplearning4j.spark.time.NTPTimeSource.server``) at
    construction and every ``update_frequency_ms``; on any failure the
    offset stays at its last value (0 initially) — training never blocks
    on the clock."""

    DEFAULT_SERVER = "0.pool.ntp.org"

    def __init__(self, server: str | None = None,
                 update_frequency_ms: int = 30 * 60 * 1000):
        self.server = server or os.environ.get("DL4J_NTP_SERVER",
                                               self.DEFAULT_SERVER)
        self.update_frequency_ms = update_frequency_ms
        self.offset_ms = 0
        self.last_error: str | None = None
        self._last_sync = 0.0
        self._sync()

    def _sync(self) -> None:
        self._last_sync = time.time()
        try:
            self.offset_ms = self._query_offset()
            self.last_error = None
        except Exception as e:  # zero-egress / DNS failure path
            self.last_error = f"{type(e).__name__}: {e}"

    def _query_offset(self) -> int:
        import socket
        import struct
        # SNTP: 48-byte packet, LI=0 VN=3 mode=3
        pkt = b"\x1b" + 47 * b"\0"
        t0 = time.time()
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.settimeout(2.0)
            s.sendto(pkt, (self.server, 123))
            data, _ = s.recvfrom(48)
        t3 = time.time()
        NTP_EPOCH_DELTA = 2208988800
        secs, frac = struct.unpack("!II", data[40:48])
        server_time = secs - NTP_EPOCH_DELTA + frac / 2 ** 32
        return int(((server_time - (t0 + t3) / 2)) * 1000)

    def current_time_millis(self) -> int:
        if (time.time() - self._last_sync) * 1000 > self.update_frequency_ms:
            self._sync()
        return int(time.time() * 1000) + self.offset_ms


class TimeSourceProvider:
    """(ref: spark/time/TimeSourceProvider.java) — class chosen by the
    DL4J_TIMESOURCE env var; defaults to the system clock (the reference
    defaults to NTP, but with no egress that would always degrade)."""

    _instance: TimeSource | None = None

    @classmethod
    def get_instance(cls) -> TimeSource:
        if cls._instance is None:
            name = os.environ.get("DL4J_TIMESOURCE", "system")
            cls._instance = (NTPTimeSource() if name.lower() == "ntp"
                             else SystemClockTimeSource())
        return cls._instance
