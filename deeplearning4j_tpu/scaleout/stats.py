"""Distributed-training phase instrumentation
(ref: dl4j-spark/.../spark/api/stats/CommonSparkTrainingStats.java,
StatsCalculationHelper.java, spark/stats/StatsUtils.java:exportStatsAsHtml,
spark/impl/paramavg/stats/ParameterAveragingTrainingMasterStats.java).

Every phase of a distributed run (split, broadcast, worker fit,
aggregate, apply) records an ``EventStats`` with wall times from the
configured TimeSource; ``export_stats_html`` renders the same timeline
view the reference produces."""

from __future__ import annotations

import dataclasses
import html
import json
from collections import defaultdict
from typing import Dict, List

from deeplearning4j_tpu.scaleout.time_source import TimeSourceProvider


@dataclasses.dataclass
class EventStats:
    """(ref: spark/stats/BaseEventStats.java)"""

    phase: str
    start_ms: int
    duration_ms: float
    worker_id: str = "driver"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class TrainingStats:
    """Accumulates per-phase events; the analog of
    ParameterAveragingTrainingMasterStats + CommonSparkTrainingStats."""

    def __init__(self):
        self.events: List[EventStats] = []
        self._ts = TimeSourceProvider.get_instance()

    # -- StatsCalculationHelper-style timers --------------------------------
    class _Timer:
        def __init__(self, owner: "TrainingStats", phase: str, worker_id: str):
            self.owner, self.phase, self.worker_id = owner, phase, worker_id

        def __enter__(self):
            self.start = self.owner._ts.current_time_millis()
            return self

        def __exit__(self, *exc):
            end = self.owner._ts.current_time_millis()
            self.owner.events.append(EventStats(
                self.phase, self.start, end - self.start, self.worker_id))
            return False

    def time(self, phase: str, worker_id: str = "driver") -> "_Timer":
        return TrainingStats._Timer(self, phase, worker_id)

    def add(self, phase: str, start_ms: int, duration_ms: float,
            worker_id: str = "driver") -> None:
        self.events.append(EventStats(phase, start_ms, duration_ms, worker_id))

    # -- aggregation --------------------------------------------------------
    def phase_totals_ms(self) -> Dict[str, float]:
        totals: Dict[str, float] = defaultdict(float)
        for e in self.events:
            totals[e.phase] += e.duration_ms
        return dict(totals)

    def to_json(self) -> str:
        return json.dumps([e.to_dict() for e in self.events])

    # -- HTML timeline (ref: StatsUtils.exportStatsAsHtml) ------------------
    def export_stats_html(self, path: str) -> None:
        if not self.events:
            body = "<p>no events recorded</p>"
        else:
            t0 = min(e.start_ms for e in self.events)
            t1 = max(e.start_ms + e.duration_ms for e in self.events)
            span = max(t1 - t0, 1.0)
            phases = sorted({e.phase for e in self.events})
            colors = ["#4C78A8", "#F58518", "#54A24B", "#E45756", "#72B7B2",
                      "#B279A2", "#FF9DA6", "#9D755D"]
            color = {p: colors[i % len(colors)] for i, p in enumerate(phases)}
            lanes = sorted({e.worker_id for e in self.events})
            rows = []
            for lane in lanes:
                bars = []
                for e in self.events:
                    if e.worker_id != lane:
                        continue
                    left = 100.0 * (e.start_ms - t0) / span
                    width = max(100.0 * e.duration_ms / span, 0.15)
                    bars.append(
                        f'<div class="bar" title="{html.escape(e.phase)}: '
                        f'{e.duration_ms:.1f} ms" style="left:{left:.2f}%;'
                        f'width:{width:.2f}%;background:{color[e.phase]}">'
                        f'</div>')
                rows.append(f'<div class="lane"><span class="label">'
                            f'{html.escape(lane)}</span>{"".join(bars)}</div>')
            legend = "".join(
                f'<span class="key"><i style="background:{color[p]}"></i>'
                f'{html.escape(p)} ({self.phase_totals_ms()[p]:.0f} ms)</span>'
                for p in phases)
            body = (f'<div class="legend">{legend}</div>'
                    f'<div class="timeline">{"".join(rows)}</div>')
        doc = f"""<!DOCTYPE html><html><head><meta charset="utf-8">
<title>Training stats timeline</title><style>
body{{font-family:sans-serif;margin:20px}}
.lane{{position:relative;height:26px;margin:3px 0;background:#f2f2f2}}
.lane .label{{position:absolute;left:4px;top:4px;font-size:11px;z-index:2}}
.bar{{position:absolute;top:2px;height:22px;opacity:.85}}
.legend{{margin-bottom:12px}}
.key{{margin-right:14px;font-size:12px}}
.key i{{display:inline-block;width:10px;height:10px;margin-right:4px}}
</style></head><body><h2>Distributed training timeline</h2>{body}
</body></html>"""
        with open(path, "w") as f:
            f.write(doc)
