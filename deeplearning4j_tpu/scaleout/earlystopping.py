"""Early stopping on the cluster tier
(ref: dl4j-spark/.../spark/earlystopping/{SparkEarlyStoppingTrainer,
SparkDataSetLossCalculator,SparkLossCalculatorComputationGraph}.java).

One epoch = one ``TrainingMaster.execute_training`` pass over the data;
the score calculator fans the loss out over partitions like the
reference's RDD score functions."""

from __future__ import annotations

import math

from deeplearning4j_tpu.nn.earlystopping import (
    EarlyStoppingConfiguration, EarlyStoppingResult,
    check_score_free_epoch_conditions, validate_termination_conditions)


class ClusterDataSetLossCalculator:
    """(ref: spark/earlystopping/SparkDataSetLossCalculator.java)"""

    def __init__(self, front_end, data, average: bool = True):
        self.front_end = front_end
        self.data = data
        self.average = average

    def calculate_score(self, model) -> float:
        # front_end.network IS the driver model being trained
        return self.front_end.calculate_score(self.data, average=self.average)


class ClusterEarlyStoppingTrainer:
    """(ref: spark/earlystopping/BaseSparkEarlyStoppingTrainer.java)"""

    def __init__(self, config: EarlyStoppingConfiguration, front_end,
                 train_data):
        self.config = config
        self.front_end = front_end
        self.train_data = train_data

    def fit(self) -> EarlyStoppingResult:
        cfg = self.config
        validate_termination_conditions(cfg)
        net = self.front_end.network
        best_score, best_epoch = math.inf, -1
        score_vs_epoch = {}
        epoch = 0
        reason, details = "MaxEpochs", ""
        while True:
            self.front_end.fit(self.train_data)
            s = float(net.score())
            terminated = False
            for cond in cfg.iteration_termination_conditions:
                if cond.terminate(net.iteration, s):
                    reason, details = "IterationTerminationCondition", repr(cond)
                    terminated = True
            if terminated:
                break
            if epoch % cfg.evaluate_every_n_epochs == 0:
                score = cfg.score_calculator.calculate_score(net)
                score_vs_epoch[epoch] = score
                if score < best_score:
                    best_score, best_epoch = score, epoch
                    cfg.model_saver.save_best(net)
                if cfg.save_last_model:
                    cfg.model_saver.save_latest(net)
                stop = False
                for cond in cfg.epoch_termination_conditions:
                    if cond.terminate(epoch, score):
                        reason, details = ("EpochTerminationCondition",
                                           repr(cond))
                        stop = True
                if stop:
                    break
            else:
                # score-independent conditions (MaxEpochs) fire every epoch,
                # not only on evaluate_every_n_epochs boundaries
                fired = check_score_free_epoch_conditions(cfg, epoch)
                if fired is not None:
                    reason, details = "EpochTerminationCondition", repr(fired)
                    break
            epoch += 1
        best = cfg.model_saver.get_best()
        return EarlyStoppingResult(
            termination_reason=reason, termination_details=details,
            total_epochs=epoch + 1, best_model_epoch=best_epoch,
            best_model_score=best_score, score_vs_epoch=score_vs_epoch,
            best_model=best if best is not None else net)
