"""t-SNE embedding (ref: plot/BarnesHutTsne.java:65, 858 LoC; plot/Tsne.java).

TPU-first split:
  - ``theta == 0`` (exact): the ENTIRE optimization — perplexity binary
    search, pairwise affinities, KL gradient, momentum+gains update loop —
    is one jitted program of dense [N, N] ops, which the MXU eats for any
    N that fits in HBM (N·N·4 bytes; ~20k points in <2 GB).  This is the
    default and the fast path: on TPU a dense quadratic kernel beats
    pointer-chasing Barnes-Hut until N is far beyond what t-SNE is
    typically used for.
  - ``theta > 0``: classic Barnes-Hut (VPTree kNN sparse affinities +
    SpTree force approximation) on the host, for API/semantics parity
    with the reference and for very large N.

Reference hyperparameter defaults preserved: learning rate 500, momentum
0.5 → 0.8 at iteration 100 (switchMomentumIteration), early exaggeration
until iteration 250 (stopLyingIteration).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.clustering.sptree import SpTree
from deeplearning4j_tpu.clustering.vptree import VPTree


# ---------------------------------------------------------------------------
# Exact TPU kernel
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnums=(1,))
def _x2p_dense(x, perplexity: float, tol: float = 1e-5, iters: int = 50):
    """Per-point conditional gaussians with bisection on beta so every
    row hits the target perplexity (ref: Tsne x2p / computeGaussianPerplexity).
    Vectorized: all N bisections advance together."""
    n = x.shape[0]
    sum_x = jnp.sum(x * x, axis=1)
    xxt = jnp.dot(x, x.T, precision=jax.lax.Precision.HIGHEST)
    d = jnp.maximum(sum_x[:, None] - 2.0 * xxt + sum_x[None, :], 0.0)
    log_u = jnp.log(perplexity)
    eye = jnp.eye(n, dtype=bool)

    def entropy_and_p(beta):
        p = jnp.where(eye, 0.0, jnp.exp(-d * beta[:, None]))
        sum_p = jnp.maximum(jnp.sum(p, axis=1), 1e-30)
        h = jnp.log(sum_p) + beta * jnp.sum(d * p, axis=1) / sum_p
        return h, p / sum_p[:, None]

    def body(i, carry):
        beta, lo, hi = carry
        h, _ = entropy_and_p(beta)
        too_high = h > log_u  # entropy too high -> beta too small
        lo = jnp.where(too_high, beta, lo)
        hi = jnp.where(too_high, hi, beta)
        beta = jnp.where(jnp.isinf(hi), beta * 2.0,
                         jnp.where(jnp.isinf(lo), beta / 2.0, (lo + hi) / 2.0))
        return beta, lo, hi

    beta0 = jnp.ones((n,), x.dtype)
    beta, _, _ = jax.lax.fori_loop(
        0, iters, body,
        (beta0, jnp.full((n,), -jnp.inf, x.dtype), jnp.full((n,), jnp.inf, x.dtype)))
    _, p = entropy_and_p(beta)
    return p


@functools.partial(jax.jit, static_argnums=(2, 3, 4, 5, 6))
def _tsne_exact(p_sym, y0, n_iter: int, lr: float,
                switch_momentum_iter: int, stop_lying_iter: int,
                exaggeration: float):
    """Momentum+gains gradient descent on KL(P||Q) — one traced loop
    (ref: Tsne.gradient / BarnesHutTsne.gradient)."""
    n = y0.shape[0]
    eye = jnp.eye(n, dtype=bool)

    def body(it, carry):
        y, inc, gains = carry
        sum_y = jnp.sum(y * y, axis=1)
        # highest precision + clamp: TPU matmuls default to bf16 passes,
        # and a slightly-negative d² here turns 1/(1+d²) into inf
        yyt = jnp.dot(y, y.T, precision=jax.lax.Precision.HIGHEST)
        d2 = jnp.maximum(sum_y[:, None] - 2.0 * yyt + sum_y[None, :], 0.0)
        num = 1.0 / (1.0 + d2)
        num = jnp.where(eye, 0.0, num)
        q = jnp.maximum(num / jnp.sum(num), 1e-12)
        exag = jnp.where(it < stop_lying_iter, exaggeration, 1.0)
        pq = (p_sym * exag - q) * num                       # [N, N]
        grad = 4.0 * ((jnp.diag(jnp.sum(pq, axis=1)) - pq) @ y)
        gains = jnp.where(jnp.sign(grad) != jnp.sign(inc),
                          gains + 0.2, gains * 0.8)
        gains = jnp.maximum(gains, 0.01)
        momentum = jnp.where(it < switch_momentum_iter, 0.5, 0.8)
        inc = momentum * inc - lr * gains * grad
        y = y + inc
        y = y - jnp.mean(y, axis=0, keepdims=True)
        return y, inc, gains

    y, _, _ = jax.lax.fori_loop(
        0, n_iter, body,
        (y0, jnp.zeros_like(y0), jnp.ones_like(y0)))
    return y


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------

class BarnesHutTsne:
    """(ref: plot/BarnesHutTsne.java — implements Model; here a plain
    estimator with fit/fit_transform)."""

    def __init__(self, n_components: int = 2, perplexity: float = 30.0,
                 theta: float = 0.0, learning_rate: float = 500.0,
                 n_iter: int = 1000, stop_lying_iteration: int = 250,
                 switch_momentum_iteration: int = 100,
                 exaggeration: float = 12.0, seed: int = 0):
        self.n_components = n_components
        self.perplexity = perplexity
        self.theta = theta
        self.learning_rate = learning_rate
        self.n_iter = n_iter
        self.stop_lying_iteration = stop_lying_iteration
        self.switch_momentum_iteration = switch_momentum_iteration
        self.exaggeration = exaggeration
        self.seed = seed
        self.Y_: Optional[np.ndarray] = None

    # -- exact path --------------------------------------------------------
    def _fit_exact(self, x):
        p = _x2p_dense(jnp.asarray(x, jnp.float32), float(self.perplexity))
        p = (p + p.T) / (2.0 * x.shape[0])
        p = jnp.maximum(p, 1e-12)
        y0 = 1e-4 * jax.random.normal(
            jax.random.PRNGKey(self.seed), (x.shape[0], self.n_components),
            jnp.float32)
        y = _tsne_exact(p, y0, self.n_iter, self.learning_rate,
                        self.switch_momentum_iteration,
                        self.stop_lying_iteration, self.exaggeration)
        return np.asarray(y)

    # -- Barnes-Hut path ---------------------------------------------------
    def _knn_p(self, x):
        """Sparse kNN affinities via VPTree
        (ref: BarnesHutTsne.computeGaussianPerplexity(…, k=3*perplexity))."""
        n = x.shape[0]
        k = min(n - 1, int(3 * self.perplexity))
        tree = VPTree(x, "euclidean", seed=self.seed)
        rows = np.zeros((n, k), np.int32)
        vals = np.zeros((n, k), np.float64)
        log_u = np.log(self.perplexity)
        for i in range(n):
            idxs, dists = tree.knn(x[i], k + 1)
            pairs_id = [(j, dj) for j, dj in zip(idxs, dists) if j != i][:k]
            idxs = [j for j, _ in pairs_id]
            d2 = np.array([dj for _, dj in pairs_id]) ** 2
            beta, lo, hi = 1.0, -np.inf, np.inf
            for _ in range(50):
                pr = np.exp(-d2 * beta)
                sum_p = max(pr.sum(), 1e-30)
                h = np.log(sum_p) + beta * float((d2 * pr).sum()) / sum_p
                if abs(h - log_u) < 1e-5:
                    break
                if h > log_u:
                    lo = beta
                    beta = beta * 2.0 if np.isinf(hi) else (lo + hi) / 2.0
                else:
                    hi = beta
                    beta = beta / 2.0 if np.isinf(lo) else (lo + hi) / 2.0
            pr = np.exp(-d2 * beta)
            pr /= max(pr.sum(), 1e-30)
            rows[i, :len(idxs)] = idxs
            vals[i, :len(idxs)] = pr
        return rows, vals

    def _fit_bh(self, x):
        n = x.shape[0]
        rows, vals = self._knn_p(x)
        # symmetrize into a dict-of-pairs sparse P
        p = {}
        for i in range(n):
            for j, v in zip(rows[i], vals[i]):
                if v <= 0:
                    continue
                key = (min(i, int(j)), max(i, int(j)))
                p[key] = p.get(key, 0.0) + v
        total = sum(p.values())
        pairs = np.array(list(p.keys()), np.int32)
        pvals = np.array(list(p.values())) / max(total, 1e-30)

        rng = np.random.default_rng(self.seed)
        y = 1e-4 * rng.standard_normal((n, self.n_components))
        inc = np.zeros_like(y)
        gains = np.ones_like(y)
        for it in range(self.n_iter):
            exag = self.exaggeration if it < self.stop_lying_iteration else 1.0
            # attractive forces from sparse P
            diff = y[pairs[:, 0]] - y[pairs[:, 1]]
            q_num = 1.0 / (1.0 + np.sum(diff * diff, axis=1))
            f = (exag * pvals * q_num)[:, None] * diff
            attr = np.zeros_like(y)
            np.add.at(attr, pairs[:, 0], f)
            np.add.at(attr, pairs[:, 1], -f)
            # repulsive via SpTree
            tree = SpTree.build(y)
            rep = np.zeros_like(y)
            sum_q = 0.0
            for i in range(n):
                neg, sq = tree.compute_non_edge_forces(y[i], self.theta)
                rep[i] = neg
                sum_q += sq
            grad = attr - rep / max(sum_q, 1e-30)
            gains = np.where(np.sign(grad) != np.sign(inc),
                             gains + 0.2, gains * 0.8)
            gains = np.maximum(gains, 0.01)
            momentum = 0.5 if it < self.switch_momentum_iteration else 0.8
            inc = momentum * inc - self.learning_rate * gains * grad
            y = y + inc
            y = y - y.mean(0, keepdims=True)
        return y

    # -- API ---------------------------------------------------------------
    def fit(self, x) -> "BarnesHutTsne":
        x = np.asarray(x, np.float32)
        self.Y_ = self._fit_exact(x) if self.theta == 0.0 else self._fit_bh(x)
        return self

    def fit_transform(self, x) -> np.ndarray:
        return self.fit(x).Y_

    def save_as_file(self, labels, path: str) -> None:
        """CSV "y1,y2,...,label" per point (ref: BarnesHutTsne.saveAsFile)."""
        with open(path, "w") as f:
            for row, lab in zip(self.Y_, labels):
                f.write(",".join(f"{v:.6f}" for v in row) + f",{lab}\n")


class Tsne(BarnesHutTsne):
    """Exact-only alias (ref: plot/Tsne.java)."""

    def __init__(self, **kw):
        kw["theta"] = 0.0
        super().__init__(**kw)
