from deeplearning4j_tpu.plot.tsne import BarnesHutTsne, Tsne  # noqa: F401
