"""Graph vector persistence (ref: models/deepwalk/GraphVectorSerializer.java
— writeGraphVectors/loadTxtVectors: line per vertex "idx v0 v1 ...")."""

from __future__ import annotations

import numpy as np

from deeplearning4j_tpu.graph.deepwalk import DeepWalk


class GraphVectorSerializer:
    @staticmethod
    def write_graph_vectors(model: DeepWalk, path: str) -> None:
        with open(path, "w") as f:
            for label in model.vocab.words():
                vec = model.word_vector(label)
                f.write(label + "\t" +
                        "\t".join(f"{v:.8g}" for v in vec) + "\n")

    @staticmethod
    def load_txt_vectors(path: str) -> dict:
        """→ {vertex_idx: np.ndarray} (ref: loadTxtVectors)."""
        out = {}
        with open(path) as f:
            for line in f:
                parts = line.rstrip("\n").split("\t")
                out[int(parts[0])] = np.array([float(v) for v in parts[1:]],
                                              np.float32)
        return out
