"""Graph embeddings (ref: deeplearning4j-graph, ~3.4k LoC —
graph/Graph.java, data/GraphLoader.java, iterator random walkers,
models/deepwalk/DeepWalk.java + GraphHuffman.java).

TPU-first: walks are generated host-side (pointer-chasing), then the
embedding training rides the same fused skip-gram/HS XLA kernels as
Word2Vec via the SequenceVectors engine — the reference's separate
InMemoryGraphLookupTable+manual HS loop collapses into that engine.
"""

from deeplearning4j_tpu.graph.graph import Edge, Graph, Vertex  # noqa: F401
from deeplearning4j_tpu.graph.loader import GraphLoader  # noqa: F401
from deeplearning4j_tpu.graph.walkers import (  # noqa: F401
    Node2VecWalker, RandomWalkIterator, WeightedRandomWalkIterator)
from deeplearning4j_tpu.graph.deepwalk import DeepWalk, GraphHuffman  # noqa: F401
from deeplearning4j_tpu.graph.serializer import GraphVectorSerializer  # noqa: F401
