"""Random walk generators (ref: iterator/RandomWalkIterator.java,
WeightedRandomWalkIterator.java; node2vec biased walks ref:
models/node2vec/ + the node2vec paper's p/q second-order scheme).

Each iterator yields one walk (list of vertex indices) per vertex per
epoch — the reference's GraphWalkIterator<Integer> contract.
``no_edge_handling``: 'self_loop' (stay), 'restart' (jump to start), or
'exception' (ref: iterator/parallel edge handling enums).
"""

from __future__ import annotations

from typing import Iterator, List, Optional

import numpy as np

from deeplearning4j_tpu.graph.graph import Graph


class NoEdgesError(RuntimeError):
    pass


class RandomWalkIterator:
    """Uniform random walks (ref: iterator/RandomWalkIterator.java)."""

    def __init__(self, graph: Graph, walk_length: int, seed: int = 0,
                 no_edge_handling: str = "self_loop"):
        self.graph = graph
        self.walk_length = walk_length
        self.seed = seed
        self.no_edge_handling = no_edge_handling
        self._order: Optional[np.ndarray] = None

    def _start_order(self, rng) -> np.ndarray:
        order = np.arange(self.graph.num_vertices())
        rng.shuffle(order)
        return order

    def _step(self, cur: int, start: int, rng) -> int:
        nxt = self.graph.get_random_connected_vertex(cur, rng)
        if nxt is not None:
            return nxt
        if self.no_edge_handling == "self_loop":
            return cur
        if self.no_edge_handling == "restart":
            return start
        raise NoEdgesError(f"Vertex {cur} has no outgoing edges")

    def __iter__(self) -> Iterator[List[int]]:
        rng = np.random.default_rng(self.seed)
        for start in self._start_order(rng):
            walk = [int(start)]
            cur = int(start)
            for _ in range(self.walk_length - 1):
                cur = self._step(cur, int(start), rng)
                walk.append(cur)
            yield walk


class WeightedRandomWalkIterator(RandomWalkIterator):
    """Edge-weight-proportional transitions
    (ref: iterator/WeightedRandomWalkIterator.java)."""

    def _step(self, cur: int, start: int, rng) -> int:
        edges = self.graph.get_edges_out(cur)
        if not edges:
            return super()._step(cur, start, rng)
        w = self.graph.get_connected_vertex_weights(cur)
        p = w / w.sum() if w.sum() > 0 else None
        return edges[int(rng.choice(len(edges), p=p))].to_idx


class Node2VecWalker(RandomWalkIterator):
    """Second-order p/q-biased walks (node2vec, Grover & Leskovec 2016;
    capability-parity extension of the reference's models/node2vec/).

    Transition weight from prev→cur→next: 1/p if next==prev,
    1 if next adjacent to prev, else 1/q, each times edge weight.
    """

    def __init__(self, graph: Graph, walk_length: int, p: float = 1.0,
                 q: float = 1.0, seed: int = 0,
                 no_edge_handling: str = "self_loop"):
        super().__init__(graph, walk_length, seed, no_edge_handling)
        self.p = p
        self.q = q
        self._nbr_sets = [set(graph.get_connected_vertices(i))
                          for i in range(graph.num_vertices())]

    def __iter__(self) -> Iterator[List[int]]:
        rng = np.random.default_rng(self.seed)
        g = self.graph
        for start in self._start_order(rng):
            walk = [int(start)]
            cur = int(start)
            prev = -1
            for _ in range(self.walk_length - 1):
                edges = g.get_edges_out(cur)
                if not edges:
                    nxt = self._step(cur, int(start), rng)
                    # the p/q bias is only meaningful relative to the true
                    # predecessor; a restart jump has none, a self-loop's
                    # predecessor is the dead-end vertex itself
                    prev = -1 if nxt != cur else cur
                    cur = nxt
                    walk.append(cur)
                    continue
                w = np.array([e.weight for e in edges], np.float64)
                if prev >= 0:
                    bias = np.empty(len(edges))
                    for i, e in enumerate(edges):
                        if e.to_idx == prev:
                            bias[i] = 1.0 / self.p
                        elif e.to_idx in self._nbr_sets[prev]:
                            bias[i] = 1.0
                        else:
                            bias[i] = 1.0 / self.q
                    w = w * bias
                probs = w / w.sum()
                nxt = edges[int(rng.choice(len(edges), p=probs))].to_idx
                prev, cur = cur, nxt
                walk.append(cur)
            yield walk
