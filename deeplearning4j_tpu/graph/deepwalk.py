"""DeepWalk (ref: models/deepwalk/DeepWalk.java — random walks +
hierarchical-softmax skip-gram over vertex ids; Huffman coding by vertex
degree ref: models/deepwalk/GraphHuffman.java; lookup table ref:
InMemoryGraphLookupTable.java).

Here the HS skip-gram training reuses the SequenceVectors engine's fused
XLA kernels — walks become ``Sequence``s of vertex-id elements; the
vocabulary's Huffman tree is built from walk occurrence counts, which
are proportional to vertex degree (the stationary distribution of a
random walk), matching the reference's degree-based coding in
expectation.
"""

from __future__ import annotations

from typing import Iterable, List, Optional

import numpy as np

from deeplearning4j_tpu.embeddings.sequencevectors import (
    SequenceVectors, VectorsConfiguration)
from deeplearning4j_tpu.graph.graph import Graph
from deeplearning4j_tpu.graph.walkers import RandomWalkIterator
from deeplearning4j_tpu.text.sequence import Sequence, SequenceElement
from deeplearning4j_tpu.text.vocab import Huffman


class GraphHuffman:
    """Huffman codes/points keyed by vertex index, built from vertex
    degrees (ref: models/deepwalk/GraphHuffman.java)."""

    def __init__(self, graph: Graph):
        elements = [SequenceElement(str(i), frequency=max(1, int(d)))
                    for i, d in enumerate(graph.degrees())]
        for i, e in enumerate(elements):
            e.index = i
        Huffman(elements).build()
        self._elements = elements

    def get_code(self, vertex: int) -> List[int]:
        return self._elements[vertex].codes

    def get_path_inner_nodes(self, vertex: int) -> List[int]:
        return self._elements[vertex].points

    def get_code_length(self, vertex: int) -> int:
        return len(self._elements[vertex].codes)


class _WalkSequenceSource:
    """Re-iterable walks→Sequence adapter."""

    def __init__(self, walker_factory):
        self.walker_factory = walker_factory

    def __iter__(self):
        for walk in self.walker_factory():
            seq = Sequence()
            for v in walk:
                seq.add_element(SequenceElement(str(v)))
            yield seq


class DeepWalk(SequenceVectors):
    """(ref: models/deepwalk/DeepWalk.java — Builder.vectorSize/windowSize/
    learningRate; fit(IGraph, walkLength) / fit(GraphWalkIterator))."""

    class Builder(SequenceVectors.Builder):
        def __init__(self, configuration: Optional[VectorsConfiguration] = None):
            super().__init__(configuration)
            self.conf.use_hierarchic_softmax = True
            self.conf.negative = 0
            self.conf.min_word_frequency = 1
            self._walks_per_vertex = 1

        def vector_size(self, n: int):
            self.conf.layer_size = n
            return self

        def walks_per_vertex(self, n: int):
            self._walks_per_vertex = n
            return self

        def build(self) -> "DeepWalk":
            dw = DeepWalk(self.conf)
            dw.vocab = self._vocab
            dw._sequence_source = self._source
            dw._walks_per_vertex = self._walks_per_vertex
            return dw

    def __init__(self, conf: Optional[VectorsConfiguration] = None):
        super().__init__(conf)
        self._walks_per_vertex = 1
        self.graph: Optional[Graph] = None

    # ---- reference fit() surface ----
    def fit_graph(self, graph: Graph, walk_length: int = 40,
                  seed: int = 0) -> "DeepWalk":
        """fit(IGraph, walkLength) (ref: DeepWalk.fit:80)."""
        def factory():
            for ep in range(self._walks_per_vertex):
                yield from RandomWalkIterator(graph, walk_length,
                                              seed=seed + ep)
        return self.fit_walker(factory, graph)

    def fit_walker(self, walker_or_factory, graph: Optional[Graph] = None
                   ) -> "DeepWalk":
        """fit(GraphWalkIterator) (ref: DeepWalk.fit:104).  Accepts a
        walker instance (re-iterated per epoch) or a zero-arg factory."""
        if callable(walker_or_factory):
            factory = walker_or_factory
        else:
            def factory():
                return iter(walker_or_factory)
        self.graph = graph
        self._sequence_source = _WalkSequenceSource(factory)
        self.fit()
        return self

    # ---- reference query surface ----
    def get_vertex_vector(self, vertex: int) -> np.ndarray:
        return self.word_vector(str(vertex))

    def vertex_similarity(self, v1: int, v2: int) -> float:
        return self.similarity(str(v1), str(v2))

    def vertices_nearest(self, vertex: int, top: int = 5) -> List[int]:
        return [int(w) for w in self.words_nearest(str(vertex), top=top)]
