"""Adjacency-list graph (ref: graph/Graph.java implementing api/IGraph.java;
vertices ref: api/Vertex.java, edges api/Edge.java)."""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

import numpy as np


@dataclasses.dataclass
class Vertex:
    idx: int
    value: Any = None


@dataclasses.dataclass
class Edge:
    from_idx: int
    to_idx: int
    weight: float = 1.0
    directed: bool = False


class Graph:
    """(ref: graph/Graph.java — addEdge, getConnectedVertices,
    getRandomConnectedVertex, getVertexDegree)"""

    def __init__(self, n_vertices: int, allow_multiple_edges: bool = False):
        self.vertices = [Vertex(i) for i in range(n_vertices)]
        self.allow_multiple_edges = allow_multiple_edges
        self._out: List[List[Edge]] = [[] for _ in range(n_vertices)]

    # ---- construction ----
    def add_edge(self, from_idx: int, to_idx: int, weight: float = 1.0,
                 directed: bool = False):
        n = len(self._out)
        if not (0 <= from_idx < n and 0 <= to_idx < n):
            raise IndexError(
                f"edge ({from_idx},{to_idx}) out of range for {n} vertices")
        e = Edge(from_idx, to_idx, weight, directed)
        if not self.allow_multiple_edges and any(
                x.to_idx == to_idx for x in self._out[from_idx]):
            return
        self._out[from_idx].append(e)
        if not directed and from_idx != to_idx:
            self._out[to_idx].append(Edge(to_idx, from_idx, weight, directed))

    # ---- queries ----
    def num_vertices(self) -> int:
        return len(self.vertices)

    def get_vertex(self, idx: int) -> Vertex:
        return self.vertices[idx]

    def get_edges_out(self, idx: int) -> List[Edge]:
        return self._out[idx]

    def get_vertex_degree(self, idx: int) -> int:
        return len(self._out[idx])

    def get_connected_vertices(self, idx: int) -> List[int]:
        return [e.to_idx for e in self._out[idx]]

    def get_random_connected_vertex(self, idx: int,
                                    rng: np.random.Generator) -> Optional[int]:
        edges = self._out[idx]
        if not edges:
            return None
        return edges[int(rng.integers(0, len(edges)))].to_idx

    def get_connected_vertex_weights(self, idx: int) -> np.ndarray:
        return np.array([e.weight for e in self._out[idx]], np.float64)

    def degrees(self) -> np.ndarray:
        return np.array([len(o) for o in self._out], np.int64)
