"""Graph file loaders (ref: data/GraphLoader.java —
loadUndirectedGraphEdgeListFile, loadWeightedEdgeListFile,
loadAdjacencyListFile)."""

from __future__ import annotations

from deeplearning4j_tpu.graph.graph import Graph


class GraphLoader:
    @staticmethod
    def load_undirected_graph_edge_list_file(path: str, n_vertices: int,
                                             delim: str = None) -> Graph:
        """Lines "i<delim>j" (ref: GraphLoader.loadUndirectedGraphEdgeListFile)."""
        g = Graph(n_vertices)
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                parts = line.split(delim)
                g.add_edge(int(parts[0]), int(parts[1]))
        return g

    @staticmethod
    def load_weighted_edge_list_file(path: str, n_vertices: int,
                                     delim: str = None,
                                     directed: bool = False) -> Graph:
        """Lines "i<delim>j<delim>w" (ref: GraphLoader.loadWeightedEdgeListFile)."""
        g = Graph(n_vertices)
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                parts = line.split(delim)
                g.add_edge(int(parts[0]), int(parts[1]), float(parts[2]),
                           directed)
        return g

    @staticmethod
    def load_adjacency_list_file(path: str, n_vertices: int = None,
                                 delim: str = None) -> Graph:
        """Line per vertex: "v n1 n2 ..." (ref: GraphLoader.loadAdjacencyListFile)."""
        rows = []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                rows.append([int(p) for p in line.split(delim)])
        n = n_vertices or (max(max(r) for r in rows if r) + 1)
        g = Graph(n)
        for r in rows:
            for dst in r[1:]:
                g.add_edge(r[0], dst, directed=True)
        return g
