"""Convolution and pooling primitives.

The reference lowers conv through cuDNN or im2col+gemm
(ref: nn/layers/convolution/ConvolutionLayer.java:171-212, im2col at
Convolution.im2col).  On TPU the idiomatic lowering is a single
``lax.conv_general_dilated`` HLO which XLA tiles directly onto the MXU —
no im2col materialization, and elementwise bias+activation fuse into the
same kernel.  Data layout is NCHW at the API surface (reference
convention); weights are OIHW ([out, in, kh, kw], matching
ConvolutionParamInitializer).
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.ops import dtypes as dtype_ops

_DIMNUMS = ("NCHW", "OIHW", "NCHW")


def _nhwc_internal() -> bool:
    """DL4J_CONV_LAYOUT=nhwc runs the conv HLO in channels-last layout
    (inputs/weights transposed at the op boundary, NCHW preserved at the
    API surface).  TPU conv tiling generally prefers NHWC; whether XLA's
    layout assignment already absorbs the logical-NCHW cost is exactly
    what the bench A/B (configs vgg16 vs vgg16_nhwc) measures — round-3
    verdict weak #4.  Read at TRACE time: flip it before building a
    model, not between steps of an already-jitted one."""
    import os
    return os.environ.get("DL4J_CONV_LAYOUT", "").lower() == "nhwc"  # dl4j: noqa[DL4J103] env flag read at trace time by design (fixed per process)


def _same_pad(kernel: Sequence[int], stride: Sequence[int], pad: Sequence[int],
              mode: str) -> list[Tuple[int, int]]:
    if mode == "same":
        return "SAME"
    return [(pad[0], pad[0]), (pad[1], pad[1])]


def conv2d(x, w, b=None, stride=(1, 1), pad=(0, 0), dilation=(1, 1),
           border_mode: str = "truncate", accum_dtype=None):
    """2D convolution, NCHW in / OIHW weights.

    border_mode: 'truncate' (explicit pad, the reference's Truncate) or
    'same' (the reference's ConvolutionMode.Same).  MXU accumulation is
    float32 for low-precision inputs (bf16 compute / f32 accumulate);
    float64 inputs (gradient checks on CPU) accumulate in f64.
    """
    if accum_dtype is None:
        accum_dtype = dtype_ops.accum_dtype_for(x.dtype)
    padding = _same_pad(w.shape[2:], stride, pad, "same" if border_mode == "same" else "explicit")
    nhwc = _nhwc_internal()
    if nhwc:
        x = jnp.transpose(x, (0, 2, 3, 1))        # NCHW → NHWC
        w = jnp.transpose(w, (2, 3, 1, 0))        # OIHW → HWIO
    y = lax.conv_general_dilated(
        x, w,
        window_strides=tuple(stride),
        padding=padding,
        rhs_dilation=tuple(dilation),
        dimension_numbers=("NHWC", "HWIO", "NHWC") if nhwc else _DIMNUMS,
        preferred_element_type=accum_dtype,
    )
    if b is not None:
        y = y + (b.reshape(1, 1, 1, -1) if nhwc else b.reshape(1, -1, 1, 1))
    if nhwc:
        y = jnp.transpose(y, (0, 3, 1, 2))        # back to the NCHW API
    return y.astype(x.dtype)


def conv2d_output_shape(in_hw, kernel, stride, pad, dilation=(1, 1),
                        border_mode: str = "truncate"):
    if border_mode == "same":
        return tuple(-(-d // s) for d, s in zip(in_hw, stride))
    out = []
    for d, k, s, p, dl in zip(in_hw, kernel, stride, pad, dilation):
        eff_k = (k - 1) * dl + 1
        out.append((d + 2 * p - eff_k) // s + 1)
    return tuple(out)


def pool2d(x, kind: str, kernel=(2, 2), stride=(2, 2), pad=(0, 0),
           border_mode: str = "truncate", pnorm: int = 2):
    """Pooling over NCHW spatial dims: max | avg | sum | pnorm.

    Matches the reference's SubsamplingLayer pooling types
    (ref: nn/layers/convolution/subsampling/SubsamplingLayer.java:76).
    """
    window = (1, 1, kernel[0], kernel[1])
    strides = (1, 1, stride[0], stride[1])
    if border_mode == "same":
        padding = "SAME"
    else:
        padding = [(0, 0), (0, 0), (pad[0], pad[0]), (pad[1], pad[1])]
    kind = kind.lower()
    if kind == "max":
        neg_inf = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
        return lax.reduce_window(x, neg_inf, lax.max, window, strides, padding)
    if kind in ("avg", "mean"):
        summed = lax.reduce_window(x, 0.0, lax.add, window, strides, padding)
        if padding == "SAME":
            ones = jnp.ones_like(x)
            counts = lax.reduce_window(ones, 0.0, lax.add, window, strides, padding)
            return summed / counts
        return summed / (kernel[0] * kernel[1])
    if kind == "sum":
        return lax.reduce_window(x, 0.0, lax.add, window, strides, padding)
    if kind == "pnorm":
        p = float(pnorm)
        summed = lax.reduce_window(jnp.abs(x) ** p, 0.0, lax.add, window, strides, padding)
        return summed ** (1.0 / p)
    raise ValueError(f"Unknown pooling type '{kind}'")


def conv1d(x, w, b=None, stride=1, pad=0, dilation=1,
           border_mode: str = "truncate", accum_dtype=None):
    """1D convolution over sequences [N, T, C] with weights [K, C_in, C_out]
    (ref: nn/conf/layers/Convolution1DLayer.java — operates on RNN-format
    data).  One conv HLO on the MXU; NWC layout is TPU-friendly (channels
    minor → lane dimension)."""
    if accum_dtype is None:
        accum_dtype = dtype_ops.accum_dtype_for(x.dtype)
    padding = "SAME" if border_mode == "same" else [(pad, pad)]
    y = lax.conv_general_dilated(
        x, w,
        window_strides=(stride,),
        padding=padding,
        rhs_dilation=(dilation,),
        dimension_numbers=("NWC", "WIO", "NWC"),
        preferred_element_type=accum_dtype,
    )
    if b is not None:
        y = y + b.reshape(1, 1, -1)
    return y.astype(x.dtype)


def pool1d(x, kind: str, kernel=2, stride=2, pad=0,
           border_mode: str = "truncate", pnorm: int = 2):
    """1D pooling over [N, T, C]
    (ref: nn/conf/layers/Subsampling1DLayer.java).  Delegates to pool2d on
    a [N, C, T, 1] view — the transposes are layout-only and fuse away."""
    x2 = jnp.transpose(x, (0, 2, 1))[..., None]
    y2 = pool2d(x2, kind, (kernel, 1), (stride, 1), (pad, 0),
                border_mode, pnorm)
    return jnp.transpose(y2[..., 0], (0, 2, 1))


def conv1d_output_len(t, kernel, stride, pad, dilation=1,
                      border_mode: str = "truncate"):
    if border_mode == "same":
        return -(-t // stride)
    eff_k = (kernel - 1) * dilation + 1
    return (t + 2 * pad - eff_k) // stride + 1


def zero_pad2d(x, pad_top, pad_bottom, pad_left, pad_right):
    """ZeroPaddingLayer (ref: nn/conf/layers/ZeroPaddingLayer)."""
    return jnp.pad(x, ((0, 0), (0, 0), (pad_top, pad_bottom), (pad_left, pad_right)))


def global_pool(x, kind: str, axes, pnorm: int = 2, mask=None):
    """GlobalPoolingLayer semantics (ref: nn/layers/pooling/GlobalPoolingLayer.java).

    axes: the dims to reduce (e.g. (2,3) for CNN NCHW, (2,) for RNN [N,C,T]).
    mask: optional broadcastable mask (1=keep) for variable-length inputs —
    matches MaskedReductionUtil semantics.
    """
    kind = kind.lower()
    if mask is not None:
        mask = mask.astype(x.dtype)
        if kind == "max":
            x = jnp.where(mask > 0, x, -jnp.inf)
        else:
            x = x * mask
    if kind == "max":
        return jnp.max(x, axis=axes)
    if kind == "sum":
        return jnp.sum(x, axis=axes)
    if kind in ("avg", "mean"):
        if mask is not None:
            denom = jnp.sum(mask, axis=axes)
            return jnp.sum(x, axis=axes) / jnp.maximum(denom, 1e-8)
        return jnp.mean(x, axis=axes)
    if kind == "pnorm":
        p = float(pnorm)
        return jnp.sum(jnp.abs(x) ** p, axis=axes) ** (1.0 / p)
    raise ValueError(f"Unknown global pooling type '{kind}'")
