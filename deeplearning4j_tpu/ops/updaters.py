"""Learning rules (updaters), LR schedules, and gradient normalization.

The reference applies per-param-block ``GradientUpdater`` rules in place on
the flat gradient view and then does ``params -= gradient``
(ref: nn/updater/UpdaterBlock.java:98-117,
optimize/solvers/StochasticGradientDescent.java:60; enum
nn/conf/Updater.java:9-10: SGD, ADAM, ADADELTA, NESTEROVS, ADAGRAD,
RMSPROP, NONE).  Here each rule is a pure function over pytrees fused by
XLA into the jitted train step: ``init(params) -> state``,
``apply(grad, state, lr, t) -> (update, state)`` with
``params_new = params - update``.

LR schedules (ref: nn/conf/LearningRatePolicy.java) are pure functions of
the iteration counter so they trace into the compiled step — no
recompilation per iteration.  Gradient normalization
(ref: nn/conf/GradientNormalization.java) operates per layer or per
param-type on the gradient pytree.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp

tree_map = jax.tree_util.tree_map


# --------------------------------------------------------------------------
# LR schedules (LearningRatePolicy)
# --------------------------------------------------------------------------

def schedule_lr(base_lr, policy: Optional[str], iteration, *,
                decay_rate=None, steps=None, power=None, schedule_map=None):
    """Compute the effective LR at `iteration` (traced; policy is static).

    Policies per the reference's LearningRatePolicy enum: None, Exponential
    (lr*gamma^iter), Inverse (lr/(1+gamma*iter)^power), Poly
    (lr*(1-iter/maxIter)^power), Sigmoid (lr/(1+exp(-gamma*(iter-steps)))),
    Step (lr*gamma^floor(iter/steps)), TorchStep, Schedule (explicit map).
    """
    it = jnp.asarray(iteration, jnp.float32)
    if policy is None or policy.lower() in ("none", "fixed"):
        return jnp.asarray(base_lr, jnp.float32)
    p = policy.lower()
    if p == "exponential":
        return base_lr * jnp.power(decay_rate, it)
    if p == "inverse":
        return base_lr / jnp.power(1.0 + decay_rate * it, power)
    if p == "poly":
        return base_lr * jnp.power(1.0 - it / jnp.maximum(steps, 1.0), power)
    if p == "sigmoid":
        return base_lr / (1.0 + jnp.exp(-decay_rate * (it - steps)))
    if p == "step":
        return base_lr * jnp.power(decay_rate, jnp.floor(it / steps))
    if p == "torchstep":
        return base_lr * jnp.power(decay_rate, jnp.floor(it / steps))
    if p == "schedule":
        # schedule_map: {iteration: lr}; piecewise-constant, traced via where-chain.
        lr = jnp.asarray(base_lr, jnp.float32)
        for k in sorted(schedule_map or {}, key=float):
            lr = jnp.where(it >= float(k), jnp.asarray(schedule_map[k], jnp.float32), lr)  # dl4j: noqa[DL4J101] k is a host-side schedule-dict key, never traced
        return lr
    raise ValueError(f"Unknown learning rate policy '{policy}'")


# --------------------------------------------------------------------------
# Updater rules
# --------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Updater:
    """A learning rule over a single param pytree."""

    name: str
    hyper: dict

    def init(self, params) -> Any:
        n = self.name
        zeros_like = lambda: tree_map(jnp.zeros_like, params)  # noqa: E731
        if n in ("sgd", "none"):
            return ()
        if n == "nesterovs":
            return {"v": zeros_like()}
        if n == "adagrad":
            return {"g2": zeros_like()}
        if n == "rmsprop":
            return {"g2": zeros_like()}
        if n == "adadelta":
            return {"g2": zeros_like(), "dx2": zeros_like()}
        if n in ("adam", "adamax"):
            return {"m": zeros_like(), "v": zeros_like()}
        raise ValueError(f"Unknown updater '{n}'")

    def apply(self, grads, state, lr, t):
        """Return (update, new_state); caller does params -= update."""
        n = self.name
        h = self.hyper
        if n == "none":
            return tree_map(jnp.zeros_like, grads), state
        if n == "sgd":
            return tree_map(lambda g: lr * g, grads), state
        if n == "nesterovs":
            # v_new = mu*v - lr*g; update = mu*v_prev - (1+mu)*v_new, applied as
            # params -= update (matches nd4j Nesterovs.getGradient).
            mu = h.get("momentum", 0.9)
            v_new = tree_map(lambda v, g: mu * v - lr * g, state["v"], grads)
            upd = tree_map(lambda vp, vn: mu * vp - (1 + mu) * vn, state["v"], v_new)
            return upd, {"v": v_new}
        if n == "adagrad":
            eps = h.get("epsilon", 1e-6)
            g2 = tree_map(lambda a, g: a + g * g, state["g2"], grads)
            upd = tree_map(lambda g, a: lr * g / (jnp.sqrt(a) + eps), grads, g2)
            return upd, {"g2": g2}
        if n == "rmsprop":
            decay = h.get("rmsdecay", 0.95)
            eps = h.get("epsilon", 1e-8)
            g2 = tree_map(lambda a, g: decay * a + (1 - decay) * g * g, state["g2"], grads)
            upd = tree_map(lambda g, a: lr * g / jnp.sqrt(a + eps), grads, g2)
            return upd, {"g2": g2}
        if n == "adadelta":
            rho = h.get("rho", 0.95)
            eps = h.get("epsilon", 1e-6)
            g2 = tree_map(lambda a, g: rho * a + (1 - rho) * g * g, state["g2"], grads)
            upd = tree_map(
                lambda g, a, d: g * jnp.sqrt(d + eps) / jnp.sqrt(a + eps),
                grads, g2, state["dx2"])
            dx2 = tree_map(lambda d, u: rho * d + (1 - rho) * u * u, state["dx2"], upd)
            return upd, {"g2": g2, "dx2": dx2}
        if n == "adam":
            b1 = h.get("beta1", 0.9)
            b2 = h.get("beta2", 0.999)
            eps = h.get("epsilon", 1e-8)
            tf = jnp.asarray(t, jnp.float32) + 1.0
            m = tree_map(lambda m_, g: b1 * m_ + (1 - b1) * g, state["m"], grads)
            v = tree_map(lambda v_, g: b2 * v_ + (1 - b2) * g * g, state["v"], grads)
            alpha = lr * jnp.sqrt(1 - jnp.power(b2, tf)) / (1 - jnp.power(b1, tf))
            upd = tree_map(lambda m_, v_: alpha * m_ / (jnp.sqrt(v_) + eps), m, v)
            return upd, {"m": m, "v": v}
        if n == "adamax":
            b1 = h.get("beta1", 0.9)
            b2 = h.get("beta2", 0.999)
            eps = h.get("epsilon", 1e-8)
            tf = jnp.asarray(t, jnp.float32) + 1.0
            m = tree_map(lambda m_, g: b1 * m_ + (1 - b1) * g, state["m"], grads)
            v = tree_map(lambda v_, g: jnp.maximum(b2 * v_, jnp.abs(g)), state["v"], grads)
            alpha = lr / (1 - jnp.power(b1, tf))
            upd = tree_map(lambda m_, v_: alpha * m_ / (v_ + eps), m, v)
            return upd, {"m": m, "v": v}
        raise ValueError(f"Unknown updater '{n}'")


def make(name: str, **hyper) -> Updater:
    return Updater(name=name.lower(), hyper=hyper)


# --------------------------------------------------------------------------
# Gradient normalization (GradientNormalization.java)
# --------------------------------------------------------------------------

def _l2(tree):
    leaves = jax.tree_util.tree_leaves(tree)
    return jnp.sqrt(sum(jnp.sum(jnp.square(l)) for l in leaves) + 1e-30)


def normalize_gradient(grads, mode: Optional[str], threshold: float = 1.0):
    """Apply the reference's gradient normalization to a per-layer grad dict.

    grads: pytree for ONE layer ({param_name: array}).  Modes:
    RenormalizeL2PerLayer, RenormalizeL2PerParamType,
    ClipElementWiseAbsoluteValue, ClipL2PerLayer, ClipL2PerParamType.
    """
    if mode is None or mode == "None":
        return grads
    m = mode.lower()
    if m == "renormalizel2perlayer":
        norm = _l2(grads)
        return tree_map(lambda g: g / norm, grads)
    if m == "renormalizel2perparamtype":
        return {k: v / _l2(v) for k, v in grads.items()}
    if m == "clipelementwiseabsolutevalue":
        return tree_map(lambda g: jnp.clip(g, -threshold, threshold), grads)
    if m == "clipl2perlayer":
        norm = _l2(grads)
        scale = jnp.minimum(1.0, threshold / norm)
        return tree_map(lambda g: g * scale, grads)
    if m == "clipl2perparamtype":
        out = {}
        for k, v in grads.items():
            norm = _l2(v)
            out[k] = v * jnp.minimum(1.0, threshold / norm)
        return out
    raise ValueError(f"Unknown gradient normalization '{mode}'")
