"""Normalization ops: batch normalization and local response normalization.

The reference implements these as layers with optional cuDNN helpers
(ref: nn/layers/normalization/BatchNormalization.java,
LocalResponseNormalization.java:69, cuDNN helpers in deeplearning4j-cuda).
On TPU both are plain HLO that XLA fuses; running statistics are carried
functionally (state-in/state-out) rather than mutated.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp


def batch_norm_train(x, gamma, beta, running_mean, running_var, *,
                     decay: float = 0.9, eps: float = 1e-5):
    """Training-mode batchnorm over feature axis 1 (dense [N,C] or conv NCHW).

    Returns (y, new_running_mean, new_running_var).  `decay` matches the
    reference's BatchNormalization.decay (momentum on running stats).
    """
    axes = (0,) if x.ndim == 2 else (0, 2, 3)
    # Batch statistics accumulate in float32 even under a bf16 compute
    # policy — bf16 mean/var over large N·H·W loses too many bits.
    stat_dtype = jnp.float32 if x.dtype == jnp.bfloat16 else x.dtype
    xs = x.astype(stat_dtype)
    mean = jnp.mean(xs, axis=axes)
    var = jnp.var(xs, axis=axes)
    shape = (1, -1) if x.ndim == 2 else (1, -1, 1, 1)
    xn = ((xs - mean.reshape(shape))
          / jnp.sqrt(var.reshape(shape) + eps)).astype(x.dtype)
    y = gamma.reshape(shape) * xn + beta.reshape(shape)
    new_mean = decay * running_mean.astype(stat_dtype) + (1 - decay) * mean
    new_var = decay * running_var.astype(stat_dtype) + (1 - decay) * var
    return y, new_mean, new_var


def batch_norm_infer(x, gamma, beta, running_mean, running_var, *, eps: float = 1e-5):
    """Inference-mode batchnorm from carried running stats.

    Running stats stay float32 under a bf16 policy (see batch_norm_train),
    so normalization runs in float32 but the OUTPUT is cast back to the
    activation dtype — otherwise a bf16 net's activations silently
    promote to f32 after every BN and the next conv crashes on the
    lhs/rhs dtype mismatch (lax.conv requires equal dtypes)."""
    shape = (1, -1) if x.ndim == 2 else (1, -1, 1, 1)
    stat_dtype = jnp.float32 if x.dtype == jnp.bfloat16 else x.dtype
    xs = x.astype(stat_dtype)
    xn = ((xs - running_mean.astype(stat_dtype).reshape(shape))
          / jnp.sqrt(running_var.astype(stat_dtype).reshape(shape) + eps)
          ).astype(x.dtype)
    return gamma.reshape(shape) * xn + beta.reshape(shape)


def local_response_norm(x, *, k: float = 2.0, n: int = 5, alpha: float = 1e-4,
                        beta: float = 0.75):
    """Across-channel LRN on NCHW (AlexNet-style), reference defaults
    (ref: nn/conf/layers/LocalResponseNormalization k=2,n=5,alpha=1e-4,beta=0.75)."""
    half = n // 2
    sq = jnp.square(x)
    # Sum over a window of `n` channels via padded cumulative trick.
    padded = jnp.pad(sq, ((0, 0), (half, half), (0, 0), (0, 0)))
    windows = [padded[:, i:i + x.shape[1]] for i in range(n)]
    summed = sum(windows)
    denom = jnp.power(k + alpha * summed, beta)
    return x / denom


def dropout(x, rate: float, rng, *, inverted: bool = True):
    """Inverted dropout (ref: util/Dropout.java — DL4J's dropOut conf value is
    the RETAIN probability; here `rate` is the retain probability too for parity)."""
    import jax
    if rate >= 1.0 or rate <= 0.0:
        return x
    keep = rate
    mask = jax.random.bernoulli(rng, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0).astype(x.dtype)
