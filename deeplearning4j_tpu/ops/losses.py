"""Loss functions — the reference's ``ILossFunction`` surface.

The reference's loss set (nd4j ILossFunction impls, exercised by
deeplearning4j-core's LossFunctionGradientCheck.java): MSE, L1, L2,
XENT (binary cross-entropy), MCXENT (multi-class cross-entropy),
NEGATIVELOGLIKELIHOOD, COSINE_PROXIMITY, HINGE, SQUARED_HINGE,
KL_DIVERGENCE, MEAN_ABSOLUTE_ERROR, MEAN_ABSOLUTE_PERCENTAGE_ERROR,
MEAN_SQUARED_LOGARITHMIC_ERROR, POISSON.

Each loss takes ``(labels, preoutput, activation_name, mask)`` and returns
per-example scores of shape [N].  Working on pre-activations lets the
softmax+cross-entropy and sigmoid+binary-cross-entropy pairs lower to the
numerically-stable fused forms, which XLA then fuses into one kernel; the
gradient comes from jax.grad of the whole jitted step rather than the
reference's hand-written computeGradient methods.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops import activations

EPS = 1e-7

LossFn = Callable[..., jnp.ndarray]


def _activate(preout: jnp.ndarray, activation: str) -> jnp.ndarray:
    return activations.get(activation)(preout)


def _reduce_features(per_elem: jnp.ndarray, mask: Optional[jnp.ndarray]) -> jnp.ndarray:
    """Sum per-element losses over all non-batch axes → per-example score [N]."""
    if mask is not None:
        per_elem = per_elem * mask
    axes = tuple(range(1, per_elem.ndim))
    return jnp.sum(per_elem, axis=axes) if axes else per_elem


def mse(labels, preout, activation="identity", mask=None):
    out = _activate(preout, activation)
    return _reduce_features(jnp.square(out - labels), mask) / labels.shape[-1]


def l2(labels, preout, activation="identity", mask=None):
    out = _activate(preout, activation)
    return _reduce_features(jnp.square(out - labels), mask)


def l1(labels, preout, activation="identity", mask=None):
    out = _activate(preout, activation)
    return _reduce_features(jnp.abs(out - labels), mask)


def mae(labels, preout, activation="identity", mask=None):
    out = _activate(preout, activation)
    return _reduce_features(jnp.abs(out - labels), mask) / labels.shape[-1]


def xent(labels, preout, activation="sigmoid", mask=None):
    """Binary cross-entropy.  Stable fused path when activation is sigmoid."""
    if activation == "sigmoid":
        # -[y*log σ(x) + (1-y)*log(1-σ(x))] = max(x,0) - x*y + log(1+exp(-|x|))
        per = jnp.maximum(preout, 0) - preout * labels + jnp.log1p(jnp.exp(-jnp.abs(preout)))
    else:
        out = jnp.clip(_activate(preout, activation), EPS, 1.0 - EPS)
        per = -(labels * jnp.log(out) + (1.0 - labels) * jnp.log(1.0 - out))
    return _reduce_features(per, mask)


def _fused_xent_wanted(labels, preout, mask) -> bool:
    """Dispatch gate for the Pallas fused softmax+CE kernel
    (ops/pallas_kernels.softmax_xent_rows): shape/mask legality decided
    here (only row-level masks — a per-class mask needs the elementwise
    path); platform/size selection delegated to the helper tier
    (ops/helpers.softmax_xent_wanted, which also meters the decision and
    honors the DL4J_FUSED_XENT=1|0 test override)."""
    if preout.ndim < 2 or preout.shape != labels.shape:
        return False
    if mask is not None and mask.ndim == preout.ndim \
            and mask.shape[-1] == preout.shape[-1] and preout.shape[-1] != 1:
        return False  # genuine per-class mask
    from deeplearning4j_tpu.ops import helpers
    V = preout.shape[-1]
    n_rows = 1
    for d in preout.shape[:-1]:
        n_rows *= d
    return helpers.softmax_xent_wanted(n_rows, V)


def mcxent(labels, preout, activation="softmax", mask=None):
    """Multi-class cross-entropy.  Stable fused path when activation is
    softmax; above the size threshold the softmax+CE+grad runs as one
    Pallas VMEM pass (ref analog: the fused libnd4j SoftMaxWithLoss op)."""
    if activation == "softmax":
        if _fused_xent_wanted(labels, preout, mask):
            from deeplearning4j_tpu.ops import pallas_kernels as pk
            V = preout.shape[-1]
            rows = pk.softmax_xent_rows(
                preout.reshape(-1, V), labels.reshape(-1, V)
            ).reshape(labels.shape[:-1])
            if mask is not None:
                m = mask
                if m.ndim == rows.ndim + 1 and m.shape[-1] == 1:
                    m = m[..., 0]
                rows = rows * m
            axes = tuple(range(1, rows.ndim))
            return jnp.sum(rows, axis=axes) if axes else rows
        logz = jax.nn.logsumexp(preout, axis=-1, keepdims=True)
        per = -labels * (preout - logz)
    else:
        out = jnp.clip(_activate(preout, activation), EPS, 1.0 - EPS)
        per = -labels * jnp.log(out)
    return _reduce_features(per, mask)


def negativeloglikelihood(labels, preout, activation="softmax", mask=None):
    # In the reference NLL == MCXENT when paired with softmax output.
    return mcxent(labels, preout, activation, mask)


def cosine_proximity(labels, preout, activation="identity", mask=None):
    out = _activate(preout, activation)
    if mask is not None:
        out = out * mask
        labels = labels * mask
    dot = jnp.sum(labels * out, axis=-1)
    nl = jnp.linalg.norm(labels, axis=-1)
    no = jnp.linalg.norm(out, axis=-1)
    cos = dot / jnp.maximum(nl * no, EPS)
    per = -cos
    axes = tuple(range(1, per.ndim))
    return jnp.sum(per, axis=axes) if axes else per


def hinge(labels, preout, activation="identity", mask=None):
    # labels expected in {-1, +1}
    out = _activate(preout, activation)
    return _reduce_features(jnp.maximum(0.0, 1.0 - labels * out), mask)


def squared_hinge(labels, preout, activation="identity", mask=None):
    out = _activate(preout, activation)
    return _reduce_features(jnp.square(jnp.maximum(0.0, 1.0 - labels * out)), mask)


def kl_divergence(labels, preout, activation="softmax", mask=None):
    out = jnp.clip(_activate(preout, activation), EPS, 1.0)
    lab = jnp.clip(labels, EPS, 1.0)
    return _reduce_features(labels * (jnp.log(lab) - jnp.log(out)), mask)


def mape(labels, preout, activation="identity", mask=None):
    out = _activate(preout, activation)
    per = 100.0 * jnp.abs((labels - out) / jnp.maximum(jnp.abs(labels), EPS))
    return _reduce_features(per, mask) / labels.shape[-1]


def msle(labels, preout, activation="identity", mask=None):
    out = _activate(preout, activation)
    per = jnp.square(jnp.log1p(jnp.maximum(out, -1 + EPS)) - jnp.log1p(jnp.maximum(labels, -1 + EPS)))
    return _reduce_features(per, mask) / labels.shape[-1]


def poisson(labels, preout, activation="identity", mask=None):
    out = jnp.maximum(_activate(preout, activation), EPS)
    return _reduce_features(out - labels * jnp.log(out), mask)


_REGISTRY: dict[str, LossFn] = {
    "mse": mse,
    "squared_loss": mse,
    "l1": l1,
    "l2": l2,
    "mae": mae,
    "mean_absolute_error": mae,
    "xent": xent,
    "mcxent": mcxent,
    "negativeloglikelihood": negativeloglikelihood,
    "nll": negativeloglikelihood,
    "cosine_proximity": cosine_proximity,
    "hinge": hinge,
    "squared_hinge": squared_hinge,
    "kl_divergence": kl_divergence,
    "reconstruction_crossentropy": xent,
    "mean_absolute_percentage_error": mape,
    "mape": mape,
    "mean_squared_logarithmic_error": msle,
    "msle": msle,
    "poisson": poisson,
}


def get(name: str) -> LossFn:
    try:
        return _REGISTRY[name.lower()]
    except KeyError:
        raise ValueError(f"Unknown loss '{name}'. Known: {sorted(_REGISTRY)}") from None


def register(name: str, fn: LossFn) -> None:
    _REGISTRY[name.lower()] = fn


def unregister(name: str) -> None:
    """Remove a user-registered loss (no-op when absent)."""
    _REGISTRY.pop(name.lower(), None)


def names() -> list[str]:
    return sorted(_REGISTRY)
