"""Tensor/runtime substrate — the nd4j/libnd4j surface, TPU-natively.

The reference consumes an external numerics stack (nd4j-api / libnd4j C++,
SURVEY.md §2.10).  Here that layer is jax.numpy / XLA HLO: ops are pure
functions, compiled and fused by XLA, with Pallas kernels where fusion
needs help.
"""

from deeplearning4j_tpu.ops import activations, losses, initializers, updaters  # noqa: F401
