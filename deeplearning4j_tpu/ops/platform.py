"""TPU capability detection + chip peak-FLOPs table.

Round-2 lesson: the bench machine's chip is exposed through an
experimental PJRT plugin (platform name ``axon``), and any gate written
as ``jax.default_backend() == "tpu"`` risks reading False there even
though the device IS a TPU ("TPU v5 lite").  Everything that keys
behavior off "are we on TPU" (auto mixed precision in ops/dtypes.py,
Pallas interpret-mode in ops/pallas_kernels.py) goes through
:func:`is_tpu`, which probes the *devices* (platform + device_kind)
rather than trusting the backend registry name, and honors an explicit
``DL4J_TPU=0|1`` env override for debugging.
"""

from __future__ import annotations

import functools
import os


def is_tpu() -> bool:
    """True when the default JAX backend is TPU hardware, however the
    PJRT plugin chooses to register itself."""
    env = os.environ.get("DL4J_TPU")  # dl4j: noqa[DL4J103] env flag read at trace time by design (fixed per process)
    if env is not None and env != "":
        return env not in ("0", "false", "False")
    return _probe_is_tpu()


@functools.lru_cache(maxsize=1)
def _probe_is_tpu() -> bool:
    try:
        import jax
        if jax.default_backend() == "tpu":
            return True
        for d in jax.devices():
            platform = (getattr(d, "platform", "") or "").lower()
            kind = (getattr(d, "device_kind", "") or "").lower()
            if "tpu" in platform or "tpu" in kind:
                return True
    except Exception:
        pass
    return False


def device_kind() -> str:
    """Device-kind string of the first device ('' when unavailable)."""
    try:
        import jax
        return getattr(jax.devices()[0], "device_kind", "") or ""
    except Exception:
        return ""


# Dense per-chip peak FLOP/s with bf16 inputs / f32 MXU accumulation
# (published cloud specs).  Keys are matched as substrings of the
# lower-cased device_kind.
_BF16_PEAK = {
    "v6": 918e12,       # Trillium / v6e
    "v5p": 459e12,
    "v5 lite": 197e12,  # v5e reports device_kind "TPU v5 lite"
    "v5e": 197e12,
    "v4": 275e12,
    "v3": 123e12,
    "v2": 45e12,
}


def peak_flops_bf16(kind: str | None = None) -> float | None:
    """Per-chip dense bf16 peak FLOP/s for MFU math; None when the chip
    is unknown (callers must then report MFU as unavailable rather than
    inventing a denominator)."""
    k = (kind if kind is not None else device_kind()).lower()
    # longest-key-first so "v5p"/"v5 lite" win over any shorter alias
    for name in sorted(_BF16_PEAK, key=len, reverse=True):
        if name in k:
            return _BF16_PEAK[name]
    return None
