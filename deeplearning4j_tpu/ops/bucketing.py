"""Shape-bucketing compile cache + retrace telemetry.

Every jitted entry point (the fused train step, ``_output_fn``,
``_score_fn``, the ``k_steps`` scan) specializes on exact input shapes,
so a ragged minibatch stream — variable batch sizes, variable RNN time
lengths — silently retraces and recompiles per shape.  On real data
streams that compile time dominates wall-clock, and the fused
``fit(fused_steps=K)`` scan path degrades to per-step whenever shapes
differ.  "Array Languages Make Neural Networks Fast" (PAPERS.md)
identifies compile-once/run-many shape discipline as the prerequisite
for hardware-limit throughput; this module enforces it:

* **Bucketing** (:func:`bucket_train_dataset` /
  :func:`bucket_train_multidataset` / :func:`bucket_inference_features`):
  pad the batch dimension (and the time dimension of ``[N, T, C]``
  sequences) up to a small set of buckets — powers of two by default,
  user-configured via ``GlobalConf.bucket_batch_sizes`` /
  ``bucket_time_sizes``.  Training batches are padded with CYCLED real
  rows and a rescaled labels mask (the exact pad-and-mask semantics of
  ``parallel/wrapper.py``: valid rows carry ``target/n``, padded rows 0,
  so the step's ``mean(per_ex)`` over the padded batch equals the
  unpadded mean for every mask-linear loss).  Inference batches are
  zero-padded and the outputs un-padded (:func:`unpad_outputs`), so
  results match the unpadded run.

* **Retrace telemetry** (:class:`CompileTelemetry`): each network counts
  distinct jit-entry signatures (shape/dtype/mask-presence — exactly
  what XLA keys its trace cache on) and per-bucket hit counts, surfaced
  through ``nn/listeners.CompileTelemetryListener`` and ``bench.py``'s
  ``bench_ragged`` workload, so compile-behavior regressions are
  measurable instead of anecdotal.

* **Persistent compilation cache**
  (:func:`maybe_enable_persistent_cache`): env-gated
  (``DL4J_PERSISTENT_CACHE=<dir>``) wiring of JAX's on-disk compilation
  cache so repeated runs skip cold compiles entirely.
"""

from __future__ import annotations

import functools
import math
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

# Losses where the labels mask does not scale the per-example loss
# linearly (ops/losses.py: cosine_proximity normalizes the masked
# vectors) — exact pad-and-mask is impossible there.  Shared with
# ParallelWrapper (this set used to live there).
MASK_NONLINEAR_LOSSES = frozenset({"cosine_proximity"})


# ---------------------------------------------------------------------------
# Bucket ladders
# ---------------------------------------------------------------------------
def next_pow2(n: int) -> int:
    """Smallest power of two >= n."""
    n = int(n)
    return 1 if n <= 1 else 1 << (n - 1).bit_length()


def bucket_size(n: int, sizes: Optional[Sequence[int]] = None) -> int:
    """Smallest configured bucket >= n; powers of two when no ladder is
    configured, and past the ladder's top rung (padding down is
    impossible)."""
    if sizes:
        for s in sorted(int(s) for s in sizes):
            if s >= n:
                return s
    return next_pow2(n)


def pow2_ladder(max_n: int) -> List[int]:
    """Power-of-two bucket ladder covering batch sizes ``1..max_n``:
    ``[1, 2, 4, ..., next_pow2(max_n)]`` — the default bucket set when
    no explicit ladder is configured."""
    top = next_pow2(max(1, int(max_n)))
    out, n = [], 1
    while n <= top:
        out.append(n)
        n <<= 1
    return out


def warmup_ladder(sizes: Optional[Sequence[int]] = None,
                  max_batch: int = 32) -> List[int]:
    """The bucket ladder a serving path should pre-compile so first
    requests never eat a cold XLA compile: the configured ladder when
    one exists — truncated at the rung a ``max_batch``-row batch lands
    on (the micro-batcher never builds a bigger batch, so higher rungs
    would be compiled for nothing) — else the power-of-two ladder up to
    ``max_batch``."""
    max_batch = max(1, int(max_batch))
    if sizes:
        ladder = sorted({int(s) for s in sizes})
        top = bucket_size(max_batch, ladder)
        out = [s for s in ladder if s < top]
        out.append(top)
        return out
    return pow2_ladder(max_batch)


def bucket_key(bucket) -> str:
    """Human/JSON key for a bucket tuple: ``b64``, ``b64t32``,
    ``b64t32/16`` (multi-input graphs)."""
    nb, tb = bucket
    if tb is None:
        return f"b{nb}"
    if isinstance(tb, tuple):
        ts = "/".join("-" if t is None else str(t) for t in tb)
        return f"b{nb}t{ts}"
    return f"b{nb}t{tb}"


# ---------------------------------------------------------------------------
# Pad/mask primitives (the parallel/wrapper.py semantics, now shared)
# ---------------------------------------------------------------------------
def cycle_rows(a, target: int):
    """Pad rows up to ``target`` by cycling REAL examples (not zeros:
    replicated real rows keep batch statistics — e.g. BatchNorm —
    well-conditioned; their loss contribution is removed by the mask)."""
    a = np.asarray(a)
    if len(a) >= target:
        return a[:target]
    reps = -(-target // len(a))
    return np.concatenate([a] * reps)[:target]


def scaled_mask(lm, y, n: int, target: int, scale: Optional[float] = None):
    """Labels mask over the PADDED batch making the step's
    ``mean(per_ex)`` over ``target`` rows equal the unpadded mean over
    ``n`` rows: valid rows carry ``target/n`` (losses are linear in the
    mask — see MASK_NONLINEAR_LOSSES), padded rows carry 0.  ``scale``
    overrides the ``target/n`` factor (``1.0`` for per-example scoring,
    where no minibatch mean is taken)."""
    scale = np.float32(target / n if scale is None else scale)
    if lm is None:
        m = np.zeros((target,) + (1,) * (np.asarray(y).ndim - 1),
                     np.float32)
        m[:n] = scale
    else:
        lm = np.asarray(lm, np.float32)
        m = np.zeros((target,) + lm.shape[1:], np.float32)
        m[:n] = lm * scale
    return m


def _pad_time(a: np.ndarray, tb: int) -> np.ndarray:
    """Zero-pad axis 1 (time) up to ``tb``."""
    if a.shape[1] >= tb:
        return a
    pad = [(0, 0)] * a.ndim
    pad[1] = (0, tb - a.shape[1])
    return np.pad(a, pad)


def pad_supported(model, require_mean: bool = True) -> bool:
    """Exact pad-and-mask needs (a) every output loss linear in the
    labels mask (CenterLoss adds an unmasked center term), (b) no
    batch-coupled aux losses (MoE load balancing sees the padded rows)
    and — for paths that reduce to a minibatch mean
    (``require_mean=True``) — (c) mean loss reduction: the ``target/n``
    mask rescale assumes division by the padded row count, so
    ``mini_batch=False`` sum-reduced nets are excluded.  BatchNorm IS
    allowed: cycled real rows keep the batch statistics
    well-conditioned, a documented approximation preferred over
    dropping examples."""
    if require_mean and not model.conf.global_conf.mini_batch:
        return False
    if type(model).__name__ == "ComputationGraph":
        outs = list(model._output_layer_confs().values())
        all_layers = [v.layer_conf() for v in model.conf.vertices.values()
                      if hasattr(v, "layer_conf")]
    else:
        outs = [model.layers[-1]]
        all_layers = model.layers
    for lc in outs:
        if getattr(lc, "requires_features_for_score", False):
            return False
        if (getattr(lc, "loss", None) or "") in MASK_NONLINEAR_LOSSES:
            return False
    for lc in all_layers:
        if "MixtureOfExperts" in type(lc).__name__:
            return False
    return True


# ---------------------------------------------------------------------------
# Training-batch bucketing
# ---------------------------------------------------------------------------
def _resolve_lm_base(lm, fm, y, t):
    """Labels-mask base for the synthesized scaled mask — the
    mask-entry resolution fixed in parallel/wrapper.py: an existing
    labels mask wins; a features mask becomes the base only when its
    shape provably matches the labels' time layout (the step's loss
    resolves the propagated time mask exactly this way); a 3-D label
    with a padded time axis needs an explicit all-ones time base so the
    padded timesteps are excluded.  Returns (base, ok)."""
    y = np.asarray(y)
    if lm is not None:
        return np.asarray(lm), True
    if fm is not None:
        fm_arr = np.asarray(fm)
        if fm_arr.ndim == y.ndim - 1 and fm_arr.shape == y.shape[:-1]:
            return fm_arr, True
        if y.ndim == 2:
            # per-example mask suffices: the step resolves a [N,T] mask
            # against a 2-D preout to None, so no time weighting to match
            return None, True
        return None, False  # mask routing ambiguous: don't guess
    if t is not None and y.ndim == 3:
        return np.ones(y.shape[:-1], np.float32), True
    return None, True


def bucket_train_dataset(ds, g, min_multiple: int = 1,
                         scale_loss: bool = True):
    """Pad a DataSet up to its (batch, time) bucket: rows are cycled
    real examples, the time axis is zero-padded, a features mask is
    synthesized/extended for sequence data and the labels mask is the
    scaled mask making the padded mean loss exactly equal the unpadded
    one.  ``min_multiple`` additionally lifts the batch bucket to a
    multiple (ParallelWrapper's data degree).  ``scale_loss=False``
    keeps valid-row mask entries at their original values (per-example
    scoring, where results are sliced back instead of averaged).

    Returns ``(padded_ds, bucket)``; ``bucket is None`` means the batch
    could not be bucketed (ambiguous mask routing) and ``ds`` is
    returned unchanged.  Idempotent: re-bucketing a bucket-shaped batch
    is a no-op fast path (the AsyncDataSetIterator pre-buckets before
    device_put; the engine must not pull the arrays back to host)."""
    from deeplearning4j_tpu.datasets.dataset import DataSet

    f, y = ds.features, ds.labels
    n = int(f.shape[0])
    nb = bucket_size(n, g.bucket_batch_sizes)
    if min_multiple > 1:
        nb = -(-nb // min_multiple) * min_multiple
    t = int(f.shape[1]) if f.ndim == 3 else None
    tb = bucket_size(t, g.bucket_time_sizes) if t is not None else None
    fm, lm = ds.features_mask, ds.labels_mask
    if nb == n and (tb is None or tb == t) and lm is not None \
            and (t is None or fm is not None):
        return ds, (nb, tb)  # already bucket-shaped (e.g. pre-bucketed)

    y = np.asarray(y)
    lm_base, ok = _resolve_lm_base(lm, fm, y, t)
    if not ok:
        return ds, None

    f_p = cycle_rows(f, nb)
    if tb is not None and tb != t:
        f_p = _pad_time(f_p, tb)
    y_p = cycle_rows(y, nb)
    if y.ndim == 3 and tb is not None and y.shape[1] == t and tb != t:
        y_p = _pad_time(y_p, tb)

    if t is not None:
        # sequence features always carry a mask once bucketed — mask
        # PRESENCE is part of the jit signature, and a batch landing
        # exactly on a bucket must not trace separately from a padded one
        fm_arr = (np.asarray(fm, np.float32) if fm is not None
                  else np.ones((n, t), np.float32))
        fm_p = cycle_rows(fm_arr, nb)
        if tb != t:
            fm_p = _pad_time(fm_p, tb)
    else:
        fm_p = None if fm is None else cycle_rows(fm, nb)

    scale = None if scale_loss else 1.0
    if lm_base is None:
        lm_p = scaled_mask(None, y, n, nb, scale)
    else:
        base = np.zeros((nb,) + tuple(
            tb if (i == 1 and t is not None and s == t and tb != t) else s
            for i, s in enumerate(lm_base.shape))[1:], np.float32)
        sl = (slice(0, n),) + tuple(slice(0, s) for s in lm_base.shape[1:])
        base[sl] = lm_base * np.float32(nb / n if scale is None else scale)
        lm_p = base
    return DataSet(f_p, y_p, fm_p, lm_p), (nb, tb)


def bucket_train_multidataset(mds, g, min_multiple: int = 1,
                              scale_loss: bool = True):
    """MultiDataSet (ComputationGraph) analog of
    :func:`bucket_train_dataset`.  Per-ENTRY mask semantics (the
    wrapper's fix: a missing mask arrives as ``[None]``, so container-
    level checks are not enough): a features mask without any labels
    mask makes multi-input→output routing ambiguous — refuse rather
    than guess.  Every 3-D entry gets its own time bucket."""
    from deeplearning4j_tpu.datasets.dataset import MultiDataSet

    def _all_none(tup):
        return tup is None or all(m is None for m in tup)

    fms = mds.features_masks
    lms = mds.labels_masks
    if not _all_none(fms) and _all_none(lms):
        return mds, None
    n = mds.num_examples()
    nb = bucket_size(n, g.bucket_batch_sizes)
    if min_multiple > 1:
        nb = -(-nb // min_multiple) * min_multiple

    def t_of(a):
        shape = getattr(a, "shape", None)
        if shape is None:
            shape = np.asarray(a).shape
        return int(shape[1]) if len(shape) == 3 else None

    f_ts = [t_of(f) for f in mds.features]
    f_tbs = [None if t is None else bucket_size(t, g.bucket_time_sizes)
             for t in f_ts]
    bucket = (nb, tuple(f_tbs))

    fm_list = list(fms) if fms is not None else [None] * len(mds.features)
    lm_list = list(lms) if lms is not None else [None] * len(mds.labels)

    # Idempotence fast path (mirrors bucket_train_dataset): a batch that
    # is already bucket-shaped with all masks in place passes through
    # untouched — the async pipeline pre-buckets on a worker BEFORE
    # device_put, and the engine's re-bucket must not pull the staged
    # arrays back to host.
    if nb == n and all(tb is None or tb == t
                       for t, tb in zip(f_ts, f_tbs)) \
            and all(m is not None for m in lm_list) \
            and all(t is None or m is not None
                    for t, m in zip(f_ts, fm_list)) \
            and all(t_of(y) is None
                    or bucket_size(t_of(y), g.bucket_time_sizes) == t_of(y)
                    for y in mds.labels):
        return mds, bucket

    def pad_entry(a, tb):
        a_p = cycle_rows(a, nb)
        if tb is not None and tb != a_p.shape[1]:
            a_p = _pad_time(a_p, tb)
        return a_p

    feats, new_fms = [], []
    for f, fm, t, tb in zip(mds.features, fm_list, f_ts, f_tbs):
        feats.append(pad_entry(np.asarray(f), tb))
        if t is not None:
            fm_arr = (np.asarray(fm, np.float32) if fm is not None
                      else np.ones((n, t), np.float32))
            fm_p = cycle_rows(fm_arr, nb)
            if tb != t:
                fm_p = _pad_time(fm_p, tb)
            new_fms.append(fm_p)
        else:
            new_fms.append(None if fm is None else cycle_rows(fm, nb))

    labels, new_lms = [], []
    for y, lm in zip(mds.labels, lm_list):
        y = np.asarray(y)
        t = t_of(y)
        tb = bucket_size(t, g.bucket_time_sizes) if t is not None else None
        y_p = pad_entry(y, tb)
        lm_base = (np.asarray(lm) if lm is not None
                   else (np.ones(y.shape[:-1], np.float32)
                         if y.ndim == 3 else None))
        scale = np.float32(nb / n if scale_loss else 1.0)
        if lm_base is None:
            m = np.zeros((nb,) + (1,) * (y.ndim - 1), np.float32)
            m[:n] = scale
        else:
            tgt = [nb] + list(lm_base.shape[1:])
            if t is not None and lm_base.ndim >= 2 \
                    and lm_base.shape[1] == t and tb != t:
                tgt[1] = tb
            m = np.zeros(tuple(tgt), np.float32)
            sl = (slice(0, n),) + tuple(slice(0, s)
                                        for s in lm_base.shape[1:])
            m[sl] = lm_base * scale
        labels.append(y_p)
        new_lms.append(m)

    return MultiDataSet(feats, labels, tuple(new_fms), tuple(new_lms)), bucket


# ---------------------------------------------------------------------------
# Inference bucketing
# ---------------------------------------------------------------------------
def bucket_inference_features(x, mask, g):
    """Zero-pad a feature batch (rows are independent at inference — no
    batch statistics are computed — so zeros are exact) up to its
    bucket, synthesizing/extending the time mask for sequences so
    recurrent state carries through padded timesteps unchanged (exact
    for bidirectional RNNs too: lstm_scan's masked steps are identity
    carries).  Returns ``(x_p, mask_p, n, t, bucket)``."""
    x = np.asarray(x)
    n = int(x.shape[0])
    nb = bucket_size(n, g.bucket_batch_sizes)
    t = int(x.shape[1]) if x.ndim == 3 else None
    tb = bucket_size(t, g.bucket_time_sizes) if t is not None else None

    x_p = x
    if nb != n:
        pad = [(0, nb - n)] + [(0, 0)] * (x.ndim - 1)
        x_p = np.pad(x_p, pad)
    if tb is not None and tb != t:
        x_p = _pad_time(x_p, tb)

    if t is not None:
        m = (np.asarray(mask, np.float32) if mask is not None
             else np.ones((n, t), np.float32))
        m_p = np.zeros((nb, tb) + m.shape[2:], np.float32)
        m_p[:n, :t] = m
    elif mask is not None:
        m = np.asarray(mask, np.float32)
        m_p = np.zeros((nb,) + m.shape[1:], np.float32)
        m_p[:n] = m
    else:
        m_p = None
    return x_p, m_p, n, t, (nb, tb)


def unpad_outputs(out, n: int, t: Optional[int], tb: Optional[int]):
    """Slice a padded output back to the real batch (and time) extent."""
    out = out[:n]
    if t is not None and tb is not None and t != tb and out.ndim >= 3 \
            and out.shape[1] == tb:
        out = out[:, :t]
    return out


# ---------------------------------------------------------------------------
# Retrace telemetry
# ---------------------------------------------------------------------------
def signature_of(tree) -> Tuple:
    """Hashable (structure, shapes, dtypes) signature of a pytree of
    arrays — the same information jax.jit keys its trace cache on, so a
    NEW signature on a given entry point is (up to jit-cache eviction)
    an XLA retrace."""
    import jax
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return (str(treedef),
            tuple((tuple(getattr(l, "shape", ())),
                   str(getattr(l, "dtype", type(l).__name__)))
                  for l in leaves))


class CompileTelemetry:
    """Retrace counter + per-bucket hit counts for one network.

    ``record(kind, args, bucket=)`` is called by every jitted entry
    point (train_step, fused_step_k*, output, score, score_examples)
    with the arrays about to cross into jit; a signature not seen on
    that entry point counts as a retrace.  ``invalidate()`` mirrors the
    engines' trace-token invalidation (the jitted callables are dropped,
    so the same shapes genuinely recompile)."""

    def __init__(self):
        self.retraces = 0
        self.calls = 0
        self.bucket_hits: Dict[str, int] = {}
        self.trace_log: List[Tuple[str, Tuple]] = []
        self._seen: Dict[str, set] = {}

    def record(self, kind: str, args, bucket=None) -> bool:
        """Returns True when this (kind, signature) is new — a retrace."""
        sig = signature_of(args)
        self.calls += 1
        seen = self._seen.setdefault(kind, set())
        new = sig not in seen
        if new:
            seen.add(sig)
            self.retraces += 1
            self.trace_log.append((kind, sig))
        if bucket is not None:
            key = f"{kind}:{bucket_key(bucket)}"
            self.bucket_hits[key] = self.bucket_hits.get(key, 0) + 1
        # mirror into the process-wide registry (monitor/) so retraces
        # show up in the same scrape as latencies and memory; aggregated
        # across networks — per-instance detail stays on this object
        from deeplearning4j_tpu.monitor import get_registry
        reg = get_registry()
        reg.counter("dl4j_compile_calls_total", "jit-entry calls",
                    labels=("kind",)).labels(kind=kind).inc()
        if new:
            reg.counter("dl4j_compile_retraces_total",
                        "new jit-entry signatures (XLA retraces)",
                        labels=("kind",)).labels(kind=kind).inc()
            # journal the retrace with the trace context (fit_id /
            # request_id): a jit_call-dominated step can be attributed
            # to the exact request/fit that paid the compile
            from deeplearning4j_tpu.monitor import events
            events.emit("compile.retrace", kind=kind,
                        retraces=self.retraces)
        if bucket is not None:
            reg.counter("dl4j_bucket_hits_total",
                        "bucketed batches dispatched",
                        labels=("kind", "bucket")).labels(
                kind=kind, bucket=bucket_key(bucket)).inc()
        return new

    def invalidate(self) -> None:
        """Ambient trace state changed (precision policy, sequence mesh):
        the engines drop their jitted fns, so seen signatures WILL
        recompile — forget them (cumulative counters keep counting)."""
        self._seen.clear()

    def reset(self) -> None:
        self.__init__()

    def snapshot(self) -> Dict[str, Any]:
        return {
            "retraces": self.retraces,
            "calls": self.calls,
            "by_kind": {k: len(v) for k, v in self._seen.items()},
            "bucket_hits": dict(self.bucket_hits),
        }


# ---------------------------------------------------------------------------
# Persistent compilation cache (env-gated)
# ---------------------------------------------------------------------------
@functools.lru_cache(maxsize=1)
def maybe_enable_persistent_cache() -> bool:
    """Point JAX's on-disk compilation cache at ``$DL4J_PERSISTENT_CACHE``
    (created if missing) so repeated runs skip cold compiles.  No-op
    (False) when the env var is unset or the config knobs don't exist.
    Idempotent and cheap — call from any fit entry point."""
    d = os.environ.get("DL4J_PERSISTENT_CACHE")
    if not d:
        return False
    try:
        import jax
        os.makedirs(d, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", os.path.abspath(d))
        # cache EVERY program: the default thresholds skip sub-second
        # compiles, but ragged streams are exactly many small programs
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        try:
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        except Exception:
            pass  # knob name varies across jax versions; best-effort
        # jax latches the cache as disabled on the FIRST jit execution if
        # the dir wasn't configured yet (anything compiles during net
        # init) — reset so the next access re-initializes with our dir
        try:
            from jax._src import compilation_cache as _cc
            _cc.reset_cache()
        except Exception:
            pass
    except Exception:
        return False
    return True
