"""Recurrent primitives: peephole (Graves) LSTM cell and time-scan.

The reference's GravesLSTM runs an eager per-timestep loop of gemms
(ref: nn/layers/recurrent/LSTMHelpers.java:60-164 — the shared
activateHelper/backpropGradientHelper).  TPU-natively the whole sequence
is a single ``lax.scan`` whose body is one fused [N, nIn+nOut] x
[nIn+nOut, 4*nOut] matmul on the MXU; backprop through time falls out of
jax.grad over the scan instead of the reference's hand-written BPTT.

Gate layout in the fused weight matrices is [input, forget, output, cell]
blocks of width H (matches GravesLSTMParamInitializer's iFogOrdering).
Peephole connections (the "Graves" part) are separate [H] vectors rather
than the reference's trick of packing them as 3 extra recurrent-weight
columns (ref: GravesLSTMParamInitializer RW shape [nOut, 4*nOut+3]).
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax


class LSTMState(NamedTuple):
    c: jnp.ndarray  # cell state  [N, H]
    h: jnp.ndarray  # hidden/output state [N, H]


def lstm_cell(params: dict, x_t: jnp.ndarray, state: LSTMState,
              gate_act=jax.nn.sigmoid, cell_act=jnp.tanh,
              peephole: bool = True) -> Tuple[LSTMState, jnp.ndarray]:
    """One peephole-LSTM step.  params: W [nIn,4H], RW [H,4H], b [4H],
    pI/pF/pO [H] (if peephole)."""
    H = state.h.shape[-1]
    z = x_t @ params["W"] + state.h @ params["RW"] + params["b"]
    zi, zf, zo, zc = jnp.split(z, 4, axis=-1)
    if peephole:
        zi = zi + state.c * params["pI"]
        zf = zf + state.c * params["pF"]
    i = gate_act(zi)
    f = gate_act(zf)
    g = cell_act(zc)
    c_new = f * state.c + i * g
    if peephole:
        zo = zo + c_new * params["pO"]
    o = gate_act(zo)
    h_new = o * cell_act(c_new)
    return LSTMState(c_new, h_new), h_new


def lstm_scan(params: dict, x: jnp.ndarray, init: Optional[LSTMState] = None,
              mask: Optional[jnp.ndarray] = None, reverse: bool = False,
              gate_act=jax.nn.sigmoid, cell_act=jnp.tanh,
              peephole: bool = True) -> Tuple[jnp.ndarray, LSTMState]:
    """Run the LSTM over a full sequence.

    x: [N, T, nIn] (time-major internally for scan).  mask: [N, T] with 1 for
    valid steps — masked steps carry state through unchanged, matching the
    reference's variable-length masking semantics (Layer.feedForwardMaskArray).
    Returns (outputs [N, T, H], final_state).
    """
    N, T, _ = x.shape
    H = params["RW"].shape[0]
    if init is None:
        init = LSTMState(jnp.zeros((N, H), x.dtype), jnp.zeros((N, H), x.dtype))

    xs = jnp.swapaxes(x, 0, 1)  # [T, N, nIn]
    ms = jnp.swapaxes(mask, 0, 1)[..., None] if mask is not None else None

    def step(carry: LSTMState, inp):
        if ms is None:
            x_t = inp
            new, h = lstm_cell(params, x_t, carry, gate_act, cell_act, peephole)
            return new, h
        x_t, m_t = inp
        new, h = lstm_cell(params, x_t, carry, gate_act, cell_act, peephole)
        c = jnp.where(m_t > 0, new.c, carry.c)
        hh = jnp.where(m_t > 0, new.h, carry.h)
        return LSTMState(c, hh), hh * (m_t > 0)

    inputs = xs if ms is None else (xs, ms)
    final, hs = lax.scan(step, init, inputs, reverse=reverse)
    return jnp.swapaxes(hs, 0, 1), final
