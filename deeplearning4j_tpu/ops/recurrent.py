"""Recurrent primitives: peephole (Graves) LSTM cell and time-scan.

The reference's GravesLSTM runs an eager per-timestep loop of gemms
(ref: nn/layers/recurrent/LSTMHelpers.java:60-164 — the shared
activateHelper/backpropGradientHelper).  TPU-natively the whole sequence
is a single ``lax.scan``: the input projection x·W+b for ALL timesteps
is hoisted into one large [N·T, nIn]×[nIn, 4H] MXU matmul outside the
scan, and the scan body keeps only the [N, H]×[H, 4H] recurrent matmul
(the cuDNN-style LSTM batching); backprop through time falls out of
jax.grad over the scan instead of the reference's hand-written BPTT.

Gate layout in the fused weight matrices is [input, forget, output, cell]
blocks of width H (matches GravesLSTMParamInitializer's iFogOrdering).
Peephole connections (the "Graves" part) are separate [H] vectors rather
than the reference's trick of packing them as 3 extra recurrent-weight
columns (ref: GravesLSTMParamInitializer RW shape [nOut, 4*nOut+3]).
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax


class LSTMState(NamedTuple):
    c: jnp.ndarray  # cell state  [N, H]
    h: jnp.ndarray  # hidden/output state [N, H]


def lstm_cell(params: dict, x_t: jnp.ndarray, state: LSTMState,
              gate_act=jax.nn.sigmoid, cell_act=jnp.tanh,
              peephole: bool = True) -> Tuple[LSTMState, jnp.ndarray]:
    """One peephole-LSTM step.  params: W [nIn,4H], RW [H,4H], b [4H],
    pI/pF/pO [H] (if peephole)."""
    return _lstm_cell_pre(params, x_t @ params["W"] + params["b"], state,
                          gate_act, cell_act, peephole)


def _lstm_cell_pre(params: dict, zx_t: jnp.ndarray, state: LSTMState,
                   gate_act=jax.nn.sigmoid, cell_act=jnp.tanh,
                   peephole: bool = True) -> Tuple[LSTMState, jnp.ndarray]:
    """LSTM step on a PRE-PROJECTED input (zx_t = x_t·W + b): only the
    [N,H]×[H,4H] recurrent matmul runs inside the time scan — the input
    projection for all timesteps is hoisted into one big MXU-friendly
    matmul by lstm_scan (the cuDNN-style LSTM batching the reference
    gets from cudnnRNNForwardTraining)."""
    z = zx_t + state.h @ params["RW"]
    zi, zf, zo, zc = jnp.split(z, 4, axis=-1)
    if peephole:
        zi = zi + state.c * params["pI"]
        zf = zf + state.c * params["pF"]
    i = gate_act(zi)
    f = gate_act(zf)
    g = cell_act(zc)
    c_new = f * state.c + i * g
    if peephole:
        zo = zo + c_new * params["pO"]
    o = gate_act(zo)
    h_new = o * cell_act(c_new)
    return LSTMState(c_new, h_new), h_new


def lstm_scan(params: dict, x: jnp.ndarray, init: Optional[LSTMState] = None,
              mask: Optional[jnp.ndarray] = None, reverse: bool = False,
              gate_act=jax.nn.sigmoid, cell_act=jnp.tanh,
              peephole: bool = True) -> Tuple[jnp.ndarray, LSTMState]:
    """Run the LSTM over a full sequence.

    x: [N, T, nIn] (time-major internally for scan).  mask: [N, T] with 1 for
    valid steps — masked steps carry state through unchanged, matching the
    reference's variable-length masking semantics (Layer.feedForwardMaskArray).
    Returns (outputs [N, T, H], final_state).
    """
    N, T, _ = x.shape
    H = params["RW"].shape[0]
    if init is None:
        init = LSTMState(jnp.zeros((N, H), x.dtype), jnp.zeros((N, H), x.dtype))

    # input projection for ALL timesteps as one [N*T, nIn]x[nIn, 4H]
    # matmul (large MXU tile) — the scan body keeps only the [N,H]x[H,4H]
    # recurrent matmul, halving per-step gemms
    zx = (x.reshape(N * T, -1) @ params["W"] + params["b"]).reshape(
        N, T, 4 * H)
    zxs = jnp.swapaxes(zx, 0, 1)  # [T, N, 4H]
    ms = jnp.swapaxes(mask, 0, 1)[..., None] if mask is not None else None

    # Helper selection (ops/helpers.py, trace time): the standard
    # sigmoid/tanh peephole cell can run as ONE Pallas VMEM pass per
    # step (recurrent matmul + all gate math fused,
    # pallas_kernels.fused_lstm_step) instead of separate HLO ops.
    from deeplearning4j_tpu.ops import helpers
    use_fused = helpers.lstm_step_wanted(params, x, gate_act, cell_act,
                                         peephole)
    if use_fused:
        from deeplearning4j_tpu.ops import pallas_kernels as pk
        p3 = jnp.stack([params["pI"], params["pF"], params["pO"]])

        def cell(zx_t, carry):
            c_new, h_new = pk.fused_lstm_step(zx_t, carry.h, carry.c,
                                              params["RW"], p3)
            return LSTMState(c_new, h_new), h_new
    else:
        def cell(zx_t, carry):
            return _lstm_cell_pre(params, zx_t, carry, gate_act, cell_act,
                                  peephole)

    def step(carry: LSTMState, inp):
        if ms is None:
            zx_t = inp
            new, h = cell(zx_t, carry)
            return new, h
        zx_t, m_t = inp
        new, h = cell(zx_t, carry)
        c = jnp.where(m_t > 0, new.c, carry.c)
        hh = jnp.where(m_t > 0, new.h, carry.h)
        return LSTMState(c, hh), hh * (m_t > 0)

    inputs = zxs if ms is None else (zxs, ms)
    final, hs = lax.scan(step, init, inputs, reverse=reverse)
    return jnp.swapaxes(hs, 0, 1), final
