"""Activation functions — the reference's ``IActivation`` surface.

Covers the reference's ``nn/conf/layers`` activation strings (identity,
cube, elu, hardsigmoid, hardtanh, leakyrelu, relu, rrelu, sigmoid,
softmax, softplus, softsign, tanh, rationaltanh; ref: nd4j IActivation
impls consumed by BaseLayer.activate).  Each is a pure jnp function so
XLA fuses it into the surrounding matmul.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

ActivationFn = Callable[[jnp.ndarray], jnp.ndarray]


def identity(x):
    return x


def cube(x):
    return x * x * x


def elu(x):
    return jax.nn.elu(x)


def hardsigmoid(x):
    return jnp.clip(0.2 * x + 0.5, 0.0, 1.0)


def hardtanh(x):
    return jnp.clip(x, -1.0, 1.0)


def leakyrelu(x, alpha: float = 0.01):
    return jnp.where(x >= 0, x, alpha * x)


def relu(x):
    return jax.nn.relu(x)


def relu6(x):
    return jnp.clip(x, 0.0, 6.0)


def sigmoid(x):
    return jax.nn.sigmoid(x)


def softmax(x):
    # Softmax over the feature axis (axis 1 for [N, C]; last axis generally).
    return jax.nn.softmax(x, axis=-1)


def softplus(x):
    return jax.nn.softplus(x)


def softsign(x):
    return jax.nn.soft_sign(x)


def tanh(x):
    return jnp.tanh(x)


def rationaltanh(x):
    # Padé-style rational approximation of tanh used by the reference's
    # ActivationRationalTanh: 1.7159 * tanh_approx(2x/3).
    a = 2.0 * x / 3.0
    approx = jnp.sign(a) * (1.0 - 1.0 / (1.0 + jnp.abs(a) + a * a + 1.41645 * a * a * a * a))
    return 1.7159 * approx


def rectifiedtanh(x):
    return jnp.maximum(0.0, jnp.tanh(x))


def selu(x):
    return jax.nn.selu(x)


def swish(x):
    return jax.nn.silu(x)


def gelu(x):
    return jax.nn.gelu(x)


_REGISTRY: dict[str, ActivationFn] = {
    "identity": identity,
    "linear": identity,
    "cube": cube,
    "elu": elu,
    "hardsigmoid": hardsigmoid,
    "hardtanh": hardtanh,
    "leakyrelu": leakyrelu,
    "relu": relu,
    "relu6": relu6,
    "sigmoid": sigmoid,
    "softmax": softmax,
    "softplus": softplus,
    "softsign": softsign,
    "tanh": tanh,
    "rationaltanh": rationaltanh,
    "rectifiedtanh": rectifiedtanh,
    "selu": selu,
    "swish": swish,
    "gelu": gelu,
}


def get(name: str) -> ActivationFn:
    """Look up an activation by its reference-compatible string name."""
    try:
        return _REGISTRY[name.lower()]
    except KeyError:
        raise ValueError(
            f"Unknown activation '{name}'. Known: {sorted(_REGISTRY)}"
        ) from None


def register(name: str, fn: ActivationFn) -> None:
    """Register a custom activation (the reference supports custom IActivation)."""
    _REGISTRY[name.lower()] = fn


def names() -> list[str]:
    return sorted(_REGISTRY)
