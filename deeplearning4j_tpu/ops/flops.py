"""Per-layer analytic FLOPs model — the MFU estimator's numerator.

``compiled.cost_analysis()`` (bench.py) is the preferred FLOPs source
when XLA exposes it, but it needs the compiled step in hand; monitoring
and the sharded bench want an estimate computable from the MODEL alone,
so MFU can be derived from the registry's ``dl4j_phase_seconds``
step spans after any fit (ROADMAP item 5).  This walks the layer stack
with the same InputType chain the engines use and counts matmul FLOPs
(2·M·N·K per GEMM); elementwise work (activations, BN, pooling) is
ignored — on MXU-class hardware it is noise next to the GEMMs.

Backward pass ≈ 2× forward (grad wrt activations + grad wrt weights),
so one train step ≈ 3× forward — the standard roofline convention.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

TRAIN_STEP_MULTIPLIER = 3.0  # forward + ~2x backward


def _layer_forward_flops(layer, params: dict, cur, batch: int) -> float:
    """One layer's forward GEMM FLOPs for ``batch`` examples given its
    initialized params and the incoming InputType ``cur``."""
    from deeplearning4j_tpu.nn.conf import layers as L
    t = 1
    if cur is not None and getattr(cur, "kind", None) == "rnn":
        t = int(cur.timesteps or 1)
    if isinstance(layer, L.ConvolutionLayer):
        w = params.get("W")
        if w is None:
            return 0.0
        n_out, c_in, kh, kw = (int(d) for d in w.shape)
        try:
            out_t = layer.output_type(cur)
            oh, ow = int(out_t.height), int(out_t.width)
        except Exception:
            oh = ow = 1
        return 2.0 * batch * oh * ow * n_out * c_in * kh * kw
    if isinstance(layer, (L.GravesBidirectionalLSTM,)):
        # two directions, each: 4 gates x (input + recurrent GEMM)
        flops = 0.0
        for wk, rk in (("f_W", "f_RW"), ("b_W", "b_RW")):
            w, r = params.get(wk), params.get(rk)
            if w is not None:
                flops += 2.0 * batch * t * int(np.prod(w.shape))
            if r is not None:
                flops += 2.0 * batch * t * int(np.prod(r.shape))
        return flops
    if isinstance(layer, L.GravesLSTM):
        w, r = params.get("W"), params.get("RW")
        flops = 0.0
        if w is not None:
            flops += 2.0 * batch * t * int(np.prod(w.shape))
        if r is not None:
            flops += 2.0 * batch * t * int(np.prod(r.shape))
        return flops
    if isinstance(layer, L.EmbeddingLayer):
        return 0.0  # a gather, not a GEMM
    # generic dense-like fallback: every >=2-D param is a GEMM operand
    # applied once per example (per timestep on rnn inputs) — exact for
    # DenseLayer/OutputLayer, a reasonable bound for attention/MoE
    return sum(2.0 * batch * t * int(np.prod(v.shape))
               for v in params.values() if getattr(v, "ndim", 0) >= 2)


def forward_flops(model, batch: int) -> Optional[float]:
    """Forward-pass FLOPs for one batch, or None when the model shape
    can't be walked (un-initialized, exotic graph)."""
    if getattr(model, "net_params", None) is None:
        return None
    if type(model).__name__ == "MultiLayerNetwork":
        try:
            cur = model._input_type_chain_start()
        except Exception:
            cur = None
        total = 0.0
        for i, layer in enumerate(model.layers):
            if cur is not None and i in model.conf.preprocessors:
                try:
                    cur = model.conf.preprocessors[i].output_type(cur)
                except Exception:
                    cur = None
            total += _layer_forward_flops(layer, model.net_params[i],
                                          cur, batch)
            if cur is not None:
                try:
                    cur = layer.output_type(cur)
                except Exception:
                    cur = None
        return total
    # ComputationGraph / anything else: GEMM-operand sum over the param
    # table (no per-vertex InputType walk; timesteps not accounted)
    try:
        table = model.param_table()
    except Exception:
        return None
    return sum(2.0 * batch * int(np.prod(v.shape))
               for v in table.values() if getattr(v, "ndim", 0) >= 2)


def train_step_flops(model, batch: int) -> Optional[float]:
    """FLOPs for one optimizer step on ``batch`` examples (≈3× forward)."""
    fwd = forward_flops(model, batch)
    return None if fwd is None else TRAIN_STEP_MULTIPLIER * fwd


def mfu(model, batch: int, step_seconds: float,
        peak_flops: Optional[float]) -> Optional[dict]:
    """Model-FLOPs-utilization estimate: analytic step FLOPs over
    measured step seconds, against the chip's published peak.  Returns
    the full derivation so a bench record is explainable on its own."""
    if not peak_flops or not step_seconds or step_seconds <= 0:
        return None
    flops = train_step_flops(model, batch)
    if not flops:
        return None
    achieved = flops / step_seconds
    return {
        "mfu_estimate": round(achieved / peak_flops, 4),
        "flops_per_step_model": flops,
        "achieved_flops_per_sec": achieved,
        "peak_flops_used": peak_flops,
        "flops_source": "per-layer analytic model (ops/flops.py), "
                        "train step = 3x forward GEMMs",
    }
