"""Reduced-precision tiers: weight-only int8/fp8 quantization and
int8 block-quantized gradient collectives with error feedback.

Three tiers share this module (selection lives in ops/helpers.py, the
same seam the Pallas kernel tiers use, so no call site changes):

* ``bf16_train`` — ops/dtypes.Policy already implements the compute
  side; this module only meters it.
* ``int8_infer`` / ``fp8_infer`` — weight-only quantization with
  per-output-channel symmetric scales.  Quantization happens ONCE on
  the host (numpy); dequantization happens IN-TRACE (`q.astype(f32) *
  scale` fuses into the first consumer matmul), so the device-resident
  weights are the ~4x-smaller codes.  Biases and 1-D leaves stay fp32:
  they are a rounding-error fraction of the bytes and quantizing them
  costs disproportionate accuracy.
* ``grad_quant`` — the distributed barrier contribution goes int8 with
  per-block scales plus a persistent error-feedback residual
  (:class:`ErrorFeedback`): what one step's quantization loses, the
  next step's contribution carries.  The cuDNN playbook (arXiv
  1410.0759) motivates the compute tiers; arXiv 2112.01075's
  redistribution cost model motivates the wire tier — cross-host
  bytes, not FLOPs, dominate the elastic step.

Every tier honors the Pallas-tier contract: byte-identical when off
(the fp32 paths are untouched), bounded-ε parity when on (pinned by
tests/test_precision.py and the self-tests helpers.py warm-runs), and
metered under ``dl4j_precision_*``.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

import numpy as np

#: gradient-quantization block length: long enough to amortize the f32
#: scale (0.2% overhead), short enough that one outlier only inflates
#: the quantization step of its own 2048 neighbours
GRAD_BLOCK = 2048

#: int8 symmetric code range (−127..127; −128 unused keeps zero exact
#: and the code symmetric)
_INT8_MAX = 127.0
#: float8_e4m3 finite max
_FP8_MAX = 448.0

# runtime kill switches, flipped by a failed self-test (mirrors
# pallas_kernels._disabled): tier -> reason
_disabled: Dict[str, str] = {}
_DISABLED_LOCK = threading.Lock()


def disable_tier(tier: str, reason: str) -> None:
    """Runtime per-tier kill: a failed parity self-test degrades that
    tier to the fp32 path without taking down the healthy ones."""
    with _DISABLED_LOCK:
        _disabled[tier] = reason


def tier_disabled(tier: str) -> Optional[str]:
    return _disabled.get(tier)


def reset_disabled() -> None:
    """Tests only."""
    with _DISABLED_LOCK:
        _disabled.clear()


def _registry():
    from deeplearning4j_tpu import monitor
    return monitor.get_registry()


def record_tier(tier: str, on: bool) -> None:
    """Meter one trace-time tier selection (same contract as
    helpers.record_selection: counts move on traces, not steps)."""
    try:
        c = _registry().counter(
            "dl4j_precision_selected_total",
            "precision-tier selection decisions at trace time",
            labels=("tier", "on"))
        c.labels(tier=tier, on="1" if on else "0").inc()
    except Exception:
        pass  # metering must never break a build


def record_grad_bytes(dtype: str, nbytes: int) -> None:
    """Meter one barrier contribution's wire payload size by dtype —
    the A/B the ≥3.5x byte-cut acceptance reads."""
    try:
        _registry().counter(
            "dl4j_precision_grad_bytes_total",
            "cross-host gradient bytes contributed to the barrier "
            "all-reduce, by wire dtype", labels=("dtype",)
        ).labels(dtype=dtype).inc(int(nbytes))
    except Exception:
        pass


def record_weight_bytes(tier: str, quantized: int, dense: int) -> None:
    """Resident-weight footprint after weight-only quantization."""
    try:
        g = _registry().gauge(
            "dl4j_precision_weight_bytes",
            "device-resident weight bytes after quantization, vs the "
            "dense fp32 footprint", labels=("tier", "kind"))
        g.labels(tier=tier, kind="quantized").set(int(quantized))
        g.labels(tier=tier, kind="dense").set(int(dense))
    except Exception:
        pass


# ---------------------------------------------------------------------------
# fp8 capability probe
# ---------------------------------------------------------------------------

def fp8_dtype():
    """The backend's fp8 storage dtype, or None when the installed
    jax/XLA has no float8 support."""
    import jax.numpy as jnp
    return getattr(jnp, "float8_e4m3fn", None)


def fp8_supported() -> bool:
    """Can this backend round-trip float8_e4m3?  Probed once per
    process (a cast either works everywhere or raises immediately)."""
    global _FP8_OK
    if _FP8_OK is None:
        dt = fp8_dtype()
        if dt is None:
            _FP8_OK = False
        else:
            try:
                import jax.numpy as jnp
                x = jnp.asarray([1.0, -2.5], jnp.float32).astype(dt)
                _FP8_OK = bool(np.isfinite(
                    np.asarray(x.astype(jnp.float32))).all())
            except Exception:
                _FP8_OK = False
    return _FP8_OK


_FP8_OK: Optional[bool] = None


# ---------------------------------------------------------------------------
# Weight-only quantization (per-output-channel scales)
# ---------------------------------------------------------------------------

def _is_qleaf(x) -> bool:
    return isinstance(x, dict) and set(x.keys()) == {"q", "s"}


def quantize_weight(w, mode: str = "int8") -> dict:
    """One weight leaf -> ``{"q": codes, "s": f32 scales}`` with
    symmetric per-output-channel scales (channels = last axis, the
    out-features axis of this codebase's ``(in, out)`` dense kernels
    and the innermost axis XLA contracts against)."""
    w = np.asarray(w, np.float32)
    reduce_axes = tuple(range(w.ndim - 1))
    amax = np.abs(w).max(axis=reduce_axes, keepdims=True) if w.ndim > 1 \
        else np.abs(w).max(keepdims=True)
    amax = np.maximum(amax, 1e-12).astype(np.float32)
    if mode == "int8":
        s = (amax / _INT8_MAX).astype(np.float32)
        q = np.clip(np.rint(w / s), -_INT8_MAX, _INT8_MAX).astype(np.int8)
    elif mode == "fp8":
        dt = fp8_dtype()
        if dt is None:
            raise ValueError("fp8 requested but this backend has no "
                             "float8_e4m3 support")
        import jax.numpy as jnp
        s = (amax / _FP8_MAX).astype(np.float32)
        q = np.asarray(jnp.asarray(w / s, jnp.float32).astype(dt))
    else:
        raise ValueError(f"unknown weight-quantization mode '{mode}' "
                         "(known: int8, fp8)")
    return {"q": q, "s": s}


def quantize_params(tree, mode: str = "int8") -> Tuple[object, dict]:
    """Weight-only quantization of a param pytree: float leaves with
    ndim>=2 become ``{"q", "s"}`` records; biases, 1-D and integer
    leaves pass through untouched.  Returns ``(qtree, stats)`` where
    stats carries the quantized/dense byte footprints."""
    import jax
    stats = {"n_quantized": 0, "n_passthrough": 0,
             "quantized_bytes": 0, "dense_bytes": 0}

    def one(x):
        a = np.asarray(x)
        stats["dense_bytes"] += a.size * 4 if np.issubdtype(
            a.dtype, np.floating) else a.nbytes
        if a.ndim >= 2 and np.issubdtype(a.dtype, np.floating):
            rec = quantize_weight(a, mode)
            stats["n_quantized"] += 1
            stats["quantized_bytes"] += rec["q"].nbytes + rec["s"].nbytes
            return rec
        stats["n_passthrough"] += 1
        stats["quantized_bytes"] += a.nbytes
        return x

    qtree = jax.tree_util.tree_map(one, tree)
    record_weight_bytes(f"{mode}_infer", stats["quantized_bytes"],
                        stats["dense_bytes"])
    return qtree, stats


def dequantize_params(qtree, dtype=None):
    """In-trace dequantization: ``{"q", "s"}`` records become
    ``q.astype(f32) * s`` (XLA fuses the expand into the consumer
    matmul); everything else passes through.  Works on host numpy
    trees too (the parity tests)."""
    import jax
    import jax.numpy as jnp
    out_dtype = dtype or jnp.float32

    def deq(x):
        if _is_qleaf(x):
            return (x["q"].astype(out_dtype) * x["s"]).astype(out_dtype)
        return x

    return jax.tree_util.tree_map(deq, qtree, is_leaf=_is_qleaf)


# ---------------------------------------------------------------------------
# Gradient block quantization + error feedback
# ---------------------------------------------------------------------------

def quantize_blocks(vec, block: int = GRAD_BLOCK
                    ) -> Tuple[np.ndarray, np.ndarray]:
    """Symmetric per-block int8 quantization of a flat f32 vector:
    ``(codes int8 [n], scales f32 [ceil(n/block)])``."""
    v = np.asarray(vec, np.float32).ravel()
    n = v.size
    nb = max(1, -(-n // block))
    pad = nb * block - n
    vp = np.pad(v, (0, pad)).reshape(nb, block) if pad else \
        v.reshape(nb, block)
    amax = np.abs(vp).max(axis=1)
    scales = np.where(amax > 0, amax / _INT8_MAX, 1.0).astype(np.float32)
    codes = np.clip(np.rint(vp / scales[:, None]),
                    -_INT8_MAX, _INT8_MAX).astype(np.int8)
    return codes.reshape(-1)[:n].copy(), scales


def dequantize_blocks(codes, scales, block: int = GRAD_BLOCK
                      ) -> np.ndarray:
    """Inverse of :func:`quantize_blocks` (exact: int8 code × f32 scale
    is representable, so every receiver reconstructs the SAME f32
    vector — what keeps the coordinator's rank-order accumulation
    bit-stable across a mixed fleet)."""
    c = np.asarray(codes).ravel().astype(np.float32)
    s = np.asarray(scales, np.float32).ravel()
    n = c.size
    nb = s.size
    pad = nb * block - n
    if pad < 0 or pad >= block:
        raise ValueError(f"codes length {n} inconsistent with "
                         f"{nb} scale blocks of {block}")
    cp = np.pad(c, (0, pad)).reshape(nb, block) if pad else \
        c.reshape(nb, block)
    return (cp * s[:, None]).reshape(-1)[:n].astype(np.float32)


class ErrorFeedback:
    """Persistent error-feedback residual for quantized gradient
    collectives: each contribution quantizes ``grad + residual`` and
    keeps ``(grad + residual) - dequant`` for the next step, so the
    quantization error is carried, not dropped — the convergence
    guarantee behind 1-bit/int8 SGD compression.

    ``commit`` only runs after the barrier ACCEPTS the contribution: a
    generation roll re-runs the same batch, and committing the residual
    for a contribution the cluster never reduced would double-count its
    error.  Rolls call :meth:`reset` instead — survivors of a resize
    restart from a synchronized snapshot, and a stale residual from the
    old population would leak pre-roll error into the new one."""

    def __init__(self, block: int = GRAD_BLOCK):
        self.block = int(block)
        self.residual: Optional[np.ndarray] = None

    def compensate(self, vec: np.ndarray
                   ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """``(compensated, codes, scales)`` for one contribution."""
        v = np.asarray(vec, np.float32).ravel()
        if self.residual is None or self.residual.size != v.size:
            self.residual = np.zeros_like(v)
        comp = v + self.residual
        codes, scales = quantize_blocks(comp, self.block)
        return comp, codes, scales

    def commit(self, comp: np.ndarray, codes: np.ndarray,
               scales: np.ndarray) -> None:
        """Persist the quantization error of an ACCEPTED contribution."""
        self.residual = comp - dequantize_blocks(codes, scales, self.block)

    def reset(self, why: str = "") -> None:
        """Drop the residual (generation roll / rejoin / resize)."""
        self.residual = None
        try:
            _registry().counter(
                "dl4j_precision_ef_resets_total",
                "error-feedback residuals dropped (generation rolls, "
                "rejoins, gradient-size changes)").inc()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# Parity self-tests (wired into helpers.ensure_precision_validated)
# ---------------------------------------------------------------------------

def _selftest_int8_weights() -> None:
    rng = np.random.default_rng(0)
    w = rng.normal(size=(64, 32)).astype(np.float32) * 3.0
    rec = quantize_weight(w, "int8")
    back = np.asarray(rec["q"], np.float32) * rec["s"]
    step = np.abs(w).max(axis=0) / _INT8_MAX  # per-channel code step
    err = np.abs(back - w).max(axis=0)
    if not (err <= 0.5 * step + 1e-7).all():
        raise FloatingPointError("int8 weight round-trip exceeded the "
                                 "half-step error bound")


def _selftest_fp8_weights() -> None:
    if not fp8_supported():
        raise RuntimeError("no float8_e4m3 support on this backend")
    rng = np.random.default_rng(1)
    w = rng.normal(size=(32, 16)).astype(np.float32)
    rec = quantize_weight(w, "fp8")
    import jax.numpy as jnp
    back = np.asarray(jnp.asarray(rec["q"]).astype(jnp.float32)) * rec["s"]
    rel = np.abs(back - w).max() / max(np.abs(w).max(), 1e-12)
    if not rel < 0.1:  # e4m3 has a ~6% max relative step
        raise FloatingPointError(f"fp8 weight round-trip error {rel}")


def _selftest_grad_blocks() -> None:
    rng = np.random.default_rng(2)
    g = (rng.normal(size=5000) * 0.01).astype(np.float32)
    codes, scales = quantize_blocks(g)
    back = dequantize_blocks(codes, scales)
    bound = np.repeat(scales, GRAD_BLOCK)[:g.size] * 0.5 + 1e-9
    if not (np.abs(back - g) <= bound).all():
        raise FloatingPointError("block quantization exceeded the "
                                 "half-step error bound")
    # error feedback: the accumulated transmitted signal tracks the
    # accumulated true signal (residual stays bounded by one code step)
    ef = ErrorFeedback()
    sent = np.zeros_like(g)
    total = np.zeros_like(g)
    for _ in range(8):
        comp, codes, scales = ef.compensate(g)
        ef.commit(comp, codes, scales)
        sent += dequantize_blocks(codes, scales)
        total += g
    drift = np.abs(sent - total).max()
    step = scales.max() * 0.5 + 1e-9
    if not drift <= step * 2:
        raise FloatingPointError(
            f"error-feedback drift {drift} exceeds one code step {step}")
