"""Reconstruction distributions p(x|z) for the variational autoencoder.

Parity with the reference's ReconstructionDistribution hierarchy
(ref: nn/conf/layers/variational/{GaussianReconstructionDistribution,
BernoulliReconstructionDistribution,ExponentialReconstructionDistribution,
CompositeReconstructionDistribution,LossFunctionWrapper}.java).

Each distribution is described by a serializable dict
``{"type": ..., "activation": ...}`` and exposes:
  - ``n_dist_params(n_features)`` — width of the decoder output head
  - ``neg_log_prob(x, preout)`` — per-example negative log likelihood [N]
  - ``sample(preout, rng)`` / ``mean(preout)`` — generation
All functions are pure/jit-safe and vectorized over the batch.
"""

from __future__ import annotations

import math
from typing import Dict

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops import activations as act_ops
from deeplearning4j_tpu.ops import losses as loss_ops

_LOG2PI = math.log(2.0 * math.pi)


def _act(name):
    return act_ops.get(name or "identity")


class _Gaussian:
    """N(mean, sigma^2) with decoder emitting [mean | log(sigma^2)]
    (ref: GaussianReconstructionDistribution.java)."""

    def __init__(self, spec):
        self.activation = spec.get("activation", "identity")

    def n_dist_params(self, n):
        return 2 * n

    def neg_log_prob(self, x, preout):
        n = x.shape[-1]
        mean = _act(self.activation)(preout[..., :n])
        log_var = preout[..., n:]
        var = jnp.exp(log_var)
        lp = -0.5 * (_LOG2PI + log_var + (x - mean) ** 2 / var)
        return -jnp.sum(lp, axis=-1)

    def sample(self, preout, rng):
        n = preout.shape[-1] // 2
        mean = _act(self.activation)(preout[..., :n])
        std = jnp.exp(0.5 * preout[..., n:])
        return mean + std * jax.random.normal(rng, mean.shape, mean.dtype)

    def mean(self, preout):
        n = preout.shape[-1] // 2
        return _act(self.activation)(preout[..., :n])


class _Bernoulli:
    """(ref: BernoulliReconstructionDistribution.java — sigmoid default)"""

    def __init__(self, spec):
        self.activation = spec.get("activation", "sigmoid")

    def n_dist_params(self, n):
        return n

    def neg_log_prob(self, x, preout):
        p = jnp.clip(_act(self.activation)(preout), 1e-7, 1.0 - 1e-7)
        lp = x * jnp.log(p) + (1.0 - x) * jnp.log1p(-p)
        return -jnp.sum(lp, axis=-1)

    def sample(self, preout, rng):
        p = _act(self.activation)(preout)
        return jax.random.bernoulli(rng, p).astype(preout.dtype)

    def mean(self, preout):
        return _act(self.activation)(preout)


class _Exponential:
    """Exp(lambda) parameterized via gamma = log(lambda)
    (ref: ExponentialReconstructionDistribution.java)."""

    def __init__(self, spec):
        self.activation = spec.get("activation", "identity")

    def n_dist_params(self, n):
        return n

    def neg_log_prob(self, x, preout):
        gamma = _act(self.activation)(preout)
        lp = gamma - jnp.exp(gamma) * x
        return -jnp.sum(lp, axis=-1)

    def sample(self, preout, rng):
        lam = jnp.exp(_act(self.activation)(preout))
        u = jax.random.uniform(rng, preout.shape, preout.dtype, 1e-7, 1.0)
        return -jnp.log(u) / lam

    def mean(self, preout):
        return 1.0 / jnp.exp(_act(self.activation)(preout))


class _LossWrapper:
    """Plain loss function as a pseudo-distribution
    (ref: LossFunctionWrapper.java — VAE degenerates to a deep AE)."""

    def __init__(self, spec):
        self.activation = spec.get("activation", "identity")
        self.loss = spec.get("loss", "mse")

    def n_dist_params(self, n):
        return n

    def neg_log_prob(self, x, preout):
        return loss_ops.get(self.loss)(x, preout, self.activation, None)

    def sample(self, preout, rng):
        return _act(self.activation)(preout)

    def mean(self, preout):
        return _act(self.activation)(preout)


class _Composite:
    """Different distributions over feature column ranges
    (ref: CompositeReconstructionDistribution.java)."""

    def __init__(self, spec):
        self.parts = [(int(p["size"]), make(p["dist"])) for p in spec["parts"]]

    def n_dist_params(self, n):
        return sum(d.n_dist_params(s) for s, d in self.parts)

    def neg_log_prob(self, x, preout):
        total, xo, po = 0.0, 0, 0
        for s, d in self.parts:
            w = d.n_dist_params(s)
            total = total + d.neg_log_prob(x[..., xo:xo + s], preout[..., po:po + w])
            xo, po = xo + s, po + w
        return total

    def sample(self, preout, rng):
        outs, po = [], 0
        for i, (s, d) in enumerate(self.parts):
            w = d.n_dist_params(s)
            outs.append(d.sample(preout[..., po:po + w], jax.random.fold_in(rng, i)))
            po += w
        return jnp.concatenate(outs, axis=-1)

    def mean(self, preout):
        outs, po = [], 0
        for s, d in self.parts:
            w = d.n_dist_params(s)
            outs.append(d.mean(preout[..., po:po + w]))
            po += w
        return jnp.concatenate(outs, axis=-1)


_TYPES = {
    "gaussian": _Gaussian,
    "bernoulli": _Bernoulli,
    "exponential": _Exponential,
    "loss": _LossWrapper,
    "composite": _Composite,
}


def make(spec: Dict):
    """Build a distribution from its serializable spec dict."""
    if spec is None:
        spec = {"type": "gaussian"}
    return _TYPES[spec.get("type", "gaussian")](spec)
