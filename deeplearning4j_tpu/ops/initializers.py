"""Weight initialization — the reference's ``WeightInit`` schemes.

Covers WeightInit.java:47-49 (ZERO, ONE, SIGMOID_UNIFORM, NORMAL,
LECUN_NORMAL, UNIFORM, XAVIER, XAVIER_UNIFORM, XAVIER_FAN_IN,
XAVIER_LEGACY, RELU, RELU_UNIFORM, DISTRIBUTION, LECUN_UNIFORM) as pure
functions of a jax PRNG key — the reference mutates a shared RNG; here
every init is reproducible from a key (ref: WeightInitUtil.java).
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp


def _fans(shape: Sequence[int], fan_in=None, fan_out=None):
    """fan_in/fan_out conventions: [nIn, nOut] for dense; OIHW
    [cout, cin, kh, kw] for conv (the project-wide conv weight layout)."""
    if fan_in is not None and fan_out is not None:
        return fan_in, fan_out
    if len(shape) == 2:
        return shape[0], shape[1]
    if len(shape) == 4:
        receptive = shape[2] * shape[3]
        return shape[1] * receptive, shape[0] * receptive
    n = 1
    for s in shape:
        n *= s
    return n, n


def init(key, name: str, shape, dtype=jnp.float32, fan_in=None, fan_out=None,
         distribution=None):
    """Draw an initial weight array per the named scheme."""
    name = name.lower()
    fi, fo = _fans(shape, fan_in, fan_out)
    if name == "zero":
        return jnp.zeros(shape, dtype)
    if name in ("one", "ones"):
        return jnp.ones(shape, dtype)
    if name == "normal" or name == "lecun_normal":
        return jax.random.normal(key, shape, dtype) / jnp.sqrt(jnp.asarray(fi, dtype))
    if name == "uniform":
        a = 1.0 / jnp.sqrt(jnp.asarray(fi, dtype))
        return jax.random.uniform(key, shape, dtype, -a, a)
    if name == "xavier":
        std = jnp.sqrt(2.0 / jnp.asarray(fi + fo, dtype))
        return jax.random.normal(key, shape, dtype) * std
    if name == "xavier_uniform":
        a = jnp.sqrt(6.0 / jnp.asarray(fi + fo, dtype))
        return jax.random.uniform(key, shape, dtype, -a, a)
    if name == "xavier_fan_in":
        return jax.random.normal(key, shape, dtype) / jnp.sqrt(jnp.asarray(fi, dtype))
    if name == "xavier_legacy":
        std = jnp.sqrt(1.0 / jnp.asarray(fi + fo, dtype))
        return jax.random.normal(key, shape, dtype) * std
    if name == "relu":
        return jax.random.normal(key, shape, dtype) * jnp.sqrt(2.0 / jnp.asarray(fi, dtype))
    if name == "relu_uniform":
        a = jnp.sqrt(6.0 / jnp.asarray(fi, dtype))
        return jax.random.uniform(key, shape, dtype, -a, a)
    if name == "sigmoid_uniform":
        a = 4.0 * jnp.sqrt(6.0 / jnp.asarray(fi + fo, dtype))
        return jax.random.uniform(key, shape, dtype, -a, a)
    if name == "lecun_uniform":
        a = jnp.sqrt(3.0 / jnp.asarray(fi, dtype))
        return jax.random.uniform(key, shape, dtype, -a, a)
    if name == "distribution":
        if distribution is None:
            raise ValueError("WeightInit DISTRIBUTION requires a distribution spec")
        return sample_distribution(key, distribution, shape, dtype)
    raise ValueError(f"Unknown WeightInit scheme '{name}'")


def sample_distribution(key, dist: dict, shape, dtype=jnp.float32):
    """Reference Distribution configs: normal/gaussian, uniform, binomial."""
    kind = dist.get("type", "normal").lower()
    if kind in ("normal", "gaussian"):
        return dist.get("mean", 0.0) + dist.get("std", 1.0) * jax.random.normal(key, shape, dtype)
    if kind == "uniform":
        return jax.random.uniform(key, shape, dtype, dist.get("lower", 0.0), dist.get("upper", 1.0))
    if kind == "binomial":
        n = dist.get("n", 1)
        p = dist.get("p", 0.5)
        return jax.random.binomial(key, n, p, shape).astype(dtype)
    raise ValueError(f"Unknown distribution type '{kind}'")
