"""Per-layer helper selection: fused Pallas kernels behind predicates,
kill switches and warm validation — the TPU-native equivalent of the
reference's cuDNN helper tier (``CudnnConvolutionHelper`` /
``CudnnLSTMHelper`` et al. with a builtin fallback, ref:
nn/layers/convolution/ConvolutionLayer.java:157-212 and the cuDNN paper
the pattern comes from, PAPERS.md arXiv 1410.0759).

Every op with a fused implementation registers a :class:`Helper` here:

==============  ======  =============================  =====================
op              tier    fused kernel (pallas_kernels)  dense XLA fallback
==============  ======  =============================  =====================
``conv2d``      conv    fused_conv2d_bias_act          ops/convolution.conv2d + activation
``lstm_step``   lstm    fused_lstm_step                ops/recurrent._lstm_cell_pre
``dropout``     dropout fused_threshold_dropout        ops/normalization.dropout
``softmax_xent`` xent   softmax_xent_rows              stable logsumexp form in ops/losses
``attention``   flash   flash_attention                dense softmax attention
==============  ======  =============================  =====================

Selection happens automatically AT TRACE TIME, per call site: each
helper's support predicate (shape/dtype/platform) decides between the
parity-tested Pallas kernel and the dense fallback, and the decision is
metered (``dl4j_pallas_selected_total`` / ``dl4j_pallas_fallback_total``
by op).  Off-TPU nothing fuses by default — the fallback IS the
pre-helper code path, byte-identical — but each tier can be forced for
testing (the kernels then run under ``interpret=True``).

Kill switches, most-specific wins:

* ``DL4J_PALLAS=0`` — global: every tier falls back.
* ``DL4J_PALLAS_{CONV,LSTM,DROPOUT,XENT,FLASH}=0|1`` — per tier:
  ``0`` forces the fallback, ``1`` forces the fused path even off-TPU
  (interpret mode; how the parity tests exercise the kernels through
  the public ``fit``/``output`` path).
* :func:`deeplearning4j_tpu.ops.pallas_kernels.disable_kernels` — the
  runtime per-tier switch :func:`kernel_self_test` flips when a Mosaic
  compile fails on the real chip, so one bad kernel degrades to XLA
  without taking down the healthy tiers.

:func:`ensure_validated` is the warm-validation hook both engines call
at the top of ``fit()``: the first time any fused tier could engage it
runs :func:`kernel_self_test` so a kernel rejection surfaces (and
disables that tier) BEFORE the first real training step compiles.
"""

from __future__ import annotations

import os
import threading
from typing import Callable, Dict, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops import pallas_kernels as pk


class Helper(NamedTuple):
    """One fused-implementation registration."""
    op: str                      # registry key (conv2d, lstm_step, ...)
    tier: str                    # kill-switch tier name (conv, lstm, ...)
    test_name: str               # key in the kernel_self_test() report
    self_test: Callable[[], None]  # small-shape compile+run validation


_ENV_TIER = {"conv": "DL4J_PALLAS_CONV", "lstm": "DL4J_PALLAS_LSTM",
             "dropout": "DL4J_PALLAS_DROPOUT", "xent": "DL4J_PALLAS_XENT",
             "flash": "DL4J_PALLAS_FLASH"}


def _registry():
    from deeplearning4j_tpu import monitor
    return monitor.get_registry()


def record_selection(op: str, fused: bool) -> None:
    """Meter one trace-time selection decision.  Counts move on TRACES
    (and un-jitted calls), not steps — a retrace-heavy run shows up here
    next to dl4j_compile_retraces_total."""
    try:
        if fused:
            c = _registry().counter(
                "dl4j_pallas_selected_total",
                "ops routed to a fused Pallas helper at trace time",
                labels=("op",))
        else:
            c = _registry().counter(
                "dl4j_pallas_fallback_total",
                "ops that took the dense XLA fallback at trace time",
                labels=("op",))
        c.labels(op=op).inc()
    except Exception:
        pass  # metering must never break a forward pass


def available(op: str) -> bool:
    """Is the fused tier for ``op`` eligible at all (before the per-call
    shape/dtype predicate)?  Order: global kill → runtime kill switch →
    per-tier env force → platform."""
    tier = _HELPERS[op].tier
    if os.environ.get("DL4J_PALLAS") == "0":  # dl4j: noqa[DL4J103] env kill switch read at trace time by design (fixed per process)
        return False
    if tier in pk._disabled:
        return False
    env = os.environ.get(_ENV_TIER[tier])  # dl4j: noqa[DL4J103] env kill switch read at trace time by design (fixed per process)
    if env == "0":
        return False
    if env == "1":
        return True
    return pk._on_tpu()


# ---------------------------------------------------------------------------
# Per-op selection wrappers — the call sites layers/ops route through.
# ---------------------------------------------------------------------------

def conv2d_bias_act(x, w, b, stride=(1, 1), pad=(0, 0), dilation=(1, 1),
                    border_mode: str = "truncate",
                    activation: Optional[str] = "identity"):
    """Conv + bias + activation for ConvolutionLayer.forward: one fused
    VMEM pass when the conv tier selects, else the dense
    conv-HLO → bias-add → activation chain (byte-identical to the
    pre-helper path)."""
    act = (activation or "identity").lower()
    if available("conv2d") and pk.conv_fused_supported(
            x.shape, w.shape, x.dtype, stride, dilation, act, pad,
            border_mode):
        record_selection("conv2d", True)
        return pk.fused_conv2d_bias_act(x, w, b, stride, pad, dilation,
                                        border_mode, act)
    record_selection("conv2d", False)
    from deeplearning4j_tpu.ops import activations as act_ops
    from deeplearning4j_tpu.ops import convolution as conv_ops
    return act_ops.get(act)(conv_ops.conv2d(x, w, b, stride, pad, dilation,
                                            border_mode))


def dropout(x, rate: float, rng):
    """Inverted dropout for Layer._maybe_dropout: in-kernel threshold
    mask when the dropout tier selects (no HBM mask tensor), else
    ops/normalization.dropout (jax.random.bernoulli).  Same keep
    distribution either way; the streams differ — see
    pallas_kernels.fused_threshold_dropout."""
    if available("dropout") and pk.dropout_fused_supported(x.shape, x.dtype):
        record_selection("dropout", True)
        return pk.fused_threshold_dropout(x, float(rate), rng)
    record_selection("dropout", False)
    from deeplearning4j_tpu.ops import normalization as norm_ops
    return norm_ops.dropout(x, rate, rng)


def _lstm_default_acts():
    from deeplearning4j_tpu.ops import activations as act_ops
    sig = {jax.nn.sigmoid, act_ops.sigmoid, act_ops.get("sigmoid")}
    tanh = {jnp.tanh, act_ops.tanh, act_ops.get("tanh")}
    return sig, tanh


def lstm_step_wanted(params: dict, x, gate_act, cell_act,
                     peephole: bool = True) -> bool:
    """Trace-time decision for ops/recurrent.lstm_scan: True routes the
    scan body through pallas_kernels.fused_lstm_step.  Fused supports
    the standard sigmoid/tanh peephole cell only — exotic gate
    activations keep the composable XLA cell."""
    sig, tanh = _lstm_default_acts()
    # every conjunct is a STATIC Python bool (shape/env/identity checks,
    # nothing traced) — selection is a trace-time decision by design
    ok = (peephole
          and all(k in params for k in ("pI", "pF", "pO", "RW"))
          and gate_act in sig and cell_act in tanh
          and available("lstm_step")
          and pk.lstm_fused_supported(x.shape[0], params["RW"].shape[0],
                                      x.dtype))
    record_selection("lstm_step", ok)
    return ok


def softmax_xent_wanted(n_rows: int, vocab: int) -> bool:
    """Trace-time decision for ops/losses.mcxent (shape/mask legality is
    the caller's check): fused pays off for wide-vocab row blocks where
    the saved HBM round-trips beat the kernel launch.
    ``DL4J_FUSED_XENT=1|0`` keeps its historical force-override role."""
    env = os.environ.get("DL4J_FUSED_XENT")  # dl4j: noqa[DL4J103] env flag read at trace time by design (fixed per process)
    if env == "0":
        ok = False
    elif env == "1":
        ok = True
    else:
        # static Python ints (shapes), nothing traced
        ok = (available("softmax_xent") and vocab >= 128
              and n_rows * vocab >= (1 << 16))
    record_selection("softmax_xent", ok)
    return ok


def attention_wanted(q) -> bool:
    """Trace-time decision for parallel/sequence.dense_attention: True
    routes the [B,H,T,D] core through the flash kernel (O(T·D) HBM both
    directions); the dense softmax path otherwise."""
    # static Python bools (env + shape-tuple comparisons), nothing traced
    ok = available("attention") and pk.flash_attention_supported(q)
    record_selection("attention", ok)
    return ok


# ---------------------------------------------------------------------------
# Warm validation — compile-check every registered helper once, through
# the real dispatch path, BEFORE anything perf-critical traces it cold.
# ---------------------------------------------------------------------------

def _selftest_flash():
    import numpy as np
    rng = np.random.default_rng(0)
    B, H, T, D = 1, 2, 256, 64
    q = jnp.asarray(rng.normal(size=(B, H, T, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, H, T, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, H, T, D)), jnp.float32)
    km = jnp.ones((B, T), jnp.float32)

    def loss(q, k, v):
        return pk.flash_attention(q, k, v, km, causal=True).sum()
    vg = jax.jit(jax.value_and_grad(loss, argnums=(0, 1, 2)))
    out, grads = vg(q, k, v)
    jax.block_until_ready(grads)
    if not bool(jnp.isfinite(out)):
        raise FloatingPointError("non-finite flash attention loss")


def _selftest_xent():
    import numpy as np
    rng = np.random.default_rng(0)
    N, V = 256, 512
    logits = jnp.asarray(rng.normal(size=(N, V)), jnp.float32)
    labels = jnp.asarray(np.eye(V, dtype=np.float32)[
        rng.integers(0, V, N)])

    def loss(lg):
        return pk.softmax_xent_rows(lg, labels).mean()
    vg = jax.jit(jax.value_and_grad(loss))
    out, g = vg(logits)
    jax.block_until_ready(g)
    if not bool(jnp.isfinite(out)):
        raise FloatingPointError("non-finite fused xent loss")


def _selftest_conv():
    import numpy as np
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(2, 3, 10, 10)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(8, 3, 3, 3)) * 0.2, jnp.float32)
    b = jnp.asarray(rng.normal(size=(8,)), jnp.float32)

    def loss(x, w, b):
        return jnp.sum(pk.fused_conv2d_bias_act(
            x, w, b, border_mode="same", activation="relu") ** 2)
    vg = jax.jit(jax.value_and_grad(loss, argnums=(0, 1, 2)))
    out, grads = vg(x, w, b)
    jax.block_until_ready(grads)
    if not bool(jnp.isfinite(out)):
        raise FloatingPointError("non-finite fused conv loss")


def _selftest_lstm():
    import numpy as np
    rng = np.random.default_rng(0)
    N, H = 4, 16
    zx = jnp.asarray(rng.normal(size=(N, 4 * H)), jnp.float32)
    h = jnp.asarray(rng.normal(size=(N, H)), jnp.float32)
    c = jnp.asarray(rng.normal(size=(N, H)), jnp.float32)
    rw = jnp.asarray(rng.normal(size=(H, 4 * H)) * 0.3, jnp.float32)
    p3 = jnp.asarray(rng.normal(size=(3, H)) * 0.1, jnp.float32)

    def loss(zx, h, c, rw, p3):
        c_new, h_new = pk.fused_lstm_step(zx, h, c, rw, p3)
        return jnp.sum(c_new ** 2) + jnp.sum(h_new ** 2)
    vg = jax.jit(jax.value_and_grad(loss, argnums=(0, 1, 2, 3, 4)))
    out, grads = vg(zx, h, c, rw, p3)
    jax.block_until_ready(grads)
    if not bool(jnp.isfinite(out)):
        raise FloatingPointError("non-finite fused lstm loss")


def _selftest_dropout():
    import numpy as np
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(64, 128)), jnp.float32)
    key = jax.random.PRNGKey(7)

    def loss(x):
        return jnp.sum(pk.fused_threshold_dropout(x, 0.8, key) ** 2)
    vg = jax.jit(jax.value_and_grad(loss))
    out, g = vg(x)
    jax.block_until_ready(g)
    if not bool(jnp.isfinite(out)):
        raise FloatingPointError("non-finite fused dropout loss")


_HELPERS: Dict[str, Helper] = {
    "conv2d": Helper("conv2d", "conv", "conv2d_bias_act", _selftest_conv),
    "lstm_step": Helper("lstm_step", "lstm", "lstm_step", _selftest_lstm),
    "dropout": Helper("dropout", "dropout", "dropout", _selftest_dropout),
    "softmax_xent": Helper("softmax_xent", "xent", "softmax_xent",
                           _selftest_xent),
    "attention": Helper("attention", "flash", "flash_attention",
                        _selftest_flash),
}

OPS = tuple(_HELPERS)


def helper_for(op: str) -> Helper:
    return _HELPERS[op]


def kernel_self_test(disable_on_error: bool = True,
                     ops: Optional[Sequence[str]] = None) -> dict:
    """Compile+run every registered helper once on small shapes through
    the REAL dispatch path (interpret only off-TPU).  On error the
    offending TIER is disabled via pallas_kernels.disable_kernels —
    callers silently fall back to dense XLA — and every verdict lands in
    ``dl4j_pallas_selftest_ok{op=}`` (1 passed / 0 failed) plus the
    per-tier ``dl4j_pallas_tier_disabled`` gauge."""
    results: dict = {}
    # snapshot BEFORE any test can flip a kill switch: the mode the
    # tests actually ran under, not the post-disable state
    interp = pk._interpret()
    try:
        gauge = _registry().gauge(
            "dl4j_pallas_selftest_ok",
            "last kernel_self_test verdict per helper (1 ok, 0 failed)",
            labels=("op",))
        tier_gauge = _registry().gauge(
            "dl4j_pallas_tier_disabled",
            "kernel-tier kill switch (1 = disabled)", labels=("tier",))
    except Exception:
        gauge = tier_gauge = None

    for op in (ops if ops is not None else OPS):
        h = _HELPERS[op]
        try:
            h.self_test()
            results[h.test_name] = "ok"
            ok = 1
        except Exception as e:  # Mosaic/XLA compile or runtime failure
            results[h.test_name] = f"error: {type(e).__name__}: {e}"[:300]
            ok = 0
            if disable_on_error:
                pk.disable_kernels(
                    f"{h.test_name} self-test failed: {e}", tier=h.tier)
        if gauge is not None:
            gauge.labels(op=op).set(ok)
        if tier_gauge is not None:
            tier_gauge.labels(tier=h.tier).set(
                1 if h.tier in pk._disabled else 0)
    results["interpret_mode"] = interp
    if pk._disabled:
        results["disabled"] = {t: r[:300] for t, r in pk._disabled.items()}
    with _WARM_LOCK:
        _WARM["done"] = True
        _WARM["result"] = results
    return results


_WARM: dict = {"done": False, "result": None}
_WARM_LOCK = threading.Lock()


def ensure_validated() -> dict:
    """Once-per-process warm validation, called at the top of both
    engines' ``fit()``: when any fused tier could engage (on TPU, or a
    tier force env is set) run :func:`kernel_self_test` over the
    ELIGIBLE helpers so a bad kernel flips its kill switch before the
    first real step compiles.  Off-TPU with nothing forced this is a
    cheap no-op — the fallback paths need no validation."""
    if _WARM["done"]:
        return _WARM["result"]
    with _WARM_LOCK:
        if _WARM["done"]:
            return _WARM["result"]
    eligible = [op for op in OPS if available(op)]
    if not eligible:
        with _WARM_LOCK:
            _WARM["done"] = True
            _WARM["result"] = {
                "skipped": "no fused tier eligible (off-TPU, nothing forced)"}
        return _WARM["result"]
    return kernel_self_test(ops=eligible)


def reset_validation() -> None:
    """Forget the cached warm-validation verdict (tests; or after
    flipping tier env switches mid-process)."""
    with _WARM_LOCK:
        _WARM["done"] = False
        _WARM["result"] = None


# ---------------------------------------------------------------------------
# Precision tiers (ISSUE 19) — reduced-precision compute/wire paths
# behind the SAME selection contract as the kernel tiers: conf opts in,
# env kill switches override, a failed parity self-test flips a runtime
# kill, and every decision is metered.  Call sites ask this registry
# (``precision_enabled``) instead of reading conf/env themselves.
# ---------------------------------------------------------------------------

class PrecisionTier(NamedTuple):
    tier: str                       # registry key
    env: str                        # kill-switch env var
    self_test: Callable[[], None]   # bounded-ε parity validation


def _precision_tiers() -> Dict[str, "PrecisionTier"]:
    from deeplearning4j_tpu.ops import quantize as q
    return {
        "bf16_train": PrecisionTier("bf16_train", "DL4J_PRECISION_BF16",
                                    lambda: None),  # ops/dtypes casts; no
        # quantization parity to validate — tests pin the ε-bound
        "int8_infer": PrecisionTier("int8_infer", "DL4J_PRECISION_INT8",
                                    q._selftest_int8_weights),
        "fp8_infer": PrecisionTier("fp8_infer", "DL4J_PRECISION_FP8",
                                   q._selftest_fp8_weights),
        "grad_quant": PrecisionTier("grad_quant", "DL4J_DIST_QUANT",
                                    q._selftest_grad_blocks),
    }


PRECISION_TIERS = ("bf16_train", "int8_infer", "fp8_infer", "grad_quant")


def precision_enabled(tier: str, configured: bool) -> bool:
    """Trace-time tier selection: does ``tier`` engage for a call site
    whose conf asks for ``configured``?  Order mirrors :func:`available`:
    global kill → runtime (self-test) kill → per-tier env (0 forces off,
    1 forces on) → the conf's word.  The decision is metered under
    ``dl4j_precision_selected_total{tier,on}``."""
    from deeplearning4j_tpu.ops import quantize as q
    tiers = _precision_tiers()
    if tier not in tiers:
        raise KeyError(f"unknown precision tier '{tier}' "
                       f"(known: {PRECISION_TIERS})")
    if os.environ.get("DL4J_PRECISION") == "0":  # dl4j: noqa[DL4J103] env kill switch read at trace time by design (fixed per process)
        on = False
    elif q.tier_disabled(tier):
        on = False
    else:
        env = os.environ.get(tiers[tier].env)  # dl4j: noqa[DL4J103] env kill switch read at trace time by design (fixed per process)
        if env is not None and env.lower() in ("0", "off", "false"):
            on = False
        elif env is not None and env.lower() in ("1", "on", "true"):
            on = True
        else:
            on = bool(configured)
    q.record_tier(tier, on)
    return on


_PRECISION_WARM: dict = {}


def ensure_precision_validated(tier: str) -> bool:
    """Once-per-process parity validation for one precision tier,
    called the first time that tier would engage: the tier's bounded-ε
    self-test runs, and a failure flips the runtime kill (the call site
    silently serves the fp32 path) instead of corrupting numerics.
    Returns True when the tier is usable."""
    from deeplearning4j_tpu.ops import quantize as q
    with _WARM_LOCK:
        if tier in _PRECISION_WARM:
            return _PRECISION_WARM[tier]
    info = _precision_tiers()[tier]
    ok = True
    try:
        info.self_test()
    except Exception as e:
        ok = False
        q.disable_tier(tier, f"self-test failed: {type(e).__name__}: {e}")
    try:
        _registry().gauge(
            "dl4j_precision_selftest_ok",
            "last precision-tier self-test verdict (1 ok, 0 failed)",
            labels=("tier",)).labels(tier=tier).set(1 if ok else 0)
    except Exception:
        pass
    with _WARM_LOCK:
        _PRECISION_WARM[tier] = ok
    return ok


def reset_precision_validation() -> None:
    """Tests only: forget cached tier verdicts and runtime kills."""
    from deeplearning4j_tpu.ops import quantize as q
    with _WARM_LOCK:
        _PRECISION_WARM.clear()
    q.reset_disabled()
