"""Precision policy for TPU execution.

The reference runs float32 (or double for gradient checks,
ref: gradientcheck/GradientCheckUtil.java:87-92).  On TPU the idiomatic
policy is: parameters and activations bfloat16-capable with float32
accumulation on the MXU (``preferred_element_type``), float32 master
params/updater state, and float64 only on the CPU backend for gradient
checks.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class Policy:
    """Mixed-precision policy applied by the training engine.

    ``cast_to_compute`` only downcasts float32 leaves: float64 (gradient
    checks) and integer leaves (embedding indices) pass through untouched,
    so the same jitted step serves f64-on-CPU numeric checks unchanged.
    """

    param_dtype: jnp.dtype = jnp.float32   # master copy of params
    compute_dtype: jnp.dtype = jnp.float32  # activations / matmul inputs
    accum_dtype: jnp.dtype = jnp.float32    # MXU accumulation / reductions

    @property
    def is_mixed(self) -> bool:
        return self.compute_dtype != self.param_dtype

    def cast_to_compute(self, tree):
        import jax
        if not self.is_mixed:
            return tree

        def cast(x):
            if hasattr(x, "dtype") and x.dtype == jnp.float32:
                return x.astype(self.compute_dtype)
            return x

        return jax.tree_util.tree_map(cast, tree)

    def cast_to_param(self, tree):
        """Upcast compute-dtype leaves back to the master dtype (carried
        state: BN running stats, RNN carries, MoE aux loss)."""
        import jax
        if not self.is_mixed:
            return tree

        def cast(x):
            if hasattr(x, "dtype") and x.dtype == self.compute_dtype:
                return x.astype(self.param_dtype)
            return x

        return jax.tree_util.tree_map(cast, tree)

    def cast_to_accum(self, x):
        if hasattr(x, "dtype") and x.dtype != self.accum_dtype \
                and jnp.issubdtype(x.dtype, jnp.floating) \
                and jnp.finfo(x.dtype).bits <= jnp.finfo(self.accum_dtype).bits:
            return x.astype(self.accum_dtype)
        return x


FLOAT32 = Policy()
# bfloat16 compute with f32 accumulation: the TPU-native fast path.
BF16 = Policy(param_dtype=jnp.float32, compute_dtype=jnp.bfloat16, accum_dtype=jnp.float32)
# float64 compute over f32 master storage: numeric-check precision,
# CPU backend only (TPU f64 is emulated).
FLOAT64 = Policy(param_dtype=jnp.float32, compute_dtype=jnp.float64, accum_dtype=jnp.float64)

_NAMED = {
    "float32": FLOAT32, "f32": FLOAT32, "fp32": FLOAT32, "float": FLOAT32,
    "bfloat16": BF16, "bf16": BF16, "mixed_bfloat16": BF16,
    # TPU has no fp16 compute path — 'half' maps to bf16 (same width,
    # wider exponent; the MXU-native low-precision format).
    "half": BF16, "float16": BF16, "f16": BF16,
    "float64": FLOAT64, "f64": FLOAT64, "double": FLOAT64,
}


def accum_dtype_for(dtype):
    """Output/accumulation dtype for a matmul/conv with inputs of `dtype`.

    bf16 inputs keep a bf16 result dtype: the TPU MXU accumulates bf16
    contractions in f32 internally, and widening the result via
    ``preferred_element_type`` breaks conv/dot transpose (VJP) rules'
    operand-dtype agreement (f32 cotangent × bf16 operand).  Wider floats
    (f32, f64 gradient checks) accumulate at their own width.
    """
    if dtype == jnp.bfloat16:
        return dtype
    return jnp.promote_types(dtype, jnp.float32)

# None = auto: bf16 compute on TPU (the MXU's native fast path), f32 elsewhere.
_default_policy: Policy | None = None


def set_default_policy(policy: Policy | None) -> None:
    """Override the ambient policy (None restores backend-auto selection)."""
    global _default_policy
    _default_policy = policy


def default_policy() -> Policy:
    if _default_policy is not None:
        return _default_policy
    # Capability probe, not backend-name string match: experimental PJRT
    # plugins (the tunneled 'axon' platform) can register TPU devices
    # under another backend name (ops/platform.py).
    from deeplearning4j_tpu.ops import platform
    return BF16 if platform.is_tpu() else FLOAT32


def resolve(name: str | None) -> Policy:
    """Map a config string ('float32' | 'bfloat16' | 'float64' | None=auto)
    to a Policy.  The engine calls this at trace-build time."""
    if name is None or name == "auto":
        policy = default_policy()
    else:
        try:
            policy = _NAMED[name.lower()]
        except KeyError:
            raise ValueError(f"Unknown precision '{name}'. "
                             f"Known: {sorted(_NAMED)} or 'auto'") from None
    if policy.is_mixed:
        # the bf16_train precision tier gates HERE, the single boundary
        # every engine resolves policies through: DL4J_PRECISION=0 /
        # DL4J_PRECISION_BF16=0 force the f32 path byte-identically to
        # an untiered conf (explicit bf16 AND the TPU auto default)
        from deeplearning4j_tpu.ops import helpers as _prec_helpers
        if not _prec_helpers.precision_enabled("bf16_train", True):
            return FLOAT32
    return policy
