"""Precision policy for TPU execution.

The reference runs float32 (or double for gradient checks,
ref: gradientcheck/GradientCheckUtil.java:87-92).  On TPU the idiomatic
policy is: parameters and activations bfloat16-capable with float32
accumulation on the MXU (``preferred_element_type``), float32 master
params/updater state, and float64 only on the CPU backend for gradient
checks.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class Policy:
    """Mixed-precision policy applied by the training engine."""

    param_dtype: jnp.dtype = jnp.float32   # master copy of params
    compute_dtype: jnp.dtype = jnp.float32  # activations / matmul inputs
    accum_dtype: jnp.dtype = jnp.float32    # MXU accumulation / reductions

    def cast_to_compute(self, tree):
        import jax
        return jax.tree_util.tree_map(
            lambda x: x.astype(self.compute_dtype) if hasattr(x, "astype") else x, tree
        )


FLOAT32 = Policy()
# bfloat16 compute with f32 accumulation: the TPU-native fast path.
BF16 = Policy(param_dtype=jnp.float32, compute_dtype=jnp.bfloat16, accum_dtype=jnp.float32)
# float64: gradient-check precision, CPU backend only (TPU f64 is emulated).
FLOAT64 = Policy(param_dtype=jnp.float64, compute_dtype=jnp.float64, accum_dtype=jnp.float64)

_default_policy = FLOAT32


def set_default_policy(policy: Policy) -> None:
    global _default_policy
    _default_policy = policy


def default_policy() -> Policy:
    return _default_policy
