"""Pallas TPU kernels for the hot ops where HLO fusion isn't enough
(SURVEY.md §7: the native-kernel tier; the reference's analog is the
fused libnd4j Aggregate ops + cuDNN helpers, §2.3/§2.10).

Two kernels:

* **flash_attention** — block-wise online-softmax attention.  The dense
  XLA path materializes the [B, H, T, T] score matrix in HBM; this
  kernel streams K/V blocks through VMEM with running max/denominator
  accumulation, so memory is O(T·D) and the MXU sees back-to-back
  (BQ×D)·(D×BK) tiles.  Used by parallel/sequence.dense_attention (and
  therefore the per-shard core of Ulysses sequence parallelism; the
  ring path keeps its own block-streaming body) on TPU; backward is a
  custom_vjp that recomputes with the standard einsum formulation (XLA
  fuses it well; forward is where the memory blow-up lived).

* **fused_softmax_xent** — softmax + cross-entropy + gradient in one
  VMEM pass per row block.  The char-RNN/output-layer hot op: avoids
  writing the [N, V] probability matrix to HBM twice (once for loss,
  once for grad).

Both run under ``interpret=True`` off-TPU so the same code is testable
on the CPU mesh (the reference's cuDNN-vs-builtin cross-check pattern,
SURVEY.md §4)."""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _interpret() -> bool:
    return not _on_tpu()


# ===========================================================================
# Flash attention
# ===========================================================================

def _flash_fwd_kernel(q_ref, k_ref, v_ref, mask_ref, out_ref, *,
                      block_k: int, causal: bool, scale: float):
    """One (batch*head, q-block) program: stream K/V blocks with online
    softmax.  Block shapes: q [BQ, D], k/v [T, D], mask [1, T]."""
    q = q_ref[...].astype(jnp.float32) * scale            # [BQ, D]
    T = k_ref.shape[0]
    BQ = q.shape[0]
    qi = pl.program_id(1)
    q_pos = qi * BQ + lax.broadcasted_iota(jnp.int32, (BQ, 1), 0)

    def body(s, carry):
        m, l, acc = carry
        k_blk = k_ref[pl.dslice(s * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.dslice(s * block_k, block_k), :].astype(jnp.float32)
        msk = mask_ref[0, pl.dslice(s * block_k, block_k)]
        scores = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)           # [BQ, BK]
        k_pos = s * block_k + lax.broadcasted_iota(jnp.int32, (1, block_k), 1)
        if causal:
            scores = jnp.where(q_pos >= k_pos, scores, NEG_INF)
        scores = jnp.where(msk[None, :] > 0, scores, NEG_INF)
        m_new = jnp.maximum(m, scores.max(axis=1, keepdims=True))
        alpha = jnp.exp(jnp.maximum(m - m_new, NEG_INF * 0.5))
        p = jnp.exp(scores - m_new)
        l_new = l * alpha + p.sum(axis=1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc

    D = q.shape[1]
    m0 = jnp.full((BQ, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((BQ, 1), jnp.float32)
    acc0 = jnp.zeros((BQ, D), jnp.float32)
    n_blocks = T // block_k
    if causal:
        # only blocks whose start <= this q block's end can contribute
        n_blocks_live = jnp.minimum(
            n_blocks, (qi + 1) * BQ // block_k + 1)
    else:
        n_blocks_live = n_blocks
    m, l, acc = lax.fori_loop(0, n_blocks_live, body, (m0, l0, acc0))
    out_ref[...] = (acc / jnp.maximum(l, 1e-30)).astype(out_ref.dtype)


def _flash_fwd(q, k, v, key_mask, *, causal: bool, scale: float,
               block_q: int = 128, block_k: int = 128):
    B, H, T, D = q.shape
    block_q = min(block_q, T)
    block_k = min(block_k, T)
    if T % block_q or T % block_k:
        raise ValueError(f"T={T} must divide block sizes "
                         f"({block_q}, {block_k})")
    qf = q.reshape(B * H, T, D)
    kf = k.reshape(B * H, T, D)
    vf = v.reshape(B * H, T, D)
    # mask per batch → per (batch, head) row, [BH, 1, T] blocks of [1, T]
    mask = jnp.broadcast_to(key_mask[:, None, :], (B, H, T)).reshape(
        B * H, 1, T).astype(jnp.float32)

    grid = (B * H, T // block_q)
    out = pl.pallas_call(
        functools.partial(_flash_fwd_kernel, block_k=block_k, causal=causal,
                          scale=scale),
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, T, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, T, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, 1, T), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
        interpret=_interpret(),
    )(qf, kf, vf, mask)
    return out.reshape(B, H, T, D)


def _dense_reference(q, k, v, key_mask, causal, scale):
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        T = q.shape[2]
        qi = jnp.arange(T)[:, None]
        ki = jnp.arange(T)[None, :]
        scores = jnp.where(qi >= ki, scores, NEG_INF)
    scores = jnp.where(key_mask[:, None, None, :] > 0, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def flash_attention(q, k, v, key_mask, causal: bool = False,
                    scale: Optional[float] = None):
    """Memory-efficient exact attention.  q,k,v: [B,H,T,D]; key_mask
    [B,T] (1=keep).  scale defaults to 1/sqrt(D)."""
    s = scale if scale is not None else 1.0 / (q.shape[-1] ** 0.5)
    return _flash_fwd(q, k, v, key_mask, causal=causal, scale=s)


def _flash_vjp_fwd(q, k, v, key_mask, causal, scale):
    out = flash_attention(q, k, v, key_mask, causal, scale)
    return out, (q, k, v, key_mask)


def _flash_vjp_bwd(causal, scale, res, g):
    q, k, v, key_mask = res
    s = scale if scale is not None else 1.0 / (q.shape[-1] ** 0.5)

    def f(q, k, v):
        return _dense_reference(q, k, v, key_mask, causal, s)

    _, vjp = jax.vjp(f, q, k, v)
    dq, dk, dv = vjp(g)
    return dq, dk, dv, None


flash_attention.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention_supported(q, block: int = 128) -> bool:
    """Shape gate: last dim must be lane-tileable and T divisible by the
    block size used; small shapes fall back to dense."""
    B, H, T, D = q.shape
    return T >= block and T % block == 0 and D % 128 == 0


# ===========================================================================
# Fused softmax cross-entropy
# ===========================================================================

def _softmax_xent_kernel(logits_ref, labels_ref, loss_ref, grad_ref):
    """One row-block: max-sub softmax, CE loss, (p - y) gradient — one
    HBM read of logits, one write of grad."""
    x = logits_ref[...].astype(jnp.float32)
    y = labels_ref[...].astype(jnp.float32)
    m = x.max(axis=1, keepdims=True)
    e = jnp.exp(x - m)
    z = e.sum(axis=1, keepdims=True)
    p = e / z
    logp = (x - m) - jnp.log(z)
    loss_ref[...] = -(y * logp).sum(axis=1, keepdims=True).astype(
        loss_ref.dtype)
    grad_ref[...] = (p - y).astype(grad_ref.dtype)


def fused_softmax_xent(logits, labels, block_rows: Optional[int] = None):
    """Returns (per_row_loss [N], dlogits [N, V]) in one fused pass.
    Rows are padded to the block size; the block height adapts to V so
    ~8 live br×V fp32 buffers (2 in, 1 out, temps) stay under the ~10 MB
    scoped-VMEM budget."""
    N, V = logits.shape
    if block_rows is None:
        budget = 10 << 20  # observed ~8 live br x V buffers in-kernel
        block_rows = max(8, min(256, budget // (V * 4 * 8) // 8 * 8))
    br = min(block_rows, max(8, N))
    pad = (-N) % br
    if pad:
        logits = jnp.concatenate(
            [logits, jnp.zeros((pad, V), logits.dtype)])
        labels = jnp.concatenate(
            [labels, jnp.zeros((pad, V), labels.dtype)])
    Np = logits.shape[0]
    loss, grad = pl.pallas_call(
        _softmax_xent_kernel,
        grid=(Np // br,),
        in_specs=[
            pl.BlockSpec((br, V), lambda i: (i, 0)),
            pl.BlockSpec((br, V), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((br, 1), lambda i: (i, 0)),
            pl.BlockSpec((br, V), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((Np, 1), logits.dtype),
            jax.ShapeDtypeStruct((Np, V), logits.dtype),
        ],
        interpret=_interpret(),
    )(logits, labels)
    return loss[:N, 0], grad[:N]
