"""Pallas TPU kernels for the hot ops where HLO fusion isn't enough
(SURVEY.md §7: the native-kernel tier; the reference's analog is the
fused libnd4j Aggregate ops + cuDNN helpers, §2.3/§2.10).

Two kernels:

* **flash_attention** — block-wise online-softmax attention.  The dense
  XLA path materializes the [B, H, T, T] score matrix in HBM; this
  kernel streams K/V blocks through VMEM with running max/denominator
  accumulation, so memory is O(T·D) and the MXU sees back-to-back
  (BQ×D)·(D×BK) tiles.  Used by parallel/sequence.dense_attention (and
  therefore the per-shard core of Ulysses sequence parallelism; the
  ring path keeps its own block-streaming body) on TPU.  Backward is
  blockwise too (FlashAttention-2 recomputation from the saved per-row
  logsumexp): dq and dk/dv kernels rebuild each [BQ, BK] probability
  tile on the fly, so TRAINING memory is O(T·D) as well — no dense
  [T, T] rematerialization.  Head dims that aren't multiples of the
  128-lane width are zero-padded outside the custom_vjp.

* **fused_softmax_xent** — softmax + cross-entropy + gradient in one
  VMEM pass per row block.  The char-RNN/output-layer hot op: avoids
  writing the [N, V] probability matrix to HBM twice (once for loss,
  once for grad).

Both run under ``interpret=True`` off-TPU so the same code is testable
on the CPU mesh (the reference's cuDNN-vs-builtin cross-check pattern,
SURVEY.md §4)."""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
NEG_INF = -1e30


# Runtime kill switches, PER KERNEL TIER: set by kernel_self_test() when
# a Mosaic compile fails on the real chip, so one bad kernel degrades to
# the dense XLA path without disabling the other, healthy one (the
# cuDNN-helper-with-builtin-fallback pattern, ref
# ConvolutionLayer.java:157-212).  DL4J_PALLAS=0 disables everything.
_disabled: dict = {}  # tier ("flash" | "xent") -> reason


def disable_kernels(reason: str, tier: Optional[str] = None) -> None:
    for t in ((tier,) if tier else ("flash", "xent")):
        _disabled[t] = reason


def _on_tpu() -> bool:
    # Device-capability probe (ops/platform.py), not a backend-name match:
    # the bench chip registers via the experimental 'axon' PJRT plugin and
    # a string compare against "tpu" would force interpret-mode emulation.
    import os
    if os.environ.get("DL4J_PALLAS") == "0":  # dl4j: noqa[DL4J103] env flag read at trace time by design (fixed per process)
        return False
    from deeplearning4j_tpu.ops import platform
    return platform.is_tpu()


def flash_available() -> bool:
    """Dispatch gate for callers of flash_attention (parallel/sequence)."""
    return "flash" not in _disabled and _on_tpu()


def xent_available() -> bool:
    """Dispatch gate for callers of softmax_xent_rows (ops/losses)."""
    return "xent" not in _disabled and _on_tpu()


def _interpret() -> bool:
    return not _on_tpu()


# ===========================================================================
# Flash attention — forward AND blockwise backward (O(T) HBM both ways).
#
# Forward saves per-row logsumexp; backward recomputes attention weights
# block-by-block from (q, k, lse) — the FlashAttention-2 recomputation
# scheme — so training never materializes the [T, T] score matrix.
# ===========================================================================

def _flash_fwd_kernel(q_ref, k_ref, v_ref, mask_ref, out_ref, lse_ref, *,
                      block_k: int, causal: bool, scale: float):
    """One (batch*head, q-block) program: stream K/V blocks with online
    softmax.  Block shapes: q [BQ, D], k/v [T, D], mask [1, T]; outputs
    out [BQ, D] and per-row logsumexp lse [BQ]."""
    q = q_ref[...].astype(jnp.float32) * scale            # [BQ, D]
    T = k_ref.shape[0]
    BQ = q.shape[0]
    qi = pl.program_id(1)
    q_pos = qi * BQ + lax.broadcasted_iota(jnp.int32, (BQ, 1), 0)

    def body(s, carry):
        m, l, acc = carry
        k_blk = k_ref[pl.dslice(s * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.dslice(s * block_k, block_k), :].astype(jnp.float32)
        msk = mask_ref[0, pl.dslice(s * block_k, block_k)]
        scores = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)           # [BQ, BK]
        k_pos = s * block_k + lax.broadcasted_iota(jnp.int32, (1, block_k), 1)
        if causal:
            scores = jnp.where(q_pos >= k_pos, scores, NEG_INF)
        scores = jnp.where(msk[None, :] > 0, scores, NEG_INF)
        m_new = jnp.maximum(m, scores.max(axis=1, keepdims=True))
        alpha = jnp.exp(jnp.maximum(m - m_new, NEG_INF * 0.5))
        p = jnp.exp(scores - m_new)
        l_new = l * alpha + p.sum(axis=1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc

    D = q.shape[1]
    m0 = jnp.full((BQ, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((BQ, 1), jnp.float32)
    acc0 = jnp.zeros((BQ, D), jnp.float32)
    n_blocks = T // block_k
    if causal:
        # only blocks whose start <= this q block's end can contribute
        n_blocks_live = jnp.minimum(
            n_blocks, (qi + 1) * BQ // block_k + 1)
    else:
        n_blocks_live = n_blocks
    m, l, acc = lax.fori_loop(0, n_blocks_live, body, (m0, l0, acc0))
    out_ref[...] = (acc / jnp.maximum(l, 1e-30)).astype(out_ref.dtype)
    # lse for backward recomputation; fully-masked rows get NEG_INF (the
    # backward kernels re-apply the mask so these rows contribute nothing)
    lse_ref[...] = jnp.where(
        l[:, 0] > 0, m[:, 0] + jnp.log(jnp.maximum(l[:, 0], 1e-30)),
        NEG_INF)


def _flash_fwd(q, k, v, key_mask, *, causal: bool, scale: float,
               block_q: int = 128, block_k: int = 128):
    B, H, T, D = q.shape
    block_q = min(block_q, T)
    block_k = min(block_k, T)
    if T % block_q or T % block_k:
        raise ValueError(f"T={T} must divide block sizes "
                         f"({block_q}, {block_k})")
    qf = q.reshape(B * H, T, D)
    kf = k.reshape(B * H, T, D)
    vf = v.reshape(B * H, T, D)
    # mask per batch → per (batch, head) row, [BH, 1, T] blocks of [1, T]
    mask = jnp.broadcast_to(key_mask[:, None, :], (B, H, T)).reshape(
        B * H, 1, T).astype(jnp.float32)

    grid = (B * H, T // block_q)
    out, lse = pl.pallas_call(
        functools.partial(_flash_fwd_kernel, block_k=block_k, causal=causal,
                          scale=scale),
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, T, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, T, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, 1, T), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_q), lambda b, i: (b, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, T), jnp.float32),
        ],
        interpret=_interpret(),
    )(qf, kf, vf, mask)
    return out.reshape(B, H, T, D), lse


def _recompute_p(q_blk, k_blk, lse_blk, mask_blk, q_pos, k_pos, causal,
                 scale):
    """Shared backward helper: rebuild the softmax probabilities for one
    (q-block, k-block) tile from saved logsumexp.  All f32."""
    s = jax.lax.dot_general(q_blk, k_blk, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    live = mask_blk > 0                                   # [1, BK]
    if causal:
        live = jnp.logical_and(live, q_pos >= k_pos)      # [BQ, BK]
    # where() (not exp of a masked score) so fully-masked rows whose lse
    # is NEG_INF don't produce exp(-inf - -inf) = 1
    p = jnp.exp(s - lse_blk[:, None])
    return jnp.where(live, p, 0.0)


def _flash_dq_kernel(q_ref, k_ref, v_ref, mask_ref, do_ref, lse_ref,
                     delta_ref, dq_ref, *, block_k: int, causal: bool,
                     scale: float):
    """dQ for one q block: stream K/V blocks, recompute p, accumulate
    dq += (p ∘ (dO·Vᵀ − δ)) · K · scale."""
    q = q_ref[...].astype(jnp.float32)                    # [BQ, D]
    do = do_ref[...].astype(jnp.float32)                  # [BQ, D]
    lse = lse_ref[...]                                    # [BQ]
    delta = delta_ref[...]                                # [BQ]
    T = k_ref.shape[0]
    BQ, D = q.shape
    qi = pl.program_id(1)
    q_pos = qi * BQ + lax.broadcasted_iota(jnp.int32, (BQ, 1), 0)

    def body(s, dq):
        k_blk = k_ref[pl.dslice(s * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.dslice(s * block_k, block_k), :].astype(jnp.float32)
        msk = mask_ref[0, pl.dslice(s * block_k, block_k)][None, :]
        k_pos = s * block_k + lax.broadcasted_iota(jnp.int32, (1, block_k), 1)
        p = _recompute_p(q, k_blk, lse, msk, q_pos, k_pos, causal, scale)
        dp = jax.lax.dot_general(do, v_blk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None])                    # [BQ, BK]
        return dq + jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    n_blocks = T // block_k
    if causal:
        n_blocks_live = jnp.minimum(n_blocks, (qi + 1) * BQ // block_k + 1)
    else:
        n_blocks_live = n_blocks
    dq = lax.fori_loop(0, n_blocks_live, body, jnp.zeros((BQ, D), jnp.float32))
    dq_ref[...] = (dq * scale).astype(dq_ref.dtype)


def _flash_dkv_kernel(q_ref, k_ref, v_ref, mask_ref, do_ref, lse_ref,
                      delta_ref, dk_ref, dv_ref, *, block_q: int,
                      causal: bool, scale: float):
    """dK/dV for one k block: stream Q/dO blocks, recompute pᵀ,
    dv += pᵀ·dO and dk += (p ∘ (dO·Vᵀ − δ))ᵀ·Q · scale."""
    k_blk = k_ref[...].astype(jnp.float32)                # [BK, D]
    v_blk = v_ref[...].astype(jnp.float32)                # [BK, D]
    msk = mask_ref[...]                                   # [1, BK]
    T = q_ref.shape[0]
    BK, D = k_blk.shape
    ki = pl.program_id(1)
    k_pos = ki * BK + lax.broadcasted_iota(jnp.int32, (1, BK), 1)

    def body(s, carry):
        dk, dv = carry
        q_blk = q_ref[pl.dslice(s * block_q, block_q), :].astype(jnp.float32)
        do_blk = do_ref[pl.dslice(s * block_q, block_q), :].astype(jnp.float32)
        lse_blk = lse_ref[pl.dslice(s * block_q, block_q)]
        delta_blk = delta_ref[pl.dslice(s * block_q, block_q)]
        q_pos = s * block_q + lax.broadcasted_iota(
            jnp.int32, (block_q, 1), 0)
        p = _recompute_p(q_blk, k_blk, lse_blk, msk, q_pos, k_pos, causal,
                         scale)                            # [BQ, BK]
        dv = dv + jax.lax.dot_general(
            p, do_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)            # [BK, D]
        dp = jax.lax.dot_general(do_blk, v_blk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_blk[:, None])
        dk = dk + jax.lax.dot_general(
            ds, q_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)            # [BK, D]
        return dk, dv

    n_blocks = T // block_q
    if causal:
        # q blocks strictly before this k block contribute nothing
        start = ki * BK // block_q
    else:
        start = 0
    dk, dv = lax.fori_loop(start, n_blocks, body,
                           (jnp.zeros((BK, D), jnp.float32),
                            jnp.zeros((BK, D), jnp.float32)))
    dk_ref[...] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[...] = dv.astype(dv_ref.dtype)


def _flash_bwd(q, k, v, key_mask, out, lse, g, *, causal: bool,
               scale: float, block_q: int = 128, block_k: int = 128):
    B, H, T, D = q.shape
    block_q = min(block_q, T)
    block_k = min(block_k, T)
    qf = q.reshape(B * H, T, D)
    kf = k.reshape(B * H, T, D)
    vf = v.reshape(B * H, T, D)
    dof = g.reshape(B * H, T, D)
    mask = jnp.broadcast_to(key_mask[:, None, :], (B, H, T)).reshape(
        B * H, 1, T).astype(jnp.float32)
    # δ_i = Σ_d dO·O — a cheap elementwise reduction XLA fuses on its own
    delta = jnp.sum(dof.astype(jnp.float32) *
                    out.reshape(B * H, T, D).astype(jnp.float32), axis=-1)

    common_specs = [
        pl.BlockSpec((None, T, D), lambda b, i: (b, 0, 0)),      # k or q
        pl.BlockSpec((None, T, D), lambda b, i: (b, 0, 0)),      # v
        pl.BlockSpec((None, 1, T), lambda b, i: (b, 0, 0)),      # mask
    ]
    dq = pl.pallas_call(
        functools.partial(_flash_dq_kernel, block_k=block_k, causal=causal,
                          scale=scale),
        grid=(B * H, T // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),  # q
            *common_specs,                                             # k,v,mask
            pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),  # do
            pl.BlockSpec((None, block_q), lambda b, i: (b, i)),        # lse
            pl.BlockSpec((None, block_q), lambda b, i: (b, i)),        # delta
        ],
        out_specs=pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
        interpret=_interpret(),
    )(qf, kf, vf, mask, dof, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_flash_dkv_kernel, block_q=block_q, causal=causal,
                          scale=scale),
        grid=(B * H, T // block_k),
        in_specs=[
            pl.BlockSpec((None, T, D), lambda b, i: (b, 0, 0)),        # q
            pl.BlockSpec((None, block_k, D), lambda b, i: (b, i, 0)),  # k
            pl.BlockSpec((None, block_k, D), lambda b, i: (b, i, 0)),  # v
            pl.BlockSpec((None, 1, block_k), lambda b, i: (b, 0, i)),  # mask
            pl.BlockSpec((None, T, D), lambda b, i: (b, 0, 0)),        # do
            pl.BlockSpec((None, T), lambda b, i: (b, 0)),              # lse
            pl.BlockSpec((None, T), lambda b, i: (b, 0)),              # delta
        ],
        out_specs=[
            pl.BlockSpec((None, block_k, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_k, D), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, T, D), k.dtype),
            jax.ShapeDtypeStruct((B * H, T, D), v.dtype),
        ],
        interpret=_interpret(),
    )(qf, kf, vf, mask, dof, lse, delta)
    return (dq.reshape(B, H, T, D), dk.reshape(B, H, T, D),
            dv.reshape(B, H, T, D))


def _dense_reference(q, k, v, key_mask, causal, scale):
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        T = q.shape[2]
        qi = jnp.arange(T)[:, None]
        ki = jnp.arange(T)[None, :]
        scores = jnp.where(qi >= ki, scores, NEG_INF)
    scores = jnp.where(key_mask[:, None, None, :] > 0, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _flash_core(q, k, v, key_mask, causal: bool, scale: float):
    out, _ = _flash_fwd(q, k, v, key_mask, causal=causal, scale=scale)
    return out


def _flash_vjp_fwd(q, k, v, key_mask, causal, scale):
    out, lse = _flash_fwd(q, k, v, key_mask, causal=causal, scale=scale)
    return out, (q, k, v, key_mask, out, lse)


def _flash_vjp_bwd(causal, scale, res, g):
    q, k, v, key_mask, out, lse = res
    dq, dk, dv = _flash_bwd(q, k, v, key_mask, out, lse, g,
                            causal=causal, scale=scale)
    return dq, dk, dv, None


_flash_core.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)

LANE = 128


def flash_attention(q, k, v, key_mask, causal: bool = False,
                    scale: Optional[float] = None):
    """Memory-efficient exact attention, differentiable with O(T) HBM in
    both directions.  q,k,v: [B,H,T,D]; key_mask [B,T] (1=keep).  scale
    defaults to 1/sqrt(D) of the ORIGINAL head dim; head dims that are
    not lane-tileable (64, 96, ...) are zero-padded to the next multiple
    of 128 — zero k/v columns change neither scores nor outputs, and the
    pad/slice sits outside the custom_vjp so gradients pass through."""
    D = q.shape[-1]
    s = scale if scale is not None else 1.0 / (D ** 0.5)
    pad = (-D) % LANE
    if pad:
        widths = [(0, 0)] * 3 + [(0, pad)]
        q = jnp.pad(q, widths)
        k = jnp.pad(k, widths)
        v = jnp.pad(v, widths)
    out = _flash_core(q, k, v, key_mask, causal, s)
    return out[..., :D] if pad else out


def flash_attention_supported(q, block: int = 128) -> bool:
    """Shape gate: T must tile into blocks; any head dim works (lane
    padding), but tiny ones waste >4x MXU lanes — fall back to dense."""
    B, H, T, D = q.shape
    return T >= block and T % block == 0 and D >= 32


# ===========================================================================
# Fused softmax cross-entropy
# ===========================================================================

def _softmax_xent_kernel(logits_ref, labels_ref, loss_ref, grad_ref):
    """One row-block: max-sub softmax, CE loss, (p·Σy − y) gradient — one
    HBM read of logits, one write of grad.  The Σy factor keeps the
    gradient exact for soft/unnormalized label rows (d/dx of Σy·logZ)."""
    x = logits_ref[...].astype(jnp.float32)
    y = labels_ref[...].astype(jnp.float32)
    m = x.max(axis=1, keepdims=True)
    e = jnp.exp(x - m)
    z = e.sum(axis=1, keepdims=True)
    p = e / z
    logp = (x - m) - jnp.log(z)
    loss_ref[...] = -(y * logp).sum(axis=1, keepdims=True).astype(
        loss_ref.dtype)
    grad_ref[...] = (p * y.sum(axis=1, keepdims=True) - y).astype(
        grad_ref.dtype)


def fused_softmax_xent(logits, labels, block_rows: Optional[int] = None):
    """Returns (per_row_loss [N], dlogits [N, V]) in one fused pass.
    Rows are padded to the block size; the block height adapts to V so
    ~8 live br×V fp32 buffers (2 in, 1 out, temps) stay under the ~10 MB
    scoped-VMEM budget."""
    N, V = logits.shape
    if block_rows is None:
        budget = 10 << 20  # observed ~8 live br x V buffers in-kernel
        block_rows = max(8, min(256, budget // (V * 4 * 8) // 8 * 8))
    br = min(block_rows, max(8, N))
    pad = (-N) % br
    if pad:
        logits = jnp.concatenate(
            [logits, jnp.zeros((pad, V), logits.dtype)])
        labels = jnp.concatenate(
            [labels, jnp.zeros((pad, V), labels.dtype)])
    Np = logits.shape[0]
    loss, grad = pl.pallas_call(
        _softmax_xent_kernel,
        grid=(Np // br,),
        in_specs=[
            pl.BlockSpec((br, V), lambda i: (i, 0)),
            pl.BlockSpec((br, V), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((br, 1), lambda i: (i, 0)),
            pl.BlockSpec((br, V), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((Np, 1), logits.dtype),
            jax.ShapeDtypeStruct((Np, V), logits.dtype),
        ],
        interpret=_interpret(),
    )(logits, labels)
    return loss[:N, 0], grad[:N]


@jax.custom_vjp
def softmax_xent_rows(logits, labels):
    """Differentiable fused softmax+CE: per-row loss [N] whose VJP reuses
    the gradient the forward kernel already produced — one VMEM pass
    total, vs softmax→log→mul→sum + their transposes on the dense path.
    Called from ops/losses.mcxent above the dispatch threshold."""
    loss, _ = fused_softmax_xent(logits, labels)
    return loss


def _sxr_fwd(logits, labels):
    loss, grad = fused_softmax_xent(logits, labels)
    return loss, grad


def _sxr_bwd(grad, g):
    # labels cotangent is never consumed (labels are data); zeros keeps the
    # vjp signature total and XLA dead-code-eliminates it
    return grad * g[:, None], jnp.zeros_like(grad)


softmax_xent_rows.defvjp(_sxr_fwd, _sxr_bwd)


def kernel_self_test(disable_on_error: bool = True) -> dict:
    """Compile+run each kernel once on small shapes through the REAL
    dispatch path (interpret only off-TPU) and report per-kernel status.

    Run this before anything perf-critical: the first Mosaic compile of
    a kernel otherwise happens cold inside whatever model hits it first,
    and a compile rejection there kills that whole run.  On error the
    offending tier is disabled via :func:`disable_kernels`, so callers
    (ops/losses.mcxent, parallel/sequence.dense_attention) silently fall
    back to the dense XLA path.  Ref analog: ConvolutionLayer's
    cuDNN-helper-try/builtin-fallback, ConvolutionLayer.java:67,157-212.
    """
    import numpy as _np
    results = {}
    # snapshot BEFORE any _try can flip a kill switch: the mode the tests
    # actually ran under, not the post-disable state
    interp = _interpret()

    def _try(name, tier, fn):
        try:
            fn()
            results[name] = "ok"
        except Exception as e:  # Mosaic/XLA compile or runtime failure
            results[name] = f"error: {type(e).__name__}: {e}"[:300]
            if disable_on_error:
                disable_kernels(f"{name} self-test failed: {e}", tier=tier)

    rng = _np.random.default_rng(0)

    def _flash():
        B, H, T, D = 1, 2, 256, 64
        q = jnp.asarray(rng.normal(size=(B, H, T, D)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(B, H, T, D)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(B, H, T, D)), jnp.float32)
        km = jnp.ones((B, T), jnp.float32)

        def loss(q, k, v):
            return flash_attention(q, k, v, km, causal=True).sum()
        vg = jax.jit(jax.value_and_grad(loss, argnums=(0, 1, 2)))
        out, grads = vg(q, k, v)
        jax.block_until_ready(grads)
        if not bool(jnp.isfinite(out)):
            raise FloatingPointError("non-finite flash attention loss")

    def _xent():
        N, V = 256, 512
        logits = jnp.asarray(rng.normal(size=(N, V)), jnp.float32)
        labels = jnp.asarray(_np.eye(V, dtype=_np.float32)[
            rng.integers(0, V, N)])

        def loss(lg):
            return softmax_xent_rows(lg, labels).mean()
        vg = jax.jit(jax.value_and_grad(loss))
        out, g = vg(logits)
        jax.block_until_ready(g)
        if not bool(jnp.isfinite(out)):
            raise FloatingPointError("non-finite fused xent loss")

    _try("flash_attention", "flash", _flash)
    _try("softmax_xent", "xent", _xent)
    results["interpret_mode"] = interp
    if _disabled:
        results["disabled"] = {t: r[:300] for t, r in _disabled.items()}
    return results
