"""Pallas TPU kernels for the hot ops where HLO fusion isn't enough
(SURVEY.md §7: the native-kernel tier; the reference's analog is the
fused libnd4j Aggregate ops + cuDNN helpers, §2.3/§2.10).

This module holds the KERNELS and their shape/dtype support predicates;
per-layer selection between a kernel and its dense XLA fallback lives in
``ops/helpers.py`` (the cuDNN-helper-selection tier: registry, per-tier
kill switches, warm validation, ``dl4j_pallas_*`` selection metrics).

Five kernels:

* **flash_attention** — block-wise online-softmax attention.  The dense
  XLA path materializes the [B, H, T, T] score matrix in HBM; this
  kernel streams K/V blocks through VMEM with running max/denominator
  accumulation, so memory is O(T·D) and the MXU sees back-to-back
  (BQ×D)·(D×BK) tiles.  Used by parallel/sequence.dense_attention (and
  therefore the per-shard core of Ulysses sequence parallelism; the
  ring path keeps its own block-streaming body) on TPU.  Backward is
  blockwise too (FlashAttention-2 recomputation from the saved per-row
  logsumexp): dq and dk/dv kernels rebuild each [BQ, BK] probability
  tile on the fly, so TRAINING memory is O(T·D) as well — no dense
  [T, T] rematerialization.  Head dims that aren't multiples of the
  128-lane width are zero-padded outside the custom_vjp.

* **fused_softmax_xent** — softmax + cross-entropy + gradient in one
  VMEM pass per row block.  The char-RNN/output-layer hot op: avoids
  writing the [N, V] probability matrix to HBM twice (once for loss,
  once for grad).

* **fused_conv2d_bias_act** — stride-1 2D convolution + bias + an
  elementwise activation in one VMEM pass (the Pallas analog of the
  reference's CudnnConvolutionHelper fused conv+bias+act path,
  ConvolutionLayer.java:171-212): the KH·KW input patches stream
  through the MXU as back-to-back [OH·OW, Cin]·[Cin, Cout] tiles and
  the bias-add + activation happen on the accumulator before it ever
  leaves VMEM — the unfused chain writes the conv result, the biased
  result AND the activated result to HBM.  Backward recomputes via the
  XLA reference (``jax.vjp``), so gradients are exactly the dense
  gradients.

* **fused_lstm_step** — one peephole-LSTM timestep (the scan body of
  ``ops/recurrent.lstm_scan``) in one VMEM pass: the [N, H]·[H, 4H]
  recurrent matmul plus ALL the elementwise gate math (2 peephole
  muls, 3 sigmoids, 2 tanhs, the cell/hidden updates) that XLA:TPU
  otherwise schedules as separate HLO ops per timestep.  Backward
  recomputes through the XLA reference cell.

* **fused_threshold_dropout** — inverted dropout whose mask is a
  counter-hash THRESHOLD test computed inside the kernel (the
  libnd4j-style threshold dropout): no [N, ...] mask tensor is ever
  materialized in HBM, and the backward pass re-derives the same mask
  from the seed instead of saving it.

All run under ``interpret=True`` off-TPU so the same code is testable
on the CPU mesh (the reference's cuDNN-vs-builtin cross-check pattern,
SURVEY.md §4)."""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
NEG_INF = -1e30


# Runtime kill switches, PER KERNEL TIER: set by kernel_self_test() when
# a Mosaic compile fails on the real chip, so one bad kernel degrades to
# the dense XLA path without disabling the other, healthy ones (the
# cuDNN-helper-with-builtin-fallback pattern, ref
# ConvolutionLayer.java:157-212).  DL4J_PALLAS=0 disables everything;
# per-tier state is read by ops/helpers.available().
ALL_TIERS = ("flash", "xent", "conv", "lstm", "dropout")
_disabled: dict = {}  # tier -> reason


def disable_kernels(reason: str, tier: Optional[str] = None) -> None:
    tiers = (tier,) if tier else ALL_TIERS
    for t in tiers:
        _disabled[t] = reason
    try:  # mirror the kill-switch state into the monitor registry
        from deeplearning4j_tpu import monitor
        g = monitor.get_registry().gauge(
            "dl4j_pallas_tier_disabled",
            "kernel-tier kill switch (1 = disabled)", labels=("tier",))
        for t in tiers:
            g.labels(tier=t).set(1)
    except Exception:
        pass  # metering must never break kernel dispatch


def _on_tpu() -> bool:
    # Device-capability probe (ops/platform.py), not a backend-name match:
    # the bench chip registers via the experimental 'axon' PJRT plugin and
    # a string compare against "tpu" would force interpret-mode emulation.
    import os
    if os.environ.get("DL4J_PALLAS") == "0":  # dl4j: noqa[DL4J103] env flag read at trace time by design (fixed per process)
        return False
    from deeplearning4j_tpu.ops import platform
    return platform.is_tpu()


def flash_available() -> bool:
    """Dispatch gate for callers of flash_attention (parallel/sequence)."""
    return "flash" not in _disabled and _on_tpu()


def xent_available() -> bool:
    """Dispatch gate for callers of softmax_xent_rows (ops/losses)."""
    return "xent" not in _disabled and _on_tpu()


def _interpret() -> bool:
    return not _on_tpu()


# ===========================================================================
# Flash attention — forward AND blockwise backward (O(T) HBM both ways).
#
# Forward saves per-row logsumexp; backward recomputes attention weights
# block-by-block from (q, k, lse) — the FlashAttention-2 recomputation
# scheme — so training never materializes the [T, T] score matrix.
# ===========================================================================

def _flash_fwd_kernel(q_ref, k_ref, v_ref, mask_ref, out_ref, lse_ref, *,
                      block_k: int, causal: bool, scale: float):
    """One (batch*head, q-block) program: stream K/V blocks with online
    softmax.  Block shapes: q [BQ, D], k/v [T, D], mask [1, T]; outputs
    out [BQ, D] and per-row logsumexp lse [BQ]."""
    q = q_ref[...].astype(jnp.float32) * scale            # [BQ, D]
    T = k_ref.shape[0]
    BQ = q.shape[0]
    qi = pl.program_id(1)
    q_pos = qi * BQ + lax.broadcasted_iota(jnp.int32, (BQ, 1), 0)

    def body(s, carry):
        m, l, acc = carry
        k_blk = k_ref[pl.dslice(s * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.dslice(s * block_k, block_k), :].astype(jnp.float32)
        msk = mask_ref[0, pl.dslice(s * block_k, block_k)]
        scores = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)           # [BQ, BK]
        k_pos = s * block_k + lax.broadcasted_iota(jnp.int32, (1, block_k), 1)
        if causal:
            scores = jnp.where(q_pos >= k_pos, scores, NEG_INF)
        scores = jnp.where(msk[None, :] > 0, scores, NEG_INF)
        m_new = jnp.maximum(m, scores.max(axis=1, keepdims=True))
        alpha = jnp.exp(jnp.maximum(m - m_new, NEG_INF * 0.5))
        p = jnp.exp(scores - m_new)
        l_new = l * alpha + p.sum(axis=1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc

    D = q.shape[1]
    m0 = jnp.full((BQ, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((BQ, 1), jnp.float32)
    acc0 = jnp.zeros((BQ, D), jnp.float32)
    n_blocks = T // block_k
    if causal:
        # only blocks whose start <= this q block's end can contribute
        n_blocks_live = jnp.minimum(
            n_blocks, (qi + 1) * BQ // block_k + 1)
    else:
        n_blocks_live = n_blocks
    m, l, acc = lax.fori_loop(0, n_blocks_live, body, (m0, l0, acc0))
    out_ref[...] = (acc / jnp.maximum(l, 1e-30)).astype(out_ref.dtype)
    # lse for backward recomputation; fully-masked rows get NEG_INF (the
    # backward kernels re-apply the mask so these rows contribute nothing)
    lse_ref[...] = jnp.where(
        l[:, 0] > 0, m[:, 0] + jnp.log(jnp.maximum(l[:, 0], 1e-30)),
        NEG_INF)


def _flash_fwd(q, k, v, key_mask, *, causal: bool, scale: float,
               block_q: int = 128, block_k: int = 128):
    B, H, T, D = q.shape
    block_q = min(block_q, T)
    block_k = min(block_k, T)
    if T % block_q or T % block_k:
        raise ValueError(f"T={T} must divide block sizes "
                         f"({block_q}, {block_k})")
    qf = q.reshape(B * H, T, D)
    kf = k.reshape(B * H, T, D)
    vf = v.reshape(B * H, T, D)
    # mask per batch → per (batch, head) row, [BH, 1, T] blocks of [1, T]
    mask = jnp.broadcast_to(key_mask[:, None, :], (B, H, T)).reshape(
        B * H, 1, T).astype(jnp.float32)

    grid = (B * H, T // block_q)
    out, lse = pl.pallas_call(
        functools.partial(_flash_fwd_kernel, block_k=block_k, causal=causal,
                          scale=scale),
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, T, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, T, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, 1, T), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_q), lambda b, i: (b, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, T), jnp.float32),
        ],
        interpret=_interpret(),
    )(qf, kf, vf, mask)
    return out.reshape(B, H, T, D), lse


def _recompute_p(q_blk, k_blk, lse_blk, mask_blk, q_pos, k_pos, causal,
                 scale):
    """Shared backward helper: rebuild the softmax probabilities for one
    (q-block, k-block) tile from saved logsumexp.  All f32."""
    s = jax.lax.dot_general(q_blk, k_blk, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    live = mask_blk > 0                                   # [1, BK]
    if causal:
        live = jnp.logical_and(live, q_pos >= k_pos)      # [BQ, BK]
    # Fully-masked/T-pad query rows carry lse = NEG_INF; exponentiating
    # s - (-1e30) would overflow to inf and 0·inf = NaN would leak into
    # dk/dv, so clamp the EXPONENT (not the result) to NEG_INF wherever
    # the tile is dead — exp then yields an exact 0.
    row_live = lse_blk[:, None] > NEG_INF * 0.5           # [BQ, 1]
    expo = jnp.where(jnp.logical_and(live, row_live),
                     s - lse_blk[:, None], NEG_INF)
    return jnp.exp(expo)


def _flash_dq_kernel(q_ref, k_ref, v_ref, mask_ref, do_ref, lse_ref,
                     delta_ref, dq_ref, *, block_k: int, causal: bool,
                     scale: float):
    """dQ for one q block: stream K/V blocks, recompute p, accumulate
    dq += (p ∘ (dO·Vᵀ − δ)) · K · scale."""
    q = q_ref[...].astype(jnp.float32)                    # [BQ, D]
    do = do_ref[...].astype(jnp.float32)                  # [BQ, D]
    lse = lse_ref[...]                                    # [BQ]
    delta = delta_ref[...]                                # [BQ]
    T = k_ref.shape[0]
    BQ, D = q.shape
    qi = pl.program_id(1)
    q_pos = qi * BQ + lax.broadcasted_iota(jnp.int32, (BQ, 1), 0)

    def body(s, dq):
        k_blk = k_ref[pl.dslice(s * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.dslice(s * block_k, block_k), :].astype(jnp.float32)
        msk = mask_ref[0, pl.dslice(s * block_k, block_k)][None, :]
        k_pos = s * block_k + lax.broadcasted_iota(jnp.int32, (1, block_k), 1)
        p = _recompute_p(q, k_blk, lse, msk, q_pos, k_pos, causal, scale)
        dp = jax.lax.dot_general(do, v_blk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None])                    # [BQ, BK]
        return dq + jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    n_blocks = T // block_k
    if causal:
        n_blocks_live = jnp.minimum(n_blocks, (qi + 1) * BQ // block_k + 1)
    else:
        n_blocks_live = n_blocks
    dq = lax.fori_loop(0, n_blocks_live, body, jnp.zeros((BQ, D), jnp.float32))
    dq_ref[...] = (dq * scale).astype(dq_ref.dtype)


def _flash_dkv_kernel(q_ref, k_ref, v_ref, mask_ref, do_ref, lse_ref,
                      delta_ref, dk_ref, dv_ref, *, block_q: int,
                      causal: bool, scale: float):
    """dK/dV for one k block: stream Q/dO blocks, recompute pᵀ,
    dv += pᵀ·dO and dk += (p ∘ (dO·Vᵀ − δ))ᵀ·Q · scale."""
    k_blk = k_ref[...].astype(jnp.float32)                # [BK, D]
    v_blk = v_ref[...].astype(jnp.float32)                # [BK, D]
    msk = mask_ref[...]                                   # [1, BK]
    T = q_ref.shape[0]
    BK, D = k_blk.shape
    ki = pl.program_id(1)
    k_pos = ki * BK + lax.broadcasted_iota(jnp.int32, (1, BK), 1)

    def body(s, carry):
        dk, dv = carry
        q_blk = q_ref[pl.dslice(s * block_q, block_q), :].astype(jnp.float32)
        do_blk = do_ref[pl.dslice(s * block_q, block_q), :].astype(jnp.float32)
        lse_blk = lse_ref[pl.dslice(s * block_q, block_q)]
        delta_blk = delta_ref[pl.dslice(s * block_q, block_q)]
        q_pos = s * block_q + lax.broadcasted_iota(
            jnp.int32, (block_q, 1), 0)
        p = _recompute_p(q_blk, k_blk, lse_blk, msk, q_pos, k_pos, causal,
                         scale)                            # [BQ, BK]
        dv = dv + jax.lax.dot_general(
            p, do_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)            # [BK, D]
        dp = jax.lax.dot_general(do_blk, v_blk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_blk[:, None])
        dk = dk + jax.lax.dot_general(
            ds, q_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)            # [BK, D]
        return dk, dv

    n_blocks = T // block_q
    if causal:
        # q blocks strictly before this k block contribute nothing
        start = ki * BK // block_q
    else:
        start = 0
    dk, dv = lax.fori_loop(start, n_blocks, body,
                           (jnp.zeros((BK, D), jnp.float32),
                            jnp.zeros((BK, D), jnp.float32)))
    dk_ref[...] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[...] = dv.astype(dv_ref.dtype)


def _flash_bwd(q, k, v, key_mask, out, lse, g, *, causal: bool,
               scale: float, block_q: int = 128, block_k: int = 128):
    B, H, T, D = q.shape
    block_q = min(block_q, T)
    block_k = min(block_k, T)
    qf = q.reshape(B * H, T, D)
    kf = k.reshape(B * H, T, D)
    vf = v.reshape(B * H, T, D)
    dof = g.reshape(B * H, T, D)
    mask = jnp.broadcast_to(key_mask[:, None, :], (B, H, T)).reshape(
        B * H, 1, T).astype(jnp.float32)
    # δ_i = Σ_d dO·O — a cheap elementwise reduction XLA fuses on its own
    delta = jnp.sum(dof.astype(jnp.float32) *
                    out.reshape(B * H, T, D).astype(jnp.float32), axis=-1)

    common_specs = [
        pl.BlockSpec((None, T, D), lambda b, i: (b, 0, 0)),      # k or q
        pl.BlockSpec((None, T, D), lambda b, i: (b, 0, 0)),      # v
        pl.BlockSpec((None, 1, T), lambda b, i: (b, 0, 0)),      # mask
    ]
    dq = pl.pallas_call(
        functools.partial(_flash_dq_kernel, block_k=block_k, causal=causal,
                          scale=scale),
        grid=(B * H, T // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),  # q
            *common_specs,                                             # k,v,mask
            pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),  # do
            pl.BlockSpec((None, block_q), lambda b, i: (b, i)),        # lse
            pl.BlockSpec((None, block_q), lambda b, i: (b, i)),        # delta
        ],
        out_specs=pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
        interpret=_interpret(),
    )(qf, kf, vf, mask, dof, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_flash_dkv_kernel, block_q=block_q, causal=causal,
                          scale=scale),
        grid=(B * H, T // block_k),
        in_specs=[
            pl.BlockSpec((None, T, D), lambda b, i: (b, 0, 0)),        # q
            pl.BlockSpec((None, block_k, D), lambda b, i: (b, i, 0)),  # k
            pl.BlockSpec((None, block_k, D), lambda b, i: (b, i, 0)),  # v
            pl.BlockSpec((None, 1, block_k), lambda b, i: (b, 0, i)),  # mask
            pl.BlockSpec((None, T, D), lambda b, i: (b, 0, 0)),        # do
            pl.BlockSpec((None, T), lambda b, i: (b, 0)),              # lse
            pl.BlockSpec((None, T), lambda b, i: (b, 0)),              # delta
        ],
        out_specs=[
            pl.BlockSpec((None, block_k, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_k, D), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, T, D), k.dtype),
            jax.ShapeDtypeStruct((B * H, T, D), v.dtype),
        ],
        interpret=_interpret(),
    )(qf, kf, vf, mask, dof, lse, delta)
    return (dq.reshape(B, H, T, D), dk.reshape(B, H, T, D),
            dv.reshape(B, H, T, D))


def _dense_reference(q, k, v, key_mask, causal, scale):
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        T = q.shape[2]
        qi = jnp.arange(T)[:, None]
        ki = jnp.arange(T)[None, :]
        scores = jnp.where(qi >= ki, scores, NEG_INF)
    scores = jnp.where(key_mask[:, None, None, :] > 0, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _flash_core(q, k, v, key_mask, causal: bool, scale: float):
    out, _ = _flash_fwd(q, k, v, key_mask, causal=causal, scale=scale)
    return out


def _flash_vjp_fwd(q, k, v, key_mask, causal, scale):
    out, lse = _flash_fwd(q, k, v, key_mask, causal=causal, scale=scale)
    return out, (q, k, v, key_mask, out, lse)


def _flash_vjp_bwd(causal, scale, res, g):
    q, k, v, key_mask, out, lse = res
    dq, dk, dv = _flash_bwd(q, k, v, key_mask, out, lse, g,
                            causal=causal, scale=scale)
    return dq, dk, dv, None


_flash_core.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)

LANE = 128


def flash_attention(q, k, v, key_mask, causal: bool = False,
                    scale: Optional[float] = None):
    """Memory-efficient exact attention, differentiable with O(T) HBM in
    both directions.  q,k,v: [B,H,T,D]; key_mask [B,T] (1=keep).  scale
    defaults to 1/sqrt(D) of the ORIGINAL head dim; head dims that are
    not lane-tileable (64, 96, ...) are zero-padded to the next multiple
    of 128, and sequence lengths that don't tile into the 128-row blocks
    (ragged/bucketed ladders) are zero-padded along T with a ZEROED key
    mask — masked keys change no real row, and fully-masked pad query
    rows come out 0 with lse = NEG_INF so the backward recomputation
    drops them (see _recompute_p).  Both pad/slice pairs sit outside the
    custom_vjp so gradients pass through."""
    D = q.shape[-1]
    T = q.shape[2]
    s = scale if scale is not None else 1.0 / (D ** 0.5)
    pad_d = (-D) % LANE
    pad_t = (-T) % LANE
    if pad_d:
        widths = [(0, 0)] * 3 + [(0, pad_d)]
        q = jnp.pad(q, widths)
        k = jnp.pad(k, widths)
        v = jnp.pad(v, widths)
    if pad_t:
        widths_t = [(0, 0), (0, 0), (0, pad_t), (0, 0)]
        q = jnp.pad(q, widths_t)
        k = jnp.pad(k, widths_t)
        v = jnp.pad(v, widths_t)
        key_mask = jnp.pad(key_mask, [(0, 0), (0, pad_t)])  # pads masked out
    out = _flash_core(q, k, v, key_mask, causal, s)
    return out[:, :, :T, :D] if (pad_d or pad_t) else out


def flash_attention_supported(q, block: int = 128) -> bool:
    """Shape gate: any T >= one block works (shorter-than-block pads
    would waste most of the MXU and dense attention is cheap there) —
    ragged/bucketed lengths that aren't 128-multiples are zero-padded
    inside flash_attention, like head-dim lane padding.  Any head dim
    works too (lane padding), but tiny ones waste >4x MXU lanes — fall
    back to dense."""
    B, H, T, D = q.shape
    return T >= block and D >= 32


# ===========================================================================
# Fused softmax cross-entropy
# ===========================================================================

def _softmax_xent_kernel(logits_ref, labels_ref, loss_ref, grad_ref):
    """One row-block: max-sub softmax, CE loss, (p·Σy − y) gradient — one
    HBM read of logits, one write of grad.  The Σy factor keeps the
    gradient exact for soft/unnormalized label rows (d/dx of Σy·logZ)."""
    x = logits_ref[...].astype(jnp.float32)
    y = labels_ref[...].astype(jnp.float32)
    m = x.max(axis=1, keepdims=True)
    e = jnp.exp(x - m)
    z = e.sum(axis=1, keepdims=True)
    p = e / z
    logp = (x - m) - jnp.log(z)
    loss_ref[...] = -(y * logp).sum(axis=1, keepdims=True).astype(
        loss_ref.dtype)
    grad_ref[...] = (p * y.sum(axis=1, keepdims=True) - y).astype(
        grad_ref.dtype)


def fused_softmax_xent(logits, labels, block_rows: Optional[int] = None):
    """Returns (per_row_loss [N], dlogits [N, V]) in one fused pass.
    Rows are padded to the block size; the block height adapts to V so
    ~8 live br×V fp32 buffers (2 in, 1 out, temps) stay under the ~10 MB
    scoped-VMEM budget."""
    N, V = logits.shape
    if block_rows is None:
        budget = 10 << 20  # observed ~8 live br x V buffers in-kernel
        block_rows = max(8, min(256, budget // (V * 4 * 8) // 8 * 8))
    br = min(block_rows, max(8, N))
    pad = (-N) % br
    if pad:
        logits = jnp.concatenate(
            [logits, jnp.zeros((pad, V), logits.dtype)])
        labels = jnp.concatenate(
            [labels, jnp.zeros((pad, V), labels.dtype)])
    Np = logits.shape[0]
    loss, grad = pl.pallas_call(
        _softmax_xent_kernel,
        grid=(Np // br,),
        in_specs=[
            pl.BlockSpec((br, V), lambda i: (i, 0)),
            pl.BlockSpec((br, V), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((br, 1), lambda i: (i, 0)),
            pl.BlockSpec((br, V), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((Np, 1), logits.dtype),
            jax.ShapeDtypeStruct((Np, V), logits.dtype),
        ],
        interpret=_interpret(),
    )(logits, labels)
    return loss[:N, 0], grad[:N]


@jax.custom_vjp
def softmax_xent_rows(logits, labels):
    """Differentiable fused softmax+CE: per-row loss [N] whose VJP reuses
    the gradient the forward kernel already produced — one VMEM pass
    total, vs softmax→log→mul→sum + their transposes on the dense path.
    Called from ops/losses.mcxent above the dispatch threshold."""
    loss, _ = fused_softmax_xent(logits, labels)
    return loss


def _sxr_fwd(logits, labels):
    loss, grad = fused_softmax_xent(logits, labels)
    return loss, grad


def _sxr_bwd(grad, g):
    # labels cotangent is never consumed (labels are data); zeros keeps the
    # vjp signature total and XLA dead-code-eliminates it
    return grad * g[:, None], jnp.zeros_like(grad)


softmax_xent_rows.defvjp(_sxr_fwd, _sxr_bwd)


# ===========================================================================
# Fused conv2d + bias + activation (stride-1) — the CudnnConvolutionHelper
# analog.  Forward is one Pallas pass (patch matmuls accumulate in VMEM,
# bias+activation applied before the single HBM write); backward
# recomputes through the XLA reference conv via jax.vjp, so training
# gradients are exactly the dense-path gradients.
# ===========================================================================

# Elementwise activations the kernel can fuse (cross-feature ones like
# softmax stay on the dense path).  Names resolve via ops/activations.
CONV_FUSED_ACTS = frozenset((
    "identity", "linear", "relu", "relu6", "tanh", "sigmoid", "leakyrelu",
    "elu", "gelu", "softplus", "softsign", "swish", "selu", "hardsigmoid",
    "hardtanh"))

_VMEM_BUDGET = 10 << 20  # bytes of live f32 buffers one program may hold


def _act_fn(name: str):
    from deeplearning4j_tpu.ops import activations as act_ops
    return act_ops.get(name or "identity")


def _conv_pads(H, W, KH, KW, pad, border_mode):
    """Explicit ((top, bottom), (left, right)) pads for stride 1.  'same'
    matches XLA's SAME split: total = K-1, low = (K-1)//2, high = rest
    (the extra row/col goes HIGH, as lax.conv does)."""
    if border_mode == "same":
        return (((KH - 1) // 2, KH - 1 - (KH - 1) // 2),
                ((KW - 1) // 2, KW - 1 - (KW - 1) // 2))
    return ((pad[0], pad[0]), (pad[1], pad[1]))


def _conv_bias_act_kernel(x_ref, w_ref, b_ref, out_ref, *, act_name: str):
    """One batch element: x [Hp, Wp, Cin] NHWC, w [KH, KW, Cin, Cout]
    HWIO, b [1, Cout] → out [OH, OW, Cout].  The KH·KW patch matmuls
    accumulate into one f32 VMEM buffer; bias + activation run on the
    accumulator before the single output write."""
    KH, KW, Cin, Cout = w_ref.shape
    OH, OW = out_ref.shape[0], out_ref.shape[1]
    acc = jnp.zeros((OH * OW, Cout), jnp.float32)
    for kh in range(KH):
        for kw in range(KW):
            patch = x_ref[pl.dslice(kh, OH), pl.dslice(kw, OW), :].astype(
                jnp.float32)                              # [OH, OW, Cin]
            acc = acc + jax.lax.dot_general(
                patch.reshape(OH * OW, Cin),
                w_ref[kh, kw].astype(jnp.float32),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
    y = acc + b_ref[...].astype(jnp.float32)              # [OH*OW, Cout]
    y = _act_fn(act_name)(y)
    out_ref[...] = y.reshape(OH, OW, Cout).astype(out_ref.dtype)


def _conv_forward(xp, w, b2, act_name: str):
    """xp [N, Hp, Wp, Cin] (already padded), w [KH, KW, Cin, Cout],
    b2 [1, Cout] → [N, OH, OW, Cout]."""
    N, Hp, Wp, Cin = xp.shape
    KH, KW, _, Cout = w.shape
    OH, OW = Hp - KH + 1, Wp - KW + 1
    return pl.pallas_call(
        functools.partial(_conv_bias_act_kernel, act_name=act_name),
        grid=(N,),
        in_specs=[
            pl.BlockSpec((None, Hp, Wp, Cin), lambda n: (n, 0, 0, 0)),
            pl.BlockSpec((KH, KW, Cin, Cout), lambda n: (0, 0, 0, 0)),
            pl.BlockSpec((1, Cout), lambda n: (0, 0)),
        ],
        out_specs=pl.BlockSpec((None, OH, OW, Cout), lambda n: (n, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((N, OH, OW, Cout), xp.dtype),
        interpret=_interpret(),
    )(xp, w, b2)


def _conv_ref_nhwc(xp, w, b2, act_name: str):
    """Dense XLA reference of the SAME math (stride-1 VALID conv on the
    pre-padded input) — the backward pass differentiates this."""
    y = lax.conv_general_dilated(
        xp, w, window_strides=(1, 1), padding=[(0, 0), (0, 0)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32)
    y = _act_fn(act_name)(y + b2.reshape(1, 1, 1, -1))
    return y.astype(xp.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _conv_core(xp, w, b2, act_name: str):
    return _conv_forward(xp, w, b2, act_name)


def _conv_vjp_fwd(xp, w, b2, act_name):
    return _conv_forward(xp, w, b2, act_name), (xp, w, b2)


def _conv_vjp_bwd(act_name, res, g):
    # Recompute-through-reference: one extra conv in the backward buys
    # gradients that are EXACTLY the dense path's (the cuDNN helpers
    # similarly run distinct bwd algorithms against the same math).
    xp, w, b2 = res
    _, vjp = jax.vjp(
        lambda x_, w_, b_: _conv_ref_nhwc(x_, w_, b_, act_name), xp, w, b2)
    return vjp(g)


_conv_core.defvjp(_conv_vjp_fwd, _conv_vjp_bwd)


def fused_conv2d_bias_act(x, w, b, stride=(1, 1), pad=(0, 0),
                          dilation=(1, 1), border_mode: str = "truncate",
                          activation: str = "identity"):
    """Fused conv+bias+activation, NCHW in / OIHW weights (the
    ops/convolution.conv2d surface plus the activation).  Only valid for
    shapes conv_fused_supported() accepts — callers go through
    ops/helpers.conv2d_bias_act, which falls back to the dense chain."""
    N, Cin, H, W = x.shape
    Cout, _, KH, KW = w.shape
    (pt, pb), (pl_, pr) = _conv_pads(H, W, KH, KW, pad, border_mode)
    xp = jnp.transpose(x, (0, 2, 3, 1))                   # NCHW → NHWC
    xp = jnp.pad(xp, ((0, 0), (pt, pb), (pl_, pr), (0, 0)))
    whwio = jnp.transpose(w, (2, 3, 1, 0))                # OIHW → HWIO
    y = _conv_core(xp, whwio, b.reshape(1, -1), activation)
    return jnp.transpose(y, (0, 3, 1, 2))                 # back to NCHW


def conv_fused_supported(x_shape, w_shape, dtype, stride=(1, 1),
                         dilation=(1, 1), activation: str = "identity",
                         pad=(0, 0), border_mode: str = "truncate") -> bool:
    """Support predicate for the conv tier: stride-1/dilation-1 convs
    with an elementwise activation whose whole working set (one image +
    the filter + accumulator + output) fits the per-program VMEM
    budget.  Strided/dilated convs and f64 (CPU gradient checks) take
    the dense path."""
    if len(x_shape) != 4 or len(w_shape) != 4:
        return False
    if tuple(stride) != (1, 1) or tuple(dilation) != (1, 1):
        return False
    if (activation or "identity").lower() not in CONV_FUSED_ACTS:
        return False
    if jnp.dtype(dtype) not in (jnp.dtype(jnp.float32),
                                jnp.dtype(jnp.bfloat16)):
        return False
    N, Cin, H, W = x_shape
    Cout, _, KH, KW = w_shape
    (pt, pb), (pl_, pr) = _conv_pads(H, W, KH, KW, pad, border_mode)
    Hp, Wp = H + pt + pb, W + pl_ + pr
    OH, OW = Hp - KH + 1, Wp - KW + 1
    if OH <= 0 or OW <= 0:
        return False
    live = (Hp * Wp * Cin + KH * KW * Cin * Cout
            + 2 * OH * OW * Cout + Cout) * 4
    return live <= _VMEM_BUDGET


# ===========================================================================
# Fused LSTM cell — one VMEM pass for the recurrent matmul + gate math
# inside the lax.scan of ops/recurrent.lstm_scan (the cudnnRNN analog).
# ===========================================================================

def _lstm_step_kernel(zx_ref, h_ref, c_ref, rw_ref, p_ref, c_out_ref,
                      h_out_ref):
    """zx [N, 4H] (pre-projected input), h/c [N, H], rw [H, 4H],
    p [3, H] (peephole pI/pF/pO rows) → (c_new, h_new) [N, H].  Gate
    layout [i, f, o, c] matches GravesLSTMParamInitializer."""
    zx = zx_ref[...].astype(jnp.float32)
    h = h_ref[...].astype(jnp.float32)
    c = c_ref[...].astype(jnp.float32)
    z = zx + jax.lax.dot_general(
        h, rw_ref[...].astype(jnp.float32), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)               # [N, 4H]
    H = c.shape[1]
    pI = p_ref[0, :].astype(jnp.float32)[None, :]
    pF = p_ref[1, :].astype(jnp.float32)[None, :]
    pO = p_ref[2, :].astype(jnp.float32)[None, :]
    i = jax.nn.sigmoid(z[:, :H] + c * pI)
    f = jax.nn.sigmoid(z[:, H:2 * H] + c * pF)
    g = jnp.tanh(z[:, 3 * H:])
    c_new = f * c + i * g
    o = jax.nn.sigmoid(z[:, 2 * H:3 * H] + c_new * pO)
    h_new = o * jnp.tanh(c_new)
    c_out_ref[...] = c_new.astype(c_out_ref.dtype)
    h_out_ref[...] = h_new.astype(h_out_ref.dtype)


def _lstm_forward(zx, h, c, rw, p3):
    N, H = c.shape
    return pl.pallas_call(
        _lstm_step_kernel,
        out_shape=[jax.ShapeDtypeStruct((N, H), c.dtype),
                   jax.ShapeDtypeStruct((N, H), h.dtype)],
        interpret=_interpret(),
    )(zx, h, c, rw, p3)


def _lstm_step_reference(zx, h, c, rw, p3):
    """XLA reference of the same cell math (matches
    ops/recurrent._lstm_cell_pre with sigmoid/tanh + peephole) — the
    backward pass differentiates this."""
    z = zx + h @ rw
    zi, zf, zo, zc = jnp.split(z, 4, axis=-1)
    i = jax.nn.sigmoid(zi + c * p3[0])
    f = jax.nn.sigmoid(zf + c * p3[1])
    g = jnp.tanh(zc)
    c_new = f * c + i * g
    o = jax.nn.sigmoid(zo + c_new * p3[2])
    h_new = o * jnp.tanh(c_new)
    return c_new, h_new


@jax.custom_vjp
def fused_lstm_step(zx, h, c, rw, p3):
    """One fused peephole-LSTM step: (c_new, h_new).  zx is the
    pre-projected input row (x_t·W + b hoisted outside the scan)."""
    return _lstm_forward(zx, h, c, rw, p3)


def _lstm_vjp_fwd(zx, h, c, rw, p3):
    return _lstm_forward(zx, h, c, rw, p3), (zx, h, c, rw, p3)


def _lstm_vjp_bwd(res, g):
    _, vjp = jax.vjp(_lstm_step_reference, *res)
    return vjp(g)


fused_lstm_step.defvjp(_lstm_vjp_fwd, _lstm_vjp_bwd)


def lstm_fused_supported(n: int, h: int, dtype) -> bool:
    """Support predicate for the lstm tier: f32/bf16, lane-friendly H,
    whole step (z + recurrent weights + states) within the VMEM
    budget.  The scan body is ONE program — no grid — so the batch must
    fit too."""
    if jnp.dtype(dtype) not in (jnp.dtype(jnp.float32),
                                jnp.dtype(jnp.bfloat16)):
        return False
    if h < 8 or h % 8:
        return False
    live = (2 * n * 4 * h + h * 4 * h + 3 * h + 4 * n * h) * 4
    return live <= _VMEM_BUDGET


# ===========================================================================
# In-kernel threshold dropout — mask generated from a counter hash inside
# the kernel; the [shape]-sized mask tensor never exists in HBM, and the
# backward pass regenerates it from the seed (same kernel applied to the
# cotangent) instead of saving it.
# ===========================================================================

_DROPOUT_WIDTH = 128     # lane width of the flattened 2-D view
_DROPOUT_ROWS = 1024     # row-block per program (512 KB f32)


def _mix32(idx, s0, s1):
    """xxhash-style avalanche over a uint32 element counter + two seed
    words.  Plain integer jnp ops, so the SAME function runs inside the
    Pallas kernel and on the XLA reference path — bit-identical masks."""
    h = idx * jnp.uint32(2654435761)
    h = h ^ s0
    h = h * jnp.uint32(2246822519)
    h = h ^ (h >> jnp.uint32(13))
    h = h ^ s1
    h = h * jnp.uint32(3266489917)
    h = h ^ (h >> jnp.uint32(16))
    return h


def _threshold_dropout_math(x, idx, s0, s1, rate: float):
    """keep iff the top-24 hash bits fall under round(rate·2²⁴) — an
    integer threshold test (P(keep) = rate to 2⁻²⁴), then inverted
    scaling, matching ops/normalization.dropout semantics (rate is the
    RETAIN probability)."""
    bits = _mix32(idx, s0, s1)
    thresh = jnp.uint32(int(round(rate * float(1 << 24))))  # dl4j: noqa[DL4J101] rate is a static Python float by contract (layer config), never traced
    keep = (bits >> jnp.uint32(8)) < thresh
    # multiply by the host-computed reciprocal (not x/rate): XLA folds a
    # divide-by-constant differently inside vs outside the kernel, and
    # the kernel-vs-reference parity contract is BIT-identical
    inv = jnp.float32(1.0 / float(rate))  # dl4j: noqa[DL4J101] rate is a static Python float by contract, never traced
    return jnp.where(keep, x.astype(jnp.float32) * inv,
                     jnp.float32(0.0)).astype(x.dtype)


def _dropout_kernel(x_ref, seed_ref, out_ref, *, rate: float):
    R, W = x_ref.shape
    r0 = pl.program_id(0) * R
    rows = (r0 + lax.broadcasted_iota(jnp.int32, (R, W), 0)).astype(
        jnp.uint32)
    cols = lax.broadcasted_iota(jnp.int32, (R, W), 1).astype(jnp.uint32)
    idx = rows * jnp.uint32(W) + cols                     # global element id
    out_ref[...] = _threshold_dropout_math(
        x_ref[...], idx, seed_ref[0, 0], seed_ref[0, 1], rate)


def _dropout_forward(x2d, seed, rate: float):
    R = x2d.shape[0]
    br = min(_DROPOUT_ROWS, R)
    return pl.pallas_call(
        functools.partial(_dropout_kernel, rate=rate),
        grid=(R // br,),
        in_specs=[
            pl.BlockSpec((br, _DROPOUT_WIDTH), lambda i: (i, 0)),
            pl.BlockSpec((1, 2), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((br, _DROPOUT_WIDTH), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct(x2d.shape, x2d.dtype),
        interpret=_interpret(),
    )(x2d, seed)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _dropout_core(x2d, seed, rate: float):
    return _dropout_forward(x2d, seed, rate)


def _dropout_vjp_fwd(x2d, seed, rate):
    # residual is the SEED alone — the mask is recomputed, never stored
    return _dropout_forward(x2d, seed, rate), seed


def _dropout_vjp_bwd(rate, seed, g):
    # d/dx of (mask ∘ x / rate) is the same masked scaling applied to g
    return _dropout_forward(g, seed, rate), None


_dropout_core.defvjp(_dropout_vjp_fwd, _dropout_vjp_bwd)


def _dropout_seed(rng):
    """Two uint32 seed words from a PRNG key (old-style uint32[2] raw
    keys and new typed keys both)."""
    kd = rng
    try:
        if jnp.issubdtype(rng.dtype, jax.dtypes.prng_key):
            kd = jax.random.key_data(rng)
    except (AttributeError, TypeError):
        pass
    kd = jnp.asarray(kd, jnp.uint32).reshape(-1)
    return jnp.stack([kd[0], kd[-1]]).reshape(1, 2)


def fused_threshold_dropout(x, rate: float, rng):
    """Inverted dropout with the mask THRESHOLD test fused into the
    kernel.  rate is the RETAIN probability (ops/normalization.dropout
    parity).  NOTE: draws from a different (hash-counter) stream than
    jax.random.bernoulli — same distribution, different masks — so the
    dense fallback is distribution-equivalent, not mask-identical;
    threshold_dropout_reference() is the bit-exact XLA reference."""
    if rate >= 1.0 or rate <= 0.0:
        return x
    n = x.size
    rows = -(-n // _DROPOUT_WIDTH)
    br = min(_DROPOUT_ROWS, max(8, rows))
    rows_p = -(-rows // br) * br
    flat = x.reshape(-1)
    pad = rows_p * _DROPOUT_WIDTH - n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), x.dtype)])
    out = _dropout_core(flat.reshape(rows_p, _DROPOUT_WIDTH),
                        _dropout_seed(rng), float(rate))  # dl4j: noqa[DL4J101] rate is a static Python float (nondiff custom_vjp arg), never traced
    return out.reshape(-1)[:n].reshape(x.shape)


def threshold_dropout_reference(x, rate: float, rng):
    """Same math on the dense XLA path (global element counter = the
    kernel's row·width+col) — bit-identical to the kernel output; the
    parity tests pin this."""
    if rate >= 1.0 or rate <= 0.0:
        return x
    seed = _dropout_seed(rng)
    idx = jnp.arange(x.size, dtype=jnp.uint32).reshape(x.shape)
    return _threshold_dropout_math(x, idx, seed[0, 0], seed[0, 1],
                                   float(rate))  # dl4j: noqa[DL4J101] rate is a static Python float by contract, never traced


def dropout_fused_supported(shape, dtype) -> bool:
    """Support predicate for the dropout tier: float tensors big enough
    that skipping the HBM mask round-trip beats the kernel launch."""
    if jnp.dtype(dtype) not in (jnp.dtype(jnp.float32),
                                jnp.dtype(jnp.bfloat16)):
        return False
    n = 1
    for d in shape:
        n *= int(d)
    return n >= (1 << 12)


def kernel_self_test(disable_on_error: bool = True) -> dict:
    """Compile+run each registered kernel once on small shapes through
    the REAL dispatch path (interpret only off-TPU) and report
    per-kernel status — delegates to the helper-selection tier
    (ops/helpers.kernel_self_test), which covers EVERY registered
    helper, disables a failing tier via :func:`disable_kernels` and
    mirrors verdicts into ``dl4j_pallas_selftest_ok``.  Ref analog:
    ConvolutionLayer's cuDNN-helper-try/builtin-fallback,
    ConvolutionLayer.java:67,157-212."""
    from deeplearning4j_tpu.ops import helpers
    return helpers.kernel_self_test(disable_on_error=disable_on_error)
