"""High-level wrappers over the native IO library, with pure-Python
fallbacks (the cuDNN-helper pattern of the reference inverted: native is
the optional fast path, Python the always-working baseline —
ref: nn/layers/convolution/ConvolutionLayer.java:67 helper loading)."""

from __future__ import annotations

import ctypes
import io as _io
from pathlib import Path
from typing import List, Optional, Sequence, Union

import numpy as np

from deeplearning4j_tpu import native as _native


def read_csv_matrix(path: Union[str, Path], delimiter: str = ",",
                    skip_lines: int = 0) -> np.ndarray:
    """Numeric CSV → float32 [rows, cols]; non-numeric cells become NaN.
    Native fast path via csv_dims/csv_read."""
    lib = _native.get_lib()
    p = str(path).encode()
    if lib is not None:
        rows, cols = ctypes.c_long(), ctypes.c_long()
        if lib.csv_dims(p, delimiter.encode(), skip_lines,
                        ctypes.byref(rows), ctypes.byref(cols)) == 0:
            out = np.empty((rows.value, cols.value), np.float32)
            got = lib.csv_read(
                p, delimiter.encode(), skip_lines,
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                rows.value, cols.value)
            if got == rows.value:
                return out
    # fallback
    rows_py: List[List[float]] = []
    with open(path) as f:
        for i, line in enumerate(f):
            if i < skip_lines or not line.strip():
                continue
            vals = []
            for c in line.rstrip("\n").split(delimiter):
                try:
                    # '_' separators are a Python-literal-ism, not CSV;
                    # reject so native and fallback parses agree
                    vals.append(float("nan") if "_" in c else float(c))
                except ValueError:
                    vals.append(float("nan"))
            rows_py.append(vals)
    width = max((len(r) for r in rows_py), default=0)
    out = np.full((len(rows_py), width), np.nan, np.float32)
    for i, r in enumerate(rows_py):
        out[i, :len(r)] = r
    return out


def read_idx(path: Union[str, Path]) -> np.ndarray:
    """IDX (MNIST) file → float32 ndarray.  Native big-endian parse."""
    lib = _native.get_lib()
    p = str(path).encode()
    if lib is not None:
        ndim = ctypes.c_long()
        dims = (ctypes.c_long * 4)()
        dtype_code = lib.idx_dims(p, ctypes.byref(ndim), dims)
        if dtype_code in (0x08, 0x0D):
            shape = tuple(dims[i] for i in range(ndim.value))
            count = int(np.prod(shape)) if shape else 0
            out = np.empty(count, np.float32)
            if lib.idx_read(
                    p, out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                    count) == 0:
                return out.reshape(shape)
    # fallback (pure numpy)
    with open(path, "rb") as f:
        magic = f.read(4)
        nd = magic[3]
        shape = tuple(int.from_bytes(f.read(4), "big") for _ in range(nd))
        code = magic[2]
        dt = np.dtype(">u1") if code == 0x08 else np.dtype(">f4")
        data = np.frombuffer(f.read(), dt, count=int(np.prod(shape)))
    return data.reshape(shape).astype(np.float32)


class NativeFilePrefetcher:
    """Threaded read-ahead over a list of files — the
    AsyncDataSetIterator prefetch queue realized natively
    (ref: AsyncDataSetIterator.java:39-127).  Yields (path, bytes) in
    submission order; with no native lib, falls back to a Python
    ThreadPoolExecutor pipeline with the same bounded-buffer behavior."""

    def __init__(self, paths: Sequence[Union[str, Path]],
                 capacity: int = 4, n_threads: int = 2):
        self.paths = [str(p) for p in paths]
        self.capacity = capacity
        self.n_threads = n_threads

    def __iter__(self):
        lib = _native.get_lib()
        if lib is not None:
            arr = (ctypes.c_char_p * len(self.paths))(
                *[p.encode() for p in self.paths])
            handle = lib.prefetch_open(arr, len(self.paths), self.capacity,
                                       self.n_threads)
            if handle:
                try:
                    import os
                    i = 0
                    while True:
                        data = ctypes.c_char_p()
                        n = lib.prefetch_next(handle, ctypes.byref(data))
                        if n < 0:
                            break
                        blob = ctypes.string_at(data, n)
                        # the C reader signals failure with an empty blob;
                        # distinguish it from a genuinely empty file so the
                        # native path raises like the Python fallback does
                        if not blob:
                            p = self.paths[i]
                            if not os.path.exists(p) or os.path.getsize(p):
                                raise FileNotFoundError(
                                    f"unreadable file in prefetch: {p}")
                        yield self.paths[i], blob
                        i += 1
                    return
                finally:
                    lib.prefetch_close(handle)
        # Python fallback
        from concurrent.futures import ThreadPoolExecutor
        with ThreadPoolExecutor(max_workers=self.n_threads) as ex:
            futs = []
            idx = 0
            for i, p in enumerate(self.paths):
                futs.append(ex.submit(Path(p).read_bytes))
                if len(futs) - idx > self.capacity:
                    yield self.paths[idx], futs[idx].result()
                    futs[idx] = None
                    idx += 1
            while idx < len(futs):
                yield self.paths[idx], futs[idx].result()
                futs[idx] = None
                idx += 1


def skipgram_pairs(ids: np.ndarray, window: int,
                   reduced: np.ndarray) -> "tuple[np.ndarray, np.ndarray]":
    """(context, center) index pairs for one sentence with per-center
    reduced windows — word2vec's windowing hot loop, natively (sg_pairs
    in dl4j_io.cc; the libnd4j AggregateSkipGram host-prep role) with a
    vectorized numpy fallback.  Self-positions and equal-id pairs are
    skipped, matching the reference's skip-gram trainer."""
    ids = np.ascontiguousarray(ids, np.int32)
    reduced = np.ascontiguousarray(reduced, np.int32)
    n = ids.size
    if n == 0 or window <= 0:
        return (np.empty(0, np.int32), np.empty(0, np.int32))
    lib = _native.get_lib()
    if lib is not None and hasattr(lib, "sg_pairs"):
        cap = int(n) * 2 * window
        ctx = np.empty(cap, np.int32)
        ctr = np.empty(cap, np.int32)
        got = lib.sg_pairs(
            ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int)), n, window,
            reduced.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
            ctx.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
            ctr.ctypes.data_as(ctypes.POINTER(ctypes.c_int)))
        return ctx[:got], ctr[:got]
    # numpy fallback: offsets grid + validity mask
    offs = np.concatenate([np.arange(-window, 0), np.arange(1, window + 1)])
    pos = np.arange(n)[:, None] + offs[None, :]            # [n, 2w]
    w_eff = (window - reduced)[:, None]
    valid = (pos >= 0) & (pos < n) & (np.abs(offs)[None, :] <= w_eff)
    pos_c = np.clip(pos, 0, n - 1)
    ctx = ids[pos_c]
    ctr = np.broadcast_to(ids[:, None], ctx.shape)
    valid &= ctx != ctr
    return ctx[valid].astype(np.int32), ctr[valid].astype(np.int32)


def load_npz_dataset_bytes(blob: bytes):
    """Decode an exported .npz DataSet blob (scaleout.data format)."""
    from deeplearning4j_tpu.datasets.dataset import DataSet
    with np.load(_io.BytesIO(blob)) as z:
        return DataSet(z["features"], z["labels"],
                       z["features_mask"] if "features_mask" in z else None,
                       z["labels_mask"] if "labels_mask" in z else None)
