"""ctypes bindings for the native host-runtime library (native/dl4j_io.cc)
— the TPU framework's equivalent of the reference's native tier
(SURVEY.md §2.3/§2.10: libnd4j + JavaCPP bridges; here the math tier is
XLA/PJRT, and the native surface is the host data path + staging arena).

The library builds on first import (g++ is baked into the image); every
consumer has a pure-Python fallback, so a missing/failed build degrades
gracefully — ``available()`` reports which path is active."""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
from pathlib import Path
from typing import Optional

log = logging.getLogger(__name__)

_LIB_PATH = Path(__file__).parent / "libdl4j_io.so"
_SRC_DIR = Path(__file__).parent.parent.parent / "native"
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    src = _SRC_DIR / "dl4j_io.cc"
    if not src.exists():
        return False
    try:
        subprocess.run(
            ["g++", "-O3", "-std=c++17", "-fPIC", "-Wall", "-pthread",
             "-shared", "-o", str(_LIB_PATH), str(src)],
            check=True, capture_output=True, timeout=120)
        return True
    except Exception as e:  # no compiler / build error → Python fallback
        log.warning("native build failed (%s); using Python fallbacks", e)
        return False


def get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if not _LIB_PATH.exists() or (
            (_SRC_DIR / "dl4j_io.cc").exists()
            and (_SRC_DIR / "dl4j_io.cc").stat().st_mtime
            > _LIB_PATH.stat().st_mtime):
        if not _build() and not _LIB_PATH.exists():
            return None
    try:
        lib = ctypes.CDLL(str(_LIB_PATH))
    except OSError as e:
        log.warning("native load failed (%s); using Python fallbacks", e)
        return None
    c_char_pp = ctypes.POINTER(ctypes.c_char_p)
    lib.csv_dims.argtypes = [ctypes.c_char_p, ctypes.c_char, ctypes.c_int,
                             ctypes.POINTER(ctypes.c_long),
                             ctypes.POINTER(ctypes.c_long)]
    lib.csv_dims.restype = ctypes.c_int
    lib.csv_read.argtypes = [ctypes.c_char_p, ctypes.c_char, ctypes.c_int,
                             ctypes.POINTER(ctypes.c_float), ctypes.c_long,
                             ctypes.c_long]
    lib.csv_read.restype = ctypes.c_int
    lib.idx_dims.argtypes = [ctypes.c_char_p,
                             ctypes.POINTER(ctypes.c_long),
                             ctypes.POINTER(ctypes.c_long)]
    lib.idx_dims.restype = ctypes.c_int
    lib.idx_read.argtypes = [ctypes.c_char_p,
                             ctypes.POINTER(ctypes.c_float), ctypes.c_long]
    lib.idx_read.restype = ctypes.c_int
    lib.prefetch_open.argtypes = [c_char_pp, ctypes.c_long, ctypes.c_long,
                                  ctypes.c_long]
    lib.prefetch_open.restype = ctypes.c_void_p
    lib.prefetch_next.argtypes = [ctypes.c_void_p,
                                  ctypes.POINTER(ctypes.c_char_p)]
    lib.prefetch_next.restype = ctypes.c_long
    lib.prefetch_close.argtypes = [ctypes.c_void_p]
    lib.arena_create.argtypes = [ctypes.c_long]
    lib.arena_create.restype = ctypes.c_void_p
    lib.arena_alloc.argtypes = [ctypes.c_void_p, ctypes.c_long]
    lib.arena_alloc.restype = ctypes.c_void_p
    lib.arena_reset.argtypes = [ctypes.c_void_p]
    lib.arena_used.argtypes = [ctypes.c_void_p]
    lib.arena_used.restype = ctypes.c_long
    lib.arena_destroy.argtypes = [ctypes.c_void_p]
    try:
        # newer symbol — a stale pre-rebuild .so must not break the
        # graceful-fallback contract for every OTHER native consumer
        c_int_p = ctypes.POINTER(ctypes.c_int)
        lib.sg_pairs.argtypes = [c_int_p, ctypes.c_long, ctypes.c_int,
                                 c_int_p, c_int_p, c_int_p]
        lib.sg_pairs.restype = ctypes.c_long
    except AttributeError:
        log.warning("libdl4j_io.so predates sg_pairs; word2vec windowing "
                    "uses the numpy fallback")
    _lib = lib
    return _lib


def available() -> bool:
    return get_lib() is not None


from deeplearning4j_tpu.native.io import (  # noqa: E402
    NativeFilePrefetcher, read_csv_matrix, read_idx)
from deeplearning4j_tpu.native.workspace import MemoryWorkspace  # noqa: E402

__all__ = ["available", "get_lib", "NativeFilePrefetcher",
           "read_csv_matrix", "read_idx", "MemoryWorkspace"]
