"""MemoryWorkspace — scope-based host staging arena
(ref: nd4j MemoryWorkspace / WorkspaceConfiguration consumed at
MultiLayerNetwork.java:117-120,1026-1032; modes NONE/SINGLE/SEPARATE in
nn/conf/WorkspaceMode.java).

On TPU the *device* side of workspaces is XLA buffer donation inside the
jitted step (no user-visible arena needed — SURVEY.md §2.10); the *host*
side — reusing pinned staging memory across batches instead of
malloc/free churn in the input pipeline — is what this arena provides,
backed by the native 64-byte-aligned bump allocator."""

from __future__ import annotations

import ctypes
from typing import Optional, Tuple

import numpy as np

from deeplearning4j_tpu import native as _native


class MemoryWorkspace:
    """``with MemoryWorkspace(bytes) as ws: buf = ws.alloc(shape, dtype)``
    — buffers are valid until the scope resets (loop-scoped reuse, the
    reference's ScopedOut semantics).  Falls back to plain numpy
    allocation when the native library is unavailable."""

    def __init__(self, size_bytes: int = 64 << 20):
        self.size_bytes = size_bytes
        self._handle = None
        self._lib = _native.get_lib()

    def __enter__(self) -> "MemoryWorkspace":
        if self._lib is not None:
            self._handle = self._lib.arena_create(self.size_bytes)
        return self

    def __exit__(self, *exc) -> bool:
        if self._handle:
            self._lib.arena_destroy(self._handle)
            self._handle = None
        return False

    # -- allocation ---------------------------------------------------------
    def alloc(self, shape: Tuple[int, ...], dtype=np.float32) -> np.ndarray:
        """64B-aligned array living in the arena (native) or heap
        (fallback).  Contents are uninitialized."""
        dtype = np.dtype(dtype)
        n_bytes = int(np.prod(shape)) * dtype.itemsize
        if self._handle:
            ptr = self._lib.arena_alloc(self._handle, n_bytes)
            if ptr:
                # view into arena memory: valid only within this scope
                # (exiting the `with` frees the arena — ScopedOut rules)
                buf = (ctypes.c_char * n_bytes).from_address(ptr)
                return np.frombuffer(buf, dtype=dtype).reshape(shape)
        return np.empty(shape, dtype)

    def reset(self) -> None:
        """Free everything allocated in this scope at once (loop
        iteration boundary; ref: workspace notifyScopeLeft)."""
        if self._handle:
            self._lib.arena_reset(self._handle)

    def used_bytes(self) -> int:
        if self._handle:
            return int(self._lib.arena_used(self._handle))
        return 0

    @property
    def native(self) -> bool:
        return self._handle is not None
