"""HTTP JSON-RPC client for one gateway replica — the router→replica
hop (docs/FLEET.md).

Speaks exactly the wire protocol ``server/gateway.Server`` serves
(``POST / {"method", "params"}`` plus the bare ``GET`` probe surfaces),
and maps the gateway's HTTP error semantics back onto the resilience
taxonomy so the router composes with ``resilience.policy``:

* connection-level failures (refused, reset, timeout) →
  :class:`ReplicaUnavailableError` — a ``TransientError``, so a
  ``RetryPolicy`` retries it (on the next candidate replica);
* 503 → :class:`OverloadedError` carrying the replica's ``Retry-After``;
* 504 → :class:`DeadlineExceededError`;
* anything else → :class:`ReplicaError` with the replica's error string.

**Trace propagation** (the PR-10 satellite): every call forwards the
``request_id`` already in scope as ``X-DL4J-Request-ID``; the replica's
gateway ADOPTS it instead of minting its own, so one ``request_scope``
correlates the full cross-replica flow in either side's ``GET /trace``.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Optional, Tuple

from deeplearning4j_tpu.monitor import events
from deeplearning4j_tpu.resilience.errors import (
    DeadlineExceededError, OverloadedError, TransientError)


class ReplicaError(RuntimeError):
    """The replica answered with an application error (HTTP 4xx/5xx
    other than the overload/deadline family)."""

    def __init__(self, message: str, code: int = 500,
                 method: str = "?"):
        super().__init__(message)
        self.code = int(code)
        self.method = method


class ReplicaUnavailableError(TransientError):
    """The replica could not be reached at all (connection refused /
    reset / timed out) — retryable, typically on another replica."""


class ReplicaClient:
    """Thin blocking JSON-RPC client bound to one replica base URL."""

    def __init__(self, base_url: str, timeout_s: float = 30.0):
        self.base_url = base_url.rstrip("/")
        self.timeout_s = float(timeout_s)

    def __repr__(self):
        return f"ReplicaClient({self.base_url!r})"

    def call(self, method: str, params: Optional[dict] = None,
             timeout_s: Optional[float] = None):
        """One RPC round trip; returns the replica's ``result``."""
        body = json.dumps({"method": method,
                           "params": params or {}}).encode()
        headers = {"Content-Type": "application/json"}
        rid = events.current_context().get("request_id")
        if rid:
            headers["X-DL4J-Request-ID"] = str(rid)
        req = urllib.request.Request(self.base_url + "/", data=body,
                                     headers=headers)
        try:
            with urllib.request.urlopen(
                    req, timeout=timeout_s or self.timeout_s) as r:
                return json.loads(r.read()).get("result")
        except urllib.error.HTTPError as e:
            raise self._map_http_error(e, method) from None
        except (urllib.error.URLError, ConnectionError, TimeoutError,
                OSError) as e:
            raise ReplicaUnavailableError(
                f"replica {self.base_url} unreachable for {method!r}: "
                f"{getattr(e, 'reason', e)}") from None

    @staticmethod
    def _map_http_error(e: "urllib.error.HTTPError",
                        method: str) -> Exception:
        try:
            payload = json.loads(e.read() or b"{}")
        except Exception:
            payload = {}
        msg = payload.get("error") or f"HTTP {e.code}"
        if e.code == 503:
            try:
                retry_after = float(payload.get(
                    "retry_after_s",
                    e.headers.get("Retry-After", 1.0) or 1.0))
            except (TypeError, ValueError):
                retry_after = 1.0
            return OverloadedError(msg, retry_after_s=retry_after)
        if e.code == 504:
            return DeadlineExceededError(msg)
        return ReplicaError(msg, code=e.code, method=method)

    def get_text(self, path: str,
                 timeout_s: Optional[float] = None) -> str:
        """A bare GET returning the raw response body — the federation
        scrape hop (``GET /metrics`` serves Prometheus text, not JSON).
        Non-200 raises :class:`ReplicaError`; transport failures raise
        :class:`ReplicaUnavailableError` like every other call."""
        url = self.base_url + "/" + path.lstrip("/")
        try:
            with urllib.request.urlopen(
                    url, timeout=timeout_s or self.timeout_s) as r:
                return r.read().decode("utf-8", "replace")
        except urllib.error.HTTPError as e:
            raise ReplicaError(f"GET /{path.lstrip('/')} -> HTTP {e.code}",
                               code=e.code, method=f"GET {path}") from None
        except (urllib.error.URLError, ConnectionError, TimeoutError,
                OSError) as e:
            raise ReplicaUnavailableError(
                f"replica {self.base_url} unreachable for GET {path}: "
                f"{getattr(e, 'reason', e)}") from None

    def get_json(self, path: str,
                 timeout_s: Optional[float] = None) -> Tuple[int, dict]:
        """A bare GET probe (``/healthz``, ``/readyz``, ``/trace``,
        ...); returns ``(status_code, parsed_body)``.  A 503 readyz is
        a VALID answer, not an exception — only transport failures
        raise (:class:`ReplicaUnavailableError`)."""
        url = self.base_url + "/" + path.lstrip("/")
        try:
            with urllib.request.urlopen(
                    url, timeout=timeout_s or self.timeout_s) as r:
                return r.status, json.loads(r.read() or b"{}")
        except urllib.error.HTTPError as e:
            try:
                return e.code, json.loads(e.read() or b"{}")
            except Exception:
                return e.code, {}
        except (urllib.error.URLError, ConnectionError, TimeoutError,
                OSError) as e:
            raise ReplicaUnavailableError(
                f"replica {self.base_url} unreachable for GET {path}: "
                f"{getattr(e, 'reason', e)}") from None
