"""FleetManager — replica supervision for the fleet tier
(docs/FLEET.md): registration, health, rebalancing, and the drain-free
rollout orchestration.

* **Health**: a poll loop hits every replica's ``/readyz`` through a
  per-replica :class:`~deeplearning4j_tpu.resilience.CircuitBreaker`
  (a replica that keeps failing probes is short-circuited for the
  cooldown instead of eating a connect timeout per tick).  Verdicts
  flow into the router (``mark_ready``): an unready replica stops
  taking placements; an UNREACHABLE one additionally loses its
  sessions (their carries died with it) so clients fail cleanly and
  reopen instead of hanging.

* **Drain-free rollout** (:meth:`rollout`): per replica —
  park it off the ring → ``drain`` RPC (its gateway sheds new session
  joins, 503) → migrate its live sessions onto the rest of the fleet →
  run the caller's roll hook (republish the checkpoint for a
  blue/green flip, bounce the process, ...) → wait for ``/readyz`` 200
  → ``undrain`` → back on the ring.  Every session keeps streaming
  through the whole pass; a final :meth:`SessionRouter.rebalance`
  shifts the ring's share back.

* **Federation + SLOs** (docs/OBSERVABILITY.md "Fleet federation &
  SLOs"): each poll tick also scrapes every replica's ``/metrics``
  into the router's :class:`~deeplearning4j_tpu.monitor.federation.
  MetricsFederation` and, when ``slo_objectives`` is set, evaluates
  the objectives fleet-wide (on the merged snapshot) AND per replica
  (on each replica's own scrape).  With ``park_on_slo_burn=True`` a
  replica whose per-replica SLO is ``burning`` while the fleet-wide
  one is healthy is parked off the placement ring (its sessions keep
  serving; it just takes no new placements) and re-ringed when its
  objectives recover — objective-driven placement, not just
  liveness-driven.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional

from deeplearning4j_tpu.fleet.client import ReplicaUnavailableError
from deeplearning4j_tpu.monitor import events
from deeplearning4j_tpu.resilience.errors import CircuitOpenError


class FleetManager:
    """Supervises a :class:`~.router.SessionRouter`'s replicas."""

    def __init__(self, router, poll_interval_s: float = 1.0,
                 probe_timeout_s: float = 5.0, federate: bool = True,
                 slo_objectives: Optional[List] = None,
                 park_on_slo_burn: bool = False):
        self.router = router
        self.poll_interval_s = max(0.05, float(poll_interval_s))
        self.probe_timeout_s = float(probe_timeout_s)
        self.federate = bool(federate)
        self.park_on_slo_burn = bool(park_on_slo_burn)
        self._slo_objectives = (list(slo_objectives)
                                if slo_objectives else None)
        self._slo_fleet = None
        self._slo_replica: dict = {}
        self._slo_parked: set = set()
        if self._slo_objectives:
            from deeplearning4j_tpu.monitor.slo import SloTracker
            self._slo_fleet = SloTracker(self._slo_objectives,
                                         series_prefix="fleet|")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        router.manager = self

    # ------------------------------------------------------------------
    # Health polling
    # ------------------------------------------------------------------
    def poll_once(self) -> dict:
        """Probe every replica's ``/readyz`` once, through its breaker.
        Returns ``{name: ready}``."""
        out = {}
        for name in self.router.replica_names():
            try:
                rep = self.router._get_replica(name)
            except KeyError:
                continue
            try:
                code, body = rep.breaker.call(
                    rep.client.get_json, "readyz",
                    timeout_s=self.probe_timeout_s)
                ready = code == 200
                err = (None if ready else
                       ",".join(sorted(
                           k for k, v in (body.get("checks") or {}).items()
                           if not v)) or f"HTTP {code}")
                self.router.mark_ready(name, ready, error=err)
            except CircuitOpenError as e:
                ready = False
                self.router.mark_ready(name, False,
                                       error=f"breaker open: {e}")
            except ReplicaUnavailableError as e:
                # transport-dead, not merely unready: sessions are lost
                ready = False
                self.router._replica_down(rep, str(e))
            except Exception as e:
                ready = False
                self.router.mark_ready(
                    name, False, error=f"{type(e).__name__}: {e}")
            out[name] = ready
        return out

    def start(self) -> "FleetManager":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="fleet-health-poll")
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout)

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception:
                pass   # the poll loop must outlive any probe surprise
            try:
                if self.federate:
                    self.router.federation_scrape()
                if self._slo_objectives:
                    self.evaluate_slo()
            except Exception:
                pass   # ...and any federation/SLO surprise
            self._stop.wait(self.poll_interval_s)

    # ------------------------------------------------------------------
    # SLO evaluation + objective-driven placement
    # ------------------------------------------------------------------
    def evaluate_slo(self, now: Optional[float] = None) -> dict:
        """One fleet-wide + per-replica SLO evaluation pass over the
        federation's current scrapes (also runs every poll tick).
        Returns ``{"fleet": ..., "replicas": {name: ...}}``."""
        if self._slo_fleet is None:
            return {}
        from deeplearning4j_tpu.monitor.slo import SloTracker
        fed = self.router.federation
        out = {"fleet": self._slo_fleet.evaluate(
            fed.merged(local_name="router"), now=now), "replicas": {}}
        per = fed.replica_snapshots()
        for name, snap in per.items():
            tr = self._slo_replica.get(name)
            if tr is None:
                tr = self._slo_replica[name] = SloTracker(
                    self._slo_objectives,
                    series_prefix=f"replica={name}|",
                    flight_dump=False)
            out["replicas"][name] = tr.evaluate(snap, now=now)
        for name in list(self._slo_replica):
            if name not in per:
                del self._slo_replica[name]
        if self.park_on_slo_burn:
            self._apply_slo_placement()
        return out

    def _apply_slo_placement(self) -> None:
        """Park a replica whose OWN SLO is burning while the fleet-wide
        objective is healthy (the problem is that box, not the
        workload); re-ring it when its objectives recover.  Only
        touches placements THIS hook parked."""
        fleet = self._slo_fleet
        for name, tr in list(self._slo_replica.items()):
            burning = tr.burning_objectives()
            if burning and name not in self._slo_parked:
                fleet_healthy = all(
                    fleet.healthy(obj) for obj in burning)
                if fleet_healthy:
                    try:
                        self.router.set_placement(name, False)
                    except KeyError:
                        continue
                    self._slo_parked.add(name)
                    events.emit("slo.replica_parked", severity="warn",
                                replica=name, parked=True,
                                objectives=sorted(burning))
            elif not burning and name in self._slo_parked:
                try:
                    self.router.set_placement(name, True)
                except KeyError:
                    pass
                self._slo_parked.discard(name)
                events.emit("slo.replica_parked", replica=name,
                            parked=False)

    # ------------------------------------------------------------------
    # Drain-free blue/green rollout
    # ------------------------------------------------------------------
    def rollout(self, roll: Optional[Callable[[str], None]] = None,
                wait_ready_s: float = 60.0,
                rebalance: bool = True) -> dict:
        """Roll every replica in turn without draining the fleet:
        sessions are MIGRATED off a replica before it rolls and the
        ring shifts back afterwards — no client ever loses a stream.

        ``roll(name)`` is the caller's hook that actually rolls the
        replica (republish the model file so its blue/green
        ``ModelCache`` flips, restart the process, swap the image, …).
        ``None`` still exercises the full drain→migrate→ready cycle —
        the runbook's dry run."""
        passes = []
        for name in self.router.replica_names():
            step = {"replica": name, "migrated": [], "errors": [],
                    "ready_again": False}
            try:
                rep = self.router._get_replica(name)
            except KeyError:
                continue
            # 1. park: no NEW sessions placed here (existing keep going)
            self.router.set_placement(name, False)
            try:
                # 2. the replica itself sheds session joins (covers
                # clients that talk to it directly, not via the router)
                try:
                    rep.client.call("drain", {})
                except Exception as e:
                    step["errors"].append(
                        {"drain": f"{type(e).__name__}: {e}"})
                # 3. migrate its live sessions onto the rest of the fleet
                for sid in self.router.sessions_on(name):
                    try:
                        self.router.migrate_session(sid, reason="rollout")
                        step["migrated"].append(sid)
                    except Exception as e:
                        step["errors"].append(
                            {"session_id": sid,
                             "error": f"{type(e).__name__}: {e}"})
                # 4. roll it
                if roll is not None:
                    roll(name)
                # 5. wait for the rolled replica to answer ready again
                step["ready_again"] = self._wait_ready(rep, wait_ready_s)
                # 6. re-admit session joins
                try:
                    rep.client.call("undrain", {})
                except Exception as e:
                    step["errors"].append(
                        {"undrain": f"{type(e).__name__}: {e}"})
            finally:
                # 7. back on the ring (even on errors — a parked
                # replica with no roll applied is still a serving one)
                self.router.set_placement(name, True)
            self.router._metrics.c_rollouts.inc()
            events.emit("fleet.rollout", replica=name,
                        migrated=len(step["migrated"]),
                        errors=len(step["errors"]),
                        ready_again=step["ready_again"])
            passes.append(step)
        result = {"replicas": passes}
        if rebalance:
            result["rebalance"] = self.router.rebalance(reason="rollout")
        return result

    def _wait_ready(self, rep, wait_ready_s: float) -> bool:
        deadline = time.monotonic() + max(0.0, float(wait_ready_s))
        while time.monotonic() < deadline:
            try:
                code, body = rep.client.get_json(
                    "readyz", timeout_s=self.probe_timeout_s)
                # drain leaves not_draining=False until undrain — every
                # OTHER check green is "rolled and healthy"
                checks = (body.get("checks") or {})
                others_ok = all(v for k, v in checks.items()
                                if k != "not_draining")
                if code == 200 or (checks and others_ok):
                    self.router.mark_ready(rep.name, True)
                    return True
            except ReplicaUnavailableError:
                pass   # still rolling
            except Exception:
                pass
            time.sleep(0.05)
        return False
