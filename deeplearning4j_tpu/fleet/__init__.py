"""Serving fleet tier (ROADMAP item 3; docs/FLEET.md): a
consistent-hash session router over N gateway replicas, live
cross-replica session migration on the compiled-carry contract, health
supervision, and drain-free blue/green rollout.

The stack, bottom-up::

    server/decode.py   DecodePool.export_session / import_session —
                       a session's carry slice as a relocatable object
    server/gateway.py  the per-replica RPC surface (+ drain/undrain)
    fleet/ring.py      weighted-vnode consistent-hash placement
    fleet/client.py    the router→replica hop (request-ID propagated)
    fleet/router.py    SessionRouter — routing, failover, migration,
                       fleet-wide admission
    fleet/manager.py   FleetManager — health polling through breakers,
                       drain-free rollout orchestration
"""

from deeplearning4j_tpu.fleet.client import (
    ReplicaClient, ReplicaError, ReplicaUnavailableError)
from deeplearning4j_tpu.fleet.manager import FleetManager
from deeplearning4j_tpu.fleet.ring import HashRing
from deeplearning4j_tpu.fleet.router import SessionLostError, SessionRouter

__all__ = ["HashRing", "ReplicaClient", "ReplicaError",
           "ReplicaUnavailableError", "SessionRouter", "SessionLostError",
           "FleetManager"]
