"""Consistent-hash ring with weighted virtual nodes — the fleet
router's placement function (docs/FLEET.md).

Each replica owns ``int(vnodes * weight)`` points on a 64-bit hash
circle (``blake2b`` of ``"{name}#{i}"``); a key routes to the first
point clockwise from its own hash.  The consistency property the fleet
tier leans on: adding or removing one replica moves only the keys whose
arc changed (~``1/N`` of them) — every other session keeps its owner,
so a rebalance migrates the minimum set of carries.

Pure data structure: no locks, no I/O.  :class:`SessionRouter` guards
it with its own lock.
"""

from __future__ import annotations

import bisect
import hashlib
from typing import Dict, List, Optional, Tuple


def _hash64(key: str) -> int:
    """Stable 64-bit position on the circle (NOT Python's ``hash()`` —
    that is salted per process, and two routers must agree)."""
    return int.from_bytes(
        hashlib.blake2b(key.encode("utf-8"), digest_size=8).digest(), "big")


class HashRing:
    """Weighted-vnode consistent-hash ring over replica names."""

    def __init__(self, vnodes: int = 64):
        self.vnodes = max(1, int(vnodes))
        self._weights: Dict[str, float] = {}
        self._points: List[Tuple[int, str]] = []   # sorted (hash, name)
        self._keys: List[int] = []                 # parallel hash list

    def add(self, name: str, weight: float = 1.0) -> None:
        """Add (or re-weight) a node: ``int(vnodes * weight)`` points,
        minimum 1 so a low-weight node still takes traffic."""
        if name in self._weights:
            self.remove(name)
        weight = max(0.0, float(weight))
        n = max(1, int(round(self.vnodes * weight))) if weight > 0 else 0
        self._weights[name] = weight
        for i in range(n):
            bisect.insort(self._points, (_hash64(f"{name}#{i}"), name))
        self._keys = [h for h, _ in self._points]

    def remove(self, name: str) -> bool:
        if name not in self._weights:
            return False
        del self._weights[name]
        self._points = [(h, n) for h, n in self._points if n != name]
        self._keys = [h for h, _ in self._points]
        return True

    def __contains__(self, name: str) -> bool:
        return name in self._weights

    def __len__(self) -> int:
        return len(self._weights)

    def nodes(self) -> Dict[str, float]:
        return dict(self._weights)

    def lookup(self, key: str) -> Optional[str]:
        """The owning node for ``key`` (None on an empty ring)."""
        if not self._points:
            return None
        i = bisect.bisect_right(self._keys, _hash64(key)) % len(self._points)
        return self._points[i][1]

    def preference(self, key: str, n: Optional[int] = None) -> List[str]:
        """Distinct nodes in ring order starting at ``key``'s owner —
        the failover order: the owner first, then each next-closest
        node.  ``n`` truncates (default: all nodes)."""
        if not self._points:
            return []
        want = len(self._weights) if n is None else min(n,
                                                        len(self._weights))
        out: List[str] = []
        start = bisect.bisect_right(self._keys, _hash64(key))
        for j in range(len(self._points)):
            name = self._points[(start + j) % len(self._points)][1]
            if name not in out:
                out.append(name)
                if len(out) >= want:
                    break
        return out

    def snapshot(self) -> dict:
        """Introspection for stats RPCs: weights and point counts."""
        counts: Dict[str, int] = {}
        for _, name in self._points:
            counts[name] = counts.get(name, 0) + 1
        return {"vnodes": self.vnodes, "nodes": dict(self._weights),
                "points": counts, "total_points": len(self._points)}
