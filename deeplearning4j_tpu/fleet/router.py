"""SessionRouter — the fleet front tier above N gateway replicas
(ROADMAP item 3; docs/FLEET.md).

Decode sessions were pinned to the one gateway process that opened them
(``server/decode.py`` slot pools are per-process) — a hard ceiling on
horizontal scale.  This tier unpins them:

* **Consistent-hash placement** (:class:`~.ring.HashRing`, weighted
  virtual nodes): ``open_session`` places a new stream on the ring;
  ``predict`` spreads stateless work the same way.  A replica
  joining/leaving moves ~1/N of placement keys — the minimum session
  set migrates on a rebalance.

* **Forwarding with failover**: every RPC forwards over the
  ``ReplicaClient`` hop (request-ID propagated, so one trace covers the
  whole flow) through a ``resilience.RetryPolicy`` — an unreachable
  replica is retried on the next ring candidate for stateless calls;
  for session-pinned calls it becomes a clean
  :class:`SessionLostError` (the carry died with the replica), and
  :meth:`reopen_session` restarts the stream elsewhere — zero client
  hangs either way.

* **Live migration**: :meth:`migrate_session` moves a RUNNING session
  between replicas — two-phase export (source holds the slot in limbo)
  → import (target restores the carry slice) → confirm (source
  releases).  Used by :meth:`rebalance` when the ring changes and by
  the ``FleetManager`` rollout so replicas can be rolled drain-free.

* **Fleet admission**: per-tenant quotas aggregated ACROSS replicas —
  router-side in-flight row counts, 503 + Retry-After
  (``OverloadedError``) when the fleet-wide quota trips, before any
  replica sees the request.

The router duck-types the gateway entry-point surface
(``predict``/``open_session``/``decode_step``/``close_session`` plus
``healthz``/``readyz``/``metrics``/``stats``/``trace_dump``), so
``server.Server(SessionRouter(...))`` serves the fleet tier on the same
wire protocol clients already speak.  Metered as ``dl4j_router_*`` /
``dl4j_fleet_*`` (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Dict, List, Optional

from deeplearning4j_tpu import monitor
from deeplearning4j_tpu.fleet.client import (
    ReplicaClient, ReplicaError, ReplicaUnavailableError)
from deeplearning4j_tpu.fleet.ring import HashRing
from deeplearning4j_tpu.monitor import events, flight
from deeplearning4j_tpu.monitor.federation import MetricsFederation
from deeplearning4j_tpu.resilience import CircuitBreaker, RetryPolicy
from deeplearning4j_tpu.resilience.errors import (
    OverloadedError, TransientError)


class SessionLostError(RuntimeError):
    """A session's owning replica died (or its pool did) before the
    carry could be migrated — the device state is gone.  Carries enough
    context for :meth:`SessionRouter.reopen_session` to restart the
    stream on a live replica (the client replays its prefix)."""

    def __init__(self, session_id: str, replica: Optional[str] = None,
                 model_path: Optional[str] = None,
                 tenant: Optional[str] = None):
        super().__init__(
            f"decode session {session_id} lost (replica {replica or '?'} "
            "unreachable) — reopen the session and replay")
        self.session_id = session_id
        self.replica = replica
        self.model_path = model_path
        self.tenant = tenant


class _Replica:
    __slots__ = ("name", "url", "weight", "client", "breaker", "ready",
                 "placeable", "last_error", "last_probe")

    def __init__(self, name: str, url: str, weight: float,
                 client: ReplicaClient, breaker: CircuitBreaker):
        self.name = name
        self.url = url
        self.weight = weight
        self.client = client
        self.breaker = breaker
        self.ready = True          # optimistic until a probe says otherwise
        self.placeable = True      # on the ring (rollout parks this False)
        self.last_error: Optional[str] = None
        self.last_probe: Optional[float] = None


class FleetMetrics:
    """The ``dl4j_router_*`` / ``dl4j_fleet_*`` families."""

    def __init__(self):
        reg = monitor.get_registry()
        self.c_requests = reg.counter(
            "dl4j_router_requests_total",
            "RPCs forwarded by the fleet router, by outcome",
            ("method", "replica", "outcome"))
        self.c_retries = reg.counter(
            "dl4j_router_retries_total",
            "router forwards retried on another candidate after a "
            "replica failure", ("method",))
        self.g_sessions = reg.gauge(
            "dl4j_router_sessions",
            "decode sessions currently tracked by the router")
        self.g_replicas = reg.gauge(
            "dl4j_fleet_replicas",
            "fleet replicas by state (registered >= ready >= placeable)",
            ("state",))
        self.c_migrations = reg.counter(
            "dl4j_fleet_migrations_total",
            "live session migrations completed, by trigger", ("reason",))
        self.c_migration_failures = reg.counter(
            "dl4j_fleet_migration_failures_total",
            "session migrations that failed (source reinstated or "
            "session lost)", ("reason",))
        self.h_migration = reg.histogram(
            "dl4j_fleet_migration_seconds",
            "export → import → confirm wall time per migrated session")
        self.c_lost = reg.counter(
            "dl4j_fleet_sessions_lost_total",
            "sessions whose carry died with their replica", ("reason",))
        self.c_rollouts = reg.counter(
            "dl4j_fleet_rollouts_total",
            "drain-free rollout replica passes completed")
        self.c_shed = reg.counter(
            "dl4j_resilience_shed_total",
            "requests shed instead of served", labels=("reason",))

    def replicas(self, registered: int, ready: int, placeable: int):
        self.g_replicas.labels(state="registered").set(registered)
        self.g_replicas.labels(state="ready").set(ready)
        self.g_replicas.labels(state="placeable").set(placeable)


class SessionRouter:
    """Consistent-hash session router over N gateway replicas."""

    def __init__(self, vnodes: int = 32,
                 retry_policy: Optional[RetryPolicy] = None,
                 fleet_quota_rows: Optional[int] = None,
                 max_fleet_rows: int = 4096,
                 retry_after_s: float = 1.0,
                 request_timeout_s: float = 60.0,
                 migrate_timeout_s: float = 30.0):
        self._lock = threading.RLock()
        self._migrate_cv = threading.Condition(self._lock)
        self._replicas: Dict[str, _Replica] = {}
        self._ring = HashRing(vnodes)
        #: sid → {"replica", "model_path", "tenant", "key", "lost"}
        self._sessions: Dict[str, dict] = {}
        self._migrating: set = set()
        self._inflight_rows = 0
        self._tenant_rows: Dict[str, int] = {}
        self.fleet_quota_rows = (None if fleet_quota_rows is None
                                 else max(1, int(fleet_quota_rows)))
        self.max_fleet_rows = max(1, int(max_fleet_rows))
        self.retry_after_s = max(0.0, float(retry_after_s))
        self.request_timeout_s = float(request_timeout_s)
        self.migrate_timeout_s = float(migrate_timeout_s)
        # retry ONLY transients (an unreachable replica, a migration
        # window) — a replica's 503/504 carries backpressure semantics
        # the client must see, not something to paper over
        self.retry = retry_policy or RetryPolicy(
            max_attempts=3, base_delay_ms=20, max_delay_ms=250,
            retry_on=(TransientError,), name="fleet.route")
        self._metrics = FleetMetrics()
        # metrics federation: per-replica /metrics scrapes merged into
        # the one fleet snapshot served at ?scope=fleet (the attached
        # FleetManager's poll loop scrapes periodically; a fleet-scope
        # read refreshes on demand when the last scrape is stale)
        self.federation = MetricsFederation()
        self.federation_max_age_s = 10.0
        self._seq = itertools.count(1)
        self._t_start = time.time()
        self.manager = None   # a FleetManager attaches itself here

    # ------------------------------------------------------------------
    # Replica registration / ring membership
    # ------------------------------------------------------------------
    def add_replica(self, name: str, url: str, weight: float = 1.0,
                    client: Optional[ReplicaClient] = None) -> None:
        """Register a gateway replica and put it on the placement ring.
        ``weight`` scales its share of virtual nodes (a bigger machine
        takes proportionally more sessions)."""
        with self._lock:
            if name in self._replicas:
                raise ValueError(f"replica {name!r} already registered")
            rep = _Replica(
                name, url, float(weight),
                client or ReplicaClient(url, timeout_s=self.request_timeout_s),
                CircuitBreaker(cooldown_s=2.0, min_calls=2, window=6,
                               name=f"replica.{name}"))
            self._replicas[name] = rep
            self._ring.add(name, weight)
            self._update_replica_gauges_locked()
        events.emit("fleet.replica_added", replica=name, url=url,
                    weight=weight)

    def remove_replica(self, name: str, migrate: bool = True) -> dict:
        """Deregister a replica: leave the ring first (no new
        placements), migrate its sessions to the rest of the fleet
        (best effort — failures mark the session lost), then drop it."""
        with self._lock:
            rep = self._replicas.get(name)
            if rep is None:
                raise KeyError(f"unknown replica {name!r}")
            self._ring.remove(name)
            rep.placeable = False
            sids = [sid for sid, i in self._sessions.items()
                    if i["replica"] == name and not i.get("lost")]
        moved, errors = [], []
        for sid in sids if migrate else []:
            try:
                self.migrate_session(sid, reason="rebalance")
                moved.append(sid)
            except Exception as e:
                errors.append({"session_id": sid,
                               "error": f"{type(e).__name__}: {e}"})
        with self._lock:
            self._replicas.pop(name, None)
            for sid, i in list(self._sessions.items()):
                if i["replica"] == name:
                    i["lost"] = True
                    self._metrics.c_lost.labels(
                        reason="replica_removed").inc()
            self._update_replica_gauges_locked()
        events.emit("fleet.replica_removed", replica=name,
                    migrated=len(moved), errors=len(errors))
        return {"replica": name, "migrated": moved, "errors": errors}

    def set_placement(self, name: str, enabled: bool) -> None:
        """Ring membership without deregistration — the rollout lever:
        a parked replica keeps serving its existing sessions but takes
        no new placements."""
        with self._lock:
            rep = self._replicas.get(name)
            if rep is None:
                raise KeyError(f"unknown replica {name!r}")
            rep.placeable = bool(enabled)
            if enabled and name not in self._ring:
                self._ring.add(name, rep.weight)
            elif not enabled:
                self._ring.remove(name)
            self._update_replica_gauges_locked()

    def replica_names(self) -> List[str]:
        with self._lock:
            return list(self._replicas)

    def sessions_on(self, name: str) -> List[str]:
        with self._lock:
            return [sid for sid, i in self._sessions.items()
                    if i["replica"] == name and not i.get("lost")]

    def _update_replica_gauges_locked(self) -> None:
        reps = self._replicas.values()
        self._metrics.replicas(
            len(self._replicas),
            sum(1 for r in reps if r.ready),
            sum(1 for r in reps if r.ready and r.placeable))

    def _get_replica(self, name: str) -> _Replica:
        with self._lock:
            rep = self._replicas.get(name)
        if rep is None:
            raise KeyError(f"unknown replica {name!r}")
        return rep

    def _candidates(self, key: str, exclude=()) -> List[_Replica]:
        """Ready replicas in ring-preference order for ``key`` —
        the owner first, failover candidates after."""
        with self._lock:
            order = self._ring.preference(key)
            # parked/unready replicas fall out; replicas not on the
            # ring at all (mid-rollout) are still appended LAST so a
            # fleet that parked everyone can still serve
            cands = [self._replicas[n] for n in order
                     if n in self._replicas
                     and self._replicas[n].ready
                     and n not in exclude]
            extra = [r for n, r in self._replicas.items()
                     if r.ready and n not in order and n not in exclude]
        cands += extra
        if not cands:
            self._metrics.c_shed.labels(reason="no_ready_replicas").inc()
            raise OverloadedError("no ready replicas in the fleet",
                                  retry_after_s=self.retry_after_s)
        return cands

    def _replica_down(self, rep: _Replica, error: str) -> None:
        """A transport-level failure: mark the replica unready and its
        sessions lost (their carries are unreachable — they will fail
        cleanly, not hang)."""
        with self._lock:
            was_ready = rep.ready
            rep.ready = False
            rep.last_error = error
            lost = [sid for sid, i in self._sessions.items()
                    if i["replica"] == rep.name and not i.get("lost")]
            for sid in lost:
                self._sessions[sid]["lost"] = True
            if lost:
                self._metrics.c_lost.labels(reason="replica_dead").inc(
                    len(lost))
            self._update_replica_gauges_locked()
        if was_ready:
            events.emit("fleet.replica_health", severity="warn",
                        replica=rep.name, ready=False, error=error,
                        sessions_lost=len(lost))

    def mark_ready(self, name: str, ready: bool,
                   error: Optional[str] = None) -> None:
        """Health verdict from the FleetManager's poll loop."""
        with self._lock:
            rep = self._replicas.get(name)
            if rep is None:
                return
            flipped = rep.ready != bool(ready)
            rep.ready = bool(ready)
            rep.last_error = error
            rep.last_probe = time.time()
            self._update_replica_gauges_locked()
        if flipped:
            events.emit("fleet.replica_health",
                        severity="info" if ready else "warn",
                        replica=name, ready=bool(ready), error=error)

    # ------------------------------------------------------------------
    # Fleet-wide admission (quotas aggregated across replicas)
    # ------------------------------------------------------------------
    def _admit(self, rows: int, tenant: Optional[str]) -> None:
        t = tenant or "-"
        with self._lock:
            if self._inflight_rows + rows > self.max_fleet_rows:
                self._metrics.c_shed.labels(reason="fleet_queue_full").inc()
                events.emit("request.shed", severity="warn",
                            reason="fleet_queue_full", rows=rows,
                            queued=self._inflight_rows)
                raise OverloadedError(
                    f"fleet queue full ({self._inflight_rows} rows in "
                    f"flight, limit {self.max_fleet_rows})",
                    retry_after_s=self.retry_after_s)
            if self.fleet_quota_rows is not None \
                    and self._tenant_rows.get(t, 0) + rows \
                    > self.fleet_quota_rows:
                self._metrics.c_shed.labels(
                    reason="fleet_tenant_quota").inc()
                events.emit("request.shed", severity="warn",
                            reason="fleet_tenant_quota", rows=rows,
                            queued=self._tenant_rows.get(t, 0))
                raise OverloadedError(
                    f"tenant {t!r} over fleet-wide quota "
                    f"({self._tenant_rows.get(t, 0)} rows in flight "
                    f"across replicas, limit {self.fleet_quota_rows})",
                    retry_after_s=self.retry_after_s)
            self._inflight_rows += rows
            self._tenant_rows[t] = self._tenant_rows.get(t, 0) + rows
            queued = self._inflight_rows
        # the router-side half of the cross-replica timeline: without
        # this event the assembled fleet trace has no router-lane entry
        # carrying the request ID the replica hop adopts
        events.emit("request.admitted", rows=rows, queued=queued)

    def _release(self, rows: int, tenant: Optional[str]) -> None:
        t = tenant or "-"
        with self._lock:
            self._inflight_rows = max(0, self._inflight_rows - rows)
            left = self._tenant_rows.get(t, 0) - rows
            if left > 0:
                self._tenant_rows[t] = left
            else:
                self._tenant_rows.pop(t, None)

    # ------------------------------------------------------------------
    # Routed entry-point surface
    # ------------------------------------------------------------------
    def predict(self, model_path: str, features=None,
                tenant: Optional[str] = None,
                top_k: Optional[int] = None, argmax_only: bool = False,
                deadline_ms: Optional[float] = None,
                coalesce: Optional[bool] = None) -> dict:
        """Stateless inference, spread over the ring and failed over to
        the next candidate when a replica is unreachable."""
        if features is None:
            raise ValueError("router predict needs inline features= "
                             "(data_dir runs on a specific replica)")
        rows = max(1, len(features))
        params = self._params(model_path=model_path, features=features,
                              tenant=tenant, top_k=top_k,
                              argmax_only=argmax_only or None,
                              deadline_ms=deadline_ms, coalesce=coalesce)
        key = f"predict-{next(self._seq)}"
        with events.request_scope(tenant=tenant):
            self._admit(rows, tenant)
            try:
                return self._route_spread("predict", params, key)
            finally:
                self._release(rows, tenant)

    def open_session(self, model_path: str,
                     tenant: Optional[str] = None) -> dict:
        """Place a new decode session on the ring and open it on the
        owning replica.  The placement key is remembered so a later
        :meth:`rebalance` knows where the ring NOW says the session
        belongs."""
        key = f"session-{next(self._seq)}"
        params = self._params(model_path=model_path, tenant=tenant)
        picked: Dict[str, str] = {}
        with events.request_scope(tenant=tenant):
            self._admit(1, tenant)
            try:
                result = self._route_spread("open_session", params, key,
                                            picked=picked)
            finally:
                self._release(1, tenant)
        sid = result["session_id"]
        with self._lock:
            self._sessions[sid] = {
                "replica": picked["name"], "model_path": str(model_path),
                "tenant": tenant, "key": key, "lost": False}
            self._metrics.g_sessions.set(len(self._sessions))
        result["replica"] = picked["name"]
        return result

    def decode_step(self, session_id: str, features, mask=None,
                    tenant: Optional[str] = None,
                    deadline_ms: Optional[float] = None,
                    top_k: Optional[int] = None,
                    argmax_only: bool = False) -> dict:
        """One step of a pinned session, routed to its owning replica.
        A migration in flight is waited out (bounded); an unreachable
        owner becomes a clean :class:`SessionLostError`."""
        info = self._session_info(session_id)
        tenant = tenant if tenant is not None else info.get("tenant")
        params = self._params(session_id=session_id, features=features,
                              mask=mask, tenant=tenant,
                              deadline_ms=deadline_ms, top_k=top_k,
                              argmax_only=argmax_only or None)
        with events.request_scope(tenant=tenant, session_id=session_id):
            self._admit(1, tenant)
            try:
                return self.retry.call(self._pinned_attempt,
                                       "decode_step", session_id, params)
            finally:
                self._release(1, tenant)

    def close_session(self, session_id: str) -> dict:
        """Close a session on its owner; the router mapping is dropped
        regardless (a dead owner's session is closed by definition)."""
        with self._lock:
            info = self._sessions.pop(session_id, None)
            self._migrating.discard(session_id)
            self._metrics.g_sessions.set(len(self._sessions))
        if info is None or info.get("lost"):
            return {"closed": False}
        rep = self._get_replica(info["replica"])
        try:
            return rep.client.call("close_session",
                                   {"session_id": session_id})
        except (ReplicaUnavailableError, ReplicaError):
            return {"closed": False}

    def reopen_session(self, session_id: str) -> dict:
        """Restart a LOST session's stream on a live replica: fresh
        carry (the client replays its prefix), same model and tenant.
        The fail-and-reopen half of the failover contract."""
        with self._lock:
            info = self._sessions.pop(session_id, None)
            self._migrating.discard(session_id)
            self._metrics.g_sessions.set(len(self._sessions))
        if info is None:
            raise KeyError(f"unknown session {session_id!r}")
        result = self.open_session(info["model_path"],
                                   tenant=info.get("tenant"))
        result["replaced"] = session_id
        result["carry_lost"] = True
        return result

    # -- forwarding internals ------------------------------------------
    @staticmethod
    def _params(**kw) -> dict:
        return {k: v for k, v in kw.items() if v is not None}

    def _route_spread(self, method: str, params: dict, key: str,
                      picked: Optional[dict] = None):
        """Forward an unpinned RPC to the ring owner of ``key``,
        failing over to the next candidate (through the retry policy)
        when a replica is unreachable."""
        tried: List[str] = []

        def attempt():
            rep = self._candidates(key, exclude=tried)[0]
            if tried:
                self._metrics.c_retries.labels(method=method).inc()
            try:
                result = rep.client.call(method, params)
            except ReplicaUnavailableError as e:
                tried.append(rep.name)
                self._metrics.c_requests.labels(
                    method=method, replica=rep.name,
                    outcome="unreachable").inc()
                self._replica_down(rep, str(e))
                raise
            except Exception:
                self._metrics.c_requests.labels(
                    method=method, replica=rep.name, outcome="error").inc()
                raise
            self._metrics.c_requests.labels(
                method=method, replica=rep.name, outcome="ok").inc()
            if picked is not None:
                picked["name"] = rep.name
            return result

        return self.retry.call(attempt)

    def _pinned_attempt(self, method: str, session_id: str, params: dict):
        """One forward of a session-pinned RPC (re-resolves the owner
        so a retry lands on the post-migration replica)."""
        info = self._session_info(session_id)
        rep = self._get_replica(info["replica"])
        try:
            result = rep.client.call(method, params)
        except ReplicaUnavailableError as e:
            self._metrics.c_requests.labels(
                method=method, replica=rep.name,
                outcome="unreachable").inc()
            self._replica_down(rep, str(e))
            raise SessionLostError(
                session_id, replica=rep.name,
                model_path=info.get("model_path"),
                tenant=info.get("tenant")) from e
        except ReplicaError as e:
            self._metrics.c_requests.labels(
                method=method, replica=rep.name, outcome="error").inc()
            msg = str(e)
            if "unknown or expired decode session" in msg:
                # the replica is alive but the session is gone (TTL,
                # pool death, confirmed migration we lost track of)
                self._forget_session(session_id)
                raise KeyError(msg) from e
            if "is migrating" in msg:
                raise TransientError(msg) from e   # retry shortly
            raise
        self._metrics.c_requests.labels(
            method=method, replica=rep.name, outcome="ok").inc()
        return result

    def _session_info(self, session_id: str) -> dict:
        """The session's routing record; waits out an in-flight
        migration (bounded) and converts a lost mapping into
        :class:`SessionLostError`."""
        deadline = time.monotonic() + self.migrate_timeout_s
        with self._migrate_cv:
            while session_id in self._migrating:
                if time.monotonic() >= deadline:
                    raise TransientError(
                        f"session {session_id} migration did not settle "
                        f"within {self.migrate_timeout_s}s")
                self._migrate_cv.wait(0.02)
            info = self._sessions.get(session_id)
            if info is None:
                raise KeyError(
                    f"unknown or expired decode session {session_id!r}")
            if info.get("lost"):
                raise SessionLostError(
                    session_id, replica=info.get("replica"),
                    model_path=info.get("model_path"),
                    tenant=info.get("tenant"))
            return dict(info)

    def _forget_session(self, session_id: str) -> None:
        with self._lock:
            self._sessions.pop(session_id, None)
            self._migrating.discard(session_id)
            self._metrics.g_sessions.set(len(self._sessions))

    # ------------------------------------------------------------------
    # Live migration + rebalance
    # ------------------------------------------------------------------
    def migrate_session(self, session_id: str,
                        target: Optional[str] = None,
                        reason: str = "manual") -> dict:
        """Move a RUNNING session between replicas: export (source slot
        held in limbo) → import (target restores the carry) → confirm
        (source releases).  An import failure reinstates the source —
        the stream never has zero owners; steps arriving mid-move are
        rejected retryable and land after the mapping flips."""
        info = self._session_info(session_id)
        with self._lock:
            if session_id in self._migrating:
                raise TransientError(
                    f"session {session_id} is already migrating")
            self._migrating.add(session_id)
        t0 = time.perf_counter()
        try:
            result = self._migrate(session_id, info, target)
        except BaseException as e:
            self._metrics.c_migration_failures.labels(reason=reason).inc()
            events.emit("fleet.migrate_failed", severity="error",
                        session_id=session_id, replica=info["replica"],
                        reason=reason,
                        error=f"{type(e).__name__}: {e}")
            raise
        finally:
            with self._migrate_cv:
                self._migrating.discard(session_id)
                self._migrate_cv.notify_all()
        dt = time.perf_counter() - t0
        self._metrics.c_migrations.labels(reason=reason).inc()
        self._metrics.h_migration.observe(dt)
        events.emit("fleet.migrated", session_id=session_id,
                    source=result["from"], target=result["to"],
                    reason=reason, steps=result.get("steps"),
                    duration_s=round(dt, 4))
        return result

    def _migrate(self, sid: str, info: dict,
                 target: Optional[str]) -> dict:
        src = self._get_replica(info["replica"])
        tgt = self._pick_target(info, exclude=src.name, target=target)
        try:
            payload = src.client.call(
                "export_session", {"session_id": sid},
                timeout_s=self.migrate_timeout_s)
        except ReplicaUnavailableError as e:
            self._replica_down(src, str(e))
            raise SessionLostError(sid, replica=src.name,
                                   model_path=info.get("model_path"),
                                   tenant=info.get("tenant")) from e
        try:
            tgt.client.call(
                "import_session",
                {"model_path": info["model_path"], "payload": payload,
                 "session_id": sid},
                timeout_s=self.migrate_timeout_s)
        except BaseException as e:
            # the carry never left the source's device pool — reinstate
            try:
                src.client.call("finish_export",
                                {"session_id": sid, "ok": False})
            except Exception:
                pass   # source TTL will reap the limbo slot eventually
            if isinstance(e, ReplicaUnavailableError):
                self._replica_down(tgt, str(e))
            raise
        try:
            src.client.call("finish_export", {"session_id": sid, "ok": True})
        except Exception:
            pass   # target owns the stream; source TTL reaps the limbo
        with self._lock:
            cur = self._sessions.get(sid)
            if cur is not None:
                cur["replica"] = tgt.name
                cur["lost"] = False
        return {"session_id": sid, "from": src.name, "to": tgt.name,
                "steps": payload.get("steps")}

    def _pick_target(self, info: dict, exclude: str,
                     target: Optional[str]) -> _Replica:
        if target is not None:
            rep = self._get_replica(target)
            if not rep.ready:
                raise OverloadedError(
                    f"migration target {target!r} is not ready",
                    retry_after_s=self.retry_after_s)
            return rep
        for rep in self._candidates(info["key"], exclude=(exclude,)):
            if rep.name != exclude:
                return rep
        raise OverloadedError("no migration target available",
                              retry_after_s=self.retry_after_s)

    def rebalance(self, reason: str = "rebalance") -> dict:
        """Move every session whose ring owner changed (replica
        joined/left/parked) onto its CURRENT owner — the consistency
        property bounds this to ~1/N of sessions per membership
        change."""
        with self._lock:
            todo = [(sid, dict(i)) for sid, i in self._sessions.items()
                    if not i.get("lost")]
        moved, errors = [], []
        for sid, info in todo:
            with self._lock:
                desired = self._ring.lookup(info["key"])
                cur = self._sessions.get(sid)
                stale = (cur is not None and desired is not None
                         and desired != cur["replica"]
                         and desired in self._replicas
                         and self._replicas[desired].ready)
            if not stale:
                continue
            try:
                self.migrate_session(sid, target=desired, reason=reason)
                moved.append(sid)
            except Exception as e:
                errors.append({"session_id": sid,
                               "error": f"{type(e).__name__}: {e}"})
        return {"moved": moved, "errors": errors}

    # ------------------------------------------------------------------
    # Probe / observability surface (Server duck-type)
    # ------------------------------------------------------------------
    def healthz(self) -> dict:
        with self._lock:
            n = len(self._replicas)
        return {"status": "ok", "tier": "fleet-router", "replicas": n,
                "uptime_s": round(time.time() - self._t_start, 1)}

    def readyz(self, live: bool = True) -> dict:
        """Fleet-level aggregated readiness: ready iff at least one
        replica answers ``/readyz`` 200.  ``live=True`` (default)
        probes each replica now; ``live=False`` trusts the
        FleetManager's cached poll verdicts."""
        with self._lock:
            reps = list(self._replicas.values())
        out = {}
        for rep in reps:
            if live:
                try:
                    code, body = rep.client.get_json("readyz", timeout_s=5.0)
                    ready = code == 200
                    err = (None if ready else
                           ",".join(sorted(
                               k for k, v in
                               (body.get("checks") or {}).items()
                               if not v)) or f"HTTP {code}")
                except ReplicaUnavailableError as e:
                    ready, err = False, str(e)
                self.mark_ready(rep.name, ready, error=err)
            out[rep.name] = {"ready": rep.ready, "url": rep.url,
                             "placeable": rep.placeable,
                             "error": rep.last_error}
        n_ready = sum(1 for r in out.values() if r["ready"])
        with self._lock:
            sessions = sum(1 for i in self._sessions.values()
                           if not i.get("lost"))
        ready = n_ready > 0
        return {"ready": ready, "replicas": out,
                "checks": {"replicas_ready": ready},
                "replicas_ready": n_ready, "sessions": sessions}

    def stats(self) -> dict:
        with self._lock:
            per_replica = {}
            for name, rep in self._replicas.items():
                per_replica[name] = {
                    "url": rep.url, "weight": rep.weight,
                    "ready": rep.ready, "placeable": rep.placeable,
                    "breaker": rep.breaker.snapshot(),
                    "sessions": sum(
                        1 for i in self._sessions.values()
                        if i["replica"] == name and not i.get("lost")),
                    "last_error": rep.last_error,
                }
            return {
                "replicas": per_replica,
                "sessions": len(self._sessions),
                "sessions_lost": sum(1 for i in self._sessions.values()
                                     if i.get("lost")),
                "migrating": sorted(self._migrating),
                "ring": self._ring.snapshot(),
                "admission": {
                    "inflight_rows": self._inflight_rows,
                    "max_fleet_rows": self.max_fleet_rows,
                    "fleet_quota_rows": self.fleet_quota_rows,
                    "by_tenant": dict(self._tenant_rows),
                },
            }

    # -- federation (docs/OBSERVABILITY.md "Fleet federation & SLOs") --
    def _federation_sources(self) -> Dict[str, callable]:
        with self._lock:
            reps = list(self._replicas.values())
        return {r.name: (lambda c=r.client: c.get_text("metrics",
                                                       timeout_s=5.0))
                for r in reps}

    def federation_scrape(self) -> Dict[str, bool]:
        """Scrape every replica's ``GET /metrics`` into the federation
        (the FleetManager poll loop calls this each tick; ``?scope=
        fleet`` reads call it on demand when the last scrape is older
        than ``federation_max_age_s``)."""
        return self.federation.scrape(self._federation_sources())

    def metrics(self, format: str = "prometheus",
                scope: str = "process"):
        """The scrape endpoint as an RPC.  ``scope="process"`` (default)
        is the router process's own registry; ``scope="fleet"`` merges
        every replica's federated scrape with it — counters/histograms
        summed fleet-wide, gauges per-replica under ``replica=``, each
        replica's staleness visible as
        ``dl4j_federation_scrape_age_seconds`` (also served raw at
        ``GET /metrics?scope=fleet``)."""
        fmt = str(format).lower()
        scope = str(scope).lower()
        if scope not in ("process", "fleet"):
            raise ValueError(f"scope must be process or fleet, "
                             f"got {scope!r}")
        if scope == "fleet":
            age = self.federation.last_scrape_age()
            if age is None or age > self.federation_max_age_s:
                self.federation_scrape()
            snap = self.federation.merged(local_name="router")
        else:
            snap = monitor.get_registry().snapshot()
        if fmt == "json":
            return snap
        if fmt != "prometheus":
            raise ValueError(f"format must be prometheus or json, "
                             f"got {format!r}")
        return {"content_type": monitor.CONTENT_TYPE,
                "body": monitor.render_prometheus(snap)}

    def trace_dump(self, last_n: Optional[int] = None,
                   format: str = "events", request_id: Optional[str] = None,
                   dump: bool = False, reason: str = "manual",
                   scope: str = "fleet") -> dict:
        """Cross-replica trace assembly (default ``scope="fleet"``):
        fetches every replica's journal over its ``GET /trace`` plus
        the router's own, and merges them by process —
        ``format="chrome"`` returns ONE Perfetto-loadable file with a
        lane per replica, so a migrated decode stream reads as one
        timeline (source replica → router → target replica, joined by
        the session/request IDs the hops propagate).  ``scope="local"``
        is the router process's own journal only."""
        fmt = str(format).lower()
        if fmt not in ("events", "chrome"):
            raise ValueError(f"format must be events or chrome, got "
                             f"{format!r}")
        scope = str(scope).lower()
        if scope not in ("fleet", "local"):
            raise ValueError(f"scope must be fleet or local, "
                             f"got {scope!r}")
        journal = events.get_journal()
        own = journal.tail(n=last_n, request_id=request_id)
        out: dict = {"total_emitted": journal.total_emitted,
                     "dropped": journal.dropped}
        if scope == "local":
            out["count"] = len(own)
            if dump:
                out["path"] = flight.dump(reason, force=True)
            if fmt == "chrome":
                out["trace"] = events.chrome_trace(own)
            else:
                out["events"] = own
            return out
        per: Dict[str, List[dict]] = {"router": own}
        errors: Dict[str, str] = {}
        query = []
        if last_n is not None:
            query.append(f"last_n={int(last_n)}")
        if request_id is not None:
            query.append(f"request_id={request_id}")
        path = "trace" + ("?" + "&".join(query) if query else "")
        with self._lock:
            reps = list(self._replicas.values())
        for rep in reps:
            try:
                code, body = rep.client.get_json(path, timeout_s=10.0)
                if code == 200:
                    per[rep.name] = body.get("events") or []
                else:
                    per[rep.name] = []
                    errors[rep.name] = f"HTTP {code}"
            except Exception as e:
                per[rep.name] = []
                errors[rep.name] = f"{type(e).__name__}: {e}"
        out["count"] = sum(len(v) for v in per.values())
        out["processes"] = {k: len(v) for k, v in per.items()}
        if errors:
            out["errors"] = errors
        if dump:
            out["path"] = flight.dump(reason, force=True)
        if fmt == "chrome":
            out["trace"] = events.chrome_trace_fleet(per)
        else:
            merged = [dict(e, process=name)
                      for name, evts in per.items() for e in evts]
            merged.sort(key=lambda e: e.get("ts", 0.0))
            out["events"] = merged
        return out

    def close(self) -> None:
        """Detach (Server shutdown hook): stops an attached
        FleetManager's poll loop; replicas are not contacted."""
        mgr = self.manager
        if mgr is not None:
            mgr.stop()
