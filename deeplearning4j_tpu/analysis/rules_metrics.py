"""Observability-drift rules (DL4J3xx): the `dl4j_*` metric names at
registry call sites and the catalog in ``docs/OBSERVABILITY.md`` must
be the same set, in both directions — and so must the journal event
taxonomy (``monitor/events.py``) and its doc catalog.

The doc catalog is the operator contract — dashboards and alerts are
built against it.  A metric registered in code but missing from the
doc is invisible to operators; a doc row with no registration behind
it is a dashboard querying nothing.  Both directions drift silently
(PR 3's catalog predates the sharding and pipeline families), so both
fail the lint.

Name matching handles the two non-literal forms the codebase uses:
f-string registrations (``f"dl4j_model_cache_{k}_total"`` becomes the
pattern ``dl4j_model_cache_[a-z0-9_]+_total``) and doc brace rows
(``dl4j_sharding_params_{sharded,replicated}`` expands to each
alternative).  Test files are exempt from the undocumented-metric
direction — ad-hoc names registered by a test are not operator surface.

DL4J303/304 apply the same contract to journal event types: every
literal ``emit("some.event", ...)`` call site and every ``EVENT_TYPES``
entry must appear in the docs "Event taxonomy" table (first cell,
backticked), and every taxonomy row must be backed by a declared or
emitted type — an event renamed in code but not in docs is a flight
recorder whose dumps nobody can grep for.
"""

from __future__ import annotations

import os
import re
from typing import Dict, Iterable, List, Set, Tuple

from deeplearning4j_tpu.analysis.core import (
    ERROR, Finding, Project, Rule, is_test_path, register)

_DOC_NAME_RE = re.compile(r"`(dl4j_[a-z0-9_{},]+)`")
_BRACE_RE = re.compile(r"\{([a-z0-9_,]+)\}")


def doc_metric_names(doc_text: str) -> List[Tuple[str, int]]:
    """(name, line) for every `dl4j_...` in a markdown TABLE row,
    brace-alternations expanded."""
    out: List[Tuple[str, int]] = []
    for lineno, line in enumerate(doc_text.splitlines(), 1):
        if not line.lstrip().startswith("|"):
            continue
        for raw in _DOC_NAME_RE.findall(line):
            for name in _expand_braces(raw):
                out.append((name, lineno))
    return out


def _expand_braces(name: str) -> List[str]:
    m = _BRACE_RE.search(name)
    if not m:
        return [name]
    head, tail = name[: m.start()], name[m.end():]
    out: List[str] = []
    for alt in m.group(1).split(","):
        out.extend(_expand_braces(head + alt + tail))
    return out


def _code_sites(project: Project):
    """[(path, node, name, is_pattern)] of registry registrations."""
    return project.metric_call_sites()


def _doc_entries(project: Project) -> Tuple[List[Tuple[str, int]], str]:
    if project.docs_path is None or not os.path.exists(project.docs_path):
        return [], ""
    with open(project.docs_path, "r", encoding="utf-8") as f:
        text = f.read()
    return doc_metric_names(text), text


@register
class UndocumentedMetric(Rule):
    id = "DL4J301"
    name = "metric-undocumented"
    severity = ERROR
    doc = ("A `dl4j_*` metric name registered at a counter/gauge/"
           "histogram call site does not appear in the "
           "docs/OBSERVABILITY.md catalog — operators cannot see it.")

    def run(self, project: Project) -> Iterable[Finding]:
        doc_names, doc_text = _doc_entries(project)
        if not doc_text:
            return
        names: Set[str] = {n for n, _ in doc_names}
        for path, node, name, is_pattern in _code_sites(project):
            if is_test_path(path):
                continue
            if is_pattern:
                rx = re.compile(name + r"\Z")
                if not any(rx.match(n) for n in names):
                    yield self.finding(
                        project, node, path,
                        f"metric pattern `{name}` matches no entry in "
                        "the docs/OBSERVABILITY.md catalog")
            elif name not in names:
                yield self.finding(
                    project, node, path,
                    f"metric `{name}` is registered here but missing "
                    "from the docs/OBSERVABILITY.md catalog")


@register
class StaleMetricDoc(Rule):
    id = "DL4J302"
    name = "metric-doc-stale"
    severity = ERROR
    doc = ("A `dl4j_*` row in the docs/OBSERVABILITY.md catalog has no "
           "registry call site behind it — a dashboard built on it "
           "queries nothing.")

    def run(self, project: Project) -> Iterable[Finding]:
        doc_names, doc_text = _doc_entries(project)
        if not doc_text:
            return
        literals: Set[str] = set()
        patterns: List[re.Pattern] = []
        for path, _node, name, is_pattern in _code_sites(project):
            if is_pattern:
                patterns.append(re.compile(name + r"\Z"))
            else:
                literals.add(name)
        doc_rel = os.path.relpath(project.docs_path) \
            if project.docs_path else "docs/OBSERVABILITY.md"
        for name, lineno in doc_names:
            if name in literals:
                continue
            if any(p.match(name) for p in patterns):
                continue
            yield Finding(
                rule=self.id, severity=self.severity, path=doc_rel,
                line=lineno, col=0,
                message=(f"documented metric `{name}` has no registry "
                         "call site in the scanned code — stale catalog "
                         "row"),
                symbol="<catalog>")


# ----------------------------------------------------------------------
# Journal event taxonomy drift (DL4J303/304)
# ----------------------------------------------------------------------
_EVENT_DOC_RE = re.compile(r"`([a-z0-9_]+(?:\.[a-z0-9_]+)+)`")
_HEADING_RE = re.compile(r"^\s{0,3}#")


def doc_event_names(doc_text: str) -> List[Tuple[str, int]]:
    """(name, line) for the backticked dotted event-type name in the
    FIRST cell of each table row under an "Event taxonomy" heading.
    Scoped to that section so prose elsewhere (``conf.sharding()``,
    module paths) can't masquerade as taxonomy entries."""
    out: List[Tuple[str, int]] = []
    in_section = False
    for lineno, line in enumerate(doc_text.splitlines(), 1):
        if _HEADING_RE.match(line):
            in_section = "event taxonomy" in line.lower()
            continue
        if not in_section or not line.lstrip().startswith("|"):
            continue
        cells = line.split("|")
        first = cells[1] if len(cells) > 1 else ""
        m = _EVENT_DOC_RE.search(first)
        if m:
            out.append((m.group(1), lineno))
    return out


@register
class UndocumentedEvent(Rule):
    id = "DL4J303"
    name = "event-undocumented"
    severity = ERROR
    doc = ("A journal event type emitted at an `emit(...)` call site "
           "(or declared in `EVENT_TYPES`) does not appear in the "
           "docs/OBSERVABILITY.md \"Event taxonomy\" catalog — a dump "
           "or /trace stream carrying it is unreadable by contract.")

    def run(self, project: Project) -> Iterable[Finding]:
        doc_names, doc_text = _doc_entries(project)
        if not doc_text:
            return
        documented: Set[str] = {n for n, _ in doc_event_names(doc_text)}
        for path, node, name in project.event_call_sites():
            if is_test_path(path):
                continue
            if name not in documented:
                yield self.finding(
                    project, node, path,
                    f"journal event `{name}` is emitted here but "
                    "missing from the docs/OBSERVABILITY.md event "
                    "taxonomy")
        for path, node, name in project.event_type_constants():
            if is_test_path(path):
                continue
            if name not in documented:
                yield self.finding(
                    project, node, path,
                    f"declared event type `{name}` (EVENT_TYPES) is "
                    "missing from the docs/OBSERVABILITY.md event "
                    "taxonomy")


@register
class StaleEventDoc(Rule):
    id = "DL4J304"
    name = "event-doc-stale"
    severity = ERROR
    doc = ("An event-type row in the docs/OBSERVABILITY.md \"Event "
           "taxonomy\" table is neither declared in `EVENT_TYPES` nor "
           "emitted anywhere — grep/alerting built on it matches "
           "nothing.")

    def run(self, project: Project) -> Iterable[Finding]:
        doc_names, doc_text = _doc_entries(project)
        if not doc_text:
            return
        in_code: Set[str] = {n for p, _, n in project.event_call_sites()
                             if not is_test_path(p)}
        in_code |= {n for p, _, n in project.event_type_constants()
                    if not is_test_path(p)}
        if not in_code:
            return  # no journal in the scanned code: nothing to drift
        doc_rel = os.path.relpath(project.docs_path) \
            if project.docs_path else "docs/OBSERVABILITY.md"
        for name, lineno in doc_event_names(doc_text):
            if name in in_code:
                continue
            yield Finding(
                rule=self.id, severity=self.severity, path=doc_rel,
                line=lineno, col=0,
                message=(f"documented event type `{name}` is neither "
                         "declared in EVENT_TYPES nor emitted in the "
                         "scanned code — stale taxonomy row"),
                symbol="<catalog>")
