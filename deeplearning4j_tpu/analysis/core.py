"""dl4j-lint core: findings, rule registry, pragmas, baseline, and the
static project model the rules query.

The hazards this framework exists for are invisible to pytest — a
host sync inside a jitted step, a lock-order inversion between the
batcher and the input pipeline, a metric renamed in code but not in
docs — because they only degrade performance or corrupt numerics on a
real mesh ("Array Languages Make Neural Networks Fast" attributes most
framework-level slowdowns to accidental host round-trips and
re-compilation, both statically detectable).  So the linter builds a
whole-program model once (:class:`Project`: per-file ASTs, a function
index with a heuristic call graph, the set of functions reachable from
``jit``/``pjit``/``scan``/``shard_map`` call sites, every lock object
and every with-lock region) and each rule walks that model.

Suppression has three layers, in precedence order:

* ``# dl4j: noqa[RULE]`` pragma on the finding's line (a reason string
  after the bracket is encouraged and kept verbatim in ``--format
  json`` output);
* a checked-in baseline file of grandfathered fingerprints
  (``.dl4j-lint-baseline.json``) — fingerprints are line-number-free
  (rule / path / enclosing symbol / message) so unrelated edits don't
  invalidate them;
* disabling the rule for the run (``--disable``).

Anything not suppressed fails the run (exit 1) unless its severity is
``info``.
"""

from __future__ import annotations

import ast
import builtins
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

ERROR, WARNING, INFO = "error", "warning", "info"

#: pragma grammar: ``# dl4j: noqa`` (all rules) or
#: ``# dl4j: noqa[DL4J101]`` / ``# dl4j: noqa[DL4J101,DL4J202] reason``
_PRAGMA_RE = re.compile(
    r"#\s*dl4j:\s*noqa(?:\[(?P<rules>[A-Za-z0-9_,\s]+)\])?"
    r"(?:\s+(?P<reason>\S.*))?")

_ALL = "__all__"


@dataclass
class Finding:
    rule: str
    severity: str
    path: str
    line: int
    col: int
    message: str
    symbol: str = ""          # enclosing function/class qualname
    suppressed: bool = False  # by a # dl4j: noqa pragma
    baselined: bool = False   # grandfathered in the baseline file
    noqa_reason: str = ""

    def fingerprint(self) -> str:
        """Line-number-free identity used by the baseline: stable under
        unrelated edits to the same file."""
        return "::".join((self.rule, self.path.replace(os.sep, "/"),
                          self.symbol, self.message))

    def gates(self) -> bool:
        """Does this finding fail the run?"""
        return (not self.suppressed and not self.baselined
                and self.severity != INFO)

    def to_dict(self) -> dict:
        return {
            "rule": self.rule, "severity": self.severity,
            "path": self.path.replace(os.sep, "/"), "line": self.line,
            "col": self.col, "message": self.message, "symbol": self.symbol,
            "suppressed": self.suppressed, "baselined": self.baselined,
            "noqa_reason": self.noqa_reason,
            "fingerprint": self.fingerprint(),
        }


class Rule:
    """One lint rule.  Subclasses set the class attrs and implement
    :meth:`run` over the whole :class:`Project` (every rule here is
    whole-program: tracer rules need the jit-reachability set, the
    concurrency rules need cross-file lock identities, the drift rules
    need every registry call site at once)."""

    id: str = "DL4J000"
    name: str = "unnamed"
    severity: str = ERROR
    doc: str = ""

    def run(self, project: "Project") -> Iterable[Finding]:
        raise NotImplementedError

    def finding(self, project: "Project", node: ast.AST, path: str,
                message: str, severity: Optional[str] = None) -> Finding:
        return Finding(
            rule=self.id, severity=severity or self.severity, path=path,
            line=getattr(node, "lineno", 1), col=getattr(node, "col_offset", 0),
            message=message, symbol=project.enclosing_symbol(path, node))


RULES: Dict[str, Rule] = {}


def register(cls):
    """Class decorator: instantiate and add to the rule registry."""
    inst = cls()
    if inst.id in RULES:
        raise ValueError(f"duplicate rule id {inst.id}")
    RULES[inst.id] = inst
    return cls


# ----------------------------------------------------------------------
# Baseline
# ----------------------------------------------------------------------
class Baseline:
    """Grandfathered findings, keyed by line-number-free fingerprints.
    The checked-in file keeps the human-readable entries so a reviewer
    can see WHAT was grandfathered, not just hashes."""

    def __init__(self, entries: Optional[List[dict]] = None,
                 path: Optional[str] = None):
        self.path = path
        self.entries = list(entries or [])
        self._fps: Set[str] = {e["fingerprint"] for e in self.entries
                               if "fingerprint" in e}
        self._used: Set[str] = set()

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not os.path.exists(path):
            return cls(path=path)
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
        return cls(data.get("findings", []), path=path)

    def __contains__(self, finding: Finding) -> bool:
        return finding.fingerprint() in self._fps

    def mark_used(self, finding: Finding) -> None:
        self._used.add(finding.fingerprint())

    def stale_entries(self) -> List[dict]:
        """Entries whose fingerprint matched NO finding in the last
        suppression pass: dead grandfathering that could silently mask
        a future regression with the same fingerprint."""
        return [e for e in self.entries
                if e.get("fingerprint") not in self._used]

    def prune(self) -> int:
        """Rewrite the baseline file keeping only entries that still
        fire; returns how many stale entries were dropped."""
        stale = {e.get("fingerprint") for e in self.stale_entries()}
        if not stale or self.path is None:
            return 0
        keep = [e for e in self.entries
                if e.get("fingerprint") not in stale]
        with open(self.path, "w", encoding="utf-8") as f:
            json.dump({"version": 1, "findings": keep}, f, indent=1,
                      sort_keys=True)
            f.write("\n")
        dropped = len(self.entries) - len(keep)
        self.entries = keep
        self._fps = {e["fingerprint"] for e in keep
                     if "fingerprint" in e}
        return dropped

    @staticmethod
    def write(path: str, findings: Sequence[Finding]) -> None:
        entries = sorted(
            ({"rule": f.rule, "path": f.path.replace(os.sep, "/"),
              "symbol": f.symbol, "message": f.message,
              "fingerprint": f.fingerprint()}
             for f in findings if not f.suppressed),
            key=lambda e: e["fingerprint"])
        with open(path, "w", encoding="utf-8") as f:
            json.dump({"version": 1, "findings": entries}, f, indent=1,
                      sort_keys=True)
            f.write("\n")


# ----------------------------------------------------------------------
# Source files and pragmas
# ----------------------------------------------------------------------
class SourceFile:
    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree: Optional[ast.Module] = None
        self.parse_error: Optional[SyntaxError] = None
        try:
            self.tree = ast.parse(source, filename=path)
        except SyntaxError as e:
            self.parse_error = e
        #: line -> (set of rule ids or _ALL, reason)
        self.pragmas: Dict[int, Tuple[object, str]] = {}
        for i, line in enumerate(self.lines, 1):
            if "dl4j:" not in line:
                continue
            m = _PRAGMA_RE.search(line)
            if not m:
                continue
            rules = m.group("rules")
            ids = (_ALL if rules is None else
                   {r.strip() for r in rules.split(",") if r.strip()})
            self.pragmas[i] = (ids, (m.group("reason") or "").strip())

    def pragma_for(self, rule_id: str, line: int) -> Optional[str]:
        """Reason string ('' if none given) when ``rule_id`` is noqa'd
        on ``line``, else None."""
        got = self.pragmas.get(line)
        if got is None:
            return None
        ids, reason = got
        if ids is _ALL or rule_id in ids:
            return reason
        return None


_TEST_FILE_RE = re.compile(r"(^|[\\/])(test_[^\\/]*\.py|conftest\.py)$")


def is_test_path(path: str) -> bool:
    return bool(_TEST_FILE_RE.search(path)) or "tests" in path.split(os.sep)


# ----------------------------------------------------------------------
# Function index / call graph
# ----------------------------------------------------------------------
@dataclass
class FunctionInfo:
    qualname: str              # "module.sub:Class.method.<locals>.inner"
    module: str
    path: str
    node: ast.AST              # FunctionDef / AsyncFunctionDef / Lambda
    class_name: str = ""       # nearest enclosing class, "" at module level
    parent: Optional["FunctionInfo"] = None
    params: Set[str] = field(default_factory=set)
    local_defs: Dict[str, "FunctionInfo"] = field(default_factory=dict)

    @property
    def name(self) -> str:
        return getattr(self.node, "name", "<lambda>")


def _attr_chain(node: ast.AST) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _param_names(node: ast.AST) -> Set[str]:
    a = node.args
    names = [p.arg for p in
             list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return set(names)


#: call names whose function argument is traced by JAX
JIT_WRAPPER_SUFFIXES = {
    "jit", "pjit", "shard_map", "scan", "vmap", "pmap", "checkpoint",
    "remat", "grad", "value_and_grad", "vjp", "jit_sharded_step",
}
#: wrappers whose *first* positional argument is the traced callable
_FN_ARG_INDEX = {name: 0 for name in JIT_WRAPPER_SUFFIXES}

#: lock constructors, with their kind ("lock", "rlock", "condition",
#: "semaphore") — conditions matter because Condition.wait releases
LOCK_CTORS = {
    "Lock": "lock", "RLock": "rlock", "Condition": "condition",
    "Semaphore": "semaphore", "BoundedSemaphore": "semaphore",
}
#: queue constructors — tracked so blocking-call rules recognize a
#: ``q.get()`` even when the variable isn't named queue-ishly
QUEUE_CTORS = {"Queue", "LifoQueue", "PriorityQueue", "SimpleQueue"}
#: calls whose result is a Future: the ctor itself plus the submit
#: verbs used across the serving stack
FUTURE_CTORS = {"Future"}
FUTURE_PRODUCERS = {"submit", "submit_step"}
_LOCKISH_NAME_RE = re.compile(r"(^|_)(lock|mutex|cond)(_|$)|lock$|cond$",
                              re.IGNORECASE)


@dataclass
class LockSite:
    """One ``with <lock>:`` region (or withitem of a multi-item with)."""
    lock_id: str              # canonical cross-file identity
    kind: str                 # lock / rlock / condition / semaphore / unknown
    node: ast.With            # the with statement
    item_expr: ast.AST        # the lock expression itself
    path: str
    func: Optional[FunctionInfo]


class Project:
    """The parsed program: files, function index, heuristic call graph,
    jit-reachability, and lock model.  Built once; rules only read."""

    def __init__(self, files: Sequence[SourceFile],
                 docs_path: Optional[str] = None):
        self.files = list(files)
        self.docs_path = docs_path
        self.functions: Dict[str, FunctionInfo] = {}
        self._by_module: Dict[str, Dict[str, FunctionInfo]] = {}
        self._by_class: Dict[Tuple[str, str], Dict[str, FunctionInfo]] = {}
        self._methods_by_name: Dict[str, List[FunctionInfo]] = {}
        self._imports: Dict[str, Dict[str, str]] = {}   # module -> alias -> target module
        self._str_consts: Dict[str, Dict[str, str]] = {}  # module -> NAME -> value
        self._parents: Dict[str, Dict[ast.AST, ast.AST]] = {}
        self._fn_of_node: Dict[Tuple[str, int], FunctionInfo] = {}
        self.lock_attrs: Dict[str, str] = {}            # lock_id -> kind
        self.queue_attrs: Set[str] = set()              # queue-typed ids
        self.future_attrs: Set[str] = set()             # future-typed ids
        self.lock_sites: List[LockSite] = []
        self._jit_roots: List[FunctionInfo] = []
        self._jit_sites: Dict[str, List[ast.Call]] = {}  # path -> jit Call nodes
        self._reachable: Optional[Set[int]] = None
        self._reachable_infos: List[FunctionInfo] = []
        for f in self.files:
            if f.tree is not None:
                self._index_file(f)
        self._find_jit_roots()
        self._find_locks()

    # -- indexing ------------------------------------------------------
    @staticmethod
    def module_of(path: str) -> str:
        mod = path.replace(os.sep, "/")
        if mod.endswith(".py"):
            mod = mod[:-3]
        if mod.endswith("/__init__"):
            mod = mod[: -len("/__init__")]
        return mod.replace("/", ".")

    def _index_file(self, f: SourceFile) -> None:
        module = self.module_of(f.path)
        parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(f.tree):
            for child in ast.iter_child_nodes(node):
                parents[child] = node
        self._parents[f.path] = parents

        consts: Dict[str, str] = {}
        for node in f.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Constant) \
                    and isinstance(node.value.value, str):
                consts[node.targets[0].id] = node.value.value
        self._str_consts[module] = consts

        imports: Dict[str, str] = {}
        for node in ast.walk(f.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    imports[alias.asname or alias.name.split(".")[0]] = \
                        alias.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for alias in node.names:
                    imports[alias.asname or alias.name] = \
                        f"{node.module}.{alias.name}"
        self._imports[module] = imports

        def visit(node: ast.AST, qual: str, class_name: str,
                  parent_fn: Optional[FunctionInfo]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    visit(child, f"{qual}{child.name}.", child.name,
                          parent_fn)
                elif isinstance(child,
                                (ast.FunctionDef, ast.AsyncFunctionDef)):
                    info = FunctionInfo(
                        qualname=f"{module}:{qual}{child.name}",
                        module=module, path=f.path, node=child,
                        class_name=class_name, parent=parent_fn,
                        params=_param_names(child))
                    self.functions[info.qualname] = info
                    self._fn_of_node[(f.path, id(child))] = info
                    if parent_fn is None and not class_name:
                        self._by_module.setdefault(module, {})[child.name] \
                            = info
                    if class_name and parent_fn is None:
                        self._by_class.setdefault(
                            (module, class_name), {})[child.name] = info
                        self._methods_by_name.setdefault(
                            child.name, []).append(info)
                    if parent_fn is not None:
                        parent_fn.local_defs[child.name] = info
                    visit(child, f"{qual}{child.name}.<locals>.",
                          class_name, info)
                else:
                    visit(child, qual, class_name, parent_fn)

        visit(f.tree, "", "", None)

    def file(self, path: str) -> Optional[SourceFile]:
        for f in self.files:
            if f.path == path:
                return f
        return None

    def parent(self, path: str, node: ast.AST) -> Optional[ast.AST]:
        return self._parents.get(path, {}).get(node)

    def ancestors(self, path: str, node: ast.AST) -> Iterable[ast.AST]:
        cur = self.parent(path, node)
        while cur is not None:
            yield cur
            cur = self.parent(path, cur)

    def enclosing_function(self, path: str,
                           node: ast.AST) -> Optional[FunctionInfo]:
        for anc in self.ancestors(path, node):
            info = self._fn_of_node.get((path, id(anc)))
            if info is not None:
                return info
        return None

    def enclosing_symbol(self, path: str, node: ast.AST) -> str:
        info = self._fn_of_node.get((path, id(node)))
        if info is None:
            info = self.enclosing_function(path, node)
        if info is not None:
            return info.qualname.split(":", 1)[1]
        for anc in self.ancestors(path, node):
            if isinstance(anc, ast.ClassDef):
                return anc.name
        return "<module>"

    # -- call resolution ----------------------------------------------
    def resolve_call(self, call: ast.Call,
                     caller: Optional[FunctionInfo],
                     path: str) -> List[FunctionInfo]:
        """Best-effort static resolution of ``call`` to project
        functions.  Handles: local defs in the lexical chain, module
        functions, ``self.method`` (same class), and
        ``imported_module.func``.  Unresolvable calls return []."""
        func = call.func
        module = self.module_of(path)
        if isinstance(func, ast.Name):
            name = func.id
            cur = caller
            while cur is not None:
                if name in cur.local_defs:
                    return [cur.local_defs[name]]
                cur = cur.parent
            if caller is not None and caller.class_name:
                pass  # bare names inside methods don't hit the class ns
            mod_fns = self._by_module.get(module, {})
            if name in mod_fns:
                return [mod_fns[name]]
            target = self._imports.get(module, {}).get(name)
            if target:
                tmod, _, tname = target.rpartition(".")
                got = self._resolve_imported(tmod, tname)
                if got:
                    return got
            return []
        if isinstance(func, ast.Attribute):
            if isinstance(func.value, ast.Name) and func.value.id == "self" \
                    and caller is not None and caller.class_name:
                meth = self._by_class.get(
                    (caller.module, caller.class_name), {}).get(func.attr)
                return [meth] if meth else []
            chain = _attr_chain(func.value)
            if chain:
                target = self._imports.get(module, {}).get(
                    chain.split(".")[0])
                if target:
                    suffix = chain.split(".", 1)[1] if "." in chain else ""
                    tmod = target + ("." + suffix if suffix else "")
                    got = self._resolve_imported(tmod, func.attr)
                    if got:
                        return got
        return []

    def _resolve_imported(self, module: str,
                          name: str) -> List[FunctionInfo]:
        """Match an absolute-module reference against indexed modules
        (which are keyed by file path): exact and suffix matches first,
        bare-basename equality only as a fallback — two project modules
        share basenames (datasets/iterators vs records/iterators) and
        must not cross-wire."""
        fallback: List[FunctionInfo] = []
        for mod, fns in self._by_module.items():
            if name not in fns:
                continue
            if (mod == module or mod.endswith("." + module)
                    or module.endswith("." + mod)):
                return [fns[name]]
            if module.split(".")[-1] == mod.split(".")[-1] \
                    and not fallback:
                fallback = [fns[name]]
        return fallback

    # -- jit roots and reachability ------------------------------------
    @staticmethod
    def _wrapper_name(func: ast.AST) -> Optional[str]:
        chain = _attr_chain(func)
        if chain is None:
            return None
        leaf = chain.split(".")[-1]
        return leaf if leaf in JIT_WRAPPER_SUFFIXES else None

    def _returned_functions(self, info: FunctionInfo) -> List[FunctionInfo]:
        out = []
        for node in ast.walk(info.node):
            if isinstance(node, ast.Return) and isinstance(node.value,
                                                           ast.Name):
                enc = self.enclosing_function(info.path, node)
                cur = enc
                while cur is not None:
                    if node.value.id in cur.local_defs:
                        out.append(cur.local_defs[node.value.id])
                        break
                    cur = cur.parent
        return out

    def _fn_arg_targets(self, arg: ast.AST, caller: Optional[FunctionInfo],
                        path: str) -> List[FunctionInfo]:
        """Resolve the callable argument of a jit-style wrapper."""
        if isinstance(arg, ast.Lambda):
            info = FunctionInfo(
                qualname=f"{self.module_of(path)}:<lambda:{arg.lineno}>",
                module=self.module_of(path), path=path, node=arg,
                class_name=caller.class_name if caller else "",
                parent=caller, params=_param_names(arg))
            self._fn_of_node[(path, id(arg))] = info
            return [info]
        if isinstance(arg, ast.Name):
            cur = caller
            while cur is not None:
                if arg.id in cur.local_defs:
                    return [cur.local_defs[arg.id]]
                cur = cur.parent
            mod_fns = self._by_module.get(self.module_of(path), {})
            if arg.id in mod_fns:
                return [mod_fns[arg.id]]
            return []
        if isinstance(arg, ast.Call):
            built = []
            for target in self.resolve_call(arg, caller, path):
                built.extend(self._returned_functions(target))
            return built
        if isinstance(arg, ast.Attribute):
            if isinstance(arg.value, ast.Name) and arg.value.id == "self" \
                    and caller is not None and caller.class_name:
                meth = self._by_class.get(
                    (caller.module, caller.class_name), {}).get(arg.attr)
                return [meth] if meth else []
        return []

    def _find_jit_roots(self) -> None:
        roots: List[FunctionInfo] = []
        for f in self.files:
            if f.tree is None:
                continue
            sites: List[ast.Call] = []
            for node in ast.walk(f.tree):
                if isinstance(node, ast.Call):
                    wname = self._wrapper_name(node.func)
                    if wname is None or not node.args:
                        continue
                    sites.append(node)
                    caller = self.enclosing_function(f.path, node)
                    roots.extend(self._fn_arg_targets(
                        node.args[_FN_ARG_INDEX[wname]], caller, f.path))
                elif isinstance(node,
                                (ast.FunctionDef, ast.AsyncFunctionDef)):
                    for dec in node.decorator_list:
                        target = dec.func if isinstance(dec, ast.Call) \
                            else dec
                        if isinstance(dec, ast.Call) and \
                                _attr_chain(dec.func) and \
                                _attr_chain(dec.func).split(".")[-1] == \
                                "partial" and dec.args:
                            target = dec.args[0]
                            inner = self._wrapper_name(target)
                            if inner:
                                info = self._fn_of_node.get(
                                    (f.path, id(node)))
                                if info:
                                    roots.append(info)
                            continue
                        if self._wrapper_name(target):
                            info = self._fn_of_node.get((f.path, id(node)))
                            if info:
                                roots.append(info)
            self._jit_sites[f.path] = sites
        self._jit_roots = roots

    def jit_reachable(self) -> List[FunctionInfo]:
        """Functions reachable (via the heuristic call graph) from any
        jit/pjit/scan/shard_map call site — the set the tracer-safety
        rules scan."""
        if self._reachable is not None:
            return self._reachable_infos
        seen: Set[int] = set()
        infos: List[FunctionInfo] = []
        frontier = list(self._jit_roots)
        while frontier:
            info = frontier.pop()
            if id(info.node) in seen:
                continue
            seen.add(id(info.node))
            infos.append(info)
            for node in ast.walk(info.node):
                if isinstance(node, ast.Call):
                    inner = self.enclosing_function(info.path, node) or info
                    for callee in self.resolve_call(node, inner, info.path):
                        if id(callee.node) not in seen:
                            frontier.append(callee)
        self._reachable = seen
        self._reachable_infos = infos
        return infos

    def is_jit_reachable(self, info: FunctionInfo) -> bool:
        self.jit_reachable()
        return id(info.node) in (self._reachable or set())

    # -- locks ---------------------------------------------------------
    def _lock_id_and_kind(self, expr: ast.AST, path: str,
                          func: Optional[FunctionInfo]) \
            -> Optional[Tuple[str, str]]:
        """Canonical identity for a lock expression, or None when the
        expression isn't lock-like.  ``self._lock`` in class C of module
        m -> ``m:C._lock`` so every method (and every instance) of C
        shares one node in the order graph — the standard static
        approximation."""
        chain = _attr_chain(expr)
        if chain is None:
            return None
        module = self.module_of(path)
        leaf = chain.split(".")[-1]
        if chain.startswith("self.") and func is not None \
                and func.class_name:
            lock_id = f"{module}:{func.class_name}.{chain[len('self.'):]}"
        elif "." not in chain:
            # bare name: module-global lock unless a known function-local
            # binding shadows it (globals are the common case — one id
            # per module-level lock, shared across every function)
            scoped = None
            if func is not None:
                cand = (f"{module}:"
                        f"{func.qualname.split(':', 1)[1]}.{chain}")
                if cand in self.lock_attrs:
                    scoped = cand
            lock_id = scoped or f"{module}:{chain}"
        else:
            lock_id = f"{module}:{chain}"
        kind = self.lock_attrs.get(lock_id)
        if kind is None and not _LOCKISH_NAME_RE.search(leaf):
            return None
        return lock_id, kind or "unknown"

    def _binding_id(self, tchain: str, module: str,
                    func: Optional[FunctionInfo]) -> str:
        """Canonical id for an assignment TARGET chain (shared by the
        lock/queue/future binding passes)."""
        if tchain.startswith("self.") and func is not None \
                and func.class_name:
            return f"{module}:{func.class_name}.{tchain[len('self.'):]}"
        if "." not in tchain and func is not None:
            scope = func.qualname.split(":", 1)[1]
            return f"{module}:{scope}.{tchain}"
        return f"{module}:{tchain}"

    def ids_for(self, expr: ast.AST, path: str,
                func: Optional[FunctionInfo]) -> List[str]:
        """Candidate canonical ids for an expression READ — used to
        look a receiver up in the lock/queue/future binding tables."""
        chain = _attr_chain(expr)
        if chain is None:
            return []
        module = self.module_of(path)
        out: List[str] = []
        if chain.startswith("self.") and func is not None \
                and func.class_name:
            out.append(f"{module}:{func.class_name}."
                       f"{chain[len('self.'):]}")
        elif "." not in chain:
            if func is not None:
                scope = func.qualname.split(":", 1)[1]
                out.append(f"{module}:{scope}.{chain}")
            out.append(f"{module}:{chain}")
        else:
            out.append(f"{module}:{chain}")
        return out

    def _find_locks(self) -> None:
        # pass 1: every `X = threading.Lock()`-style binding, so locks
        # with non-lockish names are still tracked — queue and Future
        # bindings ride the same pass for the blocking-call rules
        for f in self.files:
            if f.tree is None:
                continue
            module = self.module_of(f.path)
            for node in ast.walk(f.tree):
                if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                    continue
                value = node.value
                if not isinstance(value, ast.Call):
                    continue
                chain = _attr_chain(value.func) or ""
                ctor = chain.split(".")[-1]
                kind = LOCK_CTORS.get(ctor)
                is_queue = ctor in QUEUE_CTORS
                is_future = (ctor in FUTURE_CTORS
                             or (isinstance(value.func, ast.Attribute)
                                 and value.func.attr in FUTURE_PRODUCERS))
                if kind is None and not is_queue and not is_future:
                    continue
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                func = self.enclosing_function(f.path, node)
                for t in targets:
                    tchain = _attr_chain(t)
                    if tchain is None:
                        continue
                    bid = self._binding_id(tchain, module, func)
                    if kind is not None:
                        self.lock_attrs[bid] = kind
                    elif is_queue:
                        self.queue_attrs.add(bid)
                    elif is_future:
                        self.future_attrs.add(bid)
        # pass 2: every with-lock region
        for f in self.files:
            if f.tree is None:
                continue
            for node in ast.walk(f.tree):
                if not isinstance(node, (ast.With, ast.AsyncWith)):
                    continue
                func = self.enclosing_function(f.path, node)
                for item in node.items:
                    expr = item.context_expr
                    got = self._lock_id_and_kind(expr, f.path, func)
                    if got is None:
                        continue
                    lock_id, kind = got
                    self.lock_sites.append(LockSite(
                        lock_id=lock_id, kind=kind, node=node,
                        item_expr=expr, path=f.path, func=func))

    # -- registry call sites (for the drift rules) ---------------------
    REGISTRY_METHODS = {"counter", "gauge", "histogram"}

    def metric_call_sites(self) -> List[Tuple[str, ast.Call, str, bool]]:
        """Every ``*.counter/gauge/histogram("dl4j_...")`` call:
        ``(path, call_node, name_or_pattern, is_pattern)`` — f-string
        names become regex patterns with ``[a-z0-9_]+`` holes."""
        out = []
        for f in self.files:
            if f.tree is None:
                continue
            for node in ast.walk(f.tree):
                if not isinstance(node, ast.Call) or not node.args:
                    continue
                if not isinstance(node.func, ast.Attribute) or \
                        node.func.attr not in self.REGISTRY_METHODS:
                    continue
                arg = node.args[0]
                if isinstance(arg, ast.Name):
                    # registration through a module constant, e.g.
                    # reg.histogram(PHASE_METRIC, ...)
                    val = self._str_consts.get(
                        self.module_of(f.path), {}).get(arg.id)
                    if val is not None:
                        arg = ast.Constant(value=val)
                if isinstance(arg, ast.Constant) and \
                        isinstance(arg.value, str):
                    if arg.value.startswith("dl4j_"):
                        out.append((f.path, node, arg.value, False))
                elif isinstance(arg, ast.JoinedStr):
                    parts = []
                    for v in arg.values:
                        if isinstance(v, ast.Constant):
                            parts.append(re.escape(str(v.value)))
                        else:
                            parts.append("[a-z0-9_]+")
                    pattern = "".join(parts)
                    if pattern.startswith("dl4j_"):
                        out.append((f.path, node, pattern, True))
        return out

    # -- journal event call sites (for the event drift rules) -----------
    #: dotted lowercase event-type names, e.g. "span.close" — the shape
    #: that distinguishes journal emits from other string-first calls
    EVENT_NAME_RE = re.compile(r"[a-z0-9_]+(?:\.[a-z0-9_]+)+\Z")

    def event_call_sites(self) -> List[Tuple[str, ast.Call, str]]:
        """Every ``*.emit("type.name", ...)`` / ``emit("type.name")``
        call with a literal dotted event-type first argument:
        ``(path, call_node, name)`` — the code side of the journal
        event taxonomy (monitor/events.py)."""
        out = []
        for f in self.files:
            if f.tree is None:
                continue
            for node in ast.walk(f.tree):
                if not isinstance(node, ast.Call) or not node.args:
                    continue
                func = node.func
                name = (func.attr if isinstance(func, ast.Attribute)
                        else func.id if isinstance(func, ast.Name)
                        else None)
                if name != "emit":
                    continue
                arg = node.args[0]
                if isinstance(arg, ast.Constant) \
                        and isinstance(arg.value, str) \
                        and self.EVENT_NAME_RE.match(arg.value):
                    out.append((f.path, node, arg.value))
        return out

    def event_type_constants(self) -> List[Tuple[str, ast.AST, str]]:
        """Entries of module-level ``EVENT_TYPES`` tuples/lists — the
        declared taxonomy (one per name, with its declaring node)."""
        out = []
        for f in self.files:
            if f.tree is None:
                continue
            for node in f.tree.body:
                if not isinstance(node, ast.Assign) \
                        or len(node.targets) != 1 \
                        or not isinstance(node.targets[0], ast.Name) \
                        or node.targets[0].id != "EVENT_TYPES" \
                        or not isinstance(node.value, (ast.Tuple, ast.List)):
                    continue
                for elt in node.value.elts:
                    if isinstance(elt, ast.Constant) \
                            and isinstance(elt.value, str):
                        out.append((f.path, elt, elt.value))
        return out

    # -- thread spawn sites (for the thread-protocol rules) --------------
    def thread_targets(self) -> List[
            Tuple[str, ast.Call, List[FunctionInfo]]]:
        """Every ``Thread(target=...)`` construction with its resolved
        target functions (``[]`` when the target is not statically
        resolvable — e.g. a bound method of another object)."""
        out = []
        for f in self.files:
            if f.tree is None:
                continue
            for node in ast.walk(f.tree):
                if not isinstance(node, ast.Call):
                    continue
                chain = _attr_chain(node.func)
                if not chain or chain.split(".")[-1] != "Thread":
                    continue
                texpr = None
                for kw in node.keywords:
                    if kw.arg == "target":
                        texpr = kw.value
                if texpr is None:
                    continue
                caller = self.enclosing_function(f.path, node)
                targets = self._fn_arg_targets(texpr, caller, f.path)
                out.append((f.path, node, targets))
        return out

    def held_locks_at(self, path: str, node: ast.AST,
                      func: Optional[FunctionInfo]) -> Set[str]:
        """Lock ids lexically held at ``node`` (enclosing with-lock
        blocks in the same function)."""
        held: Set[str] = set()
        for anc in self.ancestors(path, node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                break
            if isinstance(anc, (ast.With, ast.AsyncWith)):
                for item in anc.items:
                    got = self._lock_id_and_kind(item.context_expr, path,
                                                 func)
                    if got is not None:
                        held.add(got[0])
        return held


# ----------------------------------------------------------------------
# Runner
# ----------------------------------------------------------------------
def collect_py_files(paths: Sequence[str],
                     root: Optional[str] = None) -> List[str]:
    """Expand files/directories into a sorted list of .py paths,
    relative to ``root`` (default cwd)."""
    root = os.path.abspath(root or os.getcwd())
    found: List[str] = []
    for p in paths:
        ap = os.path.abspath(p)
        if os.path.isfile(ap) and ap.endswith(".py"):
            found.append(ap)
        elif os.path.isdir(ap):
            for dirpath, dirnames, filenames in os.walk(ap):
                dirnames[:] = sorted(
                    d for d in dirnames
                    if d != "__pycache__" and not d.startswith("."))
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        found.append(os.path.join(dirpath, fn))
    rel = []
    for ap in found:
        try:
            rel.append(os.path.relpath(ap, root))
        except ValueError:
            rel.append(ap)
    return sorted(set(rel))


def build_project(paths: Sequence[str], root: Optional[str] = None,
                  docs_path: Optional[str] = None) -> Project:
    root = os.path.abspath(root or os.getcwd())
    files = []
    for rel in collect_py_files(paths, root):
        full = rel if os.path.isabs(rel) else os.path.join(root, rel)
        try:
            with open(full, "r", encoding="utf-8") as f:
                src = f.read()
        except (OSError, UnicodeDecodeError):
            continue
        files.append(SourceFile(rel, src))
    if docs_path is None:
        cand = os.path.join(root, "docs", "OBSERVABILITY.md")
        docs_path = cand if os.path.exists(cand) else None
    return Project(files, docs_path=docs_path)


def run_rules(project: Project,
              rule_ids: Optional[Sequence[str]] = None,
              disabled: Sequence[str] = ()) -> List[Finding]:
    import deeplearning4j_tpu.analysis.rules  # noqa: F401 — registers
    chosen = [RULES[r] for r in (rule_ids or sorted(RULES))
              if r in RULES and r not in set(disabled)]
    findings: List[Finding] = []
    for f in project.files:
        if f.parse_error is not None:
            findings.append(Finding(
                rule="DL4J000", severity=ERROR, path=f.path,
                line=f.parse_error.lineno or 1, col=0,
                message=f"syntax error: {f.parse_error.msg}",
                symbol="<module>"))
    for rule in chosen:
        seen = set()
        for finding in rule.run(project):
            key = (finding.rule, finding.path, finding.line, finding.col,
                   finding.message)
            if key in seen:
                continue
            seen.add(key)
            findings.append(finding)
    findings.sort(key=lambda x: (x.path, x.line, x.col, x.rule))
    return findings


def apply_suppressions(project: Project, findings: Sequence[Finding],
                       baseline: Optional[Baseline] = None) -> None:
    for finding in findings:
        # usage is tracked for EVERY matching finding (even ones a
        # pragma also covers) so staleness means "fires nowhere", not
        # "fires only where a noqa shadows it"
        if baseline is not None and finding in baseline:
            baseline.mark_used(finding)
        f = project.file(finding.path)
        if f is not None:
            reason = f.pragma_for(finding.rule, finding.line)
            if reason is not None:
                finding.suppressed = True
                finding.noqa_reason = reason
                continue
        if baseline is not None and finding in baseline:
            finding.baselined = True


def lint(paths: Sequence[str], root: Optional[str] = None,
         baseline_path: Optional[str] = None,
         docs_path: Optional[str] = None,
         rule_ids: Optional[Sequence[str]] = None,
         disabled: Sequence[str] = ()) -> Tuple[List[Finding], Project]:
    """One-call API: build the project, run the rules, apply pragma and
    baseline suppression.  Returns (findings, project)."""
    project = build_project(paths, root=root, docs_path=docs_path)
    findings = run_rules(project, rule_ids=rule_ids, disabled=disabled)
    baseline = Baseline.load(baseline_path) if baseline_path else None
    apply_suppressions(project, findings, baseline)
    return findings, project
