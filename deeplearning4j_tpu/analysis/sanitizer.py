"""Runtime sanitizer harness — the dynamic half of dl4j-lint.

The static rules catch what is visible in source; this harness catches
what only exists at runtime: an implicit host transfer the call graph
hid, a NaN born inside the compiled step, a silent rank promotion, a
retrace storm from a shape the bucketing ladder missed.  Four
env-gated modes:

``transfer``
    Arms ``jax.transfer_guard("disallow")`` around the jitted/pjit'd
    train-step dispatch (both fit loops) and the serving
    micro-batcher's compute call.  Every input the step needs is
    explicitly placed (``jnp.asarray``/``device_put``/``shard_put``)
    BEFORE the guarded region, so any implicit transfer inside it is a
    bug by construction.  Compile steps (a fresh ``CompileTelemetry``
    signature) are exempt — constant materialization during lowering is
    a legitimate transfer.
``nans``
    ``jax_debug_nans``: the step re-runs op-by-op when a NaN appears,
    pointing at the producing primitive.
``rank``
    ``jax_numpy_rank_promotion`` checking.  NOT armed by
    ``DL4J_SANITIZE=1`` (layer bias adds are rank promotion by design);
    opt in with ``DL4J_SANITIZE=all`` or ``DL4J_SANITIZE_RANK=warn|raise``.
``retrace``
    Budget assertion on ``CompileTelemetry``: a ``fit()`` that retraces
    more than ``DL4J_SANITIZE_RETRACE_BUDGET`` (default 64) times
    raises :class:`SanitizerError` at the end of the (otherwise
    successful) fit — the "your bucketing is not working" alarm.

Switches: ``DL4J_SANITIZE=1`` (transfer+nans+retrace), ``=all`` (the
four), or a comma list (``DL4J_SANITIZE=transfer,retrace``).
Programmatic arming for tests: ``with sanitizer.sanitize(modes=...):``
(the ``dl4j_sanitize`` pytest fixture in tests/conftest.py is exactly
this).  Violations and armed state meter into the registry
(``dl4j_sanitizer_*``, docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import contextlib
import os
import threading
from typing import Iterable, Optional, Tuple

MODES: Tuple[str, ...] = ("transfer", "nans", "rank", "retrace")
DEFAULT_MODES: Tuple[str, ...] = ("transfer", "nans", "retrace")
_DEFAULT_RETRACE_BUDGET = 64

_TRUTHY = ("1", "true", "on", "yes")
_local = threading.local()


class SanitizerError(AssertionError):
    """A sanitizer mode tripped (retrace budget exceeded, or a guarded
    transfer re-raised with context)."""


def _registry():
    from deeplearning4j_tpu import monitor
    return monitor.get_registry()


def _violation(mode: str) -> None:
    try:
        _registry().counter(
            "dl4j_sanitizer_violations_total",
            "sanitizer modes tripped (guarded transfer, NaN, retrace "
            "budget)", labels=("mode",)).labels(mode=mode).inc()
        from deeplearning4j_tpu.monitor import events
        events.emit("sanitizer.violation", severity="error", mode=mode)
    except Exception:
        pass  # the sanitizer must never die on telemetry


def _flight_dump(reason: str, extra=None) -> None:
    try:
        from deeplearning4j_tpu.monitor import flight
        flight.dump(reason, extra=extra)
    except Exception:
        pass  # the recorder must never worsen the crash


def _env_modes() -> frozenset:
    raw = os.environ.get("DL4J_SANITIZE", "").strip().lower()
    if raw in ("", "0", "false", "off"):
        base = frozenset()
    elif raw in _TRUTHY:
        base = frozenset(DEFAULT_MODES)
    elif raw == "all":
        base = frozenset(MODES)
    else:
        base = frozenset(m.strip() for m in raw.split(",")
                         if m.strip() in MODES)
    if os.environ.get("DL4J_SANITIZE_RANK", "").strip().lower() in (
            "1", "warn", "raise"):
        base = base | {"rank"}
    return base


def active_modes() -> frozenset:
    """Programmatic arming (innermost ``sanitize()`` block) wins over
    the environment."""
    stack = getattr(_local, "stack", None)
    if stack:
        return stack[-1][0]
    return _env_modes()


def enabled(mode: str) -> bool:
    return mode in active_modes()


def retrace_budget() -> int:
    stack = getattr(_local, "stack", None)
    if stack and stack[-1][1] is not None:
        return stack[-1][1]
    try:
        return int(os.environ.get("DL4J_SANITIZE_RETRACE_BUDGET",
                                  str(_DEFAULT_RETRACE_BUDGET)))
    except ValueError:
        return _DEFAULT_RETRACE_BUDGET


def _rank_level() -> str:
    lvl = os.environ.get("DL4J_SANITIZE_RANK", "").strip().lower()
    return "warn" if lvl == "warn" else "raise"


@contextlib.contextmanager
def sanitize(modes: Iterable[str] = DEFAULT_MODES,
             retrace_budget: Optional[int] = None):
    """Programmatically arm sanitizer modes for the current thread —
    the test-facing surface (see the ``dl4j_sanitize`` fixture)."""
    bad = set(modes) - set(MODES)
    if bad:
        raise ValueError(f"unknown sanitizer modes: {sorted(bad)}")
    stack = getattr(_local, "stack", None)
    if stack is None:
        stack = _local.stack = []
    stack.append((frozenset(modes), retrace_budget))
    try:
        yield
    finally:
        stack.pop()


@contextlib.contextmanager
def armed_fit(net):
    """Wrap one ``fit()``: flips the jax debug configs for the duration
    and asserts the retrace budget (fed by the net's
    ``CompileTelemetry``) on successful exit."""
    modes = active_modes()
    if not modes:
        yield
        return
    import jax
    try:
        _registry().gauge(
            "dl4j_sanitizer_armed",
            "sanitizer modes currently armed around fit/serve "
            "(0 = off)").set(len(modes))
    except Exception:
        pass
    saved = {}

    def _flip(key, value):
        saved[key] = getattr(jax.config, key)
        jax.config.update(key, value)

    telemetry = getattr(net, "compile_telemetry", None)
    start_retraces = telemetry.retraces if telemetry is not None else 0
    ok = False
    try:
        if "nans" in modes:
            _flip("jax_debug_nans", True)
        if "rank" in modes:
            _flip("jax_numpy_rank_promotion", _rank_level())
        yield
        ok = True
    except FloatingPointError:
        _violation("nans")
        _flight_dump("nan_in_step")
        raise
    finally:
        for key, value in saved.items():
            jax.config.update(key, value)
        try:
            _registry().gauge("dl4j_sanitizer_armed", "").set(0)
        except Exception:
            pass
    if ok and "retrace" in modes and telemetry is not None:
        budget = retrace_budget()
        delta = telemetry.retraces - start_retraces
        if delta > budget:
            _violation("retrace")
            _flight_dump("retrace_budget",
                         extra={"retraces": delta, "budget": budget})
            raise SanitizerError(
                f"retrace budget exceeded: {delta} retraces in one "
                f"fit() against a budget of {budget} — shapes are not "
                "bucketing (enable conf.shape_bucketing, or raise "
                "DL4J_SANITIZE_RETRACE_BUDGET if this workload "
                "legitimately compiles that many programs)")


@contextlib.contextmanager
def guard_step(compiling: bool = False):
    """Arm ``jax.transfer_guard("disallow")`` around one jitted step
    dispatch.  ``compiling=True`` (a fresh jit signature, per
    ``CompileTelemetry.record``) disarms for that call: constant
    materialization during lowering transfers legitimately."""
    if compiling or not enabled("transfer"):
        yield
        return
    import jax
    try:
        with jax.transfer_guard("disallow"):
            yield
    except Exception as e:
        if "transfer" in str(e).lower():
            _violation("transfer")
        raise
