"""Concurrency rules (DL4J2xx): blocking calls while holding a lock, a
whole-program lock-acquisition-order graph that fails on cycles, and
bare ``acquire()`` without a try/finally release.

Lock identity is the standard static approximation: ``self._lock`` in
class ``C`` of module ``m`` is the node ``m:C._lock`` — every method
and every instance of ``C`` shares it.  That makes the order graph
conservative (two DIFFERENT instances of one class count as one lock),
which is the right bias for deadlock detection: an inversion between
`datasets/iterators.py`'s reorder-buffer condition and
`server/batcher.py`'s dispatch condition only manifests under
concurrent load on a real serving host, never in unit tests.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from deeplearning4j_tpu.analysis.core import (
    ERROR, WARNING, Finding, FunctionInfo, LockSite, Project, Rule,
    _attr_chain, register)

#: how many call-graph levels below a with-lock block are searched for
#: blocking primitives / nested lock acquisitions
_CALL_DEPTH = 3

_BLOCKING_MODULE_CALLS = {
    "time.sleep": "time.sleep()",
    "subprocess.run": "subprocess.run()",
    "subprocess.check_output": "subprocess.check_output()",
    "subprocess.check_call": "subprocess.check_call()",
    "os.system": "os.system()",
    "urllib.request.urlopen": "urlopen()",
    "urlopen": "urlopen()",
    "socket.create_connection": "socket.create_connection()",
}


def _timeout_kw(call: ast.Call) -> bool:
    return any(kw.arg == "timeout" for kw in call.keywords)


def _is_queue_typed(project: Project, expr: ast.AST, path: str,
                    func: Optional["FunctionInfo"]) -> bool:
    """Receiver assigned from a ``queue.Queue()``-family constructor —
    catches queues whose names don't look queue-ish."""
    return any(i in project.queue_attrs
               for i in project.ids_for(expr, path, func))


def _is_future_typed(project: Project, expr: ast.AST, path: str,
                     func: Optional["FunctionInfo"]) -> bool:
    """Receiver assigned from ``Future()`` or a ``submit*()`` call —
    catches futures whose names don't say fut/promise."""
    return any(i in project.future_attrs
               for i in project.ids_for(expr, path, func))


def _block_false(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "block" and isinstance(kw.value, ast.Constant) \
                and kw.value.value is False:
            return True
    return False


def _blocking_reason(call: ast.Call, held_kinds: Dict[str, str],
                     project: Project, path: str,
                     func: "FunctionInfo") -> Optional[str]:
    """Why ``call`` blocks indefinitely, or None if it doesn't."""
    func_expr = call.func
    chain = _attr_chain(func_expr) or ""
    if chain in _BLOCKING_MODULE_CALLS:
        return _BLOCKING_MODULE_CALLS[chain]
    if isinstance(func_expr, ast.Name) and func_expr.id == "open":
        return "open() (file I/O)"
    if not isinstance(func_expr, ast.Attribute):
        return None
    attr = func_expr.attr
    if attr in ("put", "get", "put_nowait", "get_nowait"):
        if attr.endswith("_nowait") or _timeout_kw(call) \
                or _block_false(call):
            return None
        # put(item, timeout) / get(block, timeout) positional forms
        if attr == "put" and len(call.args) >= 2:
            return None
        if attr == "get" and len(call.args) >= 2:
            return None
        recv = _attr_chain(func_expr.value) or ""
        leaf = recv.split(".")[-1]
        if "q" in leaf.lower() or "queue" in leaf.lower() \
                or _is_queue_typed(project, func_expr.value, path, func):
            return f"{leaf}.{attr}() without timeout"
        return None
    if attr == "join" and not call.args and not call.keywords:
        # str.join always takes an iterable argument; a no-arg join is
        # a Thread/Process join — unbounded
        return "unbounded .join()"
    if attr == "result" and not call.args and not _timeout_kw(call):
        recv = _attr_chain(func_expr.value) or ""
        leaf = recv.split(".")[-1].lower()
        if "fut" in leaf or "promise" in leaf \
                or _is_future_typed(project, func_expr.value, path, func):
            return f"{recv.split('.')[-1]}.result() without timeout"
        return None
    if attr == "wait" and not call.args and not _timeout_kw(call):
        recv = _attr_chain(func_expr.value) or ""
        got = project._lock_id_and_kind(func_expr.value, path, func)
        if got is not None:
            lock_id, kind = got
            # Condition.wait on a lock we hold RELEASES it — fine when
            # bounded; an unbounded wait still stalls shutdown forever
            return f"{recv.split('.')[-1] or 'condition'}.wait() " \
                   "without timeout"
        return None
    if attr == "acquire" and not _timeout_kw(call) \
            and not _block_false(call):
        got = project._lock_id_and_kind(func_expr.value, path, func)
        if got is not None and got[0] not in held_kinds:
            return f"nested {got[0].split(':')[-1]}.acquire()"
    return None


def _locks_in_with(project: Project, site: LockSite) -> List[ast.AST]:
    """Statements governed by a with-lock item (its body)."""
    return site.node.body


def _prune_walk(stmts):
    """Walk a statement list without descending into nested function
    definitions (their bodies run later, outside the lock)."""
    stack = list(stmts)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _iter_block_calls(stmts):
    """Calls in a statement list, NOT descending into nested function
    definitions (a closure defined under a lock runs later, lock-free)."""
    stack = list(stmts)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if isinstance(node, ast.Call):
            yield node
        stack.extend(ast.iter_child_nodes(node))


class _LockWalker:
    """Shared traversal for DL4J201/DL4J202: from each with-lock region,
    explore the statically-resolvable call graph a few levels deep,
    reporting blocking primitives and nested lock acquisitions with the
    call chain that reaches them."""

    def __init__(self, project: Project):
        self.project = project

    def explore(self, site: LockSite):
        """Yields ('blocking'|'lock', payload, chain) events.

        payload: reason string for blocking events, (lock_id, kind) for
        nested-acquisition events.  chain: "f -> g" call path."""
        yield from self._walk_stmts(
            _locks_in_with(self.project, site), site.path, site.func,
            held={site.lock_id: site.kind}, chain=(), depth=0,
            visited={id(site.node)})

    def _walk_stmts(self, stmts, path, func, held, chain, depth, visited):
        project = self.project
        for node in _iter_block_calls(stmts):
            reason = _blocking_reason(node, held, project, path, func)
            if reason is not None:
                yield ("blocking", reason, chain, node, path)
            got = None
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "acquire":
                got = project._lock_id_and_kind(node.func.value, path,
                                                func)
            if got is not None:
                yield ("lock", got, chain, node, path)
            # descend into resolvable callees
            if depth >= _CALL_DEPTH:
                continue
            for callee in project.resolve_call(node, func, path):
                if id(callee.node) in visited:
                    continue
                visited = visited | {id(callee.node)}
                body = callee.node.body
                if isinstance(callee.node, ast.Lambda):
                    body = [callee.node.body]
                yield from self._walk_with_subwiths(
                    body, callee.path, callee, held,
                    chain + (callee.name,), depth + 1, visited)

    def _walk_with_subwiths(self, stmts, path, func, held, chain, depth,
                            visited):
        """Like _walk_stmts but also reports with-lock regions inside
        the callee (a lock ACQUIRED while the outer one is held)."""
        project = self.project
        for node in _prune_walk(stmts):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    got = project._lock_id_and_kind(
                        item.context_expr, path, func)
                    if got is not None:
                        yield ("lock", got, chain, node, path)
        yield from self._walk_stmts(stmts, path, func, held, chain,
                                    depth, visited)


@register
class BlockingUnderLock(Rule):
    id = "DL4J201"
    name = "blocking-under-lock"
    severity = WARNING
    doc = ("Blocking calls (queue put/get without timeout, unbounded "
           ".join()/.wait()/.result(), time.sleep, file/network I/O) "
           "while holding a threading lock: every other thread needing "
           "that lock stalls behind the slow operation — the classic "
           "input-pipeline/batcher tail-latency bug.")

    def run(self, project: Project) -> Iterable[Finding]:
        walker = _LockWalker(project)
        for site in project.lock_sites:
            for kind, payload, chain, node, path in walker.explore(site):
                if kind != "blocking":
                    continue
                via = f" (via {' -> '.join(chain)})" if chain else ""
                lock_name = site.lock_id.split(":")[-1]
                yield Finding(
                    rule=self.id, severity=self.severity, path=site.path,
                    line=site.node.lineno, col=site.node.col_offset,
                    message=f"{payload} while holding {lock_name}{via}",
                    symbol=project.enclosing_symbol(site.path, site.node))


@register
class LockOrderCycle(Rule):
    id = "DL4J202"
    name = "lock-order-cycle"
    severity = ERROR
    doc = ("Whole-program lock-acquisition-order graph: an edge A->B "
           "for every place lock B is acquired while A is held (same "
           "function or through resolvable calls).  A cycle means two "
           "threads can each hold one lock and wait for the other — "
           "a deadlock that only fires under concurrent load.")

    def run(self, project: Project) -> Iterable[Finding]:
        walker = _LockWalker(project)
        # edge -> first witness (path, line, chain)
        edges: Dict[Tuple[str, str], Tuple[str, int, Tuple[str, ...]]] = {}
        # nested with-blocks inside one function body
        for site in project.lock_sites:
            for stmt in site.node.body:
                for node in ast.walk(stmt):
                    if isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef,
                                         ast.Lambda)):
                        continue
                    if isinstance(node, (ast.With, ast.AsyncWith)):
                        for item in node.items:
                            got = project._lock_id_and_kind(
                                item.context_expr, site.path, site.func)
                            if got is not None:
                                self._edge(edges, site, got[0],
                                           node.lineno, ())
            for kind, payload, chain, node, path in walker.explore(site):
                if kind != "lock":
                    continue
                self._edge(edges, site, payload[0],
                           getattr(node, "lineno", site.node.lineno),
                           chain)
        # RLock self-edges are re-entrant, drop them; plain-Lock
        # self-edges are immediate self-deadlocks, keep
        adj: Dict[str, Set[str]] = {}
        for (a, b), _w in edges.items():
            if a == b:
                kind = project.lock_attrs.get(a, "unknown")
                if kind in ("rlock", "condition", "unknown"):
                    continue
            adj.setdefault(a, set()).add(b)
            adj.setdefault(b, set())
        for cycle in self._cycles(adj):
            path_desc = " -> ".join(cycle + (cycle[0],))
            witness = None
            for i in range(len(cycle)):
                w = edges.get((cycle[i], cycle[(i + 1) % len(cycle)]))
                if w is not None:
                    witness = w
                    break
            wpath, wline = (witness[0], witness[1]) if witness \
                else ("<unknown>", 1)
            yield Finding(
                rule=self.id, severity=self.severity, path=wpath,
                line=wline, col=0,
                message=("lock-order cycle: "
                         + path_desc.replace("\\", "/")
                         + " — acquisition order must be globally "
                           "consistent"),
                symbol="<lock-graph>")

    @staticmethod
    def _edge(edges, site: LockSite, to_lock: str, line: int,
              chain: Tuple[str, ...]) -> None:
        key = (site.lock_id, to_lock)
        if key not in edges:
            edges[key] = (site.path, line, chain)

    @staticmethod
    def _cycles(adj: Dict[str, Set[str]]) -> List[Tuple[str, ...]]:
        """Elementary cycles via DFS over SCCs — canonicalized (rotated
        to the smallest node, deduped) so each cycle reports once."""
        seen_cycles: Set[Tuple[str, ...]] = set()
        out: List[Tuple[str, ...]] = []
        for start in sorted(adj):
            stack: List[Tuple[str, Tuple[str, ...]]] = [(start, (start,))]
            while stack:
                node, path = stack.pop()
                for nxt in sorted(adj.get(node, ())):
                    if nxt == path[0]:
                        i = path.index(min(path))
                        canon = path[i:] + path[:i]
                        if canon not in seen_cycles and \
                                (len(path) > 1 or nxt == node):
                            seen_cycles.add(canon)
                            out.append(canon)
                    elif nxt not in path and nxt > path[0]:
                        # only explore cycles whose smallest node is the
                        # start — each elementary cycle found exactly once
                        stack.append((nxt, path + (nxt,)))
        return out


@register
class UnboundedJoin(Rule):
    id = "DL4J204"
    name = "unbounded-join"
    severity = WARNING
    doc = ("`thread.join()` with no timeout in non-test code: a worker "
           "wedged in user ETL or a dead-peer socket read blocks the "
           "caller forever — shutdown paths hang instead of failing. "
           "Join with a timeout and escalate, or noqa with the reason "
           "the unbounded wait is required.")

    def run(self, project: Project) -> Iterable[Finding]:
        from deeplearning4j_tpu.analysis.core import is_test_path
        for f in project.files:
            if f.tree is None or is_test_path(f.path):
                continue
            for node in ast.walk(f.tree):
                if not isinstance(node, ast.Call) \
                        or not isinstance(node.func, ast.Attribute) \
                        or node.func.attr != "join" \
                        or node.args or node.keywords:
                    continue
                # str.join always takes the iterable argument, so a
                # no-arg .join() is a Thread/Process join
                yield self.finding(
                    project, node, f.path,
                    f"unbounded .join() on "
                    f"`{_attr_chain(node.func.value) or '<expr>'}` — a "
                    "stuck worker blocks this caller forever; join "
                    "with a timeout and escalate")


@register
class BareAcquire(Rule):
    id = "DL4J203"
    name = "bare-lock-acquire"
    severity = ERROR
    doc = ("`lock.acquire()` without a matching `release()` in a "
           "`finally:` block (and outside a with-statement): any "
           "exception between acquire and release leaks the lock and "
           "wedges every other thread.  Use `with lock:`.")

    def run(self, project: Project) -> Iterable[Finding]:
        for f in project.files:
            if f.tree is None:
                continue
            for node in ast.walk(f.tree):
                if not isinstance(node, ast.Call) \
                        or not isinstance(node.func, ast.Attribute) \
                        or node.func.attr != "acquire":
                    continue
                func = project.enclosing_function(f.path, node)
                got = project._lock_id_and_kind(node.func.value, f.path,
                                                func)
                if got is None:
                    continue
                lock_chain = _attr_chain(node.func.value)
                if self._released_in_finally(project, f.path, node,
                                             lock_chain):
                    continue
                yield self.finding(
                    project, node, f.path,
                    f"{lock_chain}.acquire() without a release() in a "
                    "finally block — use `with " + (lock_chain or "lock")
                    + ":` instead")

    @staticmethod
    def _released_in_finally(project: Project, path: str, node: ast.AST,
                             lock_chain: Optional[str]) -> bool:
        # search the enclosing function for `lock.release()` inside any
        # finally block — pairing heuristics beyond that aren't worth
        # the false negatives
        fn = project.enclosing_function(path, node)
        scope = fn.node if fn is not None else None
        if scope is None:
            f = project.file(path)
            scope = f.tree if f else None
        if scope is None:
            return False
        for n in ast.walk(scope):
            if isinstance(n, ast.Try):
                for stmt in n.finalbody:
                    for c in ast.walk(stmt):
                        if isinstance(c, ast.Call) \
                                and isinstance(c.func, ast.Attribute) \
                                and c.func.attr == "release" \
                                and _attr_chain(c.func.value) == lock_chain:
                            return True
        return False
