"""Thread-protocol rules (DL4J205–208): the static side of the
dl4j-check concurrency checker (analysis/check/).

The checker explores interleavings of code that EXISTS; these rules
gate the structural properties every thread in the serving stack must
have before any interleaving is even safe to explore: a thread that
resolves futures must resolve them on the error path too (DL4J205), a
thread that owns device state must never park forever on an unbounded
wait (DL4J206), a shared attribute guarded by a lock in most places
must not be written lock-free in one (DL4J207), and every spawned
thread needs a crash handler so a ``ThreadKill``-class death is a
clean failure instead of a stranded-client hang (DL4J208 — the
batcher/decode ``_loop_guarded`` pattern).

All four skip test files: ad-hoc test threads are not serving-stack
protocol surface.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from deeplearning4j_tpu.analysis.core import (
    WARNING, Finding, FunctionInfo, Project, Rule, _attr_chain,
    is_test_path, register)

_BROAD = {"Exception", "BaseException"}


def _reach(project: Project, root: FunctionInfo,
           max_fns: int = 200) -> List[FunctionInfo]:
    """The statically-resolvable call-graph closure of a thread-main
    function — the code that runs ON that thread."""
    seen: Set[int] = {id(root.node)}
    out = [root]
    frontier = [root]
    while frontier and len(out) < max_fns:
        fn = frontier.pop()
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Call):
                inner = project.enclosing_function(fn.path, node) or fn
                for callee in project.resolve_call(node, inner, fn.path):
                    if id(callee.node) not in seen:
                        seen.add(id(callee.node))
                        out.append(callee)
                        frontier.append(callee)
    return out


def _handler_is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = [t] if not isinstance(t, ast.Tuple) else list(t.elts)
    for n in names:
        chain = _attr_chain(n)
        if chain and chain.split(".")[-1] in _BROAD:
            return True
    return False


def _has_crash_handler(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Try):
            if any(_handler_is_broad(h) for h in n.handlers):
                return True
    return False


def _in_except_or_finally(project: Project, path: str,
                          node: ast.AST) -> bool:
    for anc in project.ancestors(path, node):
        if isinstance(anc, ast.ExceptHandler):
            return True
        if isinstance(anc, ast.Try):
            for stmt in anc.finalbody:
                for c in ast.walk(stmt):
                    if c is node:
                        return True
    return False


def _calls_with_attr(fn: FunctionInfo, attr: str) -> List[ast.Call]:
    return [n for n in ast.walk(fn.node)
            if isinstance(n, ast.Call)
            and isinstance(n.func, ast.Attribute)
            and n.func.attr == attr]


def _thread_mains(project: Project) -> List[
        Tuple[str, ast.Call, FunctionInfo]]:
    out = []
    seen: Set[int] = set()
    for path, call, targets in project.thread_targets():
        if is_test_path(path):
            continue
        for t in targets:
            if id(t.node) in seen:
                continue
            seen.add(id(t.node))
            out.append((path, call, t))
    return out


@register
class FutureNotResolvedOnAllPaths(Rule):
    id = "DL4J205"
    name = "future-success-path-only"
    severity = WARNING
    doc = ("A thread-main function (a `Thread(target=...)`) whose "
           "reachable code resolves futures with `set_result` but has "
           "no `set_exception` in any except/finally block: when the "
           "thread's work raises, every waiter blocks forever.  The "
           "batcher pattern — fail in-flight futures in the crash "
           "handler — is the fix.")

    def run(self, project: Project) -> Iterable[Finding]:
        for _path, _call, main in _thread_mains(project):
            reach = _reach(project, main)
            set_results = [(fn, n) for fn in reach
                           for n in _calls_with_attr(fn, "set_result")]
            if not set_results:
                continue
            resolved_on_error = any(
                _in_except_or_finally(project, fn.path, n)
                for fn in reach
                for n in _calls_with_attr(fn, "set_exception"))
            if resolved_on_error:
                continue
            fn, node = set_results[0]
            yield self.finding(
                project, node, fn.path,
                f"futures resolved only on the success path in code "
                f"run by thread-main `{main.name}` — no set_exception "
                "in any except/finally; a raising step strands every "
                "waiter")


@register
class UnboundedWaitOnDeviceThread(Rule):
    id = "DL4J206"
    name = "unbounded-wait-device-thread"
    severity = WARNING
    doc = ("`Future.result()` or `queue.get()` with no timeout on a "
           "thread that owns device state (its class touches "
           "jax/jnp/device buffers): a wedged producer parks the ONLY "
           "thread allowed to touch the device pool, and every session "
           "stalls behind it.  Bound the wait and escalate.")

    _DEVICE_ATTRS = {"device_put", "device_get", "block_until_ready",
                     "jit"}

    def _owns_device_state(self, project: Project,
                           main: FunctionInfo,
                           reach: List[FunctionInfo]) -> bool:
        fns: List[FunctionInfo] = list(reach)
        if main.class_name:
            fns += list(project._by_class.get(
                (main.module, main.class_name), {}).values())
        for fn in fns:
            for node in ast.walk(fn.node):
                chain = _attr_chain(node) if isinstance(
                    node, (ast.Attribute, ast.Name)) else None
                if not chain:
                    continue
                head = chain.split(".")[0]
                leaf = chain.split(".")[-1]
                if head in ("jax", "jnp") or leaf in self._DEVICE_ATTRS:
                    return True
        return False

    def run(self, project: Project) -> Iterable[Finding]:
        from deeplearning4j_tpu.analysis.rules_concurrency import (
            _is_future_typed, _is_queue_typed, _timeout_kw)
        for _path, _call, main in _thread_mains(project):
            reach = _reach(project, main)
            if not self._owns_device_state(project, main, reach):
                continue
            for fn in reach:
                for node in ast.walk(fn.node):
                    if not isinstance(node, ast.Call) or \
                            not isinstance(node.func, ast.Attribute):
                        continue
                    attr = node.func.attr
                    if node.args or _timeout_kw(node):
                        continue
                    recv = _attr_chain(node.func.value) or ""
                    leaf = recv.split(".")[-1].lower()
                    futlike = attr == "result" and (
                        "fut" in leaf or "promise" in leaf
                        or _is_future_typed(project, node.func.value,
                                            fn.path, fn))
                    qlike = attr == "get" and (
                        "q" in leaf or "queue" in leaf
                        or _is_queue_typed(project, node.func.value,
                                           fn.path, fn))
                    if not (futlike or qlike):
                        continue
                    yield self.finding(
                        project, node, fn.path,
                        f"unbounded `{recv}.{attr}()` on thread-main "
                        f"`{main.name}`'s thread, which owns device "
                        "state — a wedged producer parks the device "
                        "owner forever; use a timeout and escalate")


@register
class SharedWriteOutsideLock(Rule):
    id = "DL4J207"
    name = "shared-write-outside-lock"
    severity = WARNING
    doc = ("A `self.<attr>` written under one lock in ≥2 places but "
           "written lock-free in a minority of sites (outside "
           "`__init__`): the attribute→lock map is inferred from the "
           "guarded accesses themselves, so the lock-free write is "
           "either a data race or needs the `_locked`-suffix "
           "convention (callers hold the lock) made explicit.")

    _EXEMPT_METHODS = {"__init__", "__new__", "__post_init__"}

    def run(self, project: Project) -> Iterable[Finding]:
        guards_by_method = self._method_call_guards(project)
        for (module, cls), methods in sorted(project._by_class.items()):
            writes = self._class_writes(project, methods)
            if not writes:
                continue
            by_attr: Dict[str, List[Tuple]] = {}
            for w in writes:
                by_attr.setdefault(w[0], []).append(w)
            for attr, ws in sorted(by_attr.items()):
                lock_counts: Dict[str, int] = {}
                for _a, _m, _n, guards in ws:
                    for lid in guards:
                        lock_counts[lid] = lock_counts.get(lid, 0) + 1
                if not lock_counts:
                    continue
                lock = max(sorted(lock_counts), key=lock_counts.get)
                guarded = lock_counts[lock]
                if guarded < 2:
                    continue
                unguarded = [
                    (a, m, n) for a, m, n, guards in ws
                    if lock not in guards
                    and not m.name.endswith("_locked")
                    and not self._always_called_under(
                        guards_by_method, m, lock)]
                if not unguarded or len(unguarded) > guarded:
                    # a majority of lock-free writes means a different
                    # ownership discipline (e.g. a single owner
                    # thread), not a forgotten lock
                    continue
                for _a, m, node in unguarded:
                    lock_name = lock.split(":")[-1]
                    yield self.finding(
                        project, node, m.path,
                        f"`self.{attr}` is written under `{lock_name}` "
                        f"at {guarded} site(s) but written here "
                        "without it — a data race, unless every caller "
                        "holds the lock (then use the `_locked` name "
                        "convention)")

    def _class_writes(self, project: Project,
                      methods: Dict[str, FunctionInfo]) -> List[Tuple]:
        out: List[Tuple] = []
        for mname, m in sorted(methods.items()):
            if mname in self._EXEMPT_METHODS or is_test_path(m.path):
                continue
            for node in ast.walk(m.node):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) \
                        and node is not m.node:
                    continue
                targets: List[ast.AST] = []
                if isinstance(node, ast.Assign):
                    targets = list(node.targets)
                elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                    targets = [node.target]
                for t in targets:
                    attr = self._self_attr_of(t)
                    if attr is None:
                        continue
                    guards = project.held_locks_at(m.path, node, m)
                    out.append((attr, m, node, guards))
        return out

    @staticmethod
    def _self_attr_of(t: ast.AST) -> Optional[str]:
        # self.X = ... and self.X[k] = ... both mutate shared state
        if isinstance(t, ast.Subscript):
            t = t.value
        if isinstance(t, ast.Attribute) and \
                isinstance(t.value, ast.Name) and t.value.id == "self":
            return t.attr
        return None

    @staticmethod
    def _method_call_guards(project: Project) -> Dict[int, List[Set[str]]]:
        """For every project function: the lock sets lexically held at
        each of its call sites (the `_close_locked` pattern — a helper
        only ever invoked under the lock — is guarded by convention)."""
        out: Dict[int, List[Set[str]]] = {}
        for f in project.files:
            if f.tree is None:
                continue
            for node in ast.walk(f.tree):
                if not isinstance(node, ast.Call):
                    continue
                caller = project.enclosing_function(f.path, node)
                for target in project.resolve_call(node, caller, f.path):
                    held = project.held_locks_at(f.path, node, caller)
                    out.setdefault(id(target.node), []).append(held)
        return out

    @staticmethod
    def _always_called_under(guards_by_method, m: FunctionInfo,
                             lock: str) -> bool:
        sites = guards_by_method.get(id(m.node))
        return bool(sites) and all(lock in held for held in sites)


@register
class ThreadWithoutCrashHandler(Rule):
    id = "DL4J208"
    name = "thread-without-crash-handler"
    severity = WARNING
    doc = ("A `Thread(target=f)` whose target has no try/except "
           "catching Exception/BaseException anywhere in its body: a "
           "ThreadKill-class death (or any bug) silently removes the "
           "thread, and whatever it owed other threads — futures, "
           "queue slots, readiness — is never delivered.  Wrap the "
           "body like the batcher's `_loop_guarded`.")

    def run(self, project: Project) -> Iterable[Finding]:
        for path, call, targets in project.thread_targets():
            if is_test_path(path):
                continue
            for t in targets:
                if _has_crash_handler(t.node):
                    continue
                yield self.finding(
                    project, call, path,
                    f"thread target `{t.name}` has no crash handler "
                    "(no except Exception/BaseException in its body) — "
                    "a dying thread strands everything that waits "
                    "on it")
