"""Machine-readable protocol specs for the serving stack, checked on
every transition while a dl4j-check harness is active.

Three layers, all feeding :meth:`Scheduler.violation`:

* **State machines over journal events** (:class:`SpecMonitor`): the
  harness routes every ``events.emit`` through the monitor on the
  emitting thread, so a transition is checked at the exact point the
  code declares it.  Specs are data — explicit legal-transition tables
  — so the protocol contract is reviewable apart from the code:

  - :class:`SessionLifecycleSpec` — the DecodePool slot/session
    lifecycle: ``(open) → claimed → active → exported-limbo →
    reinstated | migrated | closed``, plus cross-pool rules: a session
    id is live on at most ONE pool (exported limbo does not count —
    "exported slots can't double-count"), drained pools admit nothing
    (no ``session_opened``/``session_imported`` between ``decode.drain``
    and ``decode.resumed``), a close out of exported limbo must name a
    protocol reason (``migrated``/shutdown/death — never ``ttl``: a
    migration window is not idleness).

  - :class:`BreakerSpec` — the CircuitBreaker machine: ``closed → open
    → half_open → {closed, open}`` (plus the ``reset()`` ops override
    ``open → closed``); ``closed → half_open`` has no legal edge — a
    breaker that skips its cooldown is broken.

* **Invariant probes** (:func:`watch_decode_pool`): run at EVERY
  scheduling point (the system is quiescent, so reading the slot table
  without its lock is sound): no two sessions share a slot, no claimed
  slot is simultaneously on the free list, every slot index is in
  range.

* **End-of-run obligations** (checked by the explorer): every future
  created under the harness resolved — on every schedule, a dead
  batcher (or any other path) never strands a waiter.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

LIVE = ("claimed", "active")

#: legal CircuitBreaker transitions (from, to); "closed -> half_open"
#: is deliberately absent
BREAKER_LEGAL = (
    ("closed", "open"),
    ("open", "half_open"),
    ("half_open", "closed"),
    ("half_open", "open"),
    ("open", "closed"),       # reset(): the documented ops override
)

#: reasons that may close a session OUT of exported limbo — protocol
#: completions and failure teardowns, never idleness
EXPORTED_CLOSE_REASONS = ("migrated", "shutdown", "batcher_died", "error")


class SessionLifecycleSpec:
    """DecodePool slot/session lifecycle + two-phase migration + drain
    admission, driven by the ``decode.*`` journal events."""

    name = "session-lifecycle"

    def __init__(self, sched):
        self._sched = sched
        #: (model, session_id) -> state
        self._state: Dict[Tuple[str, str], str] = {}
        #: model -> draining?
        self._draining: Dict[str, bool] = {}

    # ------------------------------------------------------------------
    def _fail(self, msg: str) -> None:
        self._sched.violation("spec", f"[{self.name}] {msg}")

    def _live_elsewhere(self, model: str, sid: str) -> Optional[str]:
        for (m, s), st in self._state.items():
            if s == sid and m != model and st in LIVE:
                return m
        return None

    def on_event(self, etype: str, fields: dict) -> None:
        sid = fields.get("session_id")
        model = fields.get("model") or "-"
        if etype == "decode.drain":
            self._draining[model] = True
            return
        if etype == "decode.resumed":
            self._draining[model] = False
            return
        if sid is None or (isinstance(sid, str)
                           and sid.startswith("warmup-")):
            return
        key = (model, sid)
        st = self._state.get(key)
        if etype == "decode.session_opened":
            if self._draining.get(model):
                self._fail(f"session {sid} opened on {model} while the "
                           "pool is draining (drain must admit nothing)")
            if st in LIVE or st == "exported":
                self._fail(f"session {sid} opened on {model} while "
                           f"already {st} there (slot double-claim)")
            other = self._live_elsewhere(model, sid)
            if other:
                self._fail(f"session {sid} opened on {model} while live "
                           f"on {other} (double-live stream)")
            self._state[key] = "claimed"
        elif etype == "decode.step":
            if st not in LIVE:
                self._fail(f"decode.step for session {sid} on {model} "
                           f"in state {st!r} (only claimed/active "
                           "sessions may step)")
            self._state[key] = "active"
        elif etype == "decode.session_exported":
            if st not in LIVE:
                self._fail(f"session {sid} exported from {model} in "
                           f"state {st!r} (nothing to snapshot)")
            self._state[key] = "exported"
        elif etype == "decode.session_reinstated":
            if st != "exported":
                self._fail(f"session {sid} reinstated on {model} in "
                           f"state {st!r} (only exported limbo "
                           "reinstates)")
            self._state[key] = "active"
        elif etype == "decode.session_imported":
            if self._draining.get(model):
                self._fail(f"session {sid} imported into {model} while "
                           "the pool is draining (drain must admit "
                           "nothing)")
            if st in LIVE or st == "exported":
                self._fail(f"session {sid} imported into {model} while "
                           f"already {st} there")
            other = self._live_elsewhere(model, sid)
            if other:
                self._fail(f"session {sid} imported into {model} while "
                           f"live on {other} (the source must hold it "
                           "in exported limbo, not serve it)")
            self._state[key] = "active"
        elif etype == "decode.session_closed":
            reason = fields.get("reason")
            if st is None:
                self._fail(f"close event for unknown session {sid} on "
                           f"{model}")
            if st == "exported" and reason not in EXPORTED_CLOSE_REASONS:
                self._fail(f"session {sid} closed out of exported limbo "
                           f"with reason {reason!r} — a migration "
                           "window is not idleness (expected one of "
                           f"{EXPORTED_CLOSE_REASONS})")
            self._state[key] = "closed"


#: legal elastic-worker lifecycle transitions (distributed/coordinator)
#: — joined → active → suspect → dead | rejoined; None is pre-join.
#: A dead worker re-enters only through a fresh join (the breaker
#: gate); there is no resurrection edge dead → active.
WORKER_LEGAL = (
    (None, "joined"),
    ("dead", "joined"),          # rejoin after eviction
    ("joined", "active"),
    ("suspect", "active"),       # heartbeat recovery
    ("active", "suspect"),
    ("joined", "suspect"),       # a syncing worker can lapse too
    ("suspect", "dead"),
    ("active", "dead"),          # graceful leave / zombie replacement
    ("joined", "dead"),
)


class WorkerLifecycleSpec:
    """Elastic-runtime worker lifecycle over the ``dist.*`` journal
    events, plus generation monotonicity: ``dist.generation_rolled``
    must carry strictly increasing generation numbers — two live
    generations (or a rollback) is the split-brain the fencing
    protocol exists to prevent."""

    name = "dist-worker-lifecycle"

    def __init__(self, sched):
        self._sched = sched
        self._state: Dict[str, str] = {}
        self._generation: Optional[int] = None

    def _fail(self, msg: str) -> None:
        self._sched.violation("spec", f"[{self.name}] {msg}")

    _EDGE = {"dist.worker_joined": "joined",
             "dist.worker_active": "active",
             "dist.worker_suspect": "suspect",
             "dist.worker_dead": "dead"}

    def on_event(self, etype: str, fields: dict) -> None:
        if etype == "dist.generation_rolled":
            gen = fields.get("generation")
            if self._generation is not None and gen is not None \
                    and gen <= self._generation:
                self._fail(f"generation rolled {self._generation} -> "
                           f"{gen} (must be strictly increasing — two "
                           "live generations)")
            if gen is not None:
                self._generation = gen
            return
        to = self._EDGE.get(etype)
        if to is None:
            return
        worker = fields.get("worker") or "-"
        frm = self._state.get(worker)
        if (frm, to) not in WORKER_LEGAL and frm != to:
            legal = ", ".join(f"{a or '(new)'}->{b}"
                              for a, b in WORKER_LEGAL)
            self._fail(f"worker {worker!r} transitioned {frm} -> {to} "
                       f"(legal: {legal})")
        self._state[worker] = to


class BreakerSpec:
    """CircuitBreaker legality over ``breaker.transition`` events."""

    name = "breaker-lifecycle"

    def __init__(self, sched):
        self._sched = sched
        self._state: Dict[str, str] = {}

    def on_event(self, etype: str, fields: dict) -> None:
        if etype != "breaker.transition":
            return
        name = fields.get("breaker") or "-"
        to = fields.get("to")
        frm = self._state.get(name, "closed")
        if (frm, to) not in BREAKER_LEGAL:
            self._sched.violation(
                "spec", f"[{self.name}] breaker {name!r} transitioned "
                        f"{frm} -> {to} (legal: {sorted(BREAKER_LEGAL)})")
        self._state[name] = to


class SpecMonitor:
    """Fan events out to every registered spec (the harness installs
    this behind ``events.emit``)."""

    def __init__(self, sched, specs=None):
        self.sched = sched
        self.specs = list(specs) if specs is not None else [
            SessionLifecycleSpec(sched), BreakerSpec(sched),
            WorkerLifecycleSpec(sched)]

    def on_event(self, etype: str, severity: str, fields: dict) -> None:
        for spec in self.specs:
            spec.on_event(etype, fields)


# ----------------------------------------------------------------------
# Invariant probes (quiescent-state reads of pool internals)
# ----------------------------------------------------------------------
def _slot_probe(pool) -> Optional[str]:
    sessions = list(pool._sessions.values())
    slots = [s.slot for s in sessions]
    if len(set(slots)) != len(slots):
        dupes = sorted(x for x in set(slots) if slots.count(x) > 1)
        return f"slot double-claim: slots {dupes} held by two sessions"
    free = list(pool._free)
    overlap = sorted(set(slots) & set(free))
    if overlap:
        return f"claimed slot(s) {overlap} also on the free list"
    bad = sorted(x for x in slots if not 0 <= x < pool.max_slots)
    if bad:
        return f"slot index(es) {bad} out of range 0..{pool.max_slots - 1}"
    if len(set(free)) != len(free):
        return "free list holds a duplicate slot"
    return None


def watch_decode_pool(sched, pool) -> None:
    """Register the slot-table invariants for ``pool`` on ``sched`` —
    checked at every scheduling point of the run."""
    sched.probes.append(
        (f"slots:{pool.name or 'pool'}", lambda: _slot_probe(pool)))


# ----------------------------------------------------------------------
# KV-ring invariants (the speculative-serving subsystem's carry: each
# attention layer's per-slot ring write position is a monotone token
# counter — write index = pos % window, valid length = min(pos, W))
# ----------------------------------------------------------------------
class _KVRingWatch:
    """Quiescent-state KV write-position invariants for a decode pool
    whose carry exposes a per-slot ``kv_pos`` counter:

    * **monotone mod window**: a slot's write position never decreases
      while the same session holds it (a rewind = overwritten history);
    * **exported-limbo freezes the ring**: between
      ``decode.session_exported`` and its ``finish_export``, the slot's
      position must not move — the snapshot in flight to the target
      would silently diverge from the source;
    * **fresh claim zeroes valid-length**: a slot observed under a NEW
      session must never show more ring writes than that session has
      stepped — a larger count means the previous tenant's entries are
      still valid-attendable (stale-ring leak).
    """

    def __init__(self, pool):
        self.pool = pool
        #: slot -> (sid, last seen pos)
        self._last: Dict[int, Tuple[str, float]] = {}
        #: slot -> pos frozen at export
        self._frozen: Dict[int, float] = {}

    def _kv_pos(self, slot: int) -> Optional[float]:
        dev = self.pool._pool
        if not isinstance(dev, dict) or "kv_pos" not in dev:
            return None
        import numpy as np
        return float(np.asarray(dev["kv_pos"])[slot].ravel()[0])

    def probe(self) -> Optional[str]:
        if self.pool._pool is None:
            self._last.clear()
            self._frozen.clear()
            return None
        by_slot = {s.slot: s for s in self.pool._sessions.values()}
        for slot in range(self.pool.max_slots):
            cur = self._kv_pos(slot)
            if cur is None:
                return None
            s = by_slot.get(slot)
            if s is None:
                self._last.pop(slot, None)
                self._frozen.pop(slot, None)
                continue
            if s.importing:
                # the slot is claimed but its carry scatter hasn't
                # landed — the device state is not this session's yet
                self._last.pop(slot, None)
                continue
            if s.exported:
                frozen = self._frozen.setdefault(slot, cur)
                if cur != frozen:
                    return (f"kv ring moved in exported limbo: slot "
                            f"{slot} (session {s.sid}) pos {frozen} -> "
                            f"{cur} — the in-flight snapshot diverged")
            else:
                self._frozen.pop(slot, None)
            prev = self._last.get(slot)
            if prev is not None and prev[0] == s.sid and cur < prev[1]:
                return (f"kv write_pos rewound on slot {slot} "
                        f"(session {s.sid}): {prev[1]} -> {cur}")
            # fresh-claim zeroing is LAZY (the gather zeroes fresh rows
            # in-trace): until the session's first dispatch lands
            # (`started`), the raw buffer legitimately holds the
            # previous tenant's values — what must hold afterwards is
            # that the ring never shows more writes than this session
            # has stepped.  A dispatched step scatters the ring BEFORE
            # the step counter increments, so allow the in-flight steps.
            inflight = sum(1 for p in self.pool._inflight
                           if p.session.sid == s.sid)
            if s.started and cur > s.steps + inflight:
                return (f"fresh claim did not zero the ring: slot {slot} "
                        f"session {s.sid} shows {cur} writes after only "
                        f"{s.steps} steps (stale entries attendable)")
            self._last[slot] = (s.sid, cur)
        return None


def watch_kv_ring(sched, pool) -> None:
    """Register the KV write-position invariants for ``pool`` (no-op
    probes when the pool's carry has no ``kv_pos`` leaf)."""
    w = _KVRingWatch(pool)
    sched.probes.append((f"kv:{pool.name or 'pool'}", w.probe))


# ----------------------------------------------------------------------
# Paged-KV arena invariants (the block allocator behind kv_paged pools:
# sessions hold disjoint block sets, the free list is exact — every
# block is either free or held by exactly one live session)
# ----------------------------------------------------------------------
def _arena_probe(pool) -> Optional[str]:
    if not getattr(pool, "_arena_specs", None):
        return None
    n_layers = len(pool._arena_specs)
    free = [list(f) for f in pool._kv_free]
    held = [[] for _ in range(n_layers)]
    sessions = list(pool._sessions.values())
    for s in sessions:
        if s.kv_blocks is None:
            continue
        for li, blks in enumerate(s.kv_blocks):
            if li >= n_layers:
                return (f"session {s.sid} holds blocks for layer {li} "
                        f"but the arena has {n_layers} layers")
            held[li].extend((s.sid, b) for b in blks)
    for li in range(n_layers):
        total = pool._arena_blocks[li]
        fl = free[li] if li < len(free) else []
        if len(set(fl)) != len(fl):
            dupes = sorted(b for b in set(fl) if fl.count(b) > 1)
            return (f"layer {li}: block(s) {dupes} returned to the "
                    "free list more than once")
        bad = sorted(b for b in fl if not 0 <= b < total)
        if bad:
            return (f"layer {li}: free-list block(s) {bad} out of "
                    f"range 0..{total - 1}")
        owners: Dict[int, str] = {}
        for sid, b in held[li]:
            if not 0 <= b < total:
                return (f"layer {li}: session {sid} holds block {b} "
                        f"out of range 0..{total - 1}")
            if b in owners and owners[b] != sid:
                return (f"layer {li}: block {b} owned by two live "
                        f"sessions ({owners[b]} and {sid})")
            owners[b] = sid
        overlap = sorted(set(owners) & set(fl))
        if overlap:
            return (f"layer {li}: block(s) {overlap} both held and on "
                    "the free list")
        if len(owners) + len(fl) != total:
            return (f"layer {li}: {len(owners)} held + {len(fl)} free "
                    f"!= {total} arena blocks (leaked or conjured)")
    return None


def watch_kv_arena(sched, pool) -> None:
    """Register the paged-arena allocator invariants for ``pool``
    (no-op probes until/unless the pool materializes an arena)."""
    sched.probes.append(
        (f"arena:{pool.name or 'pool'}", lambda: _arena_probe(pool)))
