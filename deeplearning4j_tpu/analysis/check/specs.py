"""Machine-readable protocol specs for the serving stack, checked on
every transition while a dl4j-check harness is active.

Three layers, all feeding :meth:`Scheduler.violation`:

* **State machines over journal events** (:class:`SpecMonitor`): the
  harness routes every ``events.emit`` through the monitor on the
  emitting thread, so a transition is checked at the exact point the
  code declares it.  Specs are data — explicit legal-transition tables
  — so the protocol contract is reviewable apart from the code:

  - :class:`SessionLifecycleSpec` — the DecodePool slot/session
    lifecycle: ``(open) → claimed → active → exported-limbo →
    reinstated | migrated | closed``, plus cross-pool rules: a session
    id is live on at most ONE pool (exported limbo does not count —
    "exported slots can't double-count"), drained pools admit nothing
    (no ``session_opened``/``session_imported`` between ``decode.drain``
    and ``decode.resumed``), a close out of exported limbo must name a
    protocol reason (``migrated``/shutdown/death — never ``ttl``: a
    migration window is not idleness).

  - :class:`BreakerSpec` — the CircuitBreaker machine: ``closed → open
    → half_open → {closed, open}`` (plus the ``reset()`` ops override
    ``open → closed``); ``closed → half_open`` has no legal edge — a
    breaker that skips its cooldown is broken.

* **Invariant probes** (:func:`watch_decode_pool`): run at EVERY
  scheduling point (the system is quiescent, so reading the slot table
  without its lock is sound): no two sessions share a slot, no claimed
  slot is simultaneously on the free list, every slot index is in
  range.

* **End-of-run obligations** (checked by the explorer): every future
  created under the harness resolved — on every schedule, a dead
  batcher (or any other path) never strands a waiter.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

LIVE = ("claimed", "active")

#: legal CircuitBreaker transitions (from, to); "closed -> half_open"
#: is deliberately absent
BREAKER_LEGAL = (
    ("closed", "open"),
    ("open", "half_open"),
    ("half_open", "closed"),
    ("half_open", "open"),
    ("open", "closed"),       # reset(): the documented ops override
)

#: reasons that may close a session OUT of exported limbo — protocol
#: completions and failure teardowns, never idleness
EXPORTED_CLOSE_REASONS = ("migrated", "shutdown", "batcher_died", "error")


class SessionLifecycleSpec:
    """DecodePool slot/session lifecycle + two-phase migration + drain
    admission, driven by the ``decode.*`` journal events."""

    name = "session-lifecycle"

    def __init__(self, sched):
        self._sched = sched
        #: (model, session_id) -> state
        self._state: Dict[Tuple[str, str], str] = {}
        #: model -> draining?
        self._draining: Dict[str, bool] = {}

    # ------------------------------------------------------------------
    def _fail(self, msg: str) -> None:
        self._sched.violation("spec", f"[{self.name}] {msg}")

    def _live_elsewhere(self, model: str, sid: str) -> Optional[str]:
        for (m, s), st in self._state.items():
            if s == sid and m != model and st in LIVE:
                return m
        return None

    def on_event(self, etype: str, fields: dict) -> None:
        sid = fields.get("session_id")
        model = fields.get("model") or "-"
        if etype == "decode.drain":
            self._draining[model] = True
            return
        if etype == "decode.resumed":
            self._draining[model] = False
            return
        if sid is None or (isinstance(sid, str)
                           and sid.startswith("warmup-")):
            return
        key = (model, sid)
        st = self._state.get(key)
        if etype == "decode.session_opened":
            if self._draining.get(model):
                self._fail(f"session {sid} opened on {model} while the "
                           "pool is draining (drain must admit nothing)")
            if st in LIVE or st == "exported":
                self._fail(f"session {sid} opened on {model} while "
                           f"already {st} there (slot double-claim)")
            other = self._live_elsewhere(model, sid)
            if other:
                self._fail(f"session {sid} opened on {model} while live "
                           f"on {other} (double-live stream)")
            self._state[key] = "claimed"
        elif etype == "decode.step":
            if st not in LIVE:
                self._fail(f"decode.step for session {sid} on {model} "
                           f"in state {st!r} (only claimed/active "
                           "sessions may step)")
            self._state[key] = "active"
        elif etype == "decode.session_exported":
            if st not in LIVE:
                self._fail(f"session {sid} exported from {model} in "
                           f"state {st!r} (nothing to snapshot)")
            self._state[key] = "exported"
        elif etype == "decode.session_reinstated":
            if st != "exported":
                self._fail(f"session {sid} reinstated on {model} in "
                           f"state {st!r} (only exported limbo "
                           "reinstates)")
            self._state[key] = "active"
        elif etype == "decode.session_imported":
            if self._draining.get(model):
                self._fail(f"session {sid} imported into {model} while "
                           "the pool is draining (drain must admit "
                           "nothing)")
            if st in LIVE or st == "exported":
                self._fail(f"session {sid} imported into {model} while "
                           f"already {st} there")
            other = self._live_elsewhere(model, sid)
            if other:
                self._fail(f"session {sid} imported into {model} while "
                           f"live on {other} (the source must hold it "
                           "in exported limbo, not serve it)")
            self._state[key] = "active"
        elif etype == "decode.session_closed":
            reason = fields.get("reason")
            if st is None:
                self._fail(f"close event for unknown session {sid} on "
                           f"{model}")
            if st == "exported" and reason not in EXPORTED_CLOSE_REASONS:
                self._fail(f"session {sid} closed out of exported limbo "
                           f"with reason {reason!r} — a migration "
                           "window is not idleness (expected one of "
                           f"{EXPORTED_CLOSE_REASONS})")
            self._state[key] = "closed"


class BreakerSpec:
    """CircuitBreaker legality over ``breaker.transition`` events."""

    name = "breaker-lifecycle"

    def __init__(self, sched):
        self._sched = sched
        self._state: Dict[str, str] = {}

    def on_event(self, etype: str, fields: dict) -> None:
        if etype != "breaker.transition":
            return
        name = fields.get("breaker") or "-"
        to = fields.get("to")
        frm = self._state.get(name, "closed")
        if (frm, to) not in BREAKER_LEGAL:
            self._sched.violation(
                "spec", f"[{self.name}] breaker {name!r} transitioned "
                        f"{frm} -> {to} (legal: {sorted(BREAKER_LEGAL)})")
        self._state[name] = to


class SpecMonitor:
    """Fan events out to every registered spec (the harness installs
    this behind ``events.emit``)."""

    def __init__(self, sched, specs=None):
        self.sched = sched
        self.specs = list(specs) if specs is not None else [
            SessionLifecycleSpec(sched), BreakerSpec(sched)]

    def on_event(self, etype: str, severity: str, fields: dict) -> None:
        for spec in self.specs:
            spec.on_event(etype, fields)


# ----------------------------------------------------------------------
# Invariant probes (quiescent-state reads of pool internals)
# ----------------------------------------------------------------------
def _slot_probe(pool) -> Optional[str]:
    sessions = list(pool._sessions.values())
    slots = [s.slot for s in sessions]
    if len(set(slots)) != len(slots):
        dupes = sorted(x for x in set(slots) if slots.count(x) > 1)
        return f"slot double-claim: slots {dupes} held by two sessions"
    free = list(pool._free)
    overlap = sorted(set(slots) & set(free))
    if overlap:
        return f"claimed slot(s) {overlap} also on the free list"
    bad = sorted(x for x in slots if not 0 <= x < pool.max_slots)
    if bad:
        return f"slot index(es) {bad} out of range 0..{pool.max_slots - 1}"
    if len(set(free)) != len(free):
        return "free list holds a duplicate slot"
    return None


def watch_decode_pool(sched, pool) -> None:
    """Register the slot-table invariants for ``pool`` on ``sched`` —
    checked at every scheduling point of the run."""
    sched.probes.append(
        (f"slots:{pool.name or 'pool'}", lambda: _slot_probe(pool)))
