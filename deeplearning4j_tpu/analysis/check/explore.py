"""Schedule exploration: run a scenario under many interleavings,
count the distinct ones, collect violations, and make every failing
schedule replayable.

Two modes:

* ``random`` — one seeded :class:`RandomPolicy` per schedule
  (``seed + i``); preemption-bounded.  Coverage scales with the
  schedule budget and every run is reproducible from its seed alone.
* ``exhaustive`` — bounded-exhaustive DFS over decision prefixes: run
  the default schedule, then branch every recorded decision point,
  skipping alternatives that would exceed the preemption bound.  For
  small scenarios this enumerates the whole (bounded) schedule space.

A schedule's identity is its trace hash; ``distinct`` counts unique
hashes (two decision vectors can collapse to the same interleaving
when a choice was between equivalent wakeups).

Violations carry ``(scenario, seed, decisions)`` — :func:`replay`
re-runs the exact schedule; :func:`save_trace`/:func:`replay_file`
round-trip it through JSON for the bug-report workflow.

Every exploration meters ``dl4j_check_*`` (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import hashlib
import json
import time as _time
from typing import Dict, List, Optional, Sequence

from deeplearning4j_tpu.analysis.check import scenarios as _scenarios
from deeplearning4j_tpu.analysis.check.sched import (
    DFSPolicy, Harness, RandomPolicy, ReplayPolicy, Scheduler)
from deeplearning4j_tpu.analysis.check.specs import SpecMonitor

_real_perf_counter = _time.perf_counter


class RunResult:
    """One schedule's outcome."""

    def __init__(self, scenario: str, policy, sched: Scheduler,
                 wall_s: float):
        self.scenario = scenario
        self.seed = getattr(policy, "seed", None)
        self.decisions = sched.decisions
        self.branches = sched.branches
        self.violations = sched.violations
        self.steps = sched.steps
        self.preemptions = sched.preemptions
        self.trace = sched.trace_text()
        self.trace_hash = hashlib.sha256(
            self.trace.encode()).hexdigest()[:16]
        self.wall_s = wall_s

    @property
    def ok(self) -> bool:
        return not self.violations

    def violation_dicts(self) -> List[dict]:
        return [dict(v.to_dict(), scenario=self.scenario, seed=self.seed,
                     decisions=self.decisions,
                     trace_hash=self.trace_hash)
                for v in self.violations]


def _metrics():
    from deeplearning4j_tpu import monitor
    reg = monitor.get_registry()
    return {
        "schedules": reg.counter(
            "dl4j_check_schedules_total",
            "deterministic-scheduler schedules executed",
            labels=("scenario",)),
        "violations": reg.counter(
            "dl4j_check_violations_total",
            "checker violations found (invariant/spec/deadlock/...)",
            labels=("scenario", "kind")),
        "distinct": reg.gauge(
            "dl4j_check_distinct_interleavings",
            "distinct interleavings seen in the last exploration",
            labels=("scenario",)),
        "steps": reg.counter(
            "dl4j_check_schedule_steps_total",
            "scheduling points executed across all schedules",
            labels=("scenario",)),
    }


def run_once(scenario: str, policy, max_steps: int = 50000) -> RunResult:
    """One schedule of ``scenario`` under ``policy`` (with specs and
    the end-of-run obligations checked)."""
    import logging
    fn = _scenarios.SCENARIOS[scenario]
    _scenarios.warm()
    sched = Scheduler(policy=policy, max_steps=max_steps)
    monitor = SpecMonitor(sched)
    t0 = _real_perf_counter()
    # injected kills are the SCENARIO, not noise worth one log line per
    # schedule: mute the framework's error logging for the run
    logger = logging.getLogger("deeplearning4j_tpu")
    prev_level = logger.level
    logger.setLevel(logging.CRITICAL)
    try:
        with Harness(sched, monitor):
            ctx = _scenarios.Context(sched)
            sched.run(lambda: fn(ctx), name="root")
    finally:
        logger.setLevel(prev_level)
    unresolved = sum(1 for f in sched.futures if not f.done())
    if unresolved:
        from deeplearning4j_tpu.analysis.check.sched import Violation
        sched.violations.append(Violation(
            "future-unresolved",
            f"{unresolved} future(s) never resolved by the end of the "
            "schedule — a stranded waiter in real execution",
            step=sched.steps))
    return RunResult(scenario, policy, sched,
                     _real_perf_counter() - t0)


class ExploreResult:
    def __init__(self, scenario: str, mode: str, seed: int):
        self.scenario = scenario
        self.mode = mode
        self.seed = seed
        self.runs = 0
        self.distinct_hashes: set = set()
        self.violations: List[dict] = []
        self.steps_total = 0
        self.wall_s = 0.0
        self.traces: List[str] = []   # only with keep_traces

    @property
    def distinct(self) -> int:
        return len(self.distinct_hashes)

    @property
    def ok(self) -> bool:
        return not self.violations

    def to_dict(self) -> dict:
        return {
            "scenario": self.scenario, "mode": self.mode,
            "seed": self.seed, "runs": self.runs,
            "distinct": self.distinct,
            "violations": self.violations,
            "steps_total": self.steps_total,
            "wall_s": round(self.wall_s, 3),
        }

    def _absorb(self, r: RunResult, keep_traces: bool) -> None:
        self.runs += 1
        self.distinct_hashes.add(r.trace_hash)
        self.steps_total += r.steps
        self.wall_s += r.wall_s
        if not r.ok:
            self.violations.extend(r.violation_dicts())
        if keep_traces:
            self.traces.append(r.trace)


def explore(scenario: str, mode: str = "random", schedules: int = 50,
            seed: int = 0, max_preemptions: int = 4,
            p_preempt: float = 0.4, time_budget_s: Optional[float] = None,
            max_steps: int = 50000, keep_traces: bool = False,
            stop_on_violation: bool = False) -> ExploreResult:
    """Explore up to ``schedules`` interleavings of ``scenario``."""
    if scenario not in _scenarios.SCENARIOS:
        raise KeyError(f"unknown scenario {scenario!r}; one of "
                       f"{sorted(_scenarios.SCENARIOS)}")
    res = ExploreResult(scenario, mode, seed)
    t0 = _real_perf_counter()

    def over_budget() -> bool:
        return (time_budget_s is not None
                and _real_perf_counter() - t0 > time_budget_s)

    if mode == "random":
        for i in range(schedules):
            if over_budget():
                break
            policy = RandomPolicy(seed=seed + i,
                                  max_preemptions=max_preemptions,
                                  p_preempt=p_preempt)
            r = run_once(scenario, policy, max_steps=max_steps)
            res._absorb(r, keep_traces)
            if stop_on_violation and not r.ok:
                break
    elif mode == "exhaustive":
        frontier: List[List[int]] = [[]]
        explored: set = set()
        while frontier and res.runs < schedules and not over_budget():
            prefix = frontier.pop()
            key = tuple(prefix)
            if key in explored:
                continue
            explored.add(key)
            policy = DFSPolicy(prefix)
            policy.seed = seed
            r = run_once(scenario, policy, max_steps=max_steps)
            res._absorb(r, keep_traces)
            if stop_on_violation and not r.ok:
                break
            branches = r.branches
            for i in range(len(branches) - 1, len(prefix) - 1, -1):
                ncand, chosen, cur_idx = branches[i]
                pre_used = sum(
                    1 for (n2, c2, cu2) in branches[:i]
                    if cu2 is not None and c2 != cu2)
                for j in range(ncand):
                    if j == chosen:
                        continue
                    is_preempt = cur_idx is not None and j != cur_idx
                    if is_preempt and pre_used >= max_preemptions:
                        continue
                    frontier.append(
                        [b[1] for b in branches[:i]] + [j])
    else:
        raise ValueError(f"unknown mode {mode!r}")

    res.wall_s = _real_perf_counter() - t0
    m = _metrics()
    m["schedules"].labels(scenario=scenario).inc(res.runs)
    m["steps"].labels(scenario=scenario).inc(res.steps_total)
    m["distinct"].labels(scenario=scenario).set(res.distinct)
    kinds: Dict[str, int] = {}
    for v in res.violations:
        kinds[v["kind"]] = kinds.get(v["kind"], 0) + 1
    for kind, n in kinds.items():
        m["violations"].labels(scenario=scenario, kind=kind).inc(n)
    return res


def replay(scenario: str, decisions: Sequence[int],
           max_steps: int = 50000) -> RunResult:
    """Re-run one exact schedule from its recorded decision vector."""
    return run_once(scenario, ReplayPolicy(list(decisions)),
                    max_steps=max_steps)


def save_trace(violation: dict, path: str) -> None:
    """Persist a failing schedule (the replay recipe) as JSON."""
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": 1, "dl4j_check_trace": violation}, f,
                  indent=1, sort_keys=True)
        f.write("\n")


def replay_file(path: str) -> RunResult:
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    v = doc.get("dl4j_check_trace") or doc
    return replay(v["scenario"], v.get("decisions") or [])


def explore_protocols(
        scenarios: Optional[Sequence[str]] = None,
        schedules: int = 50, seed: int = 0, mode: str = "random",
        max_preemptions: int = 4,
        time_budget_s: Optional[float] = None) -> dict:
    """Explore a set of scenarios (default: the gating protocol set)
    and aggregate — the CLI/CI entry point."""
    names = list(scenarios or _scenarios.DEFAULT_SCENARIOS)
    per = (None if time_budget_s is None
           else max(1.0, time_budget_s / max(1, len(names))))
    out: Dict[str, dict] = {}
    total_runs = total_distinct = 0
    all_violations: List[dict] = []
    for name in names:
        r = explore(name, mode=mode, schedules=schedules, seed=seed,
                    max_preemptions=max_preemptions, time_budget_s=per)
        out[name] = r.to_dict()
        total_runs += r.runs
        total_distinct += r.distinct
        all_violations.extend(r.violations)
    return {
        "version": 1,
        "mode": mode,
        "seed": seed,
        "scenarios": out,
        "total_runs": total_runs,
        "total_distinct": total_distinct,
        "violations": all_violations,
        "ok": not all_violations,
    }
