"""dl4j-check core: a cooperative deterministic scheduler for the
serving stack's thread protocols.

The serving path (server/batcher.py, server/decode.py, fleet/) is a
multi-threaded protocol machine whose correctness claims — "no client
hang", "exported slots can't double-count", "kill-mid-migration fails
loudly" — are ordering properties.  Example-based tests exercise one
lucky interleaving each; this module makes the interleaving a CHOICE:

* Production threads run unmodified, but every synchronization
  primitive they touch (``threading.Lock``/``RLock``/``Condition``/
  ``Event``/``Thread``, ``queue.Queue``, the ``Future`` used by the
  batcher and the decode pool) is shimmed while a :class:`Harness` is
  active, serializing all managed threads onto ONE runnable-at-a-time
  token.  At every primitive operation the thread yields to the
  scheduler, which picks who runs next — so a whole schedule is just a
  sequence of choices, recorded as the run's decision vector.

* Time is logical: ``time.monotonic``/``perf_counter``/``sleep`` are
  patched to a scheduler clock.  A timed wait registers a wake-up time
  and fires ONLY when no thread is runnable (the clock jumps to the
  earliest timer) — poll loops like the batcher's ``cond.wait(0.1)``
  stay finite, and a deadline expires exactly when the system would
  otherwise be idle waiting for it.

* Exploration policies plug in: :class:`RandomPolicy` (seeded, with
  preemption bounding a la CHESS), :class:`DFSPolicy` (bounded-
  exhaustive over decision prefixes), :class:`ReplayPolicy` (re-run a
  recorded decision vector byte-for-byte).  Same policy decisions ⇒
  byte-identical trace — every failing schedule is replayable.

* Between any two scheduling points the system is QUIESCENT (exactly
  one thread runs at a time), so invariant probes registered on the
  scheduler can read shared protocol state (slot tables, free lists)
  without synchronization and without perturbing the schedule.

Activation is scoped to the harness: outside it (or on threads the
scheduler does not manage) every shim degrades to the real primitive,
so production code paths are unchanged and objects that outlive a run
(metric registry families created during a run) keep working.

Known limits, by design: a managed thread that blocks in a non-shimmed
primitive (real socket I/O, a pre-existing real lock held across a
yield) stalls the harness — scenarios stick to the in-process protocol
surface; CPU-bound loops with no primitive ops in them cannot be
preempted (there is no yield point to preempt at).
"""

from __future__ import annotations

import _thread
import random
import threading as _rt
import time as _time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

# Real primitives, captured before any Harness ever patches the module
# attributes.  The scheduler's OWN synchronization must be built on
# raw ``_thread`` primitives: the stdlib's Thread/Semaphore/Event
# classes resolve Condition/Event from the ``threading`` module
# NAMESPACE at construction time, so instantiating them while the
# harness has that namespace patched would hand the scheduler its own
# shims back (infinite recursion).
_REAL_THREAD = _rt.Thread
_REAL_LOCK = _rt.Lock          # _thread.allocate_lock: namespace-free
_REAL_RLOCK = _rt.RLock        # _thread.RLock: namespace-free
_real_get_ident = _rt.get_ident
_real_monotonic = _time.monotonic


class _Token:
    """A binary handoff token on a raw ``_thread`` lock (born taken).
    The scheduler's run-permit protocol is strictly alternating —
    exactly one release per acquire — so a binary token is enough and
    stays clear of every patched class."""

    __slots__ = ("_lk",)

    def __init__(self):
        self._lk = _thread.allocate_lock()
        self._lk.acquire()

    def acquire(self) -> None:
        self._lk.acquire()

    def release(self) -> None:
        self._lk.release()

RUNNABLE, BLOCKED, DONE = "runnable", "blocked", "done"

#: the active (scheduler, monitor) pair; shims and patched factories
#: consult this instead of binding a scheduler at construction so that
#: shim objects surviving a run degrade to real primitives afterwards
ACTIVE: Dict[str, object] = {"sched": None, "monitor": None}


class Violation:
    """One checker finding: an invariant/spec breach, a deadlock, or a
    suspected hang, tagged with where in the schedule it fired."""

    __slots__ = ("kind", "message", "thread", "step")

    def __init__(self, kind: str, message: str, thread: str = "",
                 step: int = 0):
        self.kind = kind
        self.message = message
        self.thread = thread
        self.step = step

    def to_dict(self) -> dict:
        return {"kind": self.kind, "message": self.message,
                "thread": self.thread, "step": self.step}

    def __repr__(self):
        return f"Violation({self.kind}: {self.message!r} @{self.step})"


class _TState:
    """Scheduler bookkeeping for one managed thread."""

    __slots__ = ("name", "index", "os_thread", "permit", "state",
                 "waiting_on", "wake_at", "wake_reason", "error",
                 "fastpath_yields")

    def __init__(self, name: str, index: int):
        self.name = name
        self.index = index
        self.os_thread = None
        self.permit = _Token()
        self.state = RUNNABLE
        self.waiting_on: Optional[Tuple[object, str]] = None
        self.wake_at: Optional[float] = None
        self.wake_reason: Optional[str] = None
        self.error: Optional[BaseException] = None
        self.fastpath_yields = 0


# ----------------------------------------------------------------------
# Policies
# ----------------------------------------------------------------------
class RandomPolicy:
    """Seeded-random exploration with preemption bounding: at a branch
    point where the current thread could keep running, switching away
    is a preemption and at most ``max_preemptions`` happen per schedule
    (the CHESS result: most concurrency bugs need very few)."""

    def __init__(self, seed: int = 0, max_preemptions: int = 4,
                 p_preempt: float = 0.4):
        self.seed = seed
        self._rng = random.Random(seed)
        self.max_preemptions = max_preemptions
        self.p_preempt = p_preempt
        self.preemptions = 0

    def choose(self, cands: Sequence[_TState],
               cur: Optional[_TState]) -> int:
        if cur is not None and cur in cands:
            others = [i for i, c in enumerate(cands) if c is not cur]
            if others and self.preemptions < self.max_preemptions \
                    and self._rng.random() < self.p_preempt:
                self.preemptions += 1
                return self._rng.choice(others)
            return cands.index(cur)
        return self._rng.randrange(len(cands))


class DFSPolicy:
    """Bounded-exhaustive driver: follow ``prefix`` decisions, then the
    deterministic default (keep the current thread; else the oldest
    runnable).  The explorer enumerates alternatives off the recorded
    branch list."""

    def __init__(self, prefix: Sequence[int] = ()):
        self.prefix = list(prefix)
        self._i = 0
        self.preemptions = 0
        self.diverged = False

    def choose(self, cands: Sequence[_TState],
               cur: Optional[_TState]) -> int:
        default = cands.index(cur) if (cur is not None and cur in cands) \
            else 0
        if self._i < len(self.prefix):
            pick = self.prefix[self._i]
            self._i += 1
            if pick >= len(cands):
                # the scenario's branch structure shifted under this
                # prefix (can only happen for a buggy, schedule-
                # dependent scenario) — fall back to the default
                self.diverged = True
                pick = default
        else:
            pick = default
        if cur is not None and cur in cands and pick != cands.index(cur):
            self.preemptions += 1
        return pick


class ReplayPolicy(DFSPolicy):
    """Replay a recorded decision vector exactly (the trace-replay
    workflow: every violation carries its decisions)."""


# ----------------------------------------------------------------------
# The scheduler
# ----------------------------------------------------------------------
class Scheduler:
    """One scheduler = one schedule = one run of a scenario."""

    #: a thread spinning through this many consecutive yield points
    #: with no other runnable thread is forced through the slow path so
    #: the step counter (and the overrun detector) advances
    _FASTPATH_LIMIT = 128

    def __init__(self, policy=None, max_steps: int = 50000,
                 clock0: float = 1000.0):
        self.policy = policy or RandomPolicy(0)
        self.max_steps = int(max_steps)
        self.clock = float(clock0)
        self.trace: List[str] = []
        #: (n_candidates, chosen_index, current_index_or_None) at every
        #: true branch point — the schedule's identity and replay key
        self.branches: List[Tuple[int, int, Optional[int]]] = []
        self.violations: List[Violation] = []
        #: (name, fn) pairs; fn() -> Optional[str], run at every
        #: scheduling point while the system is quiescent
        self.probes: List[Tuple[str, Callable[[], Optional[str]]]] = []
        self.futures: List[object] = []
        self._threads: List[_TState] = []
        self._by_ident: Dict[int, _TState] = {}
        self._current: Optional[_TState] = None
        self._sched_sem = _Token()
        self._steps = 0
        self._labels: Dict[str, int] = {}
        self._probe_seen: set = set()
        self._active = False
        self._root: Optional[_TState] = None

    # -- identity helpers ----------------------------------------------
    def next_label(self, kind: str) -> str:
        n = self._labels.get(kind, 0) + 1
        self._labels[kind] = n
        return f"{kind}-{n}"

    def label(self, obj, kind: str) -> str:
        """A run-local label for a shim object, assigned at FIRST USE
        within this run: objects that outlive a run (metric-registry
        child locks are cached process-wide) get a fresh label in the
        next run's sequence, so identical schedules produce
        byte-identical traces regardless of what earlier runs
        created."""
        if getattr(obj, "_label_gen", None) is not self:
            obj._label_gen = self
            obj._label = self.next_label(kind)
        return obj._label

    def managed_current(self) -> Optional[_TState]:
        if not self._active:
            return None
        return self._by_ident.get(_real_get_ident())

    @property
    def decisions(self) -> List[int]:
        return [b[1] for b in self.branches]

    @property
    def steps(self) -> int:
        return self._steps

    @property
    def preemptions(self) -> int:
        return getattr(self.policy, "preemptions", 0)

    def trace_text(self) -> str:
        return "\n".join(self.trace)

    def violation(self, kind: str, message: str) -> None:
        """Record a violation (deduped per run) from specs/probes."""
        key = (kind, message)
        if key in self._probe_seen:
            return
        self._probe_seen.add(key)
        ts = self.managed_current()
        self.violations.append(Violation(
            kind, message, ts.name if ts else "", self._steps))

    # -- spawn / finish ------------------------------------------------
    def _spawn(self, name: str, body: Callable[[], None],
               is_root: bool = False) -> _TState:
        ts = _TState(name, len(self._threads))
        self._threads.append(ts)
        if is_root:
            self._root = ts

        def run_body():
            self._by_ident[_real_get_ident()] = ts
            ts.permit.acquire()  # dl4j: noqa[DL4J203] scheduler handoff token: released by the run loop, never paired with a release here
            err = None
            try:
                body()
            except BaseException as e:
                err = e
            self._finish(ts, err)

        # raw _thread spawn: threading.Thread would build its started-
        # Event from the (patched) threading namespace
        ts.os_thread = _thread.start_new_thread(run_body, ())
        return ts

    def _finish(self, ts: _TState, err: Optional[BaseException]) -> None:
        ts.state = DONE
        ts.error = err
        self.trace.append(f"{self._steps:05d} {ts.name} thread.done"
                          + (f" error={type(err).__name__}" if err else ""))
        self._sched_sem.release()

    # -- yield / block / wake (called from managed threads) ------------
    def _record(self, ts: _TState, op: str, detail: str = "") -> None:
        self.trace.append(f"{self._steps:05d} {ts.name} {op}"
                          + (f" {detail}" if detail else ""))

    def _run_probes(self) -> None:
        for name, fn in self.probes:
            try:
                msg = fn()
            except Exception as e:
                msg = f"probe crashed: {type(e).__name__}: {e}"
            if msg:
                self.violation("invariant", f"[{name}] {msg}")

    def yield_point(self, op: str, detail: str = "") -> None:
        """A scheduling point: record, probe, and hand the token back
        unless this thread is the only runnable one (fast path)."""
        ts = self.managed_current()
        if ts is None:
            return
        self._record(ts, op, detail)
        self._run_probes()
        others = any(o is not ts and o.state == RUNNABLE
                     for o in self._threads)
        if not others and ts.fastpath_yields < self._FASTPATH_LIMIT:
            ts.fastpath_yields += 1
            return
        ts.fastpath_yields = 0
        self._sched_sem.release()
        ts.permit.acquire()  # dl4j: noqa[DL4J203] scheduler handoff token, released by the run loop

    def block(self, obj: object, op: str,
              timeout: Optional[float] = None, detail: str = "") -> str:
        """Block the current thread on ``obj`` until woken (or until the
        logical timer fires, when ``timeout`` is given).  Returns the
        wake reason: ``"wake"`` or ``"timeout"``."""
        ts = self.managed_current()
        if ts is None:
            raise RuntimeError("block() outside a managed thread")
        self._record(ts, op, detail)
        self._run_probes()
        ts.state = BLOCKED
        ts.waiting_on = (obj, op)
        ts.wake_at = (self.clock + max(0.0, float(timeout))
                      if timeout is not None else None)
        ts.wake_reason = None
        ts.fastpath_yields = 0
        self._sched_sem.release()
        ts.permit.acquire()  # dl4j: noqa[DL4J203] scheduler handoff token, released by the run loop
        ts.waiting_on = None
        ts.wake_at = None
        return ts.wake_reason or "wake"

    def wake(self, ts: _TState, reason: str = "wake") -> None:
        if ts.state == BLOCKED:
            ts.state = RUNNABLE
            ts.wake_reason = reason

    # -- the run loop (controlling thread) -----------------------------
    def run(self, root_fn: Callable[[], None],
            name: str = "root") -> None:
        """Execute ``root_fn`` (and every thread it spawns) to
        completion under this scheduler.  Must be called with the
        matching :class:`Harness` active."""
        self._active = True
        try:
            self._spawn(name, root_fn, is_root=True)
            while True:
                self._steps += 1
                if self._steps > self.max_steps:
                    blocked = ", ".join(
                        f"{t.name}({t.waiting_on[1] if t.waiting_on else t.state})"
                        for t in self._threads if t.state != DONE)
                    self.violations.append(Violation(
                        "overrun",
                        f"schedule exceeded {self.max_steps} steps — "
                        f"suspected hang/livelock; live: {blocked}",
                        step=self._steps))
                    break
                cands = [t for t in self._threads if t.state == RUNNABLE]
                if not cands:
                    blocked = [t for t in self._threads
                               if t.state == BLOCKED]
                    timers = [t for t in blocked if t.wake_at is not None]
                    if timers:
                        nxt = min(timers,
                                  key=lambda s: (s.wake_at, s.index))
                        self.clock = max(self.clock, nxt.wake_at)
                        nxt.wake_reason = "timeout"
                        nxt.state = RUNNABLE
                        continue
                    if blocked:
                        waits = "; ".join(
                            f"{t.name} waiting on "
                            f"{t.waiting_on[1] if t.waiting_on else '?'}"
                            for t in blocked)
                        self.violations.append(Violation(
                            "deadlock",
                            f"all threads blocked with no timers: {waits}",
                            step=self._steps))
                    break
                choice = self._choose(cands)
                self._run_slice(choice)
            root = self._root
            if root is not None and root.error is not None:
                err = root.error
                kind = ("scenario-assert"
                        if isinstance(err, AssertionError)
                        else "scenario-error")
                self.violations.append(Violation(
                    kind, f"{type(err).__name__}: {err}", root.name,
                    self._steps))
            for t in self._threads:
                if t is not root and t.error is not None:
                    self.violations.append(Violation(
                        "thread-crash",
                        f"unhandled {type(t.error).__name__} in "
                        f"{t.name}: {t.error}", t.name, self._steps))
        finally:
            self._active = False

    def _choose(self, cands: List[_TState]) -> _TState:
        cur = self._current
        if len(cands) == 1:
            return cands[0]
        idx = self.policy.choose(cands, cur)
        cur_idx = cands.index(cur) if (cur is not None and cur in cands) \
            else None
        self.branches.append((len(cands), idx, cur_idx))
        return cands[idx]

    def _run_slice(self, ts: _TState) -> None:
        self._current = ts
        self.clock += 1e-6
        ts.permit.release()
        self._sched_sem.acquire()  # dl4j: noqa[DL4J203] scheduler handoff token: released by whichever managed thread yields next


# ----------------------------------------------------------------------
# Primitive shims.  Every shim is dual-mode: cooperative when called
# from a managed thread of the ACTIVE scheduler, a plain real primitive
# otherwise — so shim objects that outlive a run degrade gracefully.
# ----------------------------------------------------------------------
def _sched_for(obj) -> Optional[Scheduler]:
    s = ACTIVE.get("sched")
    if s is None or s.managed_current() is None:
        return None
    return s


class SLock:
    """Cooperative ``threading.Lock``."""

    _reentrant = False

    def __init__(self, label: Optional[str] = None):
        self._fixed_label = label
        self._owner: Optional[_TState] = None
        self._count = 0
        self._waiters: List[_TState] = []
        self._real = _REAL_RLOCK() if self._reentrant else _REAL_LOCK()

    @classmethod
    def _kind(cls) -> str:
        return "rlock" if cls._reentrant else "lock"

    def _lbl(self, s: Scheduler) -> str:
        return self._fixed_label or s.label(self, self._kind())

    def acquire(self, blocking: bool = True, timeout: float = -1):
        s = _sched_for(self)
        if s is None:
            if timeout is not None and timeout > 0:
                return self._real.acquire(blocking, timeout)  # dl4j: noqa[DL4J203] fallback delegate: the caller owns the release pairing
            return self._real.acquire(blocking)  # dl4j: noqa[DL4J203] fallback delegate: the caller owns the release pairing
        ts = s.managed_current()
        label = self._lbl(s)
        s.yield_point("lock.acquire", label)
        while self._owner is not None and self._owner is not ts:
            if not blocking:
                return False
            self._waiters.append(ts)
            reason = s.block(
                self, "lock.blocked", detail=label,
                timeout=timeout if (timeout is not None and timeout > 0)
                else None)
            if ts in self._waiters:
                self._waiters.remove(ts)
            if reason == "timeout" and self._owner is not None \
                    and self._owner is not ts:
                return False
        if self._owner is ts:
            if not self._reentrant:
                raise RuntimeError(
                    f"non-reentrant {self._lbl(s)} re-acquired by "
                    f"{ts.name} (self-deadlock in real execution)")
            self._count += 1
        else:
            self._owner = ts
            self._count = 1
        return True

    def release(self):
        s = _sched_for(self)
        if s is None:
            return self._real.release()
        ts = s.managed_current()
        if self._owner is not ts:
            raise RuntimeError(f"release of {self._lbl(s)} not held by "
                               f"{ts.name}")
        self._count -= 1
        if self._count > 0:
            return
        self._owner = None
        for w in list(self._waiters):
            s.wake(w)
        self._waiters.clear()
        s.yield_point("lock.release", self._lbl(s))

    def locked(self) -> bool:
        if self._owner is not None:
            return True
        got = self._real.acquire(False)  # dl4j: noqa[DL4J203] probe-acquire released on the next line
        if got:
            self._real.release()
        return not got

    # Condition integration (mirrors the private threading contract)
    def _is_owned(self) -> bool:
        s = _sched_for(self)
        return s is not None and self._owner is s.managed_current()

    def _release_save(self):
        owner, count = self._owner, self._count
        self._owner, self._count = None, 0
        s = _sched_for(self)
        if s is not None:
            for w in list(self._waiters):
                s.wake(w)
            self._waiters.clear()
        return owner, count

    def _acquire_restore(self, saved):
        self.acquire()
        _owner, count = saved
        self._count = count

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class SRLock(SLock):
    """Cooperative ``threading.RLock``."""

    _reentrant = True


class SCondition:
    """Cooperative ``threading.Condition`` over an :class:`SLock`/
    :class:`SRLock` (a fresh SRLock when none is given)."""

    def __init__(self, lock=None):
        self._lock = lock if lock is not None else SRLock()
        self._waiters: List[_TState] = []

    def _lbl(self, s: Scheduler) -> str:
        return s.label(self, "cond")

    # lock surface
    def acquire(self, *a, **k):
        return self._lock.acquire(*a, **k)  # dl4j: noqa[DL4J203] delegate: the caller owns the acquire/release pairing (Condition surface)

    def release(self):
        return self._lock.release()

    def __enter__(self):
        self._lock.acquire()  # dl4j: noqa[DL4J203] released in __exit__ — this IS the with-statement implementation
        return self

    def __exit__(self, *exc):
        self._lock.release()
        return False

    def wait(self, timeout: Optional[float] = None) -> bool:
        s = _sched_for(self)
        if s is None:
            raise RuntimeError(
                "SCondition waited on outside the harness "
                "(a checker-built object escaped its run)")
        ts = s.managed_current()
        if not self._lock._is_owned():
            raise RuntimeError("cannot wait on un-acquired condition")
        saved = self._lock._release_save()
        self._waiters.append(ts)
        reason = s.block(self, "cond.wait", timeout=timeout,
                         detail=self._lbl(s))
        if ts in self._waiters:
            self._waiters.remove(ts)
        self._lock._acquire_restore(saved)
        return reason != "timeout"

    def wait_for(self, predicate, timeout: Optional[float] = None):
        result = predicate()
        while not result:
            if not self.wait(timeout):
                return predicate()
            result = predicate()
        return result

    def notify(self, n: int = 1) -> None:
        s = _sched_for(self)
        if s is None:
            return
        for w in list(self._waiters[:n]):
            self._waiters.remove(w)
            s.wake(w)
        s.yield_point("cond.notify", self._lbl(s))

    def notify_all(self) -> None:
        self.notify(len(self._waiters) or 1)


class SEvent:
    """Cooperative ``threading.Event``."""

    def __init__(self):
        self._flag = False
        self._waiters: List[_TState] = []

    def _lbl(self, s: Scheduler) -> str:
        return s.label(self, "event")

    def is_set(self) -> bool:
        return self._flag

    def set(self) -> None:
        self._flag = True
        s = _sched_for(self)
        if s is not None:
            for w in list(self._waiters):
                s.wake(w)
            self._waiters.clear()
            s.yield_point("event.set", self._lbl(s))

    def clear(self) -> None:
        self._flag = False

    def wait(self, timeout: Optional[float] = None) -> bool:
        s = _sched_for(self)
        if s is None:
            # degraded mode: a set flag is still visible
            return self._flag
        ts = s.managed_current()
        s.yield_point("event.wait", self._lbl(s))
        while not self._flag:
            self._waiters.append(ts)
            reason = s.block(self, "event.blocked", timeout=timeout,
                             detail=self._lbl(s))
            if ts in self._waiters:
                self._waiters.remove(ts)
            if reason == "timeout" and not self._flag:
                return False
        return True


class SQueue:
    """Cooperative ``queue.Queue`` (FIFO, optional maxsize)."""

    def __init__(self, maxsize: int = 0):
        self.maxsize = int(maxsize)
        self._items: List[object] = []
        self._getters: List[_TState] = []
        self._putters: List[_TState] = []
        self._unfinished = 0

    def _lbl(self, s: Scheduler) -> str:
        return s.label(self, "queue")

    def qsize(self) -> int:
        return len(self._items)

    def empty(self) -> bool:
        return not self._items

    def full(self) -> bool:
        return 0 < self.maxsize <= len(self._items)

    def put(self, item, block: bool = True,
            timeout: Optional[float] = None) -> None:
        import queue as _q
        s = _sched_for(self)
        if s is None:
            if self.full():
                raise _q.Full
            self._items.append(item)
            self._unfinished += 1
            return
        ts = s.managed_current()
        s.yield_point("queue.put", self._lbl(s))
        while self.full():
            if not block:
                raise _q.Full
            self._putters.append(ts)
            reason = s.block(self, "queue.put_blocked", timeout=timeout,
                             detail=self._lbl(s))
            if ts in self._putters:
                self._putters.remove(ts)
            if reason == "timeout" and self.full():
                raise _q.Full
        self._items.append(item)
        self._unfinished += 1
        for w in list(self._getters):
            s.wake(w)
        self._getters.clear()

    def put_nowait(self, item) -> None:
        self.put(item, block=False)

    def get(self, block: bool = True, timeout: Optional[float] = None):
        import queue as _q
        s = _sched_for(self)
        if s is None:
            if not self._items:
                raise _q.Empty
            return self._items.pop(0)
        ts = s.managed_current()
        s.yield_point("queue.get", self._lbl(s))
        while not self._items:
            if not block:
                raise _q.Empty
            self._getters.append(ts)
            reason = s.block(self, "queue.get_blocked", timeout=timeout,
                             detail=self._lbl(s))
            if ts in self._getters:
                self._getters.remove(ts)
            if reason == "timeout" and not self._items:
                raise _q.Empty
        item = self._items.pop(0)
        for w in list(self._putters):
            s.wake(w)
        self._putters.clear()
        return item

    def get_nowait(self):
        return self.get(block=False)

    def task_done(self) -> None:
        self._unfinished = max(0, self._unfinished - 1)

    def join(self) -> None:
        s = _sched_for(self)
        while self._unfinished > 0 and s is not None:
            s.block(self, "queue.join", timeout=0.01,
                    detail=self._lbl(s))


class SThread:
    """Cooperative ``threading.Thread``: the spawned thread becomes a
    managed thread of the active scheduler; outside a harness it
    degrades to a plain real thread."""

    def __init__(self, group=None, target=None, name=None, args=(),
                 kwargs=None, daemon=None):
        s = ACTIVE.get("sched")
        self._target = target
        self._args = tuple(args or ())
        self._kwargs = dict(kwargs or {})
        self.name = name or (s.next_label("thread") if s else "thread")
        self.daemon = True if daemon is None else bool(daemon)
        self._ts: Optional[_TState] = None
        self._real: Optional[_rt.Thread] = None
        self._started = False
        self._joiners: List[_TState] = []

    def _run(self):
        if self._target is not None:
            self._target(*self._args, **self._kwargs)

    def start(self) -> None:
        if self._started:
            raise RuntimeError("threads can only be started once")
        self._started = True
        s = ACTIVE.get("sched")
        if s is None or not s._active:
            self._real = _REAL_THREAD(target=self._run, daemon=self.daemon,
                                      name=self.name)
            self._real.start()
            return
        sthread = self

        def body():
            try:
                sthread._run()
            finally:
                scur = ACTIVE.get("sched")
                if scur is s:
                    for w in list(sthread._joiners):
                        s.wake(w)
                    sthread._joiners.clear()

        self._ts = s._spawn(self.name, body)
        s.yield_point("thread.start", self.name)

    def is_alive(self) -> bool:
        if self._real is not None:
            return self._real.is_alive()
        return self._started and self._ts is not None \
            and self._ts.state != DONE

    def join(self, timeout: Optional[float] = None) -> None:
        if self._real is not None:
            return self._real.join(timeout)
        s = _sched_for(self)
        if s is None:
            deadline = _real_monotonic() + (timeout or 5.0)
            while self.is_alive() and _real_monotonic() < deadline:
                _time.sleep(0.002)
            return
        ts = s.managed_current()
        s.yield_point("thread.join", self.name)
        while self.is_alive():
            self._joiners.append(ts)
            reason = s.block(self, "thread.join_blocked", timeout=timeout,
                             detail=self.name)
            if ts in self._joiners:
                self._joiners.remove(ts)
            if reason == "timeout" and self.is_alive():
                return


def make_future_class():
    """Build the cooperative Future class lazily (keeps the
    concurrent.futures import off this module's import path)."""
    import concurrent.futures as _cf

    class SFuture(_cf.Future):
        """Cooperative ``concurrent.futures.Future``: ``result()``
        blocks through the scheduler; resolution wakes waiters at a
        yield point.  Registered with the scheduler so the explorer can
        assert every future was resolved on every schedule."""

        def __init__(self):
            super().__init__()
            self._swaiters: List[_TState] = []
            s = ACTIVE.get("sched")
            if s is not None:
                s.futures.append(self)

        def result(self, timeout=None):
            s = _sched_for(self)
            if s is None:
                return super().result(timeout)
            ts = s.managed_current()
            s.yield_point("future.result")
            while not self.done():
                self._swaiters.append(ts)
                reason = s.block(self, "future.blocked", timeout=timeout)
                if ts in self._swaiters:
                    self._swaiters.remove(ts)
                if reason == "timeout" and not self.done():
                    raise _cf.TimeoutError()
            return super().result(timeout=0)

        def _wake_all(self, op: str) -> None:
            s = _sched_for(self)
            if s is None:
                return
            for w in list(self._swaiters):
                s.wake(w)
            self._swaiters.clear()
            s.yield_point(op)

        def set_result(self, result):
            super().set_result(result)
            self._wake_all("future.set_result")

        def set_exception(self, exc):
            super().set_exception(exc)
            self._wake_all("future.set_exception")

    return SFuture


def schedule_point(op: str = "schedule_point") -> None:
    """An explicit yield point for scenario code (and for synthetic
    racy fixtures): a no-op outside a managed thread."""
    s = ACTIVE.get("sched")
    if s is not None:
        s.yield_point(op)


# ----------------------------------------------------------------------
# The harness: scoped activation + monkey-patching
# ----------------------------------------------------------------------
class Harness:
    """Patch the serving stack's synchronization primitives onto the
    scheduler for the duration of a ``with`` block.  One harness at a
    time per process; production code paths outside the block are
    untouched (every patch is restored on exit)."""

    _guard = _REAL_LOCK()

    def __init__(self, sched: Scheduler, monitor=None):
        self.sched = sched
        self.monitor = monitor
        self._saved: List[Tuple[object, str, object]] = []
        self.flight_dumps = 0

    def _patch(self, obj, attr, value) -> None:
        self._saved.append((obj, attr, getattr(obj, attr)))
        setattr(obj, attr, value)

    def __enter__(self) -> "Harness":
        if not Harness._guard.acquire(blocking=False):  # dl4j: noqa[DL4J203] released in __exit__ — the harness IS the with-statement
            raise RuntimeError("another dl4j-check Harness is active")
        try:
            self._install()
        except BaseException:
            Harness._guard.release()
            raise
        return self

    def _install(self) -> None:
        import queue as queue_mod

        from deeplearning4j_tpu.monitor import events as ev_mod
        from deeplearning4j_tpu.monitor import flight as flight_mod
        from deeplearning4j_tpu.resilience import faults
        from deeplearning4j_tpu.server import batcher as batcher_mod
        from deeplearning4j_tpu.server import decode as decode_mod

        sched = self.sched
        monitor = self.monitor
        ACTIVE["sched"] = sched
        ACTIVE["monitor"] = monitor

        self._patch(_rt, "Thread", SThread)
        self._patch(_rt, "Lock", SLock)
        self._patch(_rt, "RLock", SRLock)
        self._patch(_rt, "Condition", SCondition)
        self._patch(_rt, "Event", SEvent)
        self._patch(queue_mod, "Queue", SQueue)

        # managed threads are raw _thread spawns; threading.current_
        # thread() would try to mint a _DummyThread for them, and with
        # the namespace patched the real Thread.__init__ builds its
        # started-Event from OUR shims and breaks (logging reads
        # current_thread().name on every record)
        real_current = _rt.current_thread

        class _ManagedThreadView:
            __slots__ = ("name", "daemon", "ident")

            def __init__(self, name, ident):
                self.name = name
                self.daemon = True
                self.ident = ident

            def is_alive(self):
                return True

        def fake_current_thread():
            s = ACTIVE.get("sched")
            ts = s.managed_current() if s is not None else None
            if ts is not None:
                return _ManagedThreadView(f"dl4j-check:{ts.name}",
                                          _real_get_ident())
            return real_current()

        self._patch(_rt, "current_thread", fake_current_thread)
        sfuture = make_future_class()
        self._patch(batcher_mod, "Future", sfuture)
        self._patch(decode_mod, "Future", sfuture)

        real_monotonic = _time.monotonic
        real_perf = _time.perf_counter
        real_sleep = _time.sleep

        def fake_clock():
            s = ACTIVE.get("sched")
            if s is not None and s.managed_current() is not None:
                return s.clock
            return real_monotonic()

        def fake_perf():
            s = ACTIVE.get("sched")
            if s is not None and s.managed_current() is not None:
                return s.clock
            return real_perf()

        def fake_sleep(secs):
            s = ACTIVE.get("sched")
            if s is not None and s.managed_current() is not None:
                s.block(fake_sleep, "time.sleep", timeout=max(1e-9, secs))
                return
            real_sleep(secs)

        self._patch(_time, "monotonic", fake_clock)
        self._patch(_time, "perf_counter", fake_perf)
        self._patch(_time, "sleep", fake_sleep)

        real_emit = ev_mod.emit

        def emit_hook(etype, severity="info", **fields):
            s = ACTIVE.get("sched")
            m = ACTIVE.get("monitor")
            if m is not None and s is not None \
                    and s.managed_current() is not None:
                try:
                    m.on_event(etype, severity, fields)
                except Exception as e:
                    s.violation("monitor-error",
                                f"spec monitor crashed on {etype}: "
                                f"{type(e).__name__}: {e}")
            return real_emit(etype, severity=severity, **fields)

        self._patch(ev_mod, "emit", emit_hook)

        harness = self

        def flight_stub(reason, extra=None):
            harness.flight_dumps += 1
            return None

        self._patch(flight_mod, "dump", flight_stub)
        faults.reset()

    def __exit__(self, *exc) -> bool:
        try:
            for obj, attr, value in reversed(self._saved):
                setattr(obj, attr, value)
            self._saved.clear()
            ACTIVE["sched"] = None
            ACTIVE["monitor"] = None
            from deeplearning4j_tpu.resilience import faults
            faults.reset()
        finally:
            Harness._guard.release()
        return False
