"""Checker scenarios: the serving-stack protocols driven under the
deterministic scheduler.

Each scenario runs the REAL protocol code — ``DecodePool``'s control
queue, two-phase export→import→confirm, crash handler and restart
path; ``MicroBatcher``'s dispatch/death/restart; ``CircuitBreaker``'s
window machine — with only the device compute stubbed
(:class:`CheckDecodePool` swaps the jitted gather→step→scatter for a
step-counting carry, so a slot collision or a lost/duplicated step is
visible as a wrong carry VALUE, not just a bookkeeping mismatch).
Locks, queues, futures and threads are the production ones, shimmed by
the harness; scenario actors are spawned as managed threads and every
interleaving of them is the explorer's choice.

A scenario must be deterministic given the schedule (no wall-clock, no
real randomness on the control path) and must stop its pools before
returning — a leaked batcher thread polls forever and the scheduler
reports it as an overrun.

``double_claim``/``deadlock``/``leaked_future`` are positive controls:
deliberately broken miniatures that the checker MUST flag (the tests
pin that, and pin that a saved failing schedule replays to the same
violation).
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional

import numpy as np

from deeplearning4j_tpu.analysis.check import specs as _specs
from deeplearning4j_tpu.analysis.check.sched import (
    SThread, schedule_point)
from deeplearning4j_tpu.resilience import faults
from deeplearning4j_tpu.resilience.errors import (
    CircuitOpenError, TransientError)

_WARM = {"done": False}


def warm() -> None:
    """One-time pre-harness warmup: import jax and touch the device so
    backend initialization (which spawns real helper threads) never
    happens inside a harness, and construct one throwaway pool so the
    metric registry families exist before the first measured run."""
    if _WARM["done"]:
        return
    import jax.numpy as jnp
    np.asarray(jnp.zeros((1,), np.float32))
    pool = CheckDecodePool(_StubModel(), name="chk-warm", max_slots=1)
    pool.stop(timeout=5.0)
    from deeplearning4j_tpu.server.batcher import MicroBatcher
    MicroBatcher(lambda x: x, name="chk-warm").stop(timeout=5.0)
    from deeplearning4j_tpu.distributed.coordinator import Coordinator
    Coordinator(expected=0)   # registers the dl4j_dist_* families
    _WARM["done"] = True


class Context:
    """What a scenario gets: managed-thread spawning, pool watching,
    and direct access to the run's scheduler."""

    def __init__(self, sched):
        self.sched = sched

    def thread(self, name: str, fn: Callable[[], None]) -> SThread:
        t = SThread(target=fn, name=name)
        t.start()
        return t

    def watch_pool(self, pool) -> None:
        _specs.watch_decode_pool(self.sched, pool)

    def probe(self, name: str, fn) -> None:
        self.sched.probes.append((name, fn))

    def future(self):
        from deeplearning4j_tpu.server import batcher
        return batcher.Future()


# ----------------------------------------------------------------------
# The stubbed decode model: real DecodePool, counting-carry compute
# ----------------------------------------------------------------------
class _StubGlobalConf:
    bucket_time_sizes = None


class _StubConf:
    global_conf = _StubGlobalConf()


class _StubModel:
    """The minimal engine surface DecodePool touches for a non-graph
    model; no ``_forward_all`` attr, so the pool takes the MLN path."""

    conf = _StubConf()
    net_params: Dict = {}
    net_state = [{}]


def _counting_pool_step(params, state, pool, idx, fresh, xs, fms):
    """Pure-host stand-in for the ONE compiled decode program, keeping
    its exact contract: gather slot carries by ``idx``, zero the
    ``fresh`` rows, advance, scatter back.  The carry is a step
    counter, so session i's n-th step returns exactly ``n`` — a slot
    collision, a lost scatter, or a stale migrated carry shows up as a
    wrong output value under SOME schedule."""
    h = np.asarray(pool["h"])
    idx = np.asarray(idx)
    fresh = np.asarray(fresh)
    g = h[idx] * (1.0 - fresh)[:, None]
    newh = g + 1.0
    x = np.asarray(xs[0])
    if x.ndim >= 3:
        out = np.repeat(newh[:, None, :], x.shape[1], axis=1)
    else:
        out = newh
    h2 = h.copy()
    h2[idx] = newh
    import jax.numpy as jnp
    return (out,), {"h": jnp.asarray(h2)}


from deeplearning4j_tpu.server.decode import DecodePool  # noqa: E402


class CheckDecodePool(DecodePool):
    """DecodePool with the device state stubbed to the counting carry;
    every protocol path (batcher loop, control queue, export/import,
    crash handler, drain) is the parent's real code."""

    def _ensure_device_state(self, tails, dtype) -> None:
        if self._pool is not None:
            return
        import jax.numpy as jnp
        n = self.max_slots + 1
        self._pool = {"h": jnp.zeros((n, 1), np.float32)}
        self._tails = tuple(tuple(t[1:]) for t in tails)
        self._dtype = np.dtype(np.float32)
        self._step_jit = _counting_pool_step


def _kv_pool_step(params, state, pool, idx, fresh, xs, fms):
    """Counting carry PLUS a KV write-position leaf, keeping the
    attention ring's contract: ``kv_pos`` is the per-slot count of
    tokens ever written (monotone; fresh rows zero it in-trace), which
    is exactly what ``specs._KVRingWatch`` checks at every scheduling
    point — a slot collision, a stale un-zeroed ring, or a ring that
    moves in exported limbo shows as a wrong position VALUE."""
    h = np.asarray(pool["h"])
    kv = np.asarray(pool["kv_pos"])
    idx = np.asarray(idx)
    fresh = np.asarray(fresh)
    g = h[idx] * (1.0 - fresh)[:, None]
    gkv = kv[idx] * (1.0 - fresh)[:, None]
    newh = g + 1.0
    newkv = gkv + 1.0          # one token appended per step
    x = np.asarray(xs[0])
    if x.ndim >= 3:
        out = np.repeat(newh[:, None, :], x.shape[1], axis=1)
    else:
        out = newh
    h2 = h.copy()
    h2[idx] = newh
    kv2 = kv.copy()
    kv2[idx] = newkv
    import jax.numpy as jnp
    return (out,), {"h": jnp.asarray(h2), "kv_pos": jnp.asarray(kv2)}


class CheckKVDecodePool(DecodePool):
    """DecodePool whose stub carry includes a KV ring write position —
    the miniature of the speculative-serving subsystem's attention
    carry, driven through the REAL control-queue protocol."""

    def _ensure_device_state(self, tails, dtype) -> None:
        if self._pool is not None:
            return
        import jax.numpy as jnp
        n = self.max_slots + 1
        self._pool = {"h": jnp.zeros((n, 1), np.float32),
                      "kv_pos": jnp.zeros((n, 1), np.float32)}
        self._tails = tuple(tuple(t[1:]) for t in tails)
        self._dtype = np.dtype(np.float32)
        self._step_jit = _kv_pool_step


def _paged_stub_step(params, state, pool, idx, fresh, xs, fms,
                     arenas, tbls):
    """Pure-host stand-in for the PAGED pool step, keeping its exact
    contract: counting carry plus a paged-KV node (``aid``/``pos``/
    ``tbl``) whose write position advances one token per step and whose
    table row is the dispatch's host-built block table — so the real
    allocator (admission, close/TTL frees, migration re-page) drives
    the real arena invariants at every scheduling point."""
    h = np.asarray(pool["h"])
    pos = np.asarray(pool["rnn"]["pos"])
    tbl = np.asarray(pool["rnn"]["tbl"])
    idx = np.asarray(idx)
    fresh = np.asarray(fresh)
    g = h[idx] * (1.0 - fresh)[:, None]
    gpos = (pos[idx] * (1.0 - fresh)).astype(np.int32)
    newh = g + 1.0
    newpos = gpos + 1
    x = np.asarray(xs[0])
    if x.ndim >= 3:
        out = np.repeat(newh[:, None, :], x.shape[1], axis=1)
    else:
        out = newh
    h2 = h.copy()
    h2[idx] = newh
    pos2 = pos.copy()
    pos2[idx] = newpos
    tbl2 = tbl.copy()
    tbl2[idx] = np.asarray(tbls[0])
    import jax.numpy as jnp
    new_pool = {"h": jnp.asarray(h2),
                "rnn": {"aid": pool["rnn"]["aid"],
                        "pos": jnp.asarray(pos2),
                        "tbl": jnp.asarray(tbl2)}}
    return (out,), new_pool, arenas


class CheckPagedDecodePool(DecodePool):
    """DecodePool with ``kv_paged`` on and the device compute stubbed —
    the block allocator, token admission, close/TTL frees, the
    de-page/re-page migration halves and the crash resets are all the
    parent's REAL code; only the jitted step is the host stand-in."""

    def __init__(self, *args, arena_blocks: int = 3, window: int = 8,
                 **kw):
        kw.setdefault("kv_paged", True)
        kw.setdefault("kv_block", 4)
        self._arena_nb = max(1, int(arena_blocks))
        self._window = int(window)
        super().__init__(*args, **kw)

    def _ensure_device_state(self, tails, dtype) -> None:
        if self._pool is not None:
            return
        import jax.numpy as jnp
        n = self.max_slots + 1
        bs = self.kv_block
        nbs = -(-self._window // bs)
        nb = self._arena_nb
        self._pool = {
            "h": jnp.zeros((n, 1), np.float32),
            "rnn": {"aid": jnp.zeros((n, 1), np.int32),
                    "pos": jnp.zeros((n,), np.int32),
                    "tbl": jnp.full((n, nbs), nb, np.int32)},
        }
        self._tails = tuple(tuple(t[1:]) for t in tails)
        self._dtype = np.dtype(np.float32)
        self._step_jit = _paged_stub_step
        with self._cond:
            self._arenas = ({"k": jnp.zeros((nb + 1, 1, bs, 1),
                                            np.float32),
                             "v": jnp.zeros((nb + 1, 1, bs, 1),
                                            np.float32)},)
            self._arena_specs = ({"heads": 1, "head_dim": 1,
                                  "window": self._window,
                                  "window_eff": nbs * bs,
                                  "blocks_per_slot": nbs,
                                  "dtype": "float32"},)
            self._arena_blocks = (nb,)
            self._kv_free = [list(range(nb))]
            self._update_arena_gauges_locked()


def _x():
    return np.zeros((1, 1), np.float32)


def _val(out) -> float:
    return float(np.asarray(out[0]).ravel()[0])


# ----------------------------------------------------------------------
# Protocol scenarios
# ----------------------------------------------------------------------
def scenario_migration(ctx: Context) -> None:
    """Two-phase live migration racing a client stream: export →
    import → confirm on one thread while the session keeps stepping on
    another.  The carry must count 1..4 without a gap or repeat no
    matter where the move lands in the stream."""
    faults.reset()
    src = CheckDecodePool(_StubModel(), name="chk-src", max_slots=4,
                          max_wait_ms=0.0)
    dst = CheckDecodePool(_StubModel(), name="chk-dst", max_slots=4,
                          max_wait_ms=0.0)
    ctx.watch_pool(src)
    ctx.watch_pool(dst)
    try:
        sid = src.open_session(tenant="t0")
        loc = {"pool": src}
        results = []
        errors = []

        def stepper():
            for _i in range(4):
                for _try in range(50):
                    pool = loc["pool"]
                    try:
                        out = pool.step(sid, _x(), timeout=60)
                        results.append(_val(out))
                        break
                    except (TransientError, KeyError):
                        # mid-migration: wait out the move, re-read loc
                        time.sleep(0.001)
                else:
                    errors.append("step retries exhausted")
                    return

        def migrator():
            try:
                payload = src.export_session(sid, timeout=30)
            except Exception as e:
                errors.append(f"export failed: {type(e).__name__}: {e}")
                return
            try:
                dst.import_session(payload)
            except Exception as e:
                src.finish_export(sid, ok=False)
                errors.append(f"import failed: {type(e).__name__}: {e}")
                return
            loc["pool"] = dst
            src.finish_export(sid, ok=True)

        t1 = ctx.thread("stepper", stepper)
        t2 = ctx.thread("migrator", migrator)
        t1.join(120.0)
        t2.join(120.0)
        assert not errors, errors
        assert results == [1.0, 2.0, 3.0, 4.0], \
            f"carry broke across the migration: {results}"
        assert src.active_sessions == 0, "source still counts the " \
            "migrated session (double-count)"
    finally:
        src.stop(timeout=30.0)
        dst.stop(timeout=30.0)


def scenario_migration_kill(ctx: Context) -> None:
    """A replica dying mid-migration (``fleet.migrate`` kill): the
    export must fail LOUDLY on the migrator, every client future must
    resolve, and the pool must serve new sessions after the restart."""
    faults.reset()
    src = CheckDecodePool(_StubModel(), name="chk-src", max_slots=4,
                          max_wait_ms=0.0)
    ctx.watch_pool(src)
    try:
        faults.arm({"site": "fleet.migrate", "mode": "kill", "on_call": 1})
        sid = src.open_session(tenant="t0")
        outcomes = []

        def stepper():
            for _i in range(3):
                try:
                    out = src.step(sid, _x(), timeout=60)
                    outcomes.append(("ok", _val(out)))
                except (TransientError, KeyError, RuntimeError) as e:
                    outcomes.append(("err", type(e).__name__))
                    return

        def migrator():
            try:
                src.export_session(sid, timeout=30)
                outcomes.append(("export-ok", None))
            except Exception as e:
                outcomes.append(("export-err", type(e).__name__))

        t1 = ctx.thread("stepper", stepper)
        t2 = ctx.thread("migrator", migrator)
        t1.join(120.0)
        t2.join(120.0)
        kinds = [k for k, _ in outcomes]
        assert "export-err" in kinds, \
            f"kill-mid-migration did not fail loudly: {outcomes}"
        assert src.deaths == 1, f"expected one batcher death, " \
            f"got {src.deaths}"
        # the restart path: a fresh session streams again
        sid2 = src.open_session()
        out = src.step(sid2, _x(), timeout=60)
        assert _val(out) == 1.0, "post-restart carry not fresh"
    finally:
        src.stop(timeout=30.0)


def scenario_kv_migration(ctx: Context) -> None:
    """KV-ring carry under live migration, driven through the real
    control-queue protocol: a session with ring state migrates
    export→import→confirm while it streams, a second session churns its
    slot (close + fresh claim) on the source.  The ``_KVRingWatch``
    probes check at EVERY scheduling point that the write position is
    monotone, frozen in exported limbo, and zeroed on a fresh claim;
    the counting carry pins that the migrated ring's VALUE continued
    exactly (1..4 with no gap or repeat)."""
    faults.reset()
    src = CheckKVDecodePool(_StubModel(), name="chk-kv-src", max_slots=2,
                            max_wait_ms=0.0)
    dst = CheckKVDecodePool(_StubModel(), name="chk-kv-dst", max_slots=2,
                            max_wait_ms=0.0)
    ctx.watch_pool(src)
    ctx.watch_pool(dst)
    _specs.watch_kv_ring(ctx.sched, src)
    _specs.watch_kv_ring(ctx.sched, dst)
    try:
        sid = src.open_session(tenant="t0")
        loc = {"pool": src}
        results = []
        errors = []

        def stepper():
            for _i in range(4):
                for _try in range(50):
                    pool = loc["pool"]
                    try:
                        out = pool.step(sid, _x(), timeout=60)
                        results.append(_val(out))
                        break
                    except (TransientError, KeyError):
                        time.sleep(0.001)
                else:
                    errors.append("step retries exhausted")
                    return

        def migrator():
            try:
                payload = src.export_session(sid, timeout=30)
            except Exception as e:
                errors.append(f"export failed: {type(e).__name__}: {e}")
                return
            try:
                dst.import_session(payload)
            except Exception as e:
                src.finish_export(sid, ok=False)
                errors.append(f"import failed: {type(e).__name__}: {e}")
                return
            loc["pool"] = dst
            src.finish_export(sid, ok=True)

        def churner():
            # slot churn on the source: open → step → close → reopen;
            # the fresh claim must observe a zeroed ring every time
            try:
                for _i in range(2):
                    s2 = src.open_session(tenant="t1")
                    out = src.step(s2, _x(), timeout=60)
                    if _val(out) != 1.0:
                        errors.append(
                            f"fresh claim saw stale ring: {_val(out)}")
                    src.close_session(s2)
            except (TransientError, KeyError, RuntimeError):
                pass   # pool churn racing the migration is legal

        t1 = ctx.thread("stepper", stepper)
        t2 = ctx.thread("migrator", migrator)
        t3 = ctx.thread("churner", churner)
        t1.join(120.0)
        t2.join(120.0)
        t3.join(120.0)
        assert not errors, errors
        assert results == [1.0, 2.0, 3.0, 4.0], \
            f"kv carry broke across the migration: {results}"
    finally:
        src.stop(timeout=30.0)
        dst.stop(timeout=30.0)


def scenario_kv_paging(ctx: Context) -> None:
    """Paged-KV block allocator under concurrent growth, close/TTL
    frees, exhaustion sheds and a live migration, all through the REAL
    allocator/admission/re-page code: the ``_arena_probe`` invariants
    (no block owned by two live sessions, freed blocks return exactly
    once, held+free conserves the arena) are checked at EVERY
    scheduling point, and the counting carry pins that the migrated
    stream's VALUE continued exactly across the de-page/re-page hop."""
    from deeplearning4j_tpu.server.decode import OverloadedError
    faults.reset()
    # src arena: 3 blocks of 4 tokens (window 8 -> up to 2 blocks per
    # stream) — the grower and the churner genuinely contend; dst
    # arena: exactly the 2 blocks the migrated stream needs
    src = CheckPagedDecodePool(_StubModel(), name="chk-pg-src",
                               max_slots=2, max_wait_ms=0.0,
                               arena_blocks=3)
    dst = CheckPagedDecodePool(_StubModel(), name="chk-pg-dst",
                               max_slots=2, max_wait_ms=0.0,
                               arena_blocks=2)
    ctx.watch_pool(src)
    ctx.watch_pool(dst)
    _specs.watch_kv_arena(ctx.sched, src)
    _specs.watch_kv_arena(ctx.sched, dst)
    try:
        sid = src.open_session(tenant="t0")
        loc = {"pool": src}
        results = []
        errors = []

        def grower():
            # streams past one block (5 tokens -> 2 blocks) while the
            # migration and the churner race it; arena exhaustion is a
            # legal retryable shed, never a wrong value
            for _i in range(5):
                for _try in range(80):
                    pool = loc["pool"]
                    try:
                        out = pool.step(sid, _x(), timeout=60)
                        results.append(_val(out))
                        break
                    except (TransientError, KeyError, OverloadedError):
                        time.sleep(0.001)
                else:
                    errors.append("grower retries exhausted")
                    return

        def migrator():
            try:
                payload = src.export_session(sid, timeout=30)
            except Exception as e:
                errors.append(f"export failed: {type(e).__name__}: {e}")
                return
            try:
                dst.import_session(payload)
            except OverloadedError:
                src.finish_export(sid, ok=False)   # reinstate at source
                return
            except Exception as e:
                src.finish_export(sid, ok=False)
                errors.append(f"import failed: {type(e).__name__}: {e}")
                return
            loc["pool"] = dst
            src.finish_export(sid, ok=True)

        def churner():
            # open -> grow -> close on the source: every close must
            # return the session's blocks exactly once
            for _i in range(2):
                try:
                    s2 = src.open_session(tenant="t1")
                except (OverloadedError, RuntimeError):
                    continue
                try:
                    for _s in range(2):
                        try:
                            src.step(s2, _x(), timeout=60)
                        except OverloadedError:
                            time.sleep(0.001)
                except (TransientError, KeyError, RuntimeError):
                    pass
                finally:
                    src.close_session(s2)

        def reaper():
            # the TTL path frees through the same _close_locked: age a
            # throwaway session far past the deadline, then force the
            # sweep (deterministic — no wall-clock waits)
            try:
                s3 = src.open_session(tenant="t2")
            except (OverloadedError, RuntimeError):
                return
            try:
                src.step(s3, _x(), timeout=60)
            except (TransientError, KeyError, OverloadedError,
                    RuntimeError):
                pass
            with src._cond:
                s = src._sessions.get(s3)
                if s is not None:
                    s.last_used = -1e12
                src._sweep_locked()

        t1 = ctx.thread("grower", grower)
        t2 = ctx.thread("migrator", migrator)
        t3 = ctx.thread("churner", churner)
        t4 = ctx.thread("reaper", reaper)
        for t in (t1, t2, t3, t4):
            t.join(120.0)
        assert not errors, errors
        assert results == [1.0, 2.0, 3.0, 4.0, 5.0], \
            f"paged carry broke across the migration: {results}"
    finally:
        src.stop(timeout=30.0)
        dst.stop(timeout=30.0)


def scenario_batcher_death(ctx: Context) -> None:
    """MicroBatcher thread killed mid-compute: in-flight requests fail
    with a clear error (never hang), the next submit restarts the
    thread, and every client converges to a correct answer."""
    from deeplearning4j_tpu.server.batcher import MicroBatcher
    faults.reset()
    mb = MicroBatcher(lambda x: x * 2.0, max_batch=8, max_wait_ms=0.0,
                      name="chk-mb")
    try:
        faults.arm({"site": "batcher.compute", "mode": "kill",
                    "on_call": 1})
        outs: Dict[int, object] = {}

        def client(i: int):
            x = np.full((1, 2), float(i), np.float32)
            for _try in range(4):
                try:
                    outs[i] = mb.predict(x, timeout=60)
                    return
                except RuntimeError:
                    # the batcher died under us; resubmitting restarts it
                    continue
            outs[i] = "failed"

        threads = [ctx.thread(f"client-{i}", lambda i=i: client(i))
                   for i in range(3)]
        for t in threads:
            t.join(120.0)
        for i in range(3):
            got = outs.get(i)
            assert isinstance(got, np.ndarray), f"client {i}: {got!r}"
            assert float(got[0, 0]) == 2.0 * i, f"client {i} got a " \
                f"batch-mate's rows: {got!r}"
        assert mb.deaths == 1, f"expected one death, got {mb.deaths}"
        assert mb.restarts >= 1, "dead batcher was never restarted"
    finally:
        mb.stop(timeout=30.0)


def scenario_decode_death(ctx: Context) -> None:
    """Decode batcher killed at ``decode.step``: sessions close with a
    clear error, no waiter strands, and the pool restarts clean."""
    faults.reset()
    pool = CheckDecodePool(_StubModel(), name="chk-dp", max_slots=4,
                           max_wait_ms=0.0)
    ctx.watch_pool(pool)
    try:
        faults.arm({"site": "decode.step", "mode": "kill", "on_call": 1})
        sids = [pool.open_session() for _ in range(2)]
        outcomes = []

        def stepper(sid: str):
            try:
                out = pool.step(sid, _x(), timeout=60)
                outcomes.append(("ok", _val(out)))
            except (RuntimeError, KeyError, TransientError) as e:
                outcomes.append(("err", type(e).__name__))

        threads = [ctx.thread(f"stepper-{i}",
                              lambda sid=sid: stepper(sid))
                   for i, sid in enumerate(sids)]
        for t in threads:
            t.join(120.0)
        assert len(outcomes) == 2, f"a stepper hung: {outcomes}"
        assert any(k == "err" for k, _ in outcomes), \
            f"the kill never surfaced: {outcomes}"
        assert pool.deaths == 1, f"expected one death, got {pool.deaths}"
        sid3 = pool.open_session()
        out = pool.step(sid3, _x(), timeout=60)
        assert _val(out) == 1.0, "post-restart carry not fresh"
    finally:
        pool.stop(timeout=30.0)


def scenario_drain(ctx: Context) -> None:
    """Drain admits nothing: concurrent opens/imports against a
    draining pool must shed (503), never admit, and resume re-admits."""
    from deeplearning4j_tpu.resilience.errors import OverloadedError
    faults.reset()
    src = CheckDecodePool(_StubModel(), name="chk-src", max_slots=4,
                          max_wait_ms=0.0)
    dst = CheckDecodePool(_StubModel(), name="chk-dst", max_slots=4,
                          max_wait_ms=0.0)
    ctx.watch_pool(src)
    ctx.watch_pool(dst)
    try:
        sid = dst.open_session()
        dst.step(sid, _x(), timeout=60)
        payload = dst.export_session(sid, timeout=30)
        results = []

        def drainer():
            src.drain()
            results.append(("drained", None))

        def opener():
            for _try in range(2):
                try:
                    results.append(("opened", src.open_session()))
                    return
                except OverloadedError:
                    results.append(("shed", None))
                    return

        def importer():
            try:
                results.append(("imported", src.import_session(payload)))
                dst.finish_export(sid, ok=True)
            except OverloadedError:
                results.append(("import-shed", None))
                dst.finish_export(sid, ok=False)

        threads = [ctx.thread("drainer", drainer),
                   ctx.thread("opener", opener),
                   ctx.thread("importer", importer)]
        for t in threads:
            t.join(120.0)
        assert len(results) == 3, results
        src.resume()
        sid2 = src.open_session()   # resume re-admits
        assert sid2
    finally:
        src.stop(timeout=30.0)
        dst.stop(timeout=30.0)


def scenario_breaker(ctx: Context) -> None:
    """CircuitBreaker hammered from two threads through its whole
    lifecycle (fail → open → cooldown → half-open probe → close); the
    BreakerSpec checks every transition's legality on every schedule."""
    from deeplearning4j_tpu.resilience.policy import CircuitBreaker
    faults.reset()
    br = CircuitBreaker(failure_threshold=0.5, window=4, min_calls=2,
                        cooldown_s=0.05, half_open_max=1,
                        name="chk-breaker", clock=time.monotonic)
    state = {"fail": True}

    def work():
        if state["fail"]:
            raise TransientError("chk: induced failure")
        return 1

    def caller(n: int):
        for _i in range(n):
            try:
                br.call(work)
            except (CircuitOpenError, TransientError):
                pass
            time.sleep(0.01)

    t1 = ctx.thread("caller-1", lambda: caller(5))
    t2 = ctx.thread("caller-2", lambda: caller(5))
    t1.join(120.0)
    t2.join(120.0)
    state["fail"] = False
    recovered = False
    for _i in range(10):
        try:
            br.call(work)
            recovered = True
            break
        except (CircuitOpenError, TransientError):
            time.sleep(0.05)
    assert recovered, f"breaker never recovered: {br.snapshot()}"
    assert br.state == CircuitBreaker.CLOSED


def scenario_dist_membership(ctx: Context) -> None:
    """The REAL elastic-cluster Coordinator (distributed/coordinator.py)
    driven through a preemption story under every interleaving: two
    workers form generation 1 and train; one dies mid-run (stops
    heartbeating) RACING the survivor's in-flight barrier — the
    lease/generation machinery must roll and release the waiter, never
    strand it; the dead worker then rejoins (breaker gate), resyncs
    from the survivor's snapshot, and is absorbed.  Checked: every
    barrier call returns (no stranded waiter), each committed step
    reduces under exactly ONE generation (no two live generations — the
    :class:`specs.WorkerLifecycleSpec` additionally pins generation
    monotonicity and the joined→active→suspect→dead|rejoined machine on
    the ``dist.*`` events the coordinator emits)."""
    from deeplearning4j_tpu.distributed.coordinator import Coordinator
    faults.reset()
    clk = {"t": 0.0}
    co = Coordinator(expected=2, lease_ms=1000.0, suspect_grace_ms=500.0,
                     allreduce_timeout_s=5.0,
                     breaker={"min_calls": 2, "cooldown_s": 0.0},
                     clock=lambda: clk["t"])
    N = 4
    committed: Dict[int, int] = {}   # step -> generation it reduced under
    errors = []
    done = {"wa": False, "wb": False}

    def record(resp) -> None:
        step, gen = resp["step"], resp["generation"]
        prev = committed.get(step)
        if prev is not None and prev != gen:
            errors.append(f"step {step} committed under two live "
                          f"generations: {prev} and {gen}")
        committed[step] = gen

    def run_steps(wid: str, start_step: int) -> None:
        """Drive the worker protocol loop: contribute each next step,
        riding out rolls/fences, answering snapshot-upload requests."""
        step = start_step
        for _try in range(500):
            if step >= N:
                return
            place = co.placement(wid)
            if place.get("generation", 0) < 1:
                # cluster still forming: no data plane yet (mirrors
                # DistSession.placement_tuple's wait)
                time.sleep(0.001)
                continue
            if place.get("state") == "dead":
                if not co.join(wid)["admitted"]:
                    time.sleep(0.001)
                    continue
                place = co.placement(wid)
            if place.get("state") == "joined":
                # resync: poll the snapshot (activation rides on it)
                snap = co.get_snapshot(wid, min_step=0)
                if snap is None:
                    time.sleep(0.001)
                    continue
                step = snap["step"]
                continue
            resp = co.allreduce(wid, place["generation"], step + 1, 1.0,
                                np.ones(1, np.float32))
            if resp.get("evicted") or resp.get("rolled") \
                    or resp.get("timeout"):
                continue
            if resp.get("stale_step"):
                step = int(resp["committed"])
                continue
            record(resp)
            step += 1
            if resp.get("upload_state"):
                co.put_snapshot(wid, step,
                                np.zeros(2, np.float32), None,
                                {"epoch": 0, "iteration_in_epoch": step})
        errors.append(f"{wid}: protocol loop never converged")

    def wa():
        assert co.join("wa")["admitted"]
        co.sync_done("wa")
        co.heartbeat("wa")
        run_steps("wa", 0)
        # final state relay so a late rejoiner always absorbs
        co.put_snapshot("wa", co.status()["step"],
                        np.zeros(2, np.float32), None,
                        {"epoch": 0, "iteration_in_epoch": N})
        done["wa"] = True

    def wb():
        assert co.join("wb")["admitted"]
        co.sync_done("wb")
        for _try in range(50):    # contribute step 1, riding out rolls
            place = co.placement("wb")
            if place["generation"] < 1:
                time.sleep(0.001)
                continue
            resp = co.allreduce("wb", place["generation"], 1, 1.0,
                                np.ones(1, np.float32))
            if "vec" in resp:
                record(resp)
                break
            if resp.get("stale_step") or resp.get("evicted"):
                break
        # ... and dies: no heartbeats, no more contributions (first
        # incarnation).  The rejoin incarnation:
        for _try in range(200):
            if done["wa"] and co.status()["step"] >= N:
                break
            if co.placement("wb").get("state") == "dead":
                break
            time.sleep(0.001)
        joined = co.join("wb")
        if joined["admitted"]:
            co.heartbeat("wb")
            run_steps("wb", int(joined.get("step", 0)))
        done["wb"] = True

    def reaper():
        # the cluster's clock: advance leases, keep the live workers'
        # leases fresh, sweep — death detection races the barrier here
        for _i in range(400):
            if done["wa"] and done["wb"]:
                return
            clk["t"] += 0.4
            for wid in ("wa", "wb"):
                st = co.placement(wid).get("state")
                if st in ("active", "suspect", "joined") \
                        and not _is_dead_phase(wid):
                    co.heartbeat(wid)
            time.sleep(0.001)
        errors.append("reaper budget exhausted before both workers "
                      "finished")

    dead_phase = {"wb": False}

    def _is_dead_phase(wid: str) -> bool:
        # wb's first incarnation stops heartbeating after its step-1
        # contribution: the reaper must NOT keep its lease alive.  The
        # phase flips when wb is declared dead (rejoin path re-enables).
        if wid != "wb":
            return False
        if co.status()["step"] >= 1 and not dead_phase["wb"]:
            st = co.placement("wb").get("state")
            if st == "dead":
                dead_phase["wb"] = True
                return False
            return True
        return False

    t1 = ctx.thread("dist-wa", wa)
    t2 = ctx.thread("dist-wb", wb)
    t3 = ctx.thread("dist-reaper", reaper)
    t1.join(300.0)
    t2.join(300.0)
    t3.join(300.0)
    assert not errors, errors
    assert done["wa"] and done["wb"], (done, co.status())
    assert set(committed) == set(range(1, N + 1)), committed
    gens = [committed[s] for s in sorted(committed)]
    assert gens == sorted(gens), f"generations regressed: {gens}"
    assert co.status()["step"] >= N


# ----------------------------------------------------------------------
# Positive controls: the checker MUST catch these
# ----------------------------------------------------------------------
class RacyPool:
    """A deliberately unsynchronized slot claimer (the synthetic
    double-claim bug the determinism/replay tests pin)."""

    def __init__(self, slots: int = 2):
        self.free = list(range(slots))
        self.claimed: Dict[str, int] = {}

    def claim(self, sid: str) -> Optional[int]:
        if not self.free:
            return None
        slot = self.free[0]           # read ...
        schedule_point("racy.claim")  # ... the race window ...
        self.free.pop(0)              # ... write
        self.claimed[sid] = slot
        return slot


def _racy_probe(pool: RacyPool) -> Optional[str]:
    slots = list(pool.claimed.values())
    if len(set(slots)) != len(slots):
        return f"slot double-claim: {sorted(pool.claimed.items())}"
    return None


def scenario_double_claim(ctx: Context) -> None:
    pool = RacyPool(slots=2)
    ctx.probe("racy-slots", lambda: _racy_probe(pool))
    t1 = ctx.thread("claim-a", lambda: pool.claim("s1"))
    t2 = ctx.thread("claim-b", lambda: pool.claim("s2"))
    t1.join(60.0)
    t2.join(60.0)


def scenario_deadlock(ctx: Context) -> None:
    """Classic two-lock inversion with no timers: the scheduler must
    report a deadlock naming both threads."""
    import threading
    a = threading.Lock()
    b = threading.Lock()

    def ab():
        with a:
            schedule_point("deadlock.ab")
            with b:
                pass

    def ba():
        with b:
            schedule_point("deadlock.ba")
            with a:
                pass

    t1 = ctx.thread("ab", ab)
    t2 = ctx.thread("ba", ba)
    t1.join(5.0)
    t2.join(5.0)


def scenario_leaked_future(ctx: Context) -> None:
    """A future created and never resolved: the end-of-run obligation
    check must flag it on EVERY schedule."""
    ctx.future()   # leaked on purpose


SCENARIOS: Dict[str, Callable[[Context], None]] = {
    "migration": scenario_migration,
    "migration_kill": scenario_migration_kill,
    "kv_migration": scenario_kv_migration,
    "kv_paging": scenario_kv_paging,
    "batcher_death": scenario_batcher_death,
    "decode_death": scenario_decode_death,
    "drain": scenario_drain,
    "breaker": scenario_breaker,
    "dist_membership": scenario_dist_membership,
    "double_claim": scenario_double_claim,
    "deadlock": scenario_deadlock,
    "leaked_future": scenario_leaked_future,
}

#: the scenarios a default checker run gates on (positive controls are
#: excluded — they exist to prove the checker catches bugs)
DEFAULT_SCENARIOS = ("migration", "migration_kill", "kv_migration",
                     "kv_paging", "batcher_death", "decode_death",
                     "drain", "breaker", "dist_membership")
