"""dl4j-check: deterministic-schedule concurrency checker and protocol
lifecycle verifier for the serving stack (docs/ANALYSIS.md
"Concurrency checker").

Public surface:

* :func:`explore` / :func:`explore_protocols` — run a scenario under
  many interleavings (seeded-random or bounded-exhaustive), collect
  violations, count distinct schedules.
* :func:`replay` / :func:`replay_file` / :func:`save_trace` — re-run
  an exact recorded schedule (every violation carries its decisions).
* :data:`SCENARIOS` — the scenario registry (migration, kill-mid-
  migration, batcher death/restart, decode death, drain, breaker, and
  the positive controls).
* :class:`Harness` / :class:`Scheduler` / :func:`schedule_point` —
  the cooperative scheduler itself, for bespoke scenarios.

CLI: ``python -m deeplearning4j_tpu.analysis.check`` (exit 0 = zero
violations over the explored schedules).
"""

from deeplearning4j_tpu.analysis.check.explore import (  # noqa: F401
    ExploreResult, RunResult, explore, explore_protocols, replay,
    replay_file, run_once, save_trace)
from deeplearning4j_tpu.analysis.check.scenarios import (  # noqa: F401
    DEFAULT_SCENARIOS, SCENARIOS, Context)
from deeplearning4j_tpu.analysis.check.sched import (  # noqa: F401
    DFSPolicy, Harness, RandomPolicy, ReplayPolicy, Scheduler,
    Violation, schedule_point)
from deeplearning4j_tpu.analysis.check.specs import (  # noqa: F401
    BreakerSpec, SessionLifecycleSpec, SpecMonitor, watch_decode_pool)
