"""CLI: ``python -m deeplearning4j_tpu.analysis.check``.

Explores the serving-stack protocol scenarios under the deterministic
scheduler and gates on zero violations.

Exit codes: 0 = no violations across every explored schedule; 1 =
violations (each printed with its replay recipe); 2 = usage error.

The replay workflow::

    # a failing run prints (and with --save-trace writes) the recipe
    python -m deeplearning4j_tpu.analysis.check --scenarios double_claim \
        --schedules 50 --save-trace /tmp/fail.json
    # re-run THAT schedule, byte-for-byte
    python -m deeplearning4j_tpu.analysis.check --replay /tmp/fail.json
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    # NOTE: the package __init__ re-exports the explore() FUNCTION under
    # the same name as this module — import the module via sys.modules,
    # not package attribute lookup
    import importlib
    ex = importlib.import_module(
        "deeplearning4j_tpu.analysis.check.explore")
    sc = importlib.import_module(
        "deeplearning4j_tpu.analysis.check.scenarios")

    parser = argparse.ArgumentParser(
        prog="python -m deeplearning4j_tpu.analysis.check",
        description="dl4j-check: deterministic-schedule concurrency "
                    "checker for the serving stack")
    parser.add_argument("--scenarios", default=None,
                        help="comma-separated scenario names (default: "
                             "the gating protocol set)")
    parser.add_argument("--schedules", type=int, default=60,
                        help="max schedules per scenario (default 60)")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--mode", choices=("random", "exhaustive"),
                        default="random")
    parser.add_argument("--max-preemptions", type=int, default=4)
    parser.add_argument("--budget-s", type=float, default=None,
                        help="wall-clock budget across all scenarios")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text")
    parser.add_argument("--replay", default=None, metavar="TRACE_JSON",
                        help="re-run one recorded schedule instead of "
                             "exploring")
    parser.add_argument("--save-trace", default=None, metavar="PATH",
                        help="write the first violation's replay "
                             "recipe here")
    parser.add_argument("--list", action="store_true",
                        help="list scenarios and exit")
    args = parser.parse_args(argv)

    if args.list:
        for name in sorted(sc.SCENARIOS):
            gate = "gating" if name in sc.DEFAULT_SCENARIOS \
                else "positive-control"
            doc = (sc.SCENARIOS[name].__doc__ or "").strip().split("\n")[0]
            print(f"{name:<16} [{gate}] {doc}")
        return 0

    if args.replay:
        r = ex.replay_file(args.replay)
        doc = {"version": 1, "scenario": r.scenario,
               "decisions": r.decisions, "trace_hash": r.trace_hash,
               "steps": r.steps,
               "violations": r.violation_dicts()}
        if args.format == "json":
            print(json.dumps(doc, indent=1, sort_keys=True))
        else:
            print(f"replayed {r.scenario}: {r.steps} steps, "
                  f"trace {r.trace_hash}")
            for v in r.violations:
                print(f"  VIOLATION [{v.kind}] {v.message}")
        return 1 if r.violations else 0

    names = ([s.strip() for s in args.scenarios.split(",") if s.strip()]
             if args.scenarios else None)
    try:
        summary = ex.explore_protocols(
            names, schedules=args.schedules, seed=args.seed,
            mode=args.mode, max_preemptions=args.max_preemptions,
            time_budget_s=args.budget_s)
    except KeyError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    if args.save_trace and summary["violations"]:
        ex.save_trace(summary["violations"][0], args.save_trace)

    if args.format == "json":
        print(json.dumps(summary, indent=1, sort_keys=True))
    else:
        for name, s in summary["scenarios"].items():
            print(f"{name:<16} {s['runs']:>4} schedules, "
                  f"{s['distinct']:>4} distinct, "
                  f"{len(s['violations'])} violation(s), "
                  f"{s['wall_s']:.1f}s")
        print(f"dl4j-check: {summary['total_runs']} schedules, "
              f"{summary['total_distinct']} distinct interleavings, "
              f"{len(summary['violations'])} violation(s)")
        for v in summary["violations"][:20]:
            print(f"  VIOLATION [{v['kind']}] ({v['scenario']}, "
                  f"seed={v['seed']}) {v['message']}")
            print(f"    replay: decisions={v['decisions']}")
    return 1 if summary["violations"] else 0


if __name__ == "__main__":
    sys.exit(main())
