"""Rule catalog loader: importing this module registers every built-in
rule with :data:`deeplearning4j_tpu.analysis.core.RULES`.

| id      | name                  | severity | hazard                       |
|---------|-----------------------|----------|------------------------------|
| DL4J101 | tracer-host-sync      | error    | `.item()`/float() in jit     |
| DL4J102 | tracer-host-transfer  | error    | np.asarray/device_get in jit |
| DL4J103 | tracer-impure         | warning  | time/random/print in jit     |
| DL4J104 | retrace-risk          | warning  | closure/loop-jit retraces    |
| DL4J201 | blocking-under-lock   | warning  | I/O or unbounded wait w/ lock|
| DL4J202 | lock-order-cycle      | error    | cross-file deadlock ordering |
| DL4J203 | bare-lock-acquire     | error    | acquire without finally      |
| DL4J205 | future-success-path-only | warning | thread resolves futures only on success |
| DL4J206 | unbounded-wait-device-thread | warning | no-timeout wait on device-owner thread |
| DL4J207 | shared-write-outside-lock | warning | guarded attr written lock-free |
| DL4J208 | thread-without-crash-handler | warning | spawned thread w/o crash handler |
| DL4J301 | metric-undocumented   | error    | code metric not in docs      |
| DL4J302 | metric-doc-stale      | error    | doc metric not in code       |
| DL4J303 | event-undocumented    | error    | journal event not in docs    |
| DL4J304 | event-doc-stale       | error    | doc event not in code        |

Rationale and worked examples: docs/ANALYSIS.md.
"""

from deeplearning4j_tpu.analysis import rules_concurrency  # noqa: F401
from deeplearning4j_tpu.analysis import rules_metrics  # noqa: F401
from deeplearning4j_tpu.analysis import rules_threads  # noqa: F401
from deeplearning4j_tpu.analysis import rules_tracer  # noqa: F401
from deeplearning4j_tpu.analysis.core import RULES  # noqa: F401
