"""Tracer-safety and retrace-risk rules (DL4J1xx).

Scope: functions reachable from ``jit``/``pjit``/``scan``/``shard_map``
call sites (:meth:`Project.jit_reachable`).  Inside that set, host
syncs and impure constructs either concretize a tracer (hard error on a
real mesh), force a silent device→host round-trip per step, or bake a
trace-time value into the compiled program ("Array Languages Make
Neural Networks Fast": accidental host transfers and re-compilation
are the dominant framework-level slowdowns — both statically visible).
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set

from deeplearning4j_tpu.analysis.core import (
    ERROR, WARNING, Finding, FunctionInfo, Project, Rule, _attr_chain,
    is_test_path, register)

#: methods whose mere invocation forces a device→host sync
_SYNC_METHODS = {"item", "block_until_ready", "tolist", "numpy"}
#: builtins that concretize a traced value
_CONCRETIZERS = {"float", "int", "bool", "complex"}

_HOST_TRANSFER_CALLS = {
    "np.asarray", "np.array", "np.copy", "numpy.asarray", "numpy.array",
    "onp.asarray", "onp.array", "jax.device_get", "device_get",
}

_IMPURE_PREFIXES = ("time.", "random.", "np.random.", "numpy.random.",
                    "os.environ", "os.getenv")
_IMPURE_CALLS = {"print", "input", "open"}


def _is_static_expr(node: ast.AST) -> bool:
    """Expressions that are Python-level static under tracing (shapes,
    dtypes, literals, len of pytrees) — concretizing these is fine."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.Attribute):
        if node.attr in {"shape", "ndim", "size", "dtype", "nbytes"}:
            return True
        return _is_static_expr(node.value)
    if isinstance(node, ast.Subscript):
        return _is_static_expr(node.value)
    if isinstance(node, ast.Call):
        chain = _attr_chain(node.func) or ""
        leaf = chain.split(".")[-1]
        if leaf in {"len", "prod", "range", "isinstance", "getattr",
                    "hasattr", "min", "max"} and all(
                _is_static_expr(a) for a in node.args):
            return True
        return False
    if isinstance(node, ast.BinOp):
        return _is_static_expr(node.left) and _is_static_expr(node.right)
    if isinstance(node, ast.UnaryOp):
        return _is_static_expr(node.operand)
    return False


def _is_explicit_transfer(node: ast.AST) -> bool:
    """`jax.device_get(...)` already IS the explicit, sanctioned sync —
    re-wrapping its (numpy) result is not another transfer."""
    return (isinstance(node, ast.Call)
            and (_attr_chain(node.func) or "").endswith("device_get"))


def _scan_nodes(info: FunctionInfo) -> Iterable[ast.AST]:
    """Walk a reachable function's full subtree (nested defs included —
    they are traced when called from the traced body)."""
    body = info.node.body if not isinstance(info.node, ast.Lambda) \
        else [info.node.body]
    for stmt in body if isinstance(body, list) else [body]:
        yield from ast.walk(stmt)


@register
class HostSyncInJit(Rule):
    id = "DL4J101"
    name = "tracer-host-sync"
    severity = ERROR
    doc = ("Host-sync calls (`.item()`, `.tolist()`, "
           "`.block_until_ready()`, `float()/int()/bool()` on traced "
           "values) inside functions reachable from jit/pjit/scan/"
           "shard_map call sites: they concretize a tracer (error) or "
           "silently stall the device pipeline every step.")

    def run(self, project: Project) -> Iterable[Finding]:
        for info in project.jit_reachable():
            for node in _scan_nodes(info):
                if not isinstance(node, ast.Call):
                    continue
                func = node.func
                if isinstance(func, ast.Attribute) and not node.args \
                        and func.attr in _SYNC_METHODS:
                    yield self.finding(
                        project, node, info.path,
                        f".{func.attr}() forces a device->host sync "
                        f"inside jit-reachable `{info.name}`")
                elif isinstance(func, ast.Name) \
                        and func.id in _CONCRETIZERS and len(node.args) == 1 \
                        and not _is_static_expr(node.args[0]):
                    yield self.finding(
                        project, node, info.path,
                        f"{func.id}() on a possibly-traced value inside "
                        f"jit-reachable `{info.name}` concretizes the "
                        "tracer (use jnp ops, or hoist to the host side)")


@register
class HostTransferInJit(Rule):
    id = "DL4J102"
    name = "tracer-host-transfer"
    severity = ERROR
    doc = ("`np.asarray`/`np.array`/`jax.device_get`/`.numpy()` on "
           "device arrays inside jit-reachable functions: a host "
           "round-trip per call, and a TracerArrayConversionError once "
           "actually traced.")

    def run(self, project: Project) -> Iterable[Finding]:
        for info in project.jit_reachable():
            for node in _scan_nodes(info):
                if not isinstance(node, ast.Call):
                    continue
                chain = _attr_chain(node.func)
                if chain in _HOST_TRANSFER_CALLS and node.args \
                        and not _is_static_expr(node.args[0]):
                    yield self.finding(
                        project, node, info.path,
                        f"{chain}() inside jit-reachable `{info.name}` "
                        "moves data to the host (use jnp.asarray, or "
                        "hoist out of the traced step)")


@register
class ImpureInJit(Rule):
    id = "DL4J103"
    name = "tracer-impure"
    severity = WARNING
    doc = ("Impure constructs (`time.*`, `random.*`, `print`, `open`, "
           "`global` mutation, env reads) inside jit-reachable "
           "functions run at TRACE time only — the compiled program "
           "re-runs with the stale value, silently.")

    def run(self, project: Project) -> Iterable[Finding]:
        for info in project.jit_reachable():
            for node in _scan_nodes(info):
                if isinstance(node, ast.Global):
                    yield self.finding(
                        project, node, info.path,
                        f"global mutation inside jit-reachable "
                        f"`{info.name}` happens once at trace time, "
                        "not per step")
                    continue
                if not isinstance(node, ast.Call):
                    continue
                chain = _attr_chain(node.func) or ""
                if isinstance(node.func, ast.Name) and \
                        node.func.id in _IMPURE_CALLS:
                    yield self.finding(
                        project, node, info.path,
                        f"{node.func.id}() inside jit-reachable "
                        f"`{info.name}` runs at trace time only (use "
                        "jax.debug.print for per-step output)")
                elif any(chain.startswith(p) for p in _IMPURE_PREFIXES):
                    yield self.finding(
                        project, node, info.path,
                        f"{chain}() inside jit-reachable `{info.name}` "
                        "is trace-time-impure: its value is baked into "
                        "the compiled program")


@register
class HostTransferInHotSpan(Rule):
    id = "DL4J105"
    name = "host-transfer-in-hot-span"
    severity = ERROR
    doc = ("Implicit device->host conversion (`np.asarray`/`np.array`/"
           "`float()`/`.item()`) directly inside a `monitor.span(...)` "
           "hot region (the fit-step and serve-batch phases): the span "
           "exists because the region is the per-step critical path — "
           "an implicit transfer there stalls the device pipeline "
           "every step.  Use `jax.device_get` for an explicit, "
           "sanitizer-approved sync, or move the pull off the hot "
           "path.")

    _SPAN_HOT = ("fit/", "serve/")

    def _hot_span_stmts(self, project: Project):
        for f in project.files:
            if f.tree is None:
                continue
            for node in ast.walk(f.tree):
                if not isinstance(node, ast.With):
                    continue
                for item in node.items:
                    ctx = item.context_expr
                    if not isinstance(ctx, ast.Call):
                        continue
                    chain = _attr_chain(ctx.func) or ""
                    if not chain.endswith("span") or not ctx.args:
                        continue
                    first = ctx.args[0]
                    if isinstance(first, ast.Constant) and \
                            isinstance(first.value, str) and \
                            first.value.startswith(self._SPAN_HOT):
                        yield f.path, node

    def run(self, project: Project) -> Iterable[Finding]:
        for path, with_node in self._hot_span_stmts(project):
            if is_test_path(path):
                continue
            # direct statements only — descending into callees would
            # flag every host-side helper the span legitimately times
            stack = list(with_node.body)
            while stack:
                node = stack.pop()
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.Lambda)):
                    continue
                if isinstance(node, ast.Call):
                    chain = _attr_chain(node.func) or ""
                    if chain in ("np.asarray", "np.array",
                                 "numpy.asarray", "numpy.array") \
                            and node.args \
                            and not _is_static_expr(node.args[0]) \
                            and not _is_explicit_transfer(node.args[0]):
                        yield self.finding(
                            project, node, path,
                            f"{chain}() inside a hot monitor.span "
                            "region forces an implicit device->host "
                            "sync per step — use jax.device_get (or "
                            "hoist it off the hot path)")
                    elif isinstance(node.func, ast.Attribute) \
                            and node.func.attr == "item" and not node.args:
                        yield self.finding(
                            project, node, path,
                            ".item() inside a hot monitor.span region "
                            "syncs the device per step — use "
                            "jax.device_get off the hot path")
                stack.extend(ast.iter_child_nodes(node))


def _free_loads(info: FunctionInfo) -> Set[str]:
    """Names loaded in the function subtree that are neither its
    params, its assigned locals, nor locally-defined functions."""
    bound: Set[str] = set(info.params) | {"self", "cls"}
    loads: Set[str] = set()
    node = info.node
    body = [node.body] if isinstance(node, ast.Lambda) else node.body
    for stmt in body:
        for n in ast.walk(stmt):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                bound.add(n.name)
                for p in _param_names_of(n):
                    bound.add(p)
            elif isinstance(n, ast.Lambda):
                for p in _param_names_of(n):
                    bound.add(p)
            elif isinstance(n, ast.Name):
                if isinstance(n.ctx, (ast.Store, ast.Del)):
                    bound.add(n.id)
                else:
                    loads.add(n.id)
            elif isinstance(n, (ast.For, ast.AsyncFor)):
                for t in ast.walk(n.target):
                    if isinstance(t, ast.Name):
                        bound.add(t.id)
            elif isinstance(n, ast.comprehension):
                for t in ast.walk(n.target):
                    if isinstance(t, ast.Name):
                        bound.add(t.id)
    import builtins
    return {n for n in loads - bound if not hasattr(builtins, n)}


def _param_names_of(node: ast.AST) -> List[str]:
    a = node.args
    out = [p.arg for p in
           list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)]
    if a.vararg:
        out.append(a.vararg.arg)
    if a.kwarg:
        out.append(a.kwarg.arg)
    return out


def _has_static_treatment(call: ast.Call) -> bool:
    return any(kw.arg in ("static_argnums", "static_argnames")
               for kw in call.keywords)


@register
class RetraceRisk(Rule):
    id = "DL4J104"
    name = "retrace-risk"
    severity = WARNING
    doc = ("Retrace traps: `jax.jit(f)(...)` immediately invoked (fresh "
           "cache every call), jit created inside a loop body, and "
           "jitted functions closing over a Python scalar parameter of "
           "their builder without static_argnums — each silently "
           "recompiles when the closed-over value changes.")

    def run(self, project: Project) -> Iterable[Finding]:
        for f in project.files:
            if f.tree is None:
                continue
            for node in ast.walk(f.tree):
                if not isinstance(node, ast.Call):
                    continue
                wname = project._wrapper_name(node.func)
                if wname not in ("jit", "pjit") or not node.args:
                    continue
                parent = project.parent(f.path, node)
                # (a) jax.jit(f)(...) — a new cache per invocation
                if isinstance(parent, ast.Call) and parent.func is node:
                    yield self.finding(
                        project, node, f.path,
                        "jax.jit(...) immediately invoked: every call "
                        "builds a fresh jit cache and recompiles — bind "
                        "the jitted function once")
                    continue
                # (b) jit construction inside a loop body
                for anc in project.ancestors(f.path, node):
                    if isinstance(anc, (ast.For, ast.While,
                                        ast.AsyncFor)):
                        yield self.finding(
                            project, node, f.path,
                            "jax.jit(...) created inside a loop: each "
                            "iteration makes a new jitted function and "
                            "recompiles — hoist the jit out of the loop")
                        break
                    if isinstance(anc, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        break
                # (c) closure over a builder parameter w/o static_argnums
                if _has_static_treatment(node):
                    continue
                caller = project.enclosing_function(f.path, node)
                for target in project._fn_arg_targets(
                        node.args[0], caller, f.path):
                    free = _free_loads(target)
                    cur = target.parent
                    seen_params: Set[str] = set()
                    while cur is not None:
                        seen_params |= (free & cur.params) - {"self"}
                        cur = cur.parent
                    for name in sorted(seen_params):
                        yield self.finding(
                            project, node, f.path,
                            f"jitted `{target.name}` closes over "
                            f"enclosing parameter `{name}` without "
                            "static_argnums: a different value silently "
                            "retraces — key the jit cache on it or mark "
                            "it static")


#: numpy constructors whose DEFAULT dtype is float64
_NP_F64_CTORS = {"zeros", "ones", "full", "empty", "arange", "eye",
                 "linspace", "identity"}
_NP_MODS = {"np", "numpy", "onp"}
_F64_TOKENS = {"float64", "double", "f8"}
_DTYPE_LEAF_PREFIXES = ("float", "int", "uint", "bfloat", "bool",
                        "complex", "dtype")


def _is_f64_token(node: ast.AST) -> bool:
    """``np.float64`` / ``jnp.float64`` / ``"float64"`` / bare
    ``float`` used as a dtype (numpy resolves it to float64)."""
    if isinstance(node, ast.Constant):
        return isinstance(node.value, str) and node.value in _F64_TOKENS
    if isinstance(node, ast.Name):
        return node.id == "float"
    chain = _attr_chain(node) or ""
    return chain.split(".")[-1] in _F64_TOKENS


def _passes_dtype(call: ast.Call) -> bool:
    """Whether the constructor call pins a dtype (kwarg, or an obvious
    dtype-looking positional like ``np.zeros((2, 2), np.float32)``)."""
    if any(kw.arg == "dtype" for kw in call.keywords):
        return True
    for a in call.args:
        if isinstance(a, ast.Name) and a.id in ("float", "int", "bool"):
            return True
        chain = _attr_chain(a) or ""
        if chain.split(".")[-1].startswith(_DTYPE_LEAF_PREFIXES):
            return True
    return False


@register
class Fp64PromotionInJit(Rule):
    id = "DL4J106"
    name = "tracer-fp64-promotion"
    severity = WARNING
    doc = ("Implicit fp64 in jit-reachable functions: explicit "
           "float64/double dtype tokens (dtype=np.float64, "
           ".astype('float64'), np.float64(x)) and dtype-less numpy "
           "constructors (np.zeros/ones/full/empty/arange/eye/linspace/"
           "identity default to float64).  Under the default "
           "x64-disabled config the value silently demotes at the next "
           "jnp op; with x64 enabled it silently promotes the whole "
           "step to fp64 — either way the precision tier the conf "
           "selected is not what actually runs.  Pin dtype=np.float32 "
           "or use the jnp constructors (float32 by default).")

    def run(self, project: Project) -> Iterable[Finding]:
        for info in project.jit_reachable():
            for node in _scan_nodes(info):
                if not isinstance(node, ast.Call):
                    continue
                chain = _attr_chain(node.func) or ""
                parts = chain.split(".")
                leaf = parts[-1]
                # (a) dtype-less numpy constructor → float64 default
                if len(parts) == 2 and parts[0] in _NP_MODS \
                        and leaf in _NP_F64_CTORS \
                        and not _passes_dtype(node):
                    yield self.finding(
                        project, node, info.path,
                        f"{chain}() without dtype inside jit-reachable "
                        f"`{info.name}` materializes float64 (numpy's "
                        "default) — pin dtype=np.float32 or use jnp."
                        f"{leaf}")
                    continue
                # (b) explicit float64 scalar/array construction
                if leaf in _F64_TOKENS and len(parts) >= 2:
                    yield self.finding(
                        project, node, info.path,
                        f"{chain}() inside jit-reachable `{info.name}` "
                        "forces fp64 — traced compute should stay in "
                        "the conf-selected precision tier")
                    continue
                # (c) .astype(float64-ish) on anything
                if isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "astype" and node.args \
                        and _is_f64_token(node.args[0]):
                    yield self.finding(
                        project, node, info.path,
                        f".astype(float64) inside jit-reachable "
                        f"`{info.name}` promotes to fp64 — cast to the "
                        "tier dtype (float32/bfloat16) instead")
                    continue
                # (d) explicit dtype=float64 kwarg on any call
                for kw in node.keywords:
                    if kw.arg == "dtype" and _is_f64_token(kw.value):
                        yield self.finding(
                            project, node, info.path,
                            f"dtype=float64 on {chain or leaf}() inside "
                            f"jit-reachable `{info.name}` — traced "
                            "buffers should use the tier dtype")
                        break
