"""CLI: ``python -m deeplearning4j_tpu.analysis [paths...]``.

Exit codes: 0 = no gating findings (everything clean, noqa'd, or
baselined), 1 = gating findings, 2 = usage error.

The default baseline is ``.dl4j-lint-baseline.json`` in the current
directory when it exists; ``--write-baseline`` rewrites it from the
current run's unsuppressed findings (the grandfathering workflow:
fix what you can, noqa what is intentional, baseline the residue,
then the gate holds the line at zero NEW findings).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List

from deeplearning4j_tpu.analysis import core

DEFAULT_BASELINE = ".dl4j-lint-baseline.json"


def _text_report(findings, verbose: bool) -> str:
    lines: List[str] = []
    for f in findings:
        if (f.suppressed or f.baselined) and not verbose:
            continue
        tag = ""
        if f.suppressed:
            tag = " [noqa]"
        elif f.baselined:
            tag = " [baseline]"
        lines.append(f"{f.path}:{f.line}:{f.col}: {f.rule} "
                     f"[{f.severity}]{tag} {f.message}")
    return "\n".join(lines)


def _summary(findings) -> dict:
    gating = [f for f in findings if f.gates()]
    return {
        "total": len(findings),
        "gating": len(gating),
        "suppressed": sum(1 for f in findings if f.suppressed),
        "baselined": sum(1 for f in findings if f.baselined),
        "by_rule": {r: sum(1 for f in findings if f.rule == r)
                    for r in sorted({f.rule for f in findings})},
    }


def main(argv=None) -> int:
    import os

    parser = argparse.ArgumentParser(
        prog="python -m deeplearning4j_tpu.analysis",
        description="dl4j-lint: tracer-safety & concurrency static "
                    "analysis")
    parser.add_argument("paths", nargs="*",
                        default=["deeplearning4j_tpu", "tests"],
                        help="files/directories to lint")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text")
    parser.add_argument("--baseline", default=None,
                        help=f"baseline file (default: {DEFAULT_BASELINE}"
                             " when present)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore any baseline file")
    parser.add_argument("--write-baseline", action="store_true",
                        help="rewrite the baseline from this run's "
                             "unsuppressed findings and exit 0")
    parser.add_argument("--prune-baseline", action="store_true",
                        help="drop baseline fingerprints that no "
                             "longer fire and exit 0 (baseline "
                             "hygiene: stale entries could silently "
                             "mask a future regression)")
    parser.add_argument("--rules", default=None,
                        help="comma-separated rule ids to run "
                             "(default: all)")
    parser.add_argument("--disable", default="",
                        help="comma-separated rule ids to skip")
    parser.add_argument("--docs", default=None,
                        help="observability catalog path (default: "
                             "docs/OBSERVABILITY.md)")
    parser.add_argument("--list-rules", action="store_true")
    parser.add_argument("-v", "--verbose", action="store_true",
                        help="also print noqa'd/baselined findings")
    args = parser.parse_args(argv)

    import deeplearning4j_tpu.analysis.rules  # noqa: F401

    if args.list_rules:
        for rid in sorted(core.RULES):
            r = core.RULES[rid]
            print(f"{rid}  {r.name:<22} [{r.severity}] "
                  f"{' '.join(r.doc.split())}")
        return 0

    baseline_path = args.baseline
    if baseline_path is None and not args.no_baseline \
            and os.path.exists(DEFAULT_BASELINE):
        baseline_path = DEFAULT_BASELINE
    if args.no_baseline:
        baseline_path = None

    rule_ids = ([r.strip() for r in args.rules.split(",") if r.strip()]
                if args.rules else None)
    disabled = [r.strip() for r in args.disable.split(",") if r.strip()]

    # inline core.lint() so the loaded Baseline object (and its
    # usage/staleness bookkeeping) stays in hand
    project = core.build_project(args.paths, docs_path=args.docs)
    findings = core.run_rules(project, rule_ids=rule_ids,
                              disabled=disabled)
    baseline = core.Baseline.load(baseline_path) if baseline_path \
        else None
    core.apply_suppressions(project, findings, baseline)
    stale = baseline.stale_entries() if baseline is not None else []

    if args.prune_baseline:
        if baseline is None:
            print("no baseline file to prune")
            return 0
        dropped = baseline.prune()
        print(f"baseline pruned: {baseline.path} — {dropped} stale "
              f"entr{'y' if dropped == 1 else 'ies'} dropped, "
              f"{len(baseline.entries)} kept")
        return 0

    if args.write_baseline:
        path = args.baseline or DEFAULT_BASELINE
        core.Baseline.write(path, [f for f in findings if f.gates()])
        print(f"baseline written: {path} "
              f"({sum(1 for f in findings if f.gates())} entries)")
        return 0

    if args.format == "json":
        summary = _summary(findings)
        summary["stale_baseline"] = [e.get("fingerprint")
                                     for e in stale]
        print(json.dumps({
            "version": 1,
            "findings": [f.to_dict() for f in findings],
            "summary": summary,
        }, indent=1, sort_keys=True))
    else:
        report = _text_report(findings, args.verbose)
        if report:
            print(report)
        s = _summary(findings)
        print(f"dl4j-lint: {s['total']} finding(s) — {s['gating']} "
              f"gating, {s['suppressed']} noqa'd, {s['baselined']} "
              "baselined")
        for e in stale:
            print(f"warning: stale baseline entry (fires nowhere): "
                  f"{e.get('rule')} {e.get('path')} :: "
                  f"{e.get('symbol')} — run --prune-baseline")
    return 1 if any(f.gates() for f in findings) else 0


if __name__ == "__main__":
    sys.exit(main())
