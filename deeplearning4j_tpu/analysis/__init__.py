"""dl4j-lint: tracer-safety & concurrency static analysis, plus the
runtime sanitizer harness.

Static side (``python -m deeplearning4j_tpu.analysis <paths>``): an
AST-based whole-program linter with codebase-specific rules — host
syncs and impurity inside jit-reachable functions, retrace traps,
blocking-under-lock and a whole-program lock-order graph, and
two-directional drift between registry call sites and the
docs/OBSERVABILITY.md catalog.  Suppression: ``# dl4j: noqa[RULE]``
pragmas and the checked-in ``.dl4j-lint-baseline.json``.

Runtime side (:mod:`deeplearning4j_tpu.analysis.sanitizer`): env-gated
modes (``DL4J_SANITIZE=1``) that arm ``jax.transfer_guard`` around the
jitted/pjit'd train step, ``jax_debug_nans``, rank-promotion checking
and a retrace-budget assertion fed by ``CompileTelemetry`` — through
both fit loops and the serving micro-batcher.

Rule catalog + workflow: docs/ANALYSIS.md.
"""

from deeplearning4j_tpu.analysis.core import (  # noqa: F401
    ERROR, INFO, RULES, WARNING, Baseline, Finding, Project, Rule,
    apply_suppressions, build_project, lint, register, run_rules)
