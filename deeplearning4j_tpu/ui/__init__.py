"""Training visualization stack (SURVEY.md §2.2 StatsStorage, §5
metrics/observability): StatsListener → StatsStorage (pub/sub) →
UIServer web dashboard — the reference's deeplearning4j-ui-parent tier
(Play server + SBE wire format + MapDB/SQLite storage) rebuilt on
stdlib HTTP + JSON + sqlite3."""

from deeplearning4j_tpu.ui.stats_storage import (
    FileStatsStorage, InMemoryStatsStorage, RemoteUIStatsStorageRouter,
    SqliteStatsStorage, StatsStorage, StatsStorageEvent, StatsStorageRouter)
from deeplearning4j_tpu.ui.stats_listener import StatsListener, StatsReport
from deeplearning4j_tpu.ui.activations import (
    ActivationsListener, post_word_vector_tsne)
from deeplearning4j_tpu.ui.ui_server import UIServer

__all__ = [
    "ActivationsListener", "FileStatsStorage", "InMemoryStatsStorage",
    "RemoteUIStatsStorageRouter", "SqliteStatsStorage", "StatsStorage",
    "StatsStorageEvent", "StatsStorageRouter", "StatsListener",
    "StatsReport", "UIServer", "post_word_vector_tsne",
]
