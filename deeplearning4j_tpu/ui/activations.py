"""Convolutional-activation capture for the training UI
(ref: deeplearning4j-ui-parent ConvolutionalIterationListener +
ui/module/convolutional/ConvolutionalListenerModule.java — the reference
renders per-layer feature-map image grids at /activations; here the
listener posts downsampled float grids through the stats-storage bus and
the dashboard draws them as SVG heatmaps, no image encoder needed)."""

from __future__ import annotations

import time
import uuid
from typing import List, Optional

import numpy as np

from deeplearning4j_tpu.nn.listeners import IterationListener
from deeplearning4j_tpu.ui.stats_storage import StatsStorageRouter

TYPE_ID = "ActivationsListener"

_MAX_GRID = 16      # downsample feature maps to at most 16x16
_MAX_CHANNELS = 8   # first channels per conv layer
_MAX_UNITS = 64     # first units for dense/recurrent layers


def _downsample(a: np.ndarray, target: int = _MAX_GRID) -> np.ndarray:
    """Box-mean downsample a 2-D map to <= target per side."""
    h, w = a.shape
    fh, fw = max(1, h // target), max(1, w // target)
    th, tw = h // fh * fh, w // fw * fw
    a = a[:th, :tw].reshape(th // fh, fh, tw // fw, fw).mean(axis=(1, 3))
    return a


def _layer_record(name: str, act: np.ndarray) -> Optional[dict]:
    """One layer's activation summary: conv [N,C,H,W] → channel grids;
    dense [N,F] → unit bar; recurrent [N,T,F] → time×feature grid."""
    a = np.asarray(act, np.float32)
    if a.ndim == 4:            # [N, C, H, W] — first example
        grids = [_downsample(a[0, c]).tolist()
                 for c in range(min(a.shape[1], _MAX_CHANNELS))]
        return {"name": name, "kind": "conv", "grids": grids}
    if a.ndim == 3:            # [N, T, F]
        return {"name": name, "kind": "recurrent",
                "grids": [_downsample(a[0]).tolist()]}
    if a.ndim == 2:            # [N, F]
        return {"name": name, "kind": "dense",
                "values": a[0, :_MAX_UNITS].tolist()}
    return None


def post_word_vector_tsne(base_url: str, vectors, session_id: str,
                          words: Optional[List[str]] = None,
                          max_words: int = 200, perplexity: float = 10.0,
                          n_iter: int = 250, seed: int = 0) -> int:
    """Fit 2-D t-SNE over word vectors and upload to the UI's /tsne
    endpoint (ref: TsneModule upload + word2vec UI hookup).  Returns the
    number of words posted."""
    import json
    import urllib.request

    from deeplearning4j_tpu.plot.tsne import BarnesHutTsne

    if words is None:
        words = sorted(vectors.vocab.words())[:max_words]
    else:
        words = list(words)[:max_words]
    mat = np.stack([np.asarray(vectors.word_vector(w)) for w in words])
    coords = np.asarray(BarnesHutTsne(
        n_components=2, perplexity=min(perplexity, max(2, len(words) // 4)),
        n_iter=n_iter, seed=seed).fit_transform(mat))
    body = json.dumps({"session_id": session_id, "words": words,
                       "coords": coords.tolist()}).encode()
    req = urllib.request.Request(base_url.rstrip("/") + "/tsne", data=body,
                                 headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as r:
        return json.loads(r.read())["n"]


class ActivationsListener(IterationListener):
    """Every ``frequency`` iterations, run the probe batch through the
    model's feed_forward and post per-layer activation grids."""

    def __init__(self, router: StatsStorageRouter, probe_x,
                 frequency: int = 10, session_id: Optional[str] = None,
                 worker_id: Optional[str] = None):
        self.router = router
        self.probe_x = np.asarray(probe_x)[:1]   # one example is plenty
        self.frequency = max(1, frequency)
        self.session_id = session_id or uuid.uuid4().hex[:12]
        self.worker_id = worker_id or "activations-0"

    def iteration_done(self, model, iteration):
        if iteration % self.frequency:
            return
        layers: List[dict] = []
        acts = model.feed_forward(self.probe_x)
        if isinstance(acts, dict):        # ComputationGraph: name → act
            items = acts.items()
        else:                             # MultiLayerNetwork: list
            items = ((f"layer{i} ({type(l).__name__})", a)
                     for i, (l, a) in enumerate(zip(model.layers, acts)))
        for name, a in items:
            rec = _layer_record(str(name), np.asarray(a))
            if rec is not None:
                layers.append(rec)
        self.router.put_update({
            "session_id": self.session_id,
            "type_id": TYPE_ID,
            "worker_id": self.worker_id,
            "timestamp": int(time.time() * 1000),
            "iteration": iteration,
            "layers": layers,
        })
