"""StatsStorage — pub/sub persistence for training stats
(ref: deeplearning4j-core/.../api/storage/StatsStorage.java:30,
StatsStorageRouter.java, StatsStorageListener.java;
impls: deeplearning4j-ui-model/.../ui/storage/InMemoryStatsStorage.java,
FileStatsStorage.java, mapdb/MapDBStatsStorage.java, sqlite
J7FileStatsStorage; remote: deeplearning4j-core/.../impl/
RemoteUIStatsStorageRouter.java).

Records are keyed (session_id, type_id, worker_id, timestamp) exactly as
the reference keys its Persistables; static infos are keyed without the
timestamp.  The SBE wire encoding is replaced by JSON — the schema, not
the byte layout, is the capability."""

from __future__ import annotations

import dataclasses
import json
import sqlite3
import threading
import urllib.request
from typing import Callable, Dict, List, Optional, Tuple


@dataclasses.dataclass
class StatsStorageEvent:
    """(ref: api/storage/StatsStorageEvent.java; event types in
    StatsStorageListener.EventType)"""

    event_type: str  # NewSessionID | NewTypeID | NewWorkerID | PostStaticInfo | PostUpdate
    session_id: str
    type_id: str
    worker_id: str
    timestamp: int


class StatsStorageRouter:
    """Write side (ref: api/storage/StatsStorageRouter.java)."""

    def put_static_info(self, record: dict) -> None:
        raise NotImplementedError

    def put_update(self, record: dict) -> None:
        raise NotImplementedError


class StatsStorage(StatsStorageRouter):
    """Read+write+listen (ref: api/storage/StatsStorage.java)."""

    # -- read side ----------------------------------------------------------
    def list_session_ids(self) -> List[str]:
        raise NotImplementedError

    def list_type_ids_for_session(self, session_id: str) -> List[str]:
        raise NotImplementedError

    def list_worker_ids_for_session(self, session_id: str) -> List[str]:
        raise NotImplementedError

    def get_static_info(self, session_id: str, type_id: str,
                        worker_id: str) -> Optional[dict]:
        raise NotImplementedError

    def get_all_updates_after(self, session_id: str, type_id: str,
                              worker_id: str, timestamp: int) -> List[dict]:
        raise NotImplementedError

    def get_latest_update(self, session_id: str, type_id: str,
                          worker_id: str) -> Optional[dict]:
        updates = self.get_all_updates_after(session_id, type_id, worker_id, -1)
        return updates[-1] if updates else None

    # -- listeners ----------------------------------------------------------
    def __init__(self):
        self._listeners: List[Callable[[StatsStorageEvent], None]] = []
        self._lock = threading.Lock()

    def register_stats_storage_listener(self, fn) -> None:
        self._listeners.append(fn)

    def deregister_stats_storage_listener(self, fn) -> None:
        self._listeners.remove(fn)

    def _notify(self, *events: StatsStorageEvent) -> None:
        for fn in list(self._listeners):
            for e in events:
                fn(e)

    def _events_for(self, record: dict, kind: str,
                    is_new: Tuple[bool, bool, bool]) -> List[StatsStorageEvent]:
        sid, tid, wid = (record["session_id"], record["type_id"],
                         record["worker_id"])
        ts = record.get("timestamp", 0)
        ev = []
        if is_new[0]:
            ev.append(StatsStorageEvent("NewSessionID", sid, tid, wid, ts))
        if is_new[1]:
            ev.append(StatsStorageEvent("NewTypeID", sid, tid, wid, ts))
        if is_new[2]:
            ev.append(StatsStorageEvent("NewWorkerID", sid, tid, wid, ts))
        ev.append(StatsStorageEvent(kind, sid, tid, wid, ts))
        return ev


class InMemoryStatsStorage(StatsStorage):
    """(ref: ui/storage/InMemoryStatsStorage.java)"""

    def __init__(self):
        super().__init__()
        self._static: Dict[Tuple[str, str, str], dict] = {}
        self._updates: Dict[Tuple[str, str, str], List[dict]] = {}

    def _newness(self, sid, tid, wid):
        keys = list(self._static) + list(self._updates)
        return (all(k[0] != sid for k in keys),
                all(k[:2] != (sid, tid) for k in keys),
                all(k != (sid, tid, wid) for k in keys))

    def put_static_info(self, record: dict) -> None:
        key = (record["session_id"], record["type_id"], record["worker_id"])
        with self._lock:
            new = self._newness(*key)
            self._static[key] = record
        self._notify(*self._events_for(record, "PostStaticInfo", new))

    def put_update(self, record: dict) -> None:
        key = (record["session_id"], record["type_id"], record["worker_id"])
        with self._lock:
            new = self._newness(*key)
            self._updates.setdefault(key, []).append(record)
        self._notify(*self._events_for(record, "PostUpdate", new))

    def list_session_ids(self):
        return sorted({k[0] for k in list(self._static) + list(self._updates)})

    def list_type_ids_for_session(self, session_id):
        return sorted({k[1] for k in list(self._static) + list(self._updates)
                       if k[0] == session_id})

    def list_worker_ids_for_session(self, session_id):
        return sorted({k[2] for k in list(self._static) + list(self._updates)
                       if k[0] == session_id})

    def get_static_info(self, session_id, type_id, worker_id):
        return self._static.get((session_id, type_id, worker_id))

    def get_all_updates_after(self, session_id, type_id, worker_id, timestamp):
        ups = self._updates.get((session_id, type_id, worker_id), [])
        return [u for u in ups if u.get("timestamp", 0) > timestamp]


class SqliteStatsStorage(StatsStorage):
    """Persistent storage on sqlite3 — the role of both
    MapDBStatsStorage and the reference's J7 SQLite backend
    (ref: ui/storage/mapdb/MapDBStatsStorage.java)."""

    def __init__(self, path: str):
        super().__init__()
        self.path = path
        self._conn = sqlite3.connect(path, check_same_thread=False)
        with self._lock:
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS static_info ("
                "session_id TEXT, type_id TEXT, worker_id TEXT, "
                "record TEXT, PRIMARY KEY (session_id, type_id, worker_id))")
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS updates ("
                "session_id TEXT, type_id TEXT, worker_id TEXT, "
                "timestamp INTEGER, record TEXT)")
            self._conn.commit()

    def _newness(self, sid, tid, wid):
        cur = self._conn.execute(
            "SELECT "
            "EXISTS(SELECT 1 FROM updates WHERE session_id=? UNION "
            "       SELECT 1 FROM static_info WHERE session_id=?),"
            "EXISTS(SELECT 1 FROM updates WHERE session_id=? AND type_id=? "
            "UNION SELECT 1 FROM static_info WHERE session_id=? AND type_id=?),"
            "EXISTS(SELECT 1 FROM updates WHERE session_id=? AND type_id=? "
            "AND worker_id=? UNION SELECT 1 FROM static_info WHERE "
            "session_id=? AND type_id=? AND worker_id=?)",
            (sid, sid, sid, tid, sid, tid, sid, tid, wid, sid, tid, wid))
        a, b, c = cur.fetchone()
        return (not a, not b, not c)

    def put_static_info(self, record: dict) -> None:
        key = (record["session_id"], record["type_id"], record["worker_id"])
        with self._lock:
            new = self._newness(*key)
            self._conn.execute(
                "INSERT OR REPLACE INTO static_info VALUES (?,?,?,?)",
                (*key, json.dumps(record)))
            self._conn.commit()
        self._notify(*self._events_for(record, "PostStaticInfo", new))

    def put_update(self, record: dict) -> None:
        key = (record["session_id"], record["type_id"], record["worker_id"])
        with self._lock:
            new = self._newness(*key)
            self._conn.execute(
                "INSERT INTO updates VALUES (?,?,?,?,?)",
                (*key, record.get("timestamp", 0), json.dumps(record)))
            self._conn.commit()
        self._notify(*self._events_for(record, "PostUpdate", new))

    def list_session_ids(self):
        cur = self._conn.execute(
            "SELECT DISTINCT session_id FROM updates UNION "
            "SELECT DISTINCT session_id FROM static_info")
        return sorted(r[0] for r in cur.fetchall())

    def list_type_ids_for_session(self, session_id):
        cur = self._conn.execute(
            "SELECT DISTINCT type_id FROM updates WHERE session_id=? UNION "
            "SELECT DISTINCT type_id FROM static_info WHERE session_id=?",
            (session_id, session_id))
        return sorted(r[0] for r in cur.fetchall())

    def list_worker_ids_for_session(self, session_id):
        cur = self._conn.execute(
            "SELECT DISTINCT worker_id FROM updates WHERE session_id=? UNION "
            "SELECT DISTINCT worker_id FROM static_info WHERE session_id=?",
            (session_id, session_id))
        return sorted(r[0] for r in cur.fetchall())

    def get_static_info(self, session_id, type_id, worker_id):
        cur = self._conn.execute(
            "SELECT record FROM static_info WHERE session_id=? AND type_id=? "
            "AND worker_id=?", (session_id, type_id, worker_id))
        row = cur.fetchone()
        return json.loads(row[0]) if row else None

    def get_all_updates_after(self, session_id, type_id, worker_id, timestamp):
        cur = self._conn.execute(
            "SELECT record FROM updates WHERE session_id=? AND type_id=? AND "
            "worker_id=? AND timestamp>? ORDER BY timestamp",
            (session_id, type_id, worker_id, timestamp))
        return [json.loads(r[0]) for r in cur.fetchall()]

    def close(self):
        self._conn.close()


# FileStatsStorage: same persistent contract, single-file — alias the
# sqlite implementation (ref: ui/storage/FileStatsStorage.java).
FileStatsStorage = SqliteStatsStorage


class RemoteUIStatsStorageRouter(StatsStorageRouter):
    """POSTs records to a remote UIServer
    (ref: deeplearning4j-core/.../impl/RemoteUIStatsStorageRouter.java —
    async HTTP posting with retry; endpoint served by UIServer's
    /remoteReceive)."""

    def __init__(self, address: str, retry_count: int = 3):
        self.address = address.rstrip("/")
        self.retry_count = retry_count

    def _post(self, kind: str, record: dict) -> None:
        payload = json.dumps({"kind": kind, "record": record}).encode()
        last = None
        for _ in range(self.retry_count):
            try:
                req = urllib.request.Request(
                    self.address + "/remoteReceive", data=payload,
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=10):
                    return
            except Exception as e:
                last = e
        raise ConnectionError(f"remote UI post failed: {last}")

    def put_static_info(self, record: dict) -> None:
        self._post("static", record)

    def put_update(self, record: dict) -> None:
        self._post("update", record)
