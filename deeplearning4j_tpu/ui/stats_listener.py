"""StatsListener — per-iteration training telemetry
(ref: deeplearning4j-ui-model/.../ui/stats/BaseStatsListener.java:44,297
— captures score, param/gradient/update histograms & summary stats,
memory, GC, timing; static info: model conf, hardware/software).

The reference walks the flat param view per layer; here the params
pytree is walked per layer/param name — same report schema, pytree
edition.  Reports post to any StatsStorageRouter (local storage or the
remote HTTP router)."""

from __future__ import annotations

import dataclasses
import os
import time
import uuid
from typing import Dict, List, Optional

import numpy as np

from deeplearning4j_tpu import monitor
from deeplearning4j_tpu.nn.listeners import IterationListener
from deeplearning4j_tpu.ui.stats_storage import StatsStorageRouter

TYPE_ID = "StatsListener"  # (ref: BaseStatsListener.TYPE_ID)


def _summary(arr: np.ndarray, bins: int = 20) -> dict:
    a = np.asarray(arr, np.float64).reshape(-1)
    if a.size == 0:
        return {}
    hist, edges = np.histogram(a, bins=bins)
    return {
        "mean": float(a.mean()),
        "stdev": float(a.std()),
        "min": float(a.min()),
        "max": float(a.max()),
        "mean_magnitude": float(np.abs(a).mean()),
        "histogram": {"counts": hist.tolist(),
                      "min": float(edges[0]), "max": float(edges[-1])},
    }


@dataclasses.dataclass
class StatsReport:
    """One iteration's record (ref: ui/stats/impl/SbeStatsReport.java —
    JSON instead of SBE)."""

    session_id: str
    worker_id: str
    timestamp: int
    iteration: int
    score: float
    params: Dict[str, dict]
    gradients: Dict[str, dict]
    updates: Dict[str, dict]
    perf: dict
    memory: dict

    def to_record(self) -> dict:
        d = dataclasses.asdict(self)
        d["type_id"] = TYPE_ID
        return d


class StatsListener(IterationListener):
    """(ref: ui/stats/StatsListener.java + BaseStatsListener.java)

    update_frequency: post every N iterations.  Histograms of parameters
    and parameter *updates* (deltas between posts) are collected when
    collect_histograms; gradients are approximated by updates at the
    engine level (the jitted step applies updates in-place — the
    reference's separate gradient capture corresponds to the pre-LR
    update view)."""

    def __init__(self, router: StatsStorageRouter, update_frequency: int = 1,
                 session_id: Optional[str] = None,
                 worker_id: Optional[str] = None,
                 collect_histograms: bool = True):
        self.router = router
        self.update_frequency = max(1, update_frequency)
        self.session_id = session_id or uuid.uuid4().hex[:12]
        self.worker_id = worker_id or f"pid-{os.getpid()}"
        self.collect_histograms = collect_histograms
        self._last_params: Optional[List[dict]] = None
        self._last_time: Optional[float] = None
        self._static_posted = False

    # -- static info (ref: BaseStatsListener initial report) ---------------
    def _post_static(self, model) -> None:
        import jax
        record = {
            "session_id": self.session_id,
            "type_id": TYPE_ID,
            "worker_id": self.worker_id,
            "timestamp": int(time.time() * 1000),
            "model_class": type(model).__name__,
            "model_config": model.conf.to_json(),
            "n_params": int(model.num_params()),
            "backend": jax.default_backend(),
            "devices": [str(d) for d in jax.devices()],
        }
        self.router.put_static_info(record)
        self._static_posted = True

    def _param_tree(self, model) -> Dict[str, np.ndarray]:
        out = {}
        tree = model.net_params
        if isinstance(tree, dict):  # ComputationGraph: name → params
            items = tree.items()
        else:  # MultiLayerNetwork: list of per-layer dicts
            items = ((str(i), p) for i, p in enumerate(tree))
        for name, p in items:
            if not p:
                continue
            for k, v in p.items():
                out[f"{name}_{k}"] = np.asarray(v)
        return out

    def _perf_from_registry(self, model, now: float, iteration: int) -> dict:
        """Per-iteration perf sourced from the registry gauges the fit
        loop sets (``dl4j_fit_last_step_ms`` / ``_examples_per_sec``);
        falls back to inter-post wall timing when the model is driven by
        a loop that doesn't meter (custom training loops)."""
        reg = monitor.get_registry()

        def gauge(name):
            fam = reg.get(name)
            if fam is None:
                return None
            try:
                return fam.value
            except ValueError:
                return None

        step_ms = gauge("dl4j_fit_last_step_ms")
        if step_ms:
            return {
                "duration_ms": step_ms,
                "samples_per_sec": gauge("dl4j_fit_examples_per_sec") or 0.0,
                "batches_per_sec": 1e3 / step_ms,
                "total_minibatches": iteration,
            }
        dt = (now - self._last_time) if self._last_time else 0.0
        batch = getattr(model, "last_batch_size", 0)
        return {
            "duration_ms": dt * 1000.0,
            "samples_per_sec": batch / dt if dt > 0 else 0.0,
            "batches_per_sec": 1.0 / dt if dt > 0 else 0.0,
            "total_minibatches": iteration,
        }

    def iteration_done(self, model, iteration: int) -> None:
        if not self._static_posted:
            self._post_static(model)
        now = time.perf_counter()
        if iteration % self.update_frequency == 0:
            # device→host param snapshot only on posting iterations;
            # 'updates' are deltas between consecutive POSTS
            cur = self._param_tree(model) if self.collect_histograms else {}
            params = {k: _summary(v) for k, v in cur.items()}
            updates, grads = {}, {}
            if self._last_params is not None:
                for k, v in cur.items():
                    if k in self._last_params:
                        delta = v - self._last_params[k]
                        s = _summary(delta)
                        updates[k] = s
                        grads[k] = s  # post-LR update ≈ scaled gradient
            # perf/memory come from the monitor registry — the SAME
            # numbers a /metrics scrape reports (the fit loop's phase
            # spans set the gauges, monitor/system.py owns the memory
            # capture), instead of re-measuring with resource/time
            # inline and drifting from the exposition endpoint
            memory = monitor.memory_snapshot()
            perf = self._perf_from_registry(model, now, iteration)
            report = StatsReport(
                session_id=self.session_id, worker_id=self.worker_id,
                timestamp=int(time.time() * 1000), iteration=iteration,
                score=float(model.score()),
                params=params, gradients=grads, updates=updates,
                perf=perf, memory=memory)
            self.router.put_update(report.to_record())
            # the UI post joins the event timeline: a dashboard gap can
            # be correlated against the fit/serve events around it (the
            # listener's session_id is the UI-side correlation key)
            monitor.events.emit("ui.stats_posted",
                                ui_session=self.session_id,
                                iteration=iteration)
            self._last_params = cur if self.collect_histograms else None
        self._last_time = now
