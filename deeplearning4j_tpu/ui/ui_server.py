"""UIServer — the training dashboard
(ref: deeplearning4j-ui-parent/deeplearning4j-play/.../ui/play/
PlayUIServer.java:53 (port 9000 :60), module pages
ui/module/train/TrainModule.java:53 — overview / model / system tabs;
remote posting endpoint consumed by RemoteUIStatsStorageRouter).

Play framework + SBE is replaced by stdlib http.server + JSON; the
dashboard is one self-contained HTML page (inline SVG charts, no
external assets — the environment has zero egress and so must the
browser).  Endpoints:

  GET  /                       dashboard HTML
  GET  /train/sessions         {"sessions": [...]}
  GET  /train/overview?sid=    score vs iteration + perf + memory
  GET  /train/model?sid=       per-layer param/update summary stats
  GET  /train/histograms?sid=  per-param parameter/update histograms
                               (ref: TrainModule histogram pages)
  GET  /train/graph?sid=       model topology for the graph view
                               (ref: TrainModule layer-flow page)
  GET  /train/flow?sid=        DAG + per-layer stats + performance
                               state (ref: FlowListenerModule,
                               flow/FlowIterationListener.java)
  GET  /train/system?sid=      static info + memory timeline
  GET  /train/activations?sid= latest conv/dense activation grids
                               (ref: ConvolutionalListenerModule)
  GET  /train/tsne?sid=        posted t-SNE word coordinates
  POST /tsne                   upload t-SNE coords (ref: TsneModule)
  POST /remoteReceive          remote stats ingestion
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional
from urllib.parse import parse_qs, urlparse

from deeplearning4j_tpu.ui.stats_listener import TYPE_ID
from deeplearning4j_tpu.ui.stats_storage import (
    InMemoryStatsStorage, StatsStorage)

_DASHBOARD_HTML = """<!DOCTYPE html><html><head><meta charset="utf-8">
<title>DL4J-TPU Training UI</title><style>
body{font-family:sans-serif;margin:0;background:#fafafa}
header{background:#2c3e50;color:#fff;padding:10px 20px}
nav button{margin-right:8px;padding:6px 14px;border:0;background:#3b5168;
color:#fff;cursor:pointer}nav button.active{background:#1abc9c}
main{padding:20px}.card{background:#fff;border:1px solid #ddd;
border-radius:4px;padding:14px;margin-bottom:16px}
h3{margin-top:0}svg{width:100%;height:220px}
table{border-collapse:collapse;font-size:13px}
td,th{border:1px solid #ddd;padding:4px 8px;text-align:right}
th:first-child,td:first-child{text-align:left}
</style></head><body>
<header><b>deeplearning4j_tpu</b> — <span data-i18n="train.pagetitle">Training UI</span>
<select id="session"></select>
<select id="lang" style="float:right"></select></header>
<nav style="padding:8px 20px;background:#34495e">
<button data-tab="overview" data-i18n="train.nav.overview" class="active">Overview</button>
<button data-tab="model" data-i18n="train.nav.model">Model</button>
<button data-tab="histograms" data-i18n="train.nav.histograms">Histograms</button>
<button data-tab="graph" data-i18n="train.nav.graph">Graph</button>
<button data-tab="flow" data-i18n="train.nav.flow">Flow</button>
<button data-tab="activations" data-i18n="train.nav.activations">Activations</button>
<button data-tab="tsne" data-i18n="train.nav.tsne">t-SNE</button>
<button data-tab="system" data-i18n="train.nav.system">System</button></nav>
<main id="main"></main>
<script>
let tab='overview', sid=null;
function esc(s){const d=document.createElement('div');
 d.textContent=String(s);return d.innerHTML;}
function line(points,color){if(!points.length)return '';
 const xs=points.map(p=>p[0]),ys=points.map(p=>p[1]);
 const x0=Math.min(...xs),x1=Math.max(...xs)||1;
 const y0=Math.min(...ys),y1=Math.max(...ys)||1;
 const W=800,H=200,pad=30;
 const px=x=>pad+(x-x0)/(x1-x0||1)*(W-2*pad);
 const py=y=>H-pad-(y-y0)/(y1-y0||1)*(H-2*pad);
 const d=points.map((p,i)=>(i?'L':'M')+px(p[0]).toFixed(1)+','+py(p[1]).toFixed(1)).join(' ');
 return `<svg viewBox="0 0 ${W} ${H}"><path d="${d}" fill="none" stroke="${color}" stroke-width="2"/>
 <text x="${pad}" y="12" font-size="11">max ${y1.toPrecision(4)}</text>
 <text x="${pad}" y="${H-8}" font-size="11">min ${y0.toPrecision(4)}</text></svg>`;}
function bars(counts,color){if(!counts||!counts.length)return '';
 const W=800,H=140,pad=8,n=counts.length,mx=Math.max(...counts)||1;
 const bw=(W-2*pad)/n;
 const r=counts.map((c,i)=>`<rect x="${(pad+i*bw).toFixed(1)}"
  y="${(H-pad-(c/mx)*(H-2*pad)).toFixed(1)}" width="${(bw*0.9).toFixed(1)}"
  height="${((c/mx)*(H-2*pad)).toFixed(1)}" fill="${color}"/>`).join('');
 return `<svg viewBox="0 0 ${W} ${H}" style="height:140px">${r}</svg>`;}
function heat(grid){if(!grid||!grid.length)return '';
 const rows=grid.length,cols=grid[0].length,cell=Math.min(12,192/rows);
 let lo=Infinity,hi=-Infinity;
 for(const r of grid)for(const v of r){if(v<lo)lo=v;if(v>hi)hi=v;}
 const span=hi-lo||1;
 let rects='';
 grid.forEach((row,i)=>row.forEach((v,jj)=>{
  const t=(v-lo)/span, c=Math.round(255*t);
  rects+=`<rect x="${jj*cell}" y="${i*cell}" width="${cell}" height="${cell}"
   fill="rgb(${c},${Math.round(64+96*t)},${255-c})"/>`;}));
 return `<svg viewBox="0 0 ${cols*cell} ${rows*cell}"
  style="width:${cols*cell*2}px;height:${rows*cell*2}px">${rects}</svg>`;}
async function j(u){return (await fetch(u)).json();}
async function render(){
 const m=document.getElementById('main');
 if(!sid){m.innerHTML='<p>no sessions yet</p>';return;}
 if(tab=='overview'){const d=await j('/train/overview?sid='+sid);
  m.innerHTML=`<div class="card"><h3>Score vs iteration</h3>${line(d.score,'#e74c3c')}</div>
  <div class="card"><h3>Samples/sec</h3>${line(d.samples_per_sec,'#2980b9')}</div>`;}
 else if(tab=='model'){const d=await j('/train/model?sid='+sid);
  let rows=d.layers.map(l=>`<tr><td>${esc(l.name)}</td><td>${l.mean?.toPrecision(4)??''}</td>
  <td>${l.stdev?.toPrecision(4)??''}</td><td>${l.mean_magnitude?.toPrecision(4)??''}</td>
  <td>${l.update_magnitude?.toPrecision(4)??''}</td></tr>`).join('');
  m.innerHTML=`<div class="card"><h3>Parameters (latest)</h3>
  <table><tr><th>param</th><th>mean</th><th>stdev</th><th>|mean|</th><th>|update|</th></tr>${rows}</table></div>`;}
 else if(tab=='histograms'){const d=await j('/train/histograms?sid='+sid);
  if(!d.params.length&&!d.updates.length){m.innerHTML='<p>no histogram data</p>';}
  else{const card=(h,color)=>`<div class="card"><h3>${esc(h.name)}
   <small>[${h.min.toPrecision(3)}, ${h.max.toPrecision(3)}]</small></h3>${bars(h.counts,color)}</div>`;
  m.innerHTML=`<h2>Parameter histograms (iter ${d.iteration??'-'})</h2>`
   +d.params.map(h=>card(h,'#2980b9')).join('')
   +`<h2>Update histograms</h2>`+d.updates.map(h=>card(h,'#e67e22')).join('');}}
 else if(tab=='graph'){const d=await j('/train/graph?sid='+sid);
  const W=860,rh=46,H=Math.max(120,d.nodes.length*rh+40);
  const pos={};d.nodes.forEach((n,i)=>pos[n.name]=[W/2,30+i*rh]);
  const lines=d.edges.filter(e=>pos[e[0]]&&pos[e[1]]).map(e=>{
   const a=pos[e[0]],b=pos[e[1]];
   return `<line x1="${a[0]}" y1="${a[1]+12}" x2="${b[0]}" y2="${b[1]-14}"
    stroke="#95a5a6" stroke-width="1.5" marker-end="url(#arr)"/>`;}).join('');
  const boxes=d.nodes.map(n=>{const p=pos[n.name];
   return `<rect x="${p[0]-130}" y="${p[1]-14}" width="260" height="28" rx="5"
    fill="#eaf2f8" stroke="#2980b9"/><text x="${p[0]}" y="${p[1]+4}"
    text-anchor="middle" font-size="12">${esc(n.name)} · ${esc(n.type)}</text>`;}).join('');
  m.innerHTML=`<div class="card"><h3>Model graph</h3>
   <svg viewBox="0 0 ${W} ${H}" style="height:${H}px">
   <defs><marker id="arr" markerWidth="8" markerHeight="8" refX="7" refY="4"
    orient="auto"><path d="M0,0 L8,4 L0,8 z" fill="#95a5a6"/></marker></defs>
   ${lines}${boxes}</svg></div>`;}
 else if(tab=='flow'){const d=await j('/train/flow?sid='+sid);
  const p=d.performance||{};
  const fmt=v=>v==null?'—':(typeof v=='number'?v.toPrecision(4):v);
  const strip=`<div class="card"><h3>Performance</h3><table><tr>
   <th>iteration</th><th>score</th><th>samples/sec</th><th>iter ms</th><th>RSS MB</th></tr>
   <tr><td>${fmt(p.iteration)}</td><td>${fmt(p.score)}</td>
   <td>${fmt(p.samples_per_sec)}</td><td>${fmt(p.duration_ms)}</td>
   <td>${fmt(p.memory_mb)}</td></tr></table>
   ${line(p.score_history||[],'#e74c3c')}</div>`;
  const W=860,rh=56,H=Math.max(120,d.nodes.length*rh+40);
  const pos={};d.nodes.forEach((n,i)=>pos[n.name]=[W/2,30+i*rh]);
  const mags=d.nodes.map(n=>n.update_mean_magnitude||0);
  const mx=Math.max(...mags,1e-12);
  const lines2=d.edges.filter(e=>pos[e[0]]&&pos[e[1]]).map(e=>{
   const a=pos[e[0]],b=pos[e[1]];
   return `<line x1="${a[0]}" y1="${a[1]+18}" x2="${b[0]}" y2="${b[1]-20}"
    stroke="#95a5a6" stroke-width="1.5" marker-end="url(#arr2)"/>`;}).join('');
  const boxes=d.nodes.map(n=>{const pp=pos[n.name];
   const t=(n.update_mean_magnitude||0)/mx;
   const fill=`rgb(${Math.round(234-100*t)},${Math.round(242-60*t)},248)`;
   const stats=(n.param_mean_magnitude!=null)
    ?`|w| ${n.param_mean_magnitude.toPrecision(3)}`
      +(n.update_mean_magnitude!=null?` · |Δw| ${n.update_mean_magnitude.toPrecision(3)}`:'')
    :'';
   return `<rect x="${pp[0]-160}" y="${pp[1]-18}" width="320" height="38" rx="5"
    fill="${fill}" stroke="#2980b9"/><text x="${pp[0]}" y="${pp[1]-2}"
    text-anchor="middle" font-size="12">${esc(n.name)} · ${esc(n.type)}</text>
    <text x="${pp[0]}" y="${pp[1]+13}" text-anchor="middle" font-size="10"
    fill="#555">${esc(stats)}</text>`;}).join('');
  m.innerHTML=strip+`<div class="card"><h3>Flow — per-layer state
   (shade = latest update magnitude)</h3>
   <svg viewBox="0 0 ${W} ${H}" style="height:${H}px">
   <defs><marker id="arr2" markerWidth="8" markerHeight="8" refX="7" refY="4"
    orient="auto"><path d="M0,0 L8,4 L0,8 z" fill="#95a5a6"/></marker></defs>
   ${lines2}${boxes}</svg></div>`;}
 else if(tab=='activations'){const d=await j('/train/activations?sid='+sid);
  if(!d.layers.length){m.innerHTML='<p>no activation captures — attach an ActivationsListener</p>';}
  else{m.innerHTML=`<h2>Activations (iter ${d.iteration})</h2>`+
   d.layers.map(l=>{
    if(l.kind=='dense')return `<div class="card"><h3>${esc(l.name)}</h3>${bars(l.values,'#16a085')}</div>`;
    return `<div class="card"><h3>${esc(l.name)}</h3>`+
      (l.grids||[]).map(g=>heat(g)).join(' ')+`</div>`;}).join('');}}
 else if(tab=='tsne'){const d=await j('/train/tsne?sid='+sid);
  if(!d.words.length){m.innerHTML='<p>no t-SNE upload yet — POST /tsne</p>';}
  else{const xs=d.coords.map(c=>c[0]),ys=d.coords.map(c=>c[1]);
  const x0=Math.min(...xs),x1=Math.max(...xs)||1,y0=Math.min(...ys),y1=Math.max(...ys)||1;
  const W=860,H=560,pad=40;
  const px=x=>pad+(x-x0)/(x1-x0||1)*(W-2*pad), py=y=>H-pad-(y-y0)/(y1-y0||1)*(H-2*pad);
  const pts=d.words.map((w,i)=>`<circle cx="${px(xs[i]).toFixed(1)}" cy="${py(ys[i]).toFixed(1)}"
   r="3" fill="#c0392b"/><text x="${(px(xs[i])+5).toFixed(1)}" y="${(py(ys[i])+3).toFixed(1)}"
   font-size="10">${esc(w)}</text>`).join('');
  m.innerHTML=`<div class="card"><h3>t-SNE word map (${d.words.length} words)</h3>
   <svg viewBox="0 0 ${W} ${H}" style="height:${H}px">${pts}</svg></div>`;}}
 else{const d=await j('/train/system?sid='+sid);
  m.innerHTML=`<div class="card"><h3>Host RSS (MB)</h3>${line(d.memory,'#8e44ad')}</div>
  <div class="card"><h3>Static info</h3><pre>${esc(JSON.stringify(d.static,null,2))}</pre></div>`;}
}
async function refreshSessions(){const d=await j('/train/sessions');
 const sel=document.getElementById('session');
 if(d.sessions.length&&sel.options.length!=d.sessions.length){
  sel.innerHTML='';
  for(const s of d.sessions){const o=document.createElement('option');
   o.textContent=s;o.value=s;sel.appendChild(o);}}
 sid=sel.value||d.sessions[0];}
document.querySelectorAll('nav button').forEach(b=>b.onclick=()=>{
 tab=b.dataset.tab;document.querySelectorAll('nav button').forEach(x=>
 x.classList.toggle('active',x===b));render();});
document.getElementById('session').onchange=e=>{sid=e.target.value;render();};
async function applyLang(code){
 const d=await j('/lang/messages'+(code?('?lang='+code):''));
 const sel=document.getElementById('lang');
 if(!sel.options.length){for(const l of d.languages){
  const o=document.createElement('option');o.textContent=l;o.value=l;
  sel.appendChild(o);}}
 sel.value=d.language;
 document.querySelectorAll('[data-i18n]').forEach(el=>{
  const m=d.messages[el.dataset.i18n];if(m)el.textContent=m;});}
document.getElementById('lang').onchange=async e=>{
 await j('/lang/setCurrent/'+e.target.value);await applyLang(e.target.value);};
setInterval(async()=>{await refreshSessions();await render();},2000);
refreshSessions().then(render);applyLang();
</script></body></html>"""


class UIServer:
    """(ref: ui/play/PlayUIServer.java — getInstance/attach pattern via
    api/UIServer.java)"""

    _instance: Optional["UIServer"] = None

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._storages: List[StatsStorage] = []
        self._remote_storage = InMemoryStatsStorage()
        self._tsne: dict = {}   # session_id → {"words", "coords"}
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def _send(self, code, payload: bytes, ctype="application/json"):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def _json(self, obj):
                self._send(200, json.dumps(obj).encode())

            def do_GET(self):
                u = urlparse(self.path)
                q = parse_qs(u.query)
                sid = q.get("sid", [None])[0]
                try:
                    if u.path == "/":
                        self._send(200, _DASHBOARD_HTML.encode(),
                                   "text/html; charset=utf-8")
                    elif u.path == "/train/sessions":
                        self._json({"sessions": server._session_ids()})
                    elif u.path == "/train/overview":
                        self._json(server._overview(sid))
                    elif u.path == "/train/model":
                        self._json(server._model(sid))
                    elif u.path == "/train/histograms":
                        self._json(server._histograms(sid))
                    elif u.path == "/train/graph":
                        self._json(server._graph(sid))
                    elif u.path == "/train/flow":
                        self._json(server._flow(sid))
                    elif u.path == "/train/system":
                        self._json(server._system(sid))
                    elif u.path == "/train/activations":
                        self._json(server._activations(sid))
                    elif u.path == "/train/tsne":
                        self._json(server._tsne.get(sid) or
                                   {"words": [], "coords": []})
                    elif u.path == "/lang/getCurrent":
                        from deeplearning4j_tpu.ui.i18n import DefaultI18N
                        self._json({"currentLanguage":
                                    DefaultI18N.get_instance()
                                    .get_default_language()})
                    elif u.path.startswith("/lang/setCurrent/"):
                        from deeplearning4j_tpu.ui.i18n import DefaultI18N
                        code = u.path.rsplit("/", 1)[-1]
                        DefaultI18N.get_instance().set_default_language(code)
                        self._json({"ok": True, "currentLanguage": code})
                    elif u.path == "/lang/messages":
                        from deeplearning4j_tpu.ui.i18n import DefaultI18N
                        i18n = DefaultI18N.get_instance()
                        lang = q.get("lang", [None])[0] or \
                            i18n.get_default_language()
                        self._json({"language": lang,
                                    "languages": i18n.languages(),
                                    "messages": i18n.messages_for(lang)})
                    else:
                        self._send(404, b'{"error":"not found"}')
                except Exception as e:
                    self._send(500, json.dumps({"error": str(e)}).encode())

            def do_POST(self):
                if self.path == "/tsne":
                    # (ref: TsneModule POST /tsne/upload — coordinate file
                    # upload; JSON body {"session_id","words","coords"})
                    try:
                        n = int(self.headers.get("Content-Length", 0))
                        body = json.loads(self.rfile.read(n))
                        sid = str(body["session_id"])
                        words = list(map(str, body["words"]))
                        coords = [[float(c[0]), float(c[1])]
                                  for c in body["coords"]]
                        if len(words) != len(coords):
                            raise ValueError("words/coords length mismatch")
                        server._tsne[sid] = {"words": words,
                                             "coords": coords}
                        self._json({"ok": True, "n": len(words)})
                    except Exception as e:
                        self._send(400, json.dumps(
                            {"error": f"{type(e).__name__}: {e}"}).encode())
                    return
                if self.path != "/remoteReceive":
                    self._send(404, b'{"error":"not found"}')
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    body = json.loads(self.rfile.read(n))
                    record = body["record"]
                    for key in ("session_id", "type_id", "worker_id"):
                        if key not in record:
                            raise KeyError(key)
                    if body.get("kind") == "static":
                        server._remote_storage.put_static_info(record)
                    else:
                        server._remote_storage.put_update(record)
                    self._json({"ok": True})
                except Exception as e:  # malformed payload → 400, not a
                    self._send(400, json.dumps(  # dropped connection
                        {"error": f"{type(e).__name__}: {e}"}).encode())

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._httpd.server_address
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    # -- lifecycle (ref: UIServer.getInstance / attach / detach) -----------
    @classmethod
    def get_instance(cls) -> "UIServer":
        if cls._instance is None:
            cls._instance = UIServer()
        return cls._instance

    def attach(self, storage: StatsStorage) -> None:
        self._storages.append(storage)

    def detach(self, storage: StatsStorage) -> None:
        self._storages.remove(storage)

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if UIServer._instance is self:
            UIServer._instance = None

    # -- data assembly ------------------------------------------------------
    def _all_storages(self) -> List[StatsStorage]:
        return self._storages + [self._remote_storage]

    def _session_ids(self) -> List[str]:
        out: List[str] = []
        for st in self._all_storages():
            out.extend(st.list_session_ids())
        return sorted(set(out))

    def _updates(self, sid: Optional[str]) -> List[dict]:
        if sid is None:
            return []
        ups: List[dict] = []
        for st in self._all_storages():
            for wid in st.list_worker_ids_for_session(sid):
                ups.extend(st.get_all_updates_after(sid, TYPE_ID, wid, -1))
        ups.sort(key=lambda r: (r.get("iteration", 0),
                                r.get("timestamp", 0)))
        return ups

    def _static(self, sid: Optional[str]) -> Optional[dict]:
        if sid is None:
            return None
        for st in self._all_storages():
            for wid in st.list_worker_ids_for_session(sid):
                info = st.get_static_info(sid, TYPE_ID, wid)
                if info:
                    return info
        return None

    def _overview(self, sid) -> dict:
        ups = self._updates(sid)
        return {
            "score": [[u["iteration"], u["score"]] for u in ups],
            "samples_per_sec": [[u["iteration"],
                                 u["perf"]["samples_per_sec"]] for u in ups],
            "duration_ms": [[u["iteration"], u["perf"]["duration_ms"]]
                            for u in ups],
        }

    def _model(self, sid) -> dict:
        ups = self._updates(sid)
        if not ups:
            return {"layers": []}
        latest = ups[-1]
        layers = []
        for name, s in latest.get("params", {}).items():
            upd = latest.get("updates", {}).get(name, {})
            layers.append({
                "name": name,
                "mean": s.get("mean"), "stdev": s.get("stdev"),
                "mean_magnitude": s.get("mean_magnitude"),
                "update_magnitude": upd.get("mean_magnitude"),
                "histogram": s.get("histogram"),
            })
        return {"layers": layers}

    def _histograms(self, sid) -> dict:
        """Latest param + update histograms per tensor — renders the data
        StatsListener always collected (ref: TrainModule histogram page,
        ui/module/train/TrainModule.java:53 'histograms' route)."""
        ups = self._updates(sid)
        if not ups:
            return {"iteration": None, "params": [], "updates": []}
        latest = ups[-1]

        def series(src):
            out = []
            for name, s in sorted(latest.get(src, {}).items()):
                h = (s or {}).get("histogram")
                if h:
                    out.append({"name": name, **h})
            return out

        return {"iteration": latest.get("iteration"),
                "params": series("params"), "updates": series("updates")}

    def _graph(self, sid) -> dict:
        """Model topology for the flow view (ref: TrainModule layer-flow
        page).  Nodes + directed edges, derived from the static-info
        model_config JSON — works for MultiLayerNetwork chains and
        ComputationGraph DAGs alike."""
        info = self._static(sid)
        if not info:
            return {"nodes": [], "edges": []}
        try:
            conf = json.loads(info.get("model_config", "{}"))
        except (TypeError, ValueError):
            return {"nodes": [], "edges": []}
        nodes, edges = [], []
        if "vertices" in conf:
            for name in conf.get("network_inputs", []):
                nodes.append({"name": name, "type": "Input"})
            for name, v in conf["vertices"].items():
                t = v.get("@class", "Vertex")
                if t == "LayerVertex":
                    t = (v.get("layer") or {}).get("@class", t)
                nodes.append({"name": name, "type": t})
            for name, ins in conf.get("vertex_inputs", {}).items():
                for i in ins:
                    edges.append([i, name])
        else:
            nodes.append({"name": "input", "type": "Input"})
            prev = "input"
            for i, ld in enumerate(conf.get("layers", [])):
                name = f"layer{i}"
                nodes.append({"name": name,
                              "type": ld.get("@class", "Layer")})
                edges.append([prev, name])
                prev = name
        return {"nodes": nodes, "edges": edges}

    def _flow(self, sid) -> dict:
        """Flow page payload: the network DAG annotated with per-layer
        parameter/update stats plus the model performance state
        (ref: ui/module/flow/FlowListenerModule.java routes;
        ui/flow/FlowIterationListener.java:251-266 — ModelInfo layers +
        ModelState score/performance/memory)."""
        topo = self._graph(sid)
        ups = self._updates(sid)
        latest = ups[-1] if ups else {}
        params = latest.get("params", {})
        updates = latest.get("updates", {})

        static = self._static(sid) or {}
        is_mln = static.get("model_class") == "MultiLayerNetwork"

        def prefix_for(name):
            # StatsListener flattens MLN params as "<i>_<key>" while the
            # topology names chain nodes "layer<i>"; CG params use the
            # vertex name directly — decided by the recorded model class
            # (a CG vertex may legitimately be NAMED "layer1")
            if is_mln and name.startswith("layer") and name[5:].isdigit():
                return name[5:] + "_"
            return name + "_"

        def agg(src, pre):
            vals = [v.get("mean_magnitude") for k, v in src.items()
                    if k.startswith(pre)
                    and v.get("mean_magnitude") is not None]
            return (sum(vals) / len(vals)) if vals else None

        nodes = []
        for nd in topo["nodes"]:
            pre = prefix_for(nd["name"])
            node = dict(nd)
            node["param_mean_magnitude"] = agg(params, pre)
            node["update_mean_magnitude"] = agg(updates, pre)
            node["params"] = sorted(
                k[len(pre):] for k in params if k.startswith(pre))
            nodes.append(node)
        perf = latest.get("perf", {})
        mem = latest.get("memory", {})
        return {
            "nodes": nodes,
            "edges": topo["edges"],
            "performance": {
                "iteration": latest.get("iteration"),
                "score": latest.get("score"),
                "samples_per_sec": perf.get("samples_per_sec"),
                "duration_ms": perf.get("duration_ms"),
                "memory_mb": mem.get("host_rss_mb"),
                "score_history": [[u["iteration"], u["score"]]
                                  for u in ups][-100:],
            },
        }

    def _system(self, sid) -> dict:
        ups = self._updates(sid)
        return {
            "memory": [[u["iteration"], u["memory"]["host_rss_mb"]]
                       for u in ups],
            "static": self._static(sid),
        }

    def _activations(self, sid) -> dict:
        """Latest ActivationsListener record for the session
        (ref: ConvolutionalListenerModule /activations)."""
        from deeplearning4j_tpu.ui.activations import TYPE_ID as ACT_TYPE
        if sid is None:
            return {"iteration": None, "layers": []}
        latest = None
        for st in self._all_storages():
            for wid in st.list_worker_ids_for_session(sid):
                rec = st.get_latest_update(sid, ACT_TYPE, wid)
                if rec and (latest is None
                            or rec.get("iteration", 0)
                            > latest.get("iteration", 0)):
                    latest = rec
        if latest is None:
            return {"iteration": None, "layers": []}
        return {"iteration": latest.get("iteration"),
                "layers": latest.get("layers", [])}
