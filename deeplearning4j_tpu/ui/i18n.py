"""UI internationalization
(ref: deeplearning4j-play/.../ui/i18n/DefaultI18N.java:38-160 — a
singleton I18N with per-language key→message tables loaded from
``dl4j_i18n`` resource files, a current language, and an English
fallback when a key is missing in the requested language; the Play
resources ship train.<lang> files for en/de/ja/ko/ru/zh).

Resource files become in-module tables plus an optional directory
loader (``load_directory``) accepting the reference's
``<prefix>.<lang>`` files of ``key=value`` lines."""

from __future__ import annotations

import threading
from pathlib import Path
from typing import Dict, Optional, Union

DEFAULT_LANGUAGE = "en"
FALLBACK_LANGUAGE = "en"

# Train-UI messages, keyed as the reference's train.* resources
_MESSAGES: Dict[str, Dict[str, str]] = {
    "en": {
        "train.pagetitle": "Training UI",
        "train.nav.overview": "Overview",
        "train.nav.model": "Model",
        "train.nav.histograms": "Histograms",
        "train.nav.graph": "Graph",
        "train.nav.flow": "Flow",
        "train.nav.activations": "Activations",
        "train.nav.tsne": "t-SNE",
        "train.nav.system": "System",
        "train.overview.chart.score": "Score vs iteration",
        "train.overview.chart.rate": "Samples/sec",
        "train.model.paramtable.title": "Parameters (latest)",
        "train.system.memory": "Host RSS (MB)",
    },
    "de": {
        "train.pagetitle": "Trainings-UI",
        "train.nav.overview": "Übersicht",
        "train.nav.model": "Modell",
        "train.nav.histograms": "Histogramme",
        "train.nav.graph": "Graph",
        "train.nav.flow": "Fluss",
        "train.nav.activations": "Aktivierungen",
        "train.nav.tsne": "t-SNE",
        "train.nav.system": "System",
        "train.overview.chart.score": "Score je Iteration",
        "train.overview.chart.rate": "Beispiele/Sek",
        "train.model.paramtable.title": "Parameter (aktuell)",
        "train.system.memory": "Host-RSS (MB)",
    },
    "ja": {
        "train.pagetitle": "トレーニングUI",
        "train.nav.overview": "概要",
        "train.nav.model": "モデル",
        "train.nav.histograms": "ヒストグラム",
        "train.nav.graph": "グラフ",
        "train.nav.flow": "フロー",
        "train.nav.activations": "活性化",
        "train.nav.tsne": "t-SNE",
        "train.nav.system": "システム",
        "train.overview.chart.score": "スコア対反復",
        "train.overview.chart.rate": "サンプル/秒",
        "train.model.paramtable.title": "パラメータ（最新）",
        "train.system.memory": "ホストRSS (MB)",
    },
    "ko": {
        "train.pagetitle": "훈련 UI",
        "train.nav.overview": "개요",
        "train.nav.model": "모델",
        "train.nav.histograms": "히스토그램",
        "train.nav.graph": "그래프",
        "train.nav.flow": "플로우",
        "train.nav.activations": "활성화",
        "train.nav.tsne": "t-SNE",
        "train.nav.system": "시스템",
        "train.overview.chart.score": "반복별 점수",
        "train.overview.chart.rate": "샘플/초",
        "train.model.paramtable.title": "파라미터 (최신)",
        "train.system.memory": "호스트 RSS (MB)",
    },
    "ru": {
        "train.pagetitle": "Интерфейс обучения",
        "train.nav.overview": "Обзор",
        "train.nav.model": "Модель",
        "train.nav.histograms": "Гистограммы",
        "train.nav.graph": "Граф",
        "train.nav.flow": "Поток",
        "train.nav.activations": "Активации",
        "train.nav.tsne": "t-SNE",
        "train.nav.system": "Система",
        "train.overview.chart.score": "Ошибка по итерациям",
        "train.overview.chart.rate": "Примеров/сек",
        "train.model.paramtable.title": "Параметры (последние)",
        "train.system.memory": "RSS хоста (МБ)",
    },
    "zh": {
        "train.pagetitle": "训练界面",
        "train.nav.overview": "概览",
        "train.nav.model": "模型",
        "train.nav.histograms": "直方图",
        "train.nav.graph": "图",
        "train.nav.flow": "流程",
        "train.nav.activations": "激活",
        "train.nav.tsne": "t-SNE",
        "train.nav.system": "系统",
        "train.overview.chart.score": "得分随迭代变化",
        "train.overview.chart.rate": "样本/秒",
        "train.model.paramtable.title": "参数（最新）",
        "train.system.memory": "主机RSS (MB)",
    },
}


class DefaultI18N:
    """Singleton message lookup with English fallback
    (ref: DefaultI18N.java:48 getInstance, :128-152 getMessage with
    fallback, :155-165 default-language accessors)."""

    _instance: Optional["DefaultI18N"] = None
    _lock = threading.Lock()

    def __init__(self):
        self._messages: Dict[str, Dict[str, str]] = {
            lang: dict(tbl) for lang, tbl in _MESSAGES.items()}
        self._current = DEFAULT_LANGUAGE

    @classmethod
    def get_instance(cls) -> "DefaultI18N":
        with cls._lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    # -- I18N surface (ref: i18n/I18N.java) --------------------------------
    def get_message(self, key: str, lang_code: Optional[str] = None) -> str:
        lang = lang_code or self._current
        msg = self._messages.get(lang, {}).get(key)
        if msg is None and lang != FALLBACK_LANGUAGE:
            msg = self._messages.get(FALLBACK_LANGUAGE, {}).get(key)
        return msg if msg is not None else key

    def get_default_language(self) -> str:
        return self._current

    def set_default_language(self, lang_code: str) -> None:
        self._current = lang_code

    def languages(self):
        return sorted(self._messages)

    def messages_for(self, lang_code: str) -> Dict[str, str]:
        """Fallback-merged table for one language (what the dashboard
        fetches to relabel itself)."""
        out = dict(self._messages.get(FALLBACK_LANGUAGE, {}))
        out.update(self._messages.get(lang_code, {}))
        return out

    # -- resource loading ---------------------------------------------------
    def load_directory(self, directory: Union[str, Path]) -> int:
        """Load ``<prefix>.<lang>`` files of ``key=value`` lines — the
        reference's dl4j_i18n resource layout (DefaultI18N.java:69-106).
        Returns the number of messages loaded."""
        import re
        n = 0
        for p in sorted(Path(directory).iterdir()):
            lang = p.suffix.lstrip(".").lower()
            # the extension must be a 2-letter ISO 639-1 code (the
            # reference's train.en/.de/... layout) — a stray README.md
            # or notes.txt must not register an "md"/"txt" UI language
            if not p.is_file() or not re.fullmatch(r"[a-z]{2}", lang):
                continue
            entries = {}
            for line in p.read_text(encoding="utf-8").splitlines():
                line = line.strip()
                if not line or line.startswith("#") or "=" not in line:
                    continue
                key, _, val = line.partition("=")
                entries[key.strip()] = val.strip()
            if entries:   # never create empty language tables
                self._messages.setdefault(lang, {}).update(entries)
                n += len(entries)
        return n
