"""deeplearning4j_tpu — a TPU-native deep learning framework.

A from-scratch JAX/XLA/Pallas re-realization of the capabilities of
Deeplearning4j 0.8.x (reference: seetharamireddy540/deeplearning4j).  Instead of the
reference's eager per-op JVM dispatch over libnd4j/cuDNN
(ref: deeplearning4j-nn/.../nn/multilayer/MultiLayerNetwork.java), every
training update step is traced once and compiled into a single XLA program,
parameters live in pytrees (with a flat-view adapter for checkpoint parity
with the reference's 1xN param row vector, ref: nn/api/Model.java:128),
and multi-device training is expressed as shardings over a
``jax.sharding.Mesh`` with XLA collectives instead of parameter averaging
over threads/Aeron/Spark (ref: parallelism/ParallelWrapper.java:218).
"""

__version__ = "0.1.0"

from deeplearning4j_tpu.nn.conf.network import (  # noqa: F401
    NeuralNetConfiguration,
    MultiLayerConfiguration,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork  # noqa: F401
