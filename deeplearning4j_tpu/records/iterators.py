"""RecordReader → DataSet iterators
(ref: deeplearning4j-core/.../datasets/datavec/
RecordReaderDataSetIterator.java:54 (466 LoC),
SequenceRecordReaderDataSetIterator.java,
RecordReaderMultiDataSetIterator.java)."""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.datasets.iterators import DataSetIterator
from deeplearning4j_tpu.records.readers import (
    RecordReader, SequenceRecordReader)


def _one_hot(indices: np.ndarray, n_classes: int) -> np.ndarray:
    """Whole-batch one-hot: one fancy-indexed assignment, no per-row
    Python (arxiv 1912.05234's point: batch-level array code is where
    framework throughput lives)."""
    idx = np.asarray(indices, np.float32).astype(np.int64).reshape(-1)
    y = np.zeros((idx.shape[0], n_classes), np.float32)
    y[np.arange(idx.shape[0]), idx] = 1.0
    return y


def _record_to_arrays(rec, label_index: Optional[int], n_labels: int,
                      regression: bool) -> Tuple[np.ndarray, np.ndarray]:
    """Split one record into (features, labels) following the reference's
    labelIndex semantics; image records carry ndarray features.  Kept as
    the per-row fallback for object records — the steady-state batch
    path is the vectorized ``collate``."""
    if label_index is None:
        feats = rec
        label = None
    else:
        li = label_index if label_index >= 0 else len(rec) + label_index
        feats = rec[:li] + rec[li + 1:]
        label = rec[li]
    if len(feats) == 1 and isinstance(feats[0], np.ndarray):
        f = feats[0].astype(np.float32)
    else:
        f = np.asarray([float(v) for v in feats], np.float32)
    if label is None:
        return f, np.zeros((0,), np.float32)
    if regression:
        y = np.asarray([float(label)], np.float32)
    else:
        y = np.zeros((n_labels,), np.float32)
        y[int(label)] = 1.0
    return f, y


class RecordReaderDataSetIterator(DataSetIterator):
    """(ref: RecordReaderDataSetIterator.java:54 — batchSize,
    labelIndex, numPossibleLabels, regression)

    ``next_raw()``/``collate()`` split the serial record pull from the
    vectorized batch assembly so AsyncDataSetIterator's workers can run
    the assembly in parallel while order stays deterministic."""

    def __init__(self, reader: RecordReader, batch_size: int,
                 label_index: Optional[int] = -1,
                 num_possible_labels: int = 0, regression: bool = False):
        self.reader = reader
        self.batch_size = batch_size
        self.label_index = label_index
        self.num_possible_labels = num_possible_labels
        self.regression = regression
        self.reader.reset()

    def has_next(self) -> bool:
        return self.reader.has_next()

    def next_raw(self) -> List[list]:
        recs = []
        while self.reader.has_next() and len(recs) < self.batch_size:
            recs.append(self.reader.next_record())
        return recs

    def collate(self, recs: List[list]) -> DataSet:
        n = len(recs)
        li = self.label_index
        li_n = None if li is None else \
            (li if li >= 0 else len(recs[0]) + li)
        feats0 = recs[0] if li is None else \
            recs[0][:li_n] + recs[0][li_n + 1:]
        if len(feats0) == 1 and isinstance(feats0[0], np.ndarray):
            # image records: ndarray features + scalar label column
            x = np.stack([(r[:li_n] + r[li_n + 1:])[0] if li is not None
                          else r[0] for r in recs]).astype(np.float32)
            labels = None if li is None else \
                np.asarray([float(r[li_n]) for r in recs], np.float32)
        else:
            try:
                # whole-batch parse: numpy converts a list of number- or
                # string-valued rows in one C-loop pass
                arr = np.asarray(recs, dtype=np.float32)
                if arr.ndim != 2:
                    raise ValueError("ragged records")
            except (TypeError, ValueError):
                fs, ys = zip(*(_record_to_arrays(
                    r, li, self.num_possible_labels, self.regression)
                    for r in recs))
                return DataSet(np.stack(fs), np.stack(ys))
            if li is None:
                x, labels = arr, None
            else:
                x = np.delete(arr, li_n, axis=1)
                labels = arr[:, li_n]
        if labels is None:
            y = np.zeros((n, 0), np.float32)
        elif self.regression:
            y = np.asarray(labels, np.float32).reshape(n, 1)
        else:
            y = _one_hot(labels, self.num_possible_labels)
        return DataSet(x, y)

    def next(self) -> DataSet:
        return self.collate(self.next_raw())

    def reset(self) -> None:
        self.reader.reset()


class SequenceRecordReaderDataSetIterator(DataSetIterator):
    """Sequences → padded+masked [N, T, C] DataSets
    (ref: SequenceRecordReaderDataSetIterator.java; alignment modes:
    same reader for features+labels per-step, or separate readers with
    ALIGN_END last-step labels)."""

    ALIGN_END = "ALIGN_END"
    EQUAL_LENGTH = "EQUAL_LENGTH"

    def __init__(self, features_reader: SequenceRecordReader,
                 batch_size: int, num_possible_labels: int,
                 labels_reader: Optional[SequenceRecordReader] = None,
                 label_index: int = -1, regression: bool = False,
                 alignment: str = "EQUAL_LENGTH"):
        self.freader = features_reader
        self.lreader = labels_reader
        self.batch_size = batch_size
        self.num_possible_labels = num_possible_labels
        self.label_index = label_index
        self.regression = regression
        self.alignment = alignment
        self.reset()

    def has_next(self) -> bool:
        return self.freader.has_next()

    def _one(self, fseq, lseq):
        """One (features, labels) sequence pair as arrays — whole-sequence
        numpy parse + batched one-hot, no per-timestep Python."""
        if lseq is not None:
            f = np.asarray(fseq, np.float32)
            if self.regression:
                y = np.asarray(lseq, np.float32)
            else:
                lab = np.asarray(lseq, np.float32).astype(np.int64)[:, 0]
                y = np.zeros((lab.shape[0], self.num_possible_labels),
                             np.float32)
                y[np.arange(lab.shape[0]), lab] = 1.0
            return f, y
        # same reader carries features + per-step label column
        arr = np.asarray(fseq, np.float32)
        li = (self.label_index if self.label_index >= 0
              else arr.shape[1] + self.label_index)
        f = np.delete(arr, li, axis=1)
        labels = arr[:, li]
        if self.regression:
            y = labels[:, None]
        else:
            lab = labels.astype(np.int64)
            y = np.zeros((lab.shape[0], self.num_possible_labels),
                         np.float32)
            y[np.arange(lab.shape[0]), lab] = 1.0
        return f, y

    def next_raw(self) -> List[tuple]:
        raw = []
        while self.freader.has_next() and len(raw) < self.batch_size:
            fseq = self.freader.next_sequence()
            lseq = (self.lreader.next_sequence()
                    if self.lreader is not None else None)
            raw.append((fseq, lseq))
        return raw

    def collate(self, raw: List[tuple]) -> DataSet:
        seqs = [self._one(fs, ls) for fs, ls in raw]
        T = max(f.shape[0] for f, _ in seqs)
        align_end = self.alignment == self.ALIGN_END
        Tl = T if align_end else max(y.shape[0] for _, y in seqs)
        N = len(seqs)
        C = seqs[0][0].shape[1]
        L = seqs[0][1].shape[1]
        x = np.zeros((N, T, C), np.float32)
        y = np.zeros((N, Tl, L), np.float32)
        fm = np.zeros((N, T), np.float32)
        lm = np.zeros((N, Tl), np.float32)
        for i, (f, lab) in enumerate(seqs):
            x[i, :f.shape[0]] = f
            fm[i, :f.shape[0]] = 1.0
            if align_end:
                # labels end-aligned with each example's LAST valid
                # feature step (ref: AlignmentMode.ALIGN_END)
                off = f.shape[0] - lab.shape[0]
                y[i, off:f.shape[0]] = lab
                lm[i, off:f.shape[0]] = 1.0
            else:
                y[i, :lab.shape[0]] = lab
                lm[i, :lab.shape[0]] = 1.0
        pad_free = fm.all() and lm.all()
        return DataSet(x, y, None if pad_free else fm,
                       None if pad_free else lm)

    def next(self) -> DataSet:
        return self.collate(self.next_raw())

    def reset(self) -> None:
        self.freader.reset()
        if self.lreader is not None:
            self.lreader.reset()


class RecordReaderMultiDataSetIterator:
    """Named multi-input/multi-output assembly
    (ref: RecordReaderMultiDataSetIterator.java — builder with
    addReader/addInput/addOutputOneHot)."""

    class Builder:
        def __init__(self, batch_size: int):
            self.batch_size = batch_size
            self.readers: Dict[str, RecordReader] = {}
            self.inputs: List[Tuple[str, Optional[int], Optional[int]]] = []
            self.outputs: List[Tuple[str, int, Optional[int], bool]] = []

        def add_reader(self, name: str, reader: RecordReader):
            self.readers[name] = reader
            return self

        def add_input(self, reader_name: str, col_from: Optional[int] = None,
                      col_to: Optional[int] = None):
            self.inputs.append((reader_name, col_from, col_to))
            return self

        def add_output_one_hot(self, reader_name: str, column: int,
                               num_classes: int):
            self.outputs.append((reader_name, column, num_classes, False))
            return self

        def add_output(self, reader_name: str, col_from: Optional[int] = None,
                       col_to: Optional[int] = None):
            self.outputs.append((reader_name, col_from, col_to, True))
            return self

        def build(self) -> "RecordReaderMultiDataSetIterator":
            return RecordReaderMultiDataSetIterator(self)

    def __init__(self, builder: "RecordReaderMultiDataSetIterator.Builder"):
        self.b = builder
        self.reset()

    def has_next(self) -> bool:
        return all(r.has_next() for r in self.b.readers.values())

    def next_raw(self) -> List[Dict[str, list]]:
        rows: List[Dict[str, list]] = []
        while self.has_next() and len(rows) < self.b.batch_size:
            rows.append({n: r.next_record()
                         for n, r in self.b.readers.items()})
        return rows

    def collate(self, rows: List[Dict[str, list]]) -> MultiDataSet:
        n = len(rows)
        mats: Dict[str, np.ndarray] = {}

        def mat(name):  # each reader's batch parses once, then slices
            if name not in mats:
                mats[name] = np.asarray([row[name] for row in rows],
                                        np.float32)
            return mats[name]

        ins = [np.ascontiguousarray(mat(name)[:, c0:c1]) if c0 is not None
               else mat(name) for name, c0, c1 in self.b.inputs]
        outs = []
        for name, a, b, is_range in self.b.outputs:
            if is_range:
                outs.append(np.ascontiguousarray(mat(name)[:, a:b])
                            if a is not None else mat(name))
            else:
                y = np.zeros((n, b), np.float32)
                y[np.arange(n), mat(name)[:, a].astype(np.int64)] = 1.0
                outs.append(y)
        return MultiDataSet(ins, outs)

    def next(self) -> MultiDataSet:
        return self.collate(self.next_raw())

    def reset(self) -> None:
        for r in self.b.readers.values():
            r.reset()
