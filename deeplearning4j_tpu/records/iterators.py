"""RecordReader → DataSet iterators
(ref: deeplearning4j-core/.../datasets/datavec/
RecordReaderDataSetIterator.java:54 (466 LoC),
SequenceRecordReaderDataSetIterator.java,
RecordReaderMultiDataSetIterator.java)."""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.datasets.iterators import DataSetIterator
from deeplearning4j_tpu.records.readers import (
    RecordReader, SequenceRecordReader)


def _record_to_arrays(rec, label_index: Optional[int], n_labels: int,
                      regression: bool) -> Tuple[np.ndarray, np.ndarray]:
    """Split one record into (features, labels) following the reference's
    labelIndex semantics; image records carry ndarray features."""
    if label_index is None:
        feats = rec
        label = None
    else:
        li = label_index if label_index >= 0 else len(rec) + label_index
        feats = rec[:li] + rec[li + 1:]
        label = rec[li]
    if len(feats) == 1 and isinstance(feats[0], np.ndarray):
        f = feats[0].astype(np.float32)
    else:
        f = np.asarray([float(v) for v in feats], np.float32)
    if label is None:
        return f, np.zeros((0,), np.float32)
    if regression:
        y = np.asarray([float(label)], np.float32)
    else:
        y = np.zeros((n_labels,), np.float32)
        y[int(label)] = 1.0
    return f, y


class RecordReaderDataSetIterator(DataSetIterator):
    """(ref: RecordReaderDataSetIterator.java:54 — batchSize,
    labelIndex, numPossibleLabels, regression)"""

    def __init__(self, reader: RecordReader, batch_size: int,
                 label_index: Optional[int] = -1,
                 num_possible_labels: int = 0, regression: bool = False):
        self.reader = reader
        self.batch_size = batch_size
        self.label_index = label_index
        self.num_possible_labels = num_possible_labels
        self.regression = regression
        self.reader.reset()

    def has_next(self) -> bool:
        return self.reader.has_next()

    def next(self) -> DataSet:
        fs, ys = [], []
        while self.reader.has_next() and len(fs) < self.batch_size:
            f, y = _record_to_arrays(self.reader.next_record(),
                                     self.label_index,
                                     self.num_possible_labels,
                                     self.regression)
            fs.append(f)
            ys.append(y)
        return DataSet(np.stack(fs), np.stack(ys))

    def reset(self) -> None:
        self.reader.reset()


class SequenceRecordReaderDataSetIterator(DataSetIterator):
    """Sequences → padded+masked [N, T, C] DataSets
    (ref: SequenceRecordReaderDataSetIterator.java; alignment modes:
    same reader for features+labels per-step, or separate readers with
    ALIGN_END last-step labels)."""

    ALIGN_END = "ALIGN_END"
    EQUAL_LENGTH = "EQUAL_LENGTH"

    def __init__(self, features_reader: SequenceRecordReader,
                 batch_size: int, num_possible_labels: int,
                 labels_reader: Optional[SequenceRecordReader] = None,
                 label_index: int = -1, regression: bool = False,
                 alignment: str = "EQUAL_LENGTH"):
        self.freader = features_reader
        self.lreader = labels_reader
        self.batch_size = batch_size
        self.num_possible_labels = num_possible_labels
        self.label_index = label_index
        self.regression = regression
        self.alignment = alignment
        self.reset()

    def has_next(self) -> bool:
        return self.freader.has_next()

    def _one(self):
        fseq = self.freader.next_sequence()
        if self.lreader is not None:
            lseq = self.lreader.next_sequence()
            f = np.asarray([[float(v) for v in r] for r in fseq], np.float32)
            if self.regression:
                y = np.asarray([[float(v) for v in r] for r in lseq],
                               np.float32)
            else:
                y = np.zeros((len(lseq), self.num_possible_labels),
                             np.float32)
                for t, r in enumerate(lseq):
                    y[t, int(r[0])] = 1.0
            return f, y
        # same reader carries features + per-step label column
        feats, labels = [], []
        for r in fseq:
            li = (self.label_index if self.label_index >= 0
                  else len(r) + self.label_index)
            feats.append([float(v) for i, v in enumerate(r) if i != li])
            labels.append(r[li])
        f = np.asarray(feats, np.float32)
        if self.regression:
            y = np.asarray(labels, np.float32)[:, None]
        else:
            y = np.zeros((len(labels), self.num_possible_labels), np.float32)
            for t, lab in enumerate(labels):
                y[t, int(lab)] = 1.0
        return f, y

    def next(self) -> DataSet:
        seqs = []
        while self.freader.has_next() and len(seqs) < self.batch_size:
            seqs.append(self._one())
        T = max(f.shape[0] for f, _ in seqs)
        align_end = self.alignment == self.ALIGN_END
        Tl = T if align_end else max(y.shape[0] for _, y in seqs)
        N = len(seqs)
        C = seqs[0][0].shape[1]
        L = seqs[0][1].shape[1]
        x = np.zeros((N, T, C), np.float32)
        y = np.zeros((N, Tl, L), np.float32)
        fm = np.zeros((N, T), np.float32)
        lm = np.zeros((N, Tl), np.float32)
        for i, (f, lab) in enumerate(seqs):
            x[i, :f.shape[0]] = f
            fm[i, :f.shape[0]] = 1.0
            if align_end:
                # labels end-aligned with each example's LAST valid
                # feature step (ref: AlignmentMode.ALIGN_END)
                off = f.shape[0] - lab.shape[0]
                y[i, off:f.shape[0]] = lab
                lm[i, off:f.shape[0]] = 1.0
            else:
                y[i, :lab.shape[0]] = lab
                lm[i, :lab.shape[0]] = 1.0
        pad_free = fm.all() and lm.all()
        return DataSet(x, y, None if pad_free else fm,
                       None if pad_free else lm)

    def reset(self) -> None:
        self.freader.reset()
        if self.lreader is not None:
            self.lreader.reset()


class RecordReaderMultiDataSetIterator:
    """Named multi-input/multi-output assembly
    (ref: RecordReaderMultiDataSetIterator.java — builder with
    addReader/addInput/addOutputOneHot)."""

    class Builder:
        def __init__(self, batch_size: int):
            self.batch_size = batch_size
            self.readers: Dict[str, RecordReader] = {}
            self.inputs: List[Tuple[str, Optional[int], Optional[int]]] = []
            self.outputs: List[Tuple[str, int, Optional[int], bool]] = []

        def add_reader(self, name: str, reader: RecordReader):
            self.readers[name] = reader
            return self

        def add_input(self, reader_name: str, col_from: Optional[int] = None,
                      col_to: Optional[int] = None):
            self.inputs.append((reader_name, col_from, col_to))
            return self

        def add_output_one_hot(self, reader_name: str, column: int,
                               num_classes: int):
            self.outputs.append((reader_name, column, num_classes, False))
            return self

        def add_output(self, reader_name: str, col_from: Optional[int] = None,
                       col_to: Optional[int] = None):
            self.outputs.append((reader_name, col_from, col_to, True))
            return self

        def build(self) -> "RecordReaderMultiDataSetIterator":
            return RecordReaderMultiDataSetIterator(self)

    def __init__(self, builder: "RecordReaderMultiDataSetIterator.Builder"):
        self.b = builder
        self.reset()

    def has_next(self) -> bool:
        return all(r.has_next() for r in self.b.readers.values())

    def next(self) -> MultiDataSet:
        rows: List[Dict[str, list]] = []
        while self.has_next() and len(rows) < self.b.batch_size:
            rows.append({n: r.next_record()
                         for n, r in self.b.readers.items()})
        ins = []
        for name, c0, c1 in self.b.inputs:
            vals = [[float(v) for v in
                     (row[name][c0:c1] if c0 is not None else row[name])]
                    for row in rows]
            ins.append(np.asarray(vals, np.float32))
        outs = []
        for name, a, b, is_range in self.b.outputs:
            if is_range:
                vals = [[float(v) for v in
                         (row[name][a:b] if a is not None else row[name])]
                        for row in rows]
                outs.append(np.asarray(vals, np.float32))
            else:
                y = np.zeros((len(rows), b), np.float32)
                for i, row in enumerate(rows):
                    y[i, int(row[name][a])] = 1.0
                outs.append(y)
        return MultiDataSet(ins, outs)

    def reset(self) -> None:
        for r in self.b.readers.values():
            r.reset()
