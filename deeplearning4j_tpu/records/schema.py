"""Schema — typed column metadata for transform pipelines
(ref: datavec-api transform Schema — consumed via the DataVec surface,
SURVEY.md §2.10)."""

from __future__ import annotations

import dataclasses
import json
from typing import List, Optional


@dataclasses.dataclass
class ColumnMetaData:
    name: str
    column_type: str  # Double | Integer | Categorical | String | Time
    state_names: Optional[List[str]] = None  # for Categorical


class Schema:
    """Builder-style schema (ref: datavec Schema.Builder)."""

    def __init__(self, columns: Optional[List[ColumnMetaData]] = None):
        self.columns: List[ColumnMetaData] = columns or []

    # -- builder ------------------------------------------------------------
    class Builder:
        def __init__(self):
            self._cols: List[ColumnMetaData] = []

        def add_column_double(self, name: str) -> "Schema.Builder":
            self._cols.append(ColumnMetaData(name, "Double"))
            return self

        def add_column_integer(self, name: str) -> "Schema.Builder":
            self._cols.append(ColumnMetaData(name, "Integer"))
            return self

        def add_column_string(self, name: str) -> "Schema.Builder":
            self._cols.append(ColumnMetaData(name, "String"))
            return self

        def add_column_categorical(self, name: str,
                                   *state_names: str) -> "Schema.Builder":
            self._cols.append(
                ColumnMetaData(name, "Categorical", list(state_names)))
            return self

        def add_columns_double(self, *names: str) -> "Schema.Builder":
            for n in names:
                self.add_column_double(n)
            return self

        def build(self) -> "Schema":
            return Schema(list(self._cols))

    @staticmethod
    def builder() -> "Schema.Builder":
        return Schema.Builder()

    # -- queries ------------------------------------------------------------
    def num_columns(self) -> int:
        return len(self.columns)

    def column_names(self) -> List[str]:
        return [c.name for c in self.columns]

    def index_of(self, name: str) -> int:
        for i, c in enumerate(self.columns):
            if c.name == name:
                return i
        raise KeyError(name)

    def column_type(self, name: str) -> str:
        return self.columns[self.index_of(name)].column_type

    # -- serialization ------------------------------------------------------
    def to_json(self) -> str:
        return json.dumps([dataclasses.asdict(c) for c in self.columns])

    @staticmethod
    def from_json(s: str) -> "Schema":
        return Schema([ColumnMetaData(**d) for d in json.loads(s)])
