"""Record readers + transforms — the consumed DataVec surface
(SURVEY.md §2.10: RecordReader / transforms / image loading behind
`RecordReaderDataSetIterator.java:54`)."""

from deeplearning4j_tpu.records.readers import (
    CollectionRecordReader, CollectionSequenceRecordReader, CSVRecordReader,
    CSVSequenceRecordReader, ImageRecordReader, LineRecordReader,
    RecordReader, SequenceRecordReader)
from deeplearning4j_tpu.records.schema import Schema
from deeplearning4j_tpu.records.transforms import TransformProcess
from deeplearning4j_tpu.records.iterators import (
    RecordReaderDataSetIterator, RecordReaderMultiDataSetIterator,
    SequenceRecordReaderDataSetIterator)

__all__ = [
    "CollectionRecordReader", "CollectionSequenceRecordReader",
    "CSVRecordReader", "CSVSequenceRecordReader", "ImageRecordReader",
    "LineRecordReader", "RecordReader", "SequenceRecordReader", "Schema",
    "TransformProcess", "RecordReaderDataSetIterator",
    "RecordReaderMultiDataSetIterator",
    "SequenceRecordReaderDataSetIterator",
]
