"""TransformProcess — schema-aware record transformation pipeline
(ref: datavec-api TransformProcess — the ETL step between RecordReader
and RecordReaderDataSetIterator, SURVEY.md §2.10).

Each operation maps (schema, records) → (schema', records'); the builder
records the chain, ``execute`` streams records through it on the host
(ETL stays host-side; devices only ever see the assembled DataSet
arrays)."""

from __future__ import annotations

import json
from typing import Callable, List, Optional

from deeplearning4j_tpu.records.schema import ColumnMetaData, Schema

Record = list


class TransformProcess:
    def __init__(self, initial_schema: Schema, ops: List[dict]):
        self.initial_schema = initial_schema
        self.ops = ops

    # -- builder ------------------------------------------------------------
    class Builder:
        def __init__(self, initial_schema: Schema):
            self.schema = initial_schema
            self.ops: List[dict] = []

        def remove_columns(self, *names: str) -> "TransformProcess.Builder":
            self.ops.append({"op": "remove_columns", "names": list(names)})
            return self

        def keep_columns(self, *names: str) -> "TransformProcess.Builder":
            self.ops.append({"op": "keep_columns", "names": list(names)})
            return self

        def categorical_to_integer(self, *names: str
                                   ) -> "TransformProcess.Builder":
            self.ops.append({"op": "categorical_to_integer",
                             "names": list(names)})
            return self

        def categorical_to_one_hot(self, *names: str
                                   ) -> "TransformProcess.Builder":
            self.ops.append({"op": "categorical_to_one_hot",
                             "names": list(names)})
            return self

        def string_to_categorical(self, name: str, state_names: List[str]
                                  ) -> "TransformProcess.Builder":
            self.ops.append({"op": "string_to_categorical", "name": name,
                             "state_names": state_names})
            return self

        def double_math_op(self, name: str, op: str, scalar: float
                           ) -> "TransformProcess.Builder":
            self.ops.append({"op": "double_math_op", "name": name,
                             "math": op, "scalar": scalar})
            return self

        def normalize_min_max(self, name: str, mn: float, mx: float
                              ) -> "TransformProcess.Builder":
            self.ops.append({"op": "normalize_min_max", "name": name,
                             "min": mn, "max": mx})
            return self

        def filter_invalid(self) -> "TransformProcess.Builder":
            self.ops.append({"op": "filter_invalid"})
            return self

        def build(self) -> "TransformProcess":
            return TransformProcess(self.schema, list(self.ops))

    @staticmethod
    def builder(initial_schema: Schema) -> "TransformProcess.Builder":
        return TransformProcess.Builder(initial_schema)

    # -- schema propagation --------------------------------------------------
    def final_schema(self) -> Schema:
        schema = self.initial_schema
        for op in self.ops:
            schema = self._apply_schema(schema, op)
        return schema

    @staticmethod
    def _apply_schema(schema: Schema, op: dict) -> Schema:
        cols = list(schema.columns)
        kind = op["op"]
        if kind == "remove_columns":
            cols = [c for c in cols if c.name not in op["names"]]
        elif kind == "keep_columns":
            cols = [c for c in cols if c.name in op["names"]]
        elif kind == "categorical_to_integer":
            cols = [ColumnMetaData(c.name, "Integer")
                    if c.name in op["names"] else c for c in cols]
        elif kind == "categorical_to_one_hot":
            out = []
            for c in cols:
                if c.name in op["names"]:
                    for s in (c.state_names or []):
                        out.append(ColumnMetaData(f"{c.name}[{s}]", "Double"))
                else:
                    out.append(c)
            cols = out
        elif kind == "string_to_categorical":
            cols = [ColumnMetaData(c.name, "Categorical", op["state_names"])
                    if c.name == op["name"] else c for c in cols]
        # math / normalize / filter keep the schema
        return Schema(cols)

    # -- execution ------------------------------------------------------------
    def execute(self, records: List[Record]) -> List[Record]:
        schema = self.initial_schema
        out = [list(r) for r in records]
        for op in self.ops:
            out = self._apply_records(schema, out, op)
            schema = self._apply_schema(schema, op)
        return out

    @staticmethod
    def _apply_records(schema: Schema, records: List[Record],
                       op: dict) -> List[Record]:
        kind = op["op"]
        if kind in ("remove_columns", "keep_columns"):
            keep = [i for i, c in enumerate(schema.columns)
                    if (c.name in op["names"]) == (kind == "keep_columns")]
            return [[r[i] for i in keep] for r in records]
        if kind == "categorical_to_integer":
            idxs = {schema.index_of(n): schema.columns[schema.index_of(n)]
                    for n in op["names"]}
            out = []
            for r in records:
                r = list(r)
                for i, col in idxs.items():
                    r[i] = (col.state_names or []).index(r[i])
                out.append(r)
            return out
        if kind == "categorical_to_one_hot":
            out = []
            for r in records:
                nr: Record = []
                for i, c in enumerate(schema.columns):
                    if c.name in op["names"]:
                        states = c.state_names or []
                        hot = [0.0] * len(states)
                        hot[states.index(r[i])] = 1.0
                        nr.extend(hot)
                    else:
                        nr.append(r[i])
                out.append(nr)
            return out
        if kind == "string_to_categorical":
            i = schema.index_of(op["name"])
            for r in records:
                if r[i] not in op["state_names"]:
                    raise ValueError(
                        f"value {r[i]!r} not in states {op['state_names']}")
            return records
        if kind == "double_math_op":
            i = schema.index_of(op["name"])
            fn: Callable[[float], float] = {
                "Add": lambda x: x + op["scalar"],
                "Subtract": lambda x: x - op["scalar"],
                "Multiply": lambda x: x * op["scalar"],
                "Divide": lambda x: x / op["scalar"],
            }[op["math"]]
            return [[fn(v) if j == i else v for j, v in enumerate(r)]
                    for r in records]
        if kind == "normalize_min_max":
            i = schema.index_of(op["name"])
            rng = op["max"] - op["min"] or 1.0
            return [[(v - op["min"]) / rng if j == i else v
                     for j, v in enumerate(r)] for r in records]
        if kind == "filter_invalid":
            def ok(r):
                for v, c in zip(r, schema.columns):
                    if c.column_type in ("Double", "Integer"):
                        if not isinstance(v, (int, float)):
                            return False
                        if v != v:  # NaN
                            return False
                return True
            return [r for r in records if ok(r)]
        raise ValueError(f"unknown op {kind}")

    # -- serialization (ref: TransformProcess.toJson) -------------------------
    def to_json(self) -> str:
        return json.dumps({"schema": self.initial_schema.to_json(),
                           "ops": self.ops})

    @staticmethod
    def from_json(s: str) -> "TransformProcess":
        d = json.loads(s)
        return TransformProcess(Schema.from_json(d["schema"]), d["ops"])
