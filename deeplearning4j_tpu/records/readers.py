"""RecordReader SPI + implementations
(ref: external DataVec consumed surface — datavec-api
RecordReader/SequenceRecordReader and datavec-data-image's
ImageRecordReader, as used by
deeplearning4j-core/.../datasets/datavec/RecordReaderDataSetIterator.java:54).

A record is a list of values (numbers or strings); a sequence record is
a list of records (timesteps).  Readers stream from files/collections;
the iterators in records/iterators.py assemble DataSets from them."""

from __future__ import annotations

import csv
import io
from pathlib import Path
from typing import Iterable, List, Optional, Sequence, Union

import numpy as np

Record = List[object]


class RecordReader:
    """(ref: datavec RecordReader — hasNext/next/reset contract)"""

    def has_next(self) -> bool:
        raise NotImplementedError

    def next_record(self) -> Record:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError

    def __iter__(self):
        self.reset()
        while self.has_next():
            yield self.next_record()


class SequenceRecordReader(RecordReader):
    """(ref: datavec SequenceRecordReader)"""

    def next_sequence(self) -> List[Record]:
        raise NotImplementedError


# ---------------------------------------------------------------------------


class CollectionRecordReader(RecordReader):
    """In-memory records (ref: datavec CollectionRecordReader)."""

    def __init__(self, records: Iterable[Record]):
        self.records = [list(r) for r in records]
        self._i = 0

    def has_next(self):
        return self._i < len(self.records)

    def next_record(self):
        r = self.records[self._i]
        self._i += 1
        return list(r)

    def reset(self):
        self._i = 0


class CollectionSequenceRecordReader(SequenceRecordReader):
    def __init__(self, sequences: Iterable[Iterable[Record]]):
        self.sequences = [[list(r) for r in s] for s in sequences]
        self._i = 0

    def has_next(self):
        return self._i < len(self.sequences)

    def next_sequence(self):
        s = self.sequences[self._i]
        self._i += 1
        return [list(r) for r in s]

    next_record = next_sequence

    def reset(self):
        self._i = 0


class LineRecordReader(RecordReader):
    """One line → one single-column record (ref: datavec LineRecordReader)."""

    def __init__(self, path: Union[str, Path]):
        self.path = str(path)
        self._lines: Optional[List[str]] = None
        self._i = 0

    def _load(self):
        if self._lines is None:
            with open(self.path) as f:
                self._lines = [ln.rstrip("\n") for ln in f]

    def has_next(self):
        self._load()
        return self._i < len(self._lines)

    def next_record(self):
        self._load()
        ln = self._lines[self._i]
        self._i += 1
        return [ln]

    def reset(self):
        self._i = 0


def _parse_field(s: str):
    """Numbers become floats (ints stay int-valued floats), everything
    else stays a string — matching DataVec's Writable coercion at the
    DataSet boundary."""
    try:
        return int(s)
    except ValueError:
        try:
            return float(s)
        except ValueError:
            return s


class CSVRecordReader(RecordReader):
    """(ref: datavec CSVRecordReader — skipNumLines, delimiter, quote)"""

    def __init__(self, path_or_text: Union[str, Path] = None,
                 skip_num_lines: int = 0, delimiter: str = ",",
                 quote: str = '"', text: Optional[str] = None):
        self.path = None if text is not None else str(path_or_text)
        self.text = text
        self.skip_num_lines = skip_num_lines
        self.delimiter = delimiter
        self.quote = quote
        self._rows: Optional[List[Record]] = None
        self._i = 0

    def _load(self):
        if self._rows is not None:
            return
        if self.text is not None:
            src = io.StringIO(self.text)
        else:
            src = open(self.path, newline="")
        with src:
            reader = csv.reader(src, delimiter=self.delimiter,
                                quotechar=self.quote)
            rows = list(reader)
        rows = rows[self.skip_num_lines:]
        self._rows = [[_parse_field(c) for c in row] for row in rows if row]

    def has_next(self):
        self._load()
        return self._i < len(self._rows)

    def next_record(self):
        self._load()
        r = self._rows[self._i]
        self._i += 1
        return list(r)

    def reset(self):
        self._i = 0


class CSVSequenceRecordReader(SequenceRecordReader):
    """One file per sequence, or one file with blank-line-separated
    sequences (ref: datavec CSVSequenceRecordReader)."""

    def __init__(self, paths: Union[str, Path, Sequence[Union[str, Path]]],
                 skip_num_lines: int = 0, delimiter: str = ","):
        if isinstance(paths, (str, Path)):
            paths = [paths]
        self.paths = [str(p) for p in paths]
        self.skip_num_lines = skip_num_lines
        self.delimiter = delimiter
        self._seqs: Optional[List[List[Record]]] = None
        self._i = 0

    def _load(self):
        if self._seqs is not None:
            return
        seqs: List[List[Record]] = []
        for p in self.paths:
            with open(p) as f:
                lines = [ln.rstrip("\n") for ln in f][self.skip_num_lines:]
            cur: List[Record] = []
            multi = any(not ln.strip() for ln in lines)
            for ln in lines:
                if not ln.strip():
                    if cur:
                        seqs.append(cur)
                        cur = []
                    continue
                cur.append([_parse_field(c)
                            for c in ln.split(self.delimiter)])
            if cur:
                seqs.append(cur)
            if not multi and not cur and not seqs:
                seqs.append([])
        self._seqs = seqs

    def has_next(self):
        self._load()
        return self._i < len(self._seqs)

    def next_sequence(self):
        self._load()
        s = self._seqs[self._i]
        self._i += 1
        return [list(r) for r in s]

    next_record = next_sequence

    def reset(self):
        self._i = 0


class ImageRecordReader(RecordReader):
    """Images from a labelled directory tree (ref: datavec-data-image
    ImageRecordReader + ParentPathLabelGenerator): each record is
    [flattened CHW float array, label index].  Resizes to (height,
    width); channels 1 = grayscale, 3 = RGB."""

    def __init__(self, height: int, width: int, channels: int = 3,
                 label_from_parent_dir: bool = True):
        self.height = height
        self.width = width
        self.channels = channels
        self.label_from_parent_dir = label_from_parent_dir
        self.labels: List[str] = []
        self._files: List[Path] = []
        self._i = 0

    EXTS = {".png", ".jpg", ".jpeg", ".bmp", ".gif", ".ppm", ".pgm", ".npy"}

    def initialize(self, root: Union[str, Path]) -> "ImageRecordReader":
        root = Path(root)
        self._files = sorted(p for p in root.rglob("*")
                             if p.suffix.lower() in self.EXTS)
        if self.label_from_parent_dir:
            self.labels = sorted({p.parent.name for p in self._files})
        self._i = 0
        return self

    def _load_image(self, path: Path) -> np.ndarray:
        if path.suffix.lower() == ".npy":
            arr = np.load(path)
            if arr.ndim == 2:
                arr = arr[None]
            elif arr.ndim == 3 and arr.shape[-1] in (1, 3, 4):
                arr = arr.transpose(2, 0, 1)
        else:
            from PIL import Image
            with Image.open(path) as im:
                im = im.convert("L" if self.channels == 1 else "RGB")
                im = im.resize((self.width, self.height))
                arr = np.asarray(im, np.float32)
            arr = arr[None] if arr.ndim == 2 else arr.transpose(2, 0, 1)
        # pad/trim channels, then resize check
        arr = arr[:self.channels]
        if arr.shape != (self.channels, self.height, self.width):
            out = np.zeros((self.channels, self.height, self.width),
                           np.float32)
            c = min(arr.shape[0], self.channels)
            h = min(arr.shape[1], self.height)
            w = min(arr.shape[2], self.width)
            out[:c, :h, :w] = arr[:c, :h, :w]
            arr = out
        return arr.astype(np.float32)

    def has_next(self):
        return self._i < len(self._files)

    def next_record(self):
        p = self._files[self._i]
        self._i += 1
        img = self._load_image(p)
        rec: Record = [img]
        if self.label_from_parent_dir:
            rec.append(self.labels.index(p.parent.name))
        return rec

    def reset(self):
        self._i = 0

    def num_labels(self) -> int:
        return len(self.labels)
