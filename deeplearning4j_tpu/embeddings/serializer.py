"""Word-vector persistence.

Mirrors the reference's ``WordVectorSerializer`` (ref: models/embeddings/
loader/WordVectorSerializer.java — original-C text & binary formats,
plus full-model zip with config json + vocab + syn0/syn1).
"""

from __future__ import annotations

import io
import json
import os
import struct
import zipfile
from typing import Optional

import numpy as np
import jax.numpy as jnp

from deeplearning4j_tpu.embeddings.lookup import InMemoryLookupTable
from deeplearning4j_tpu.embeddings.sequencevectors import (
    SequenceVectors, VectorsConfiguration)
from deeplearning4j_tpu.text.sequence import VocabWord
from deeplearning4j_tpu.text.vocab import AbstractCache, Huffman


class WordVectorSerializer:

    # -- original C text format -------------------------------------------
    @staticmethod
    def write_word_vectors(vectors, path: str) -> None:
        """``V D`` header then ``word f f f...`` per line (word2vec text)."""
        table = vectors.lookup_table
        vocab = vectors.vocab
        with open(path, "w", encoding="utf-8") as f:
            f.write(f"{vocab.num_words()} {table.vector_length}\n")
            syn0 = np.asarray(table.syn0)
            for i in range(vocab.num_words()):
                word = vocab.word_at_index(i)
                vals = " ".join(f"{x:.6f}" for x in syn0[i])
                f.write(f"{word.label} {vals}\n")

    @staticmethod
    def read_word_vectors(path: str) -> SequenceVectors:
        vocab = AbstractCache()
        rows = []
        with open(path, "r", encoding="utf-8") as f:
            header = f.readline().split()
            _v, d = int(header[0]), int(header[1])
            for line in f:
                parts = line.rstrip("\n").split(" ")
                if len(parts) < d + 1:
                    continue
                # Parse from the right: the last d fields are the vector,
                # everything before is the token (tokens may contain
                # spaces, e.g. n-grams or multi-word PV labels).
                word = VocabWord(" ".join(parts[:-d]))
                vocab.add_token(word)
                rows.append(np.array(parts[-d:], np.float32))
        # preserve file order as index order
        for i, label in enumerate(list(vocab._map)):
            vocab._map[label].index = i
        vocab._index = list(vocab._map.values())
        vocab.update_words_occurrences()
        sv = SequenceVectors(VectorsConfiguration(layer_size=d), vocab=vocab)
        sv.lookup_table = InMemoryLookupTable(vocab, d)
        sv.lookup_table.syn0 = jnp.asarray(np.stack(rows))
        return sv

    # -- original C binary format -----------------------------------------
    @staticmethod
    def write_binary(vectors, path: str) -> None:
        table = vectors.lookup_table
        vocab = vectors.vocab
        syn0 = np.asarray(table.syn0, np.float32)
        with open(path, "wb") as f:
            f.write(f"{vocab.num_words()} {table.vector_length}\n"
                    .encode("utf-8"))
            for i in range(vocab.num_words()):
                f.write(vocab.word_at_index(i).label.encode("utf-8") + b" ")
                f.write(syn0[i].tobytes())
                f.write(b"\n")

    @staticmethod
    def read_binary(path: str) -> SequenceVectors:
        with open(path, "rb") as f:
            header = f.readline().decode("utf-8").split()
            v, d = int(header[0]), int(header[1])
            vocab = AbstractCache()
            rows = []
            for _ in range(v):
                label = bytearray()
                while True:
                    ch = f.read(1)
                    if ch in (b" ", b""):
                        break
                    label += ch
                vec = np.frombuffer(f.read(4 * d), np.float32)
                f.read(1)  # trailing newline
                word = VocabWord(label.decode("utf-8"))
                vocab.add_token(word)
                rows.append(vec)
        for i, lab in enumerate(list(vocab._map)):
            vocab._map[lab].index = i
        vocab._index = list(vocab._map.values())
        sv = SequenceVectors(VectorsConfiguration(layer_size=d), vocab=vocab)
        sv.lookup_table = InMemoryLookupTable(vocab, d)
        sv.lookup_table.syn0 = jnp.asarray(np.stack(rows))
        return sv

    # -- full model zip ----------------------------------------------------
    @staticmethod
    def write_word2vec_model(vectors, path: str) -> None:
        """Zip: config.json + vocab.json + syn0/syn1/syn1neg .npy
        (ref: writeWord2VecModel's zip of config/vocab/syn arrays)."""
        table = vectors.lookup_table
        vocab = vectors.vocab
        with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
            z.writestr("config.json", json.dumps(vectors.conf.to_json()))
            vocab_entries = [
                {"label": w.label, "frequency": w.element_frequency,
                 "index": w.index, "codes": w.codes, "points": w.points,
                 "special": w.special, "isLabel": w.is_label}
                for w in vocab.vocab_words()]
            z.writestr("vocab.json", json.dumps(vocab_entries))
            for name in ("syn0", "syn1", "syn1neg"):
                arr = getattr(table, name)
                if arr is not None:
                    buf = io.BytesIO()
                    np.save(buf, np.asarray(arr))
                    z.writestr(f"{name}.npy", buf.getvalue())

    @staticmethod
    def read_word2vec_model(path: str, cls=None) -> SequenceVectors:
        cls = cls or SequenceVectors
        with zipfile.ZipFile(path, "r") as z:
            conf = VectorsConfiguration(**json.loads(z.read("config.json")))
            vocab = AbstractCache()
            entries = json.loads(z.read("vocab.json"))
            for e in entries:
                w = VocabWord(e["label"], e["frequency"])
                w.index = e["index"]
                w.codes = e["codes"]
                w.points = e["points"]
                w.special = e.get("special", False)
                w.is_label = e.get("isLabel", False)
                vocab._map[w.label] = w
            vocab._index = sorted(vocab._map.values(), key=lambda w: w.index)
            vocab.update_words_occurrences()
            sv = cls(conf)
            sv.vocab = vocab
            sv.lookup_table = InMemoryLookupTable(
                vocab, conf.layer_size, seed=conf.seed,
                use_hs=conf.use_hierarchic_softmax, negative=conf.negative)
            for name in ("syn0", "syn1", "syn1neg"):
                if f"{name}.npy" in z.namelist():
                    arr = np.load(io.BytesIO(z.read(f"{name}.npy")))
                    setattr(sv.lookup_table, name, jnp.asarray(arr))
        return sv
