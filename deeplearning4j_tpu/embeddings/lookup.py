"""Weight lookup table holding syn0 / syn1 / syn1neg on device.

Mirrors the reference's ``InMemoryLookupTable`` (ref: models/embeddings/
inmemory/InMemoryLookupTable.java — syn0 init U(-0.5,0.5)/D per word2vec
convention, syn1 zeros for hierarchical softmax, syn1neg zeros lazily for
negative sampling, plus the unigram^0.75 negative-sampling distribution
from makeTable).  Tables are jnp arrays living on the default device; the
negative-sampling distribution is kept as a host-side cdf sampled with
``np.searchsorted`` instead of the reference's 100M-entry lookup table.
"""

from __future__ import annotations

from typing import Optional

import numpy as np
import jax.numpy as jnp

from deeplearning4j_tpu.text.vocab import AbstractCache


class InMemoryLookupTable:

    def __init__(self, vocab: AbstractCache, vector_length: int,
                 seed: int = 12345, use_hs: bool = True,
                 negative: float = 0.0, dtype=jnp.float32):
        self.vocab = vocab
        self.vector_length = int(vector_length)
        self.seed = int(seed)
        self.use_hs = bool(use_hs)
        self.negative = float(negative)
        self.dtype = dtype
        self.syn0: Optional[jnp.ndarray] = None
        self.syn1: Optional[jnp.ndarray] = None
        self.syn1neg: Optional[jnp.ndarray] = None
        self._neg_cdf: Optional[np.ndarray] = None

    def reset_weights(self, reset: bool = True) -> None:
        v = self.vocab.num_words()
        d = self.vector_length
        if reset or self.syn0 is None:
            rng = np.random.default_rng(self.seed)
            # word2vec init: (rand - 0.5) / layer_size
            syn0 = (rng.random((v, d), dtype=np.float32) - 0.5) / d
            self.syn0 = jnp.asarray(syn0, self.dtype)
            # syn1 rows = inner Huffman nodes (v-1); keep >=1 row so the
            # kernels' gathers stay shape-stable when HS is off.
            n_inner = max(v - 1, 1)
            self.syn1 = jnp.zeros((n_inner if self.use_hs else 1, d),
                                  self.dtype)
            self.syn1neg = jnp.zeros((v if self.negative > 0 else 1, d),
                                     self.dtype)

    # -- negative sampling -------------------------------------------------
    def neg_sampler(self) -> np.ndarray:
        """Cumulative unigram^0.75 distribution over vocab indices."""
        if self._neg_cdf is None:
            freqs = np.array(
                [max(e.element_frequency, 1.0)
                 for e in self.vocab.vocab_words()], np.float64) ** 0.75
            self._neg_cdf = np.cumsum(freqs / freqs.sum())
        return self._neg_cdf

    def sample_negatives(self, rng: np.random.Generator, shape) -> np.ndarray:
        cdf = self.neg_sampler()
        return np.searchsorted(cdf, rng.random(shape)).astype(np.int32)

    # -- vector access -----------------------------------------------------
    def vector(self, label: str) -> Optional[np.ndarray]:
        idx = self.vocab.index_of(label)
        if idx < 0 or self.syn0 is None:
            return None
        return np.asarray(self.syn0[idx])

    def get_weights(self) -> np.ndarray:
        return np.asarray(self.syn0)

    def set_weights(self, w) -> None:
        self.syn0 = jnp.asarray(w, self.dtype)
