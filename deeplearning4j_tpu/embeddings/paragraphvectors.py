"""ParagraphVectors (doc2vec) — PV-DM / PV-DBOW.

Mirrors the reference (ref: models/paragraphvectors/ParagraphVectors.java
— label-aware sequences trained with learning/impl/sequence/{DBOW,DM}.java;
``inferVector`` trains a fresh vector against frozen tables).  Document
labels live in the same lookup table as words, exactly as the reference
stores labels in the shared vocab/lookup.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np
import jax.numpy as jnp

from deeplearning4j_tpu.embeddings import kernels
from deeplearning4j_tpu.embeddings.sequencevectors import VectorsConfiguration
from deeplearning4j_tpu.embeddings.word2vec import Word2Vec, _SentenceSequenceSource
from deeplearning4j_tpu.text.sequence import Sequence, VocabWord
from deeplearning4j_tpu.text.sentence_iterators import (
    LabelAwareSentenceIterator, LabelsSource, SentenceIterator)
from deeplearning4j_tpu.text.tokenization import TokenizerFactory


class _LabelledSource:
    """Attach labels (explicit or generated) to tokenized sentences."""

    def __init__(self, sentences: SentenceIterator, tf: TokenizerFactory,
                 labels_source: LabelsSource):
        self.sentences = sentences
        self.tf = tf
        self.labels_source = labels_source

    def __iter__(self):
        self.sentences.reset()
        self.labels_source.reset()
        label_aware = isinstance(self.sentences, LabelAwareSentenceIterator)
        while self.sentences.has_next():
            sentence = self.sentences.next_sentence()
            seq = Sequence()
            for tok in self.tf.create(sentence).get_tokens():
                if tok:
                    seq.add_element(VocabWord(tok))
            if label_aware:
                label = self.sentences.current_label()
                self.labels_source.store_label(label)
            else:
                label = self.labels_source.next_label()
            lbl = VocabWord(label)
            lbl.special = True
            seq.set_sequence_label(lbl)
            yield seq


class ParagraphVectors(Word2Vec):

    def __init__(self, conf: Optional[VectorsConfiguration] = None):
        conf = conf or VectorsConfiguration()
        conf.train_sequences = True
        super().__init__(conf)
        self.labels_source = LabelsSource()

    class Builder(Word2Vec.Builder):
        def __init__(self, configuration: Optional[VectorsConfiguration] = None):
            super().__init__(configuration)
            self._labels_source = LabelsSource()
            # PV-DM is the reference default sequence algorithm
            self.conf.sequence_learning_algorithm = "DM"
            self.conf.train_sequences = True

        def labels_source(self, source: LabelsSource):
            self._labels_source = source
            return self

        def labels(self, labels: List[str]):
            self._labels_source = LabelsSource(labels=labels)
            return self

        def train_word_vectors(self, b: bool):
            self.conf.train_elements = b
            return self

        def build(self) -> "ParagraphVectors":
            pv = ParagraphVectors(self.conf)
            pv.labels_source = self._labels_source
            if self._sentences is not None:
                pv._sequence_source = _LabelledSource(
                    self._sentences, self._tf, self._labels_source)
            else:
                pv._sequence_source = self._source
            pv.vocab = self._vocab
            return pv

    # -- inference ---------------------------------------------------------
    def infer_vector(self, text_or_tokens, steps: int = 10,
                     learning_rate: float = 0.01) -> np.ndarray:
        """Train a fresh doc vector against the frozen tables
        (ref: ParagraphVectors.inferVector → SkipGram.iterateSample with
        isInference=true updating only inferenceVector)."""
        if isinstance(text_or_tokens, str):
            from deeplearning4j_tpu.text.tokenization import DefaultTokenizerFactory
            tokens = DefaultTokenizerFactory().create(text_or_tokens).get_tokens()
        else:
            tokens = list(text_or_tokens)
        ids = [self.vocab.index_of(t) for t in tokens]
        ids = np.array([i for i in ids if i >= 0], np.int32)
        D = self.conf.layer_size
        rng = np.random.default_rng(self.conf.seed)
        vec = jnp.asarray((rng.random((1, D), dtype=np.float32) - 0.5) / D)
        if ids.size == 0:
            return np.asarray(vec[0])

        points_m, codes_m, cmask_m = self._code_matrices()
        t = self.lookup_table
        K = max(self.conf.negative, 0) + 1
        for _step in range(steps):
            for center in ids:
                pts = jnp.asarray(points_m[None, center])
                codes = jnp.asarray(codes_m[None, center])
                cmask = jnp.asarray(cmask_m[None, center]
                                    if self.conf.use_hierarchic_softmax
                                    else np.zeros_like(cmask_m[None, center]))
                nidx = np.zeros((1, K), np.int32)
                nidx[0, 0] = center
                nlab = np.zeros((1, K), np.float32)
                nlab[0, 0] = 1.0
                nmask = np.zeros((1, K), np.float32)
                if self.conf.negative > 0:
                    negs = t.sample_negatives(rng, (1, K - 1))
                    nidx[0, 1:] = negs
                    nmask[:] = 1.0
                    nmask[0, 1:] = (negs != center).astype(np.float32)
                vec = kernels.infer_step(
                    vec, t.syn1, t.syn1neg, pts, codes, cmask,
                    jnp.asarray(nidx), jnp.asarray(nlab), jnp.asarray(nmask),
                    jnp.asarray([learning_rate], np.float32))
        return np.asarray(vec[0])

    # -- label queries ------------------------------------------------------
    def nearest_labels(self, text_or_vec, top: int = 5) -> List[str]:
        if isinstance(text_or_vec, (str, list)):
            vec = self.infer_vector(text_or_vec)
        else:
            vec = np.asarray(text_or_vec)
        labels = [l for l in self.labels_source.get_labels()
                  if self.vocab.contains_word(l)]
        if not labels:
            return []
        table = np.stack([self.word_vector(l) for l in labels])
        table = table / np.maximum(
            np.linalg.norm(table, axis=1, keepdims=True), 1e-12)
        v = vec / max(np.linalg.norm(vec), 1e-12)
        order = np.argsort(-(table @ v))[:top]
        return [labels[i] for i in order]

    def similarity_to_label(self, text_or_vec, label: str) -> float:
        if isinstance(text_or_vec, (str, list)):
            vec = self.infer_vector(text_or_vec)
        else:
            vec = np.asarray(text_or_vec)
        lv = self.word_vector(label)
        if lv is None:
            return float("nan")
        return float(np.dot(vec, lv) /
                     max(np.linalg.norm(vec) * np.linalg.norm(lv), 1e-12))


ParagraphVectors.Builder._vectors_cls = ParagraphVectors
