"""Embedding models: SequenceVectors, Word2Vec, ParagraphVectors, GloVe.

TPU-native re-realization of the reference's embedding stack
(ref: models/sequencevectors/SequenceVectors.java, models/word2vec/,
models/paragraphvectors/, models/glove/).  The reference's hot loop is a
fused native op per (center, context) pair batched 4096-at-a-time into
libnd4j (ref: models/embeddings/learning/impl/elements/SkipGram.java:271
``AggregateSkipGram``).  Here the equivalent is a single jitted XLA
program per batch of pairs: gather rows → dense sigmoid/GEMM math on the
MXU → scatter-add updates, with buffers donated so XLA updates in place.
"""

from deeplearning4j_tpu.embeddings.lookup import InMemoryLookupTable  # noqa: F401
from deeplearning4j_tpu.embeddings.sequencevectors import (  # noqa: F401
    SequenceVectors,
    VectorsConfiguration,
)
from deeplearning4j_tpu.embeddings.word2vec import Word2Vec  # noqa: F401
from deeplearning4j_tpu.embeddings.paragraphvectors import ParagraphVectors  # noqa: F401
from deeplearning4j_tpu.embeddings.glove import Glove  # noqa: F401
from deeplearning4j_tpu.embeddings.serializer import WordVectorSerializer  # noqa: F401
