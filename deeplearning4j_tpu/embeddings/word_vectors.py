"""WordVectors query API — nearest words, similarity, arithmetic.

Mirrors the reference's ``WordVectors`` interface + ``BasicModelUtils``
(ref: models/embeddings/wordvectors/WordVectorsImpl.java,
models/embeddings/reader/impl/BasicModelUtils.java — cosine similarity
over mean-of-positive-minus-negative query vectors).  Queries run as one
matmul over the normalized table — on TPU this is a single MXU pass.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Sequence

import numpy as np


class WordVectorsMixin:
    """Query surface over (vocab, lookup_table)."""

    vocab = None
    lookup_table = None

    # -- basics ------------------------------------------------------------
    def has_word(self, word: str) -> bool:
        return self.vocab is not None and self.vocab.contains_word(word)

    def word_vector(self, word: str) -> Optional[np.ndarray]:
        return self.lookup_table.vector(word)

    getWordVectorMatrix = word_vector

    def vocab_size(self) -> int:
        return self.vocab.num_words()

    def _table(self) -> np.ndarray:
        return np.asarray(self.lookup_table.syn0, np.float32)

    def _normed_table(self) -> np.ndarray:
        t = self._table()
        norms = np.linalg.norm(t, axis=1, keepdims=True)
        return t / np.maximum(norms, 1e-12)

    # -- similarity --------------------------------------------------------
    def similarity(self, w1: str, w2: str) -> float:
        v1, v2 = self.word_vector(w1), self.word_vector(w2)
        if v1 is None or v2 is None:
            return float("nan")
        denom = (np.linalg.norm(v1) * np.linalg.norm(v2))
        if denom == 0:
            return 0.0
        return float(np.dot(v1, v2) / denom)

    def words_nearest(self, positive, negative=(), top: int = 10) -> List[str]:
        """Analogy query: nearest to mean(positive) - mean(negative)."""
        if isinstance(positive, str):
            positive = [positive]
        query = np.zeros(self.lookup_table.vector_length, np.float32)
        exclude = set()
        for w in positive:
            v = self.word_vector(w)
            if v is not None:
                query += v / max(np.linalg.norm(v), 1e-12)
                exclude.add(w)
        for w in negative:
            v = self.word_vector(w)
            if v is not None:
                query -= v / max(np.linalg.norm(v), 1e-12)
                exclude.add(w)
        qn = np.linalg.norm(query)
        if qn == 0:
            return []
        sims = self._normed_table() @ (query / qn)
        order = np.argsort(-sims)
        out: List[str] = []
        for idx in order:
            w = self.vocab.word_at_index(int(idx))
            if w is None or w.label in exclude:
                continue
            out.append(w.label)
            if len(out) >= top:
                break
        return out

    wordsNearest = words_nearest

    def words_nearest_vector(self, vector: np.ndarray, top: int = 10) -> List[str]:
        v = np.asarray(vector, np.float32)
        v = v / max(np.linalg.norm(v), 1e-12)
        sims = self._normed_table() @ v
        order = np.argsort(-sims)[:top]
        return [self.vocab.word_at_index(int(i)).label for i in order]

    def similar_words_in_vocab_to(self, word: str, accuracy: float) -> List[str]:
        v = self.word_vector(word)
        if v is None:
            return []
        sims = self._normed_table() @ (v / max(np.linalg.norm(v), 1e-12))
        return [self.vocab.word_at_index(int(i)).label
                for i in np.nonzero(sims >= accuracy)[0]
                if self.vocab.word_at_index(int(i)).label != word]
