"""SequenceVectors — the generic embedding trainer.

Mirrors the reference engine (ref: models/sequencevectors/
SequenceVectors.java:51 — fit() at :187: vocab construction, then an
``AsyncSequencer`` producer thread (:996) feeding
``VectorCalculationsThread`` workers (:1101) that queue fused native ops).

TPU-first redesign: the producer thread is kept (host-side ETL overlap),
but the N CPU worker threads collapse into ONE device stream — the host
assembles fixed-shape integer batches of training pairs and each flush is
a single jitted XLA scatter/gather program (see
``deeplearning4j_tpu.embeddings.kernels``).  Learning-rate decay follows
word2vec: linear from ``learning_rate`` down to ``min_learning_rate``
over the expected total word count.
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass, field, asdict
from typing import Iterable, List, Optional

import numpy as np
import jax.numpy as jnp

from deeplearning4j_tpu.embeddings import kernels
from deeplearning4j_tpu.embeddings.lookup import InMemoryLookupTable
from deeplearning4j_tpu.embeddings.word_vectors import WordVectorsMixin
from deeplearning4j_tpu.native.io import skipgram_pairs
from deeplearning4j_tpu.text.sequence import Sequence, SequenceElement
from deeplearning4j_tpu.text.vocab import AbstractCache, VocabConstructor


@dataclass
class VectorsConfiguration:
    """Hyperparameters (ref: models/embeddings/loader/VectorsConfiguration.java)."""

    layer_size: int = 100
    window: int = 5
    epochs: int = 1
    iterations: int = 1
    learning_rate: float = 0.025
    min_learning_rate: float = 1e-4
    negative: int = 0
    sampling: float = 0.0
    min_word_frequency: int = 1
    use_hierarchic_softmax: bool = True
    batch_size: int = 2048
    seed: int = 12345
    elements_learning_algorithm: str = "SkipGram"   # or "CBOW"
    sequence_learning_algorithm: str = "DBOW"       # or "DM"
    train_elements: bool = True
    train_sequences: bool = False
    max_labels_per_sequence: int = 1

    def to_json(self) -> dict:
        return asdict(self)


class _BatchBuffer:
    """Accumulates training examples; flushes fixed-shape device batches.

    Static shapes per flush (B pairs × C codes × K negative columns ×
    W window slots) so each kernel compiles once.
    """

    def __init__(self, table: InMemoryLookupTable, conf: VectorsConfiguration,
                 points_m: np.ndarray, codes_m: np.ndarray,
                 code_mask_m: np.ndarray, rng: np.random.Generator,
                 window_width: int):
        self.table = table
        self.conf = conf
        self.points_m = points_m      # (V, C) int32
        self.codes_m = codes_m        # (V, C) f32  (1 - code)
        self.code_mask_m = code_mask_m
        self.rng = rng
        self.W = window_width
        self.K = max(int(conf.negative), 0) + 1
        self.sg_ctx: List[int] = []
        self.sg_center: List[int] = []
        self.sg_alpha: List[float] = []
        # bulk intake: whole-sentence pair arrays from the native/numpy
        # windowing path (native.io.skipgram_pairs) — no per-pair Python
        self.sg_chunks: List[tuple] = []
        self._sg_bulk_n = 0
        self.cb_win: List[List[int]] = []
        self.cb_center: List[int] = []
        self.cb_alpha: List[float] = []

    # -- example intake ---------------------------------------------------
    def add_pair(self, ctx: int, center: int, alpha: float):
        self.sg_ctx.append(ctx)
        self.sg_center.append(center)
        self.sg_alpha.append(alpha)
        if len(self.sg_ctx) + self._sg_bulk_n >= self.conf.batch_size:
            self.flush_sg(final=False)

    def add_pairs_bulk(self, ctx: np.ndarray, center: np.ndarray,
                       alpha: float):
        """Whole arrays of (context, center) pairs at one learning rate —
        the sentence-at-a-time fast path."""
        if ctx.size == 0:
            return
        self.sg_chunks.append((ctx, center, float(alpha)))
        self._sg_bulk_n += int(ctx.size)
        if len(self.sg_ctx) + self._sg_bulk_n >= self.conf.batch_size:
            self.flush_sg(final=False)

    def add_window(self, window_rows: List[int], center: int, alpha: float):
        self.cb_win.append(window_rows)
        self.cb_center.append(center)
        self.cb_alpha.append(alpha)
        if len(self.cb_win) >= self.conf.batch_size:
            self.flush_cbow()

    # -- helpers ----------------------------------------------------------
    def _hs_neg_arrays(self, center: np.ndarray, pair_mask: np.ndarray):
        conf = self.conf
        points = self.points_m[center]
        codes = self.codes_m[center]
        cmask = self.code_mask_m[center] * pair_mask[:, None]
        if not conf.use_hierarchic_softmax:
            cmask = np.zeros_like(cmask)
        B = center.shape[0]
        neg_idx = np.zeros((B, self.K), np.int32)
        neg_idx[:, 0] = center
        neg_label = np.zeros((B, self.K), np.float32)
        neg_label[:, 0] = 1.0
        neg_mask = np.zeros((B, self.K), np.float32)
        if conf.negative > 0:
            negs = self.table.sample_negatives(self.rng, (B, self.K - 1))
            neg_idx[:, 1:] = negs
            neg_mask[:, :] = 1.0
            # word2vec skips a sampled negative equal to the target
            neg_mask[:, 1:] = (negs != center[:, None]).astype(np.float32)
        neg_mask *= pair_mask[:, None]
        return points, codes, cmask, neg_idx, neg_label, neg_mask

    # -- flushes ----------------------------------------------------------
    def flush_sg(self, final: bool = True):
        """Launch skip-gram kernel batches.  Auto-flushes (final=False)
        only process FULL batch_size slices and keep the tail buffered —
        a padded partial batch per sentence would double kernel launches
        for nothing; the tail rides along until the next full batch (or
        the end-of-training flush())."""
        total = len(self.sg_ctx) + self._sg_bulk_n
        if total == 0:
            return
        B = self.conf.batch_size
        if not final and total < B:
            return
        parts_ctx, parts_ctr, parts_a = [], [], []
        if self.sg_ctx:
            parts_ctx.append(np.asarray(self.sg_ctx, np.int32))
            parts_ctr.append(np.asarray(self.sg_center, np.int32))
            parts_a.append(np.asarray(self.sg_alpha, np.float32))
        for c, t_, a in self.sg_chunks:
            parts_ctx.append(np.asarray(c, np.int32))
            parts_ctr.append(np.asarray(t_, np.int32))
            parts_a.append(np.asarray(a, np.float32) if np.ndim(a)
                           else np.full(c.size, a, np.float32))
        ctx_all = np.concatenate(parts_ctx)
        ctr_all = np.concatenate(parts_ctr)
        a_all = np.concatenate(parts_a)
        self.sg_ctx, self.sg_center, self.sg_alpha = [], [], []
        self.sg_chunks, self._sg_bulk_n = [], 0

        stop = total if final else (total // B) * B
        t = self.table
        for s in range(0, stop, B):
            n = min(B, stop - s)
            ctx = np.zeros(B, np.int32)
            center = np.zeros(B, np.int32)
            alpha = np.zeros(B, np.float32)
            pair_mask = np.zeros(B, np.float32)
            ctx[:n] = ctx_all[s:s + n]
            center[:n] = ctr_all[s:s + n]
            alpha[:n] = a_all[s:s + n]
            pair_mask[:n] = 1.0
            pts, codes, cmask, nidx, nlab, nmask = self._hs_neg_arrays(
                center, pair_mask)
            t.syn0, t.syn1, t.syn1neg = kernels.skipgram_step(
                t.syn0, t.syn1, t.syn1neg,
                jnp.asarray(ctx), jnp.asarray(pts), jnp.asarray(codes),
                jnp.asarray(cmask), jnp.asarray(nidx), jnp.asarray(nlab),
                jnp.asarray(nmask), jnp.asarray(alpha))
        if stop < total:  # re-buffer the tail (per-pair alphas preserved)
            self.sg_chunks.append((ctx_all[stop:], ctr_all[stop:],
                                   a_all[stop:]))
            self._sg_bulk_n = total - stop

    def flush_cbow(self):
        if not self.cb_win:
            return
        B = self.conf.batch_size
        n = len(self.cb_win)
        win = np.zeros((B, self.W), np.int32)
        wmask = np.zeros((B, self.W), np.float32)
        center = np.zeros(B, np.int32)
        alpha = np.zeros(B, np.float32)
        pair_mask = np.zeros(B, np.float32)
        for i, rows in enumerate(self.cb_win):
            rows = rows[:self.W]
            win[i, :len(rows)] = rows
            wmask[i, :len(rows)] = 1.0
        center[:n] = self.cb_center
        alpha[:n] = self.cb_alpha
        pair_mask[:n] = 1.0
        wmask *= pair_mask[:, None]
        pts, codes, cmask, nidx, nlab, nmask = self._hs_neg_arrays(
            center, pair_mask)
        t = self.table
        t.syn0, t.syn1, t.syn1neg = kernels.cbow_step(
            t.syn0, t.syn1, t.syn1neg,
            jnp.asarray(win), jnp.asarray(wmask), jnp.asarray(pts),
            jnp.asarray(codes), jnp.asarray(cmask), jnp.asarray(nidx),
            jnp.asarray(nlab), jnp.asarray(nmask), jnp.asarray(alpha))
        self.cb_win, self.cb_center, self.cb_alpha = [], [], []

    def flush(self):
        self.flush_sg()
        self.flush_cbow()


class SequenceVectors(WordVectorsMixin):
    """Generic trainer over ``Sequence`` streams (ref: SequenceVectors.java)."""

    def __init__(self, conf: Optional[VectorsConfiguration] = None,
                 vocab: Optional[AbstractCache] = None,
                 lookup_table: Optional[InMemoryLookupTable] = None):
        self.conf = conf or VectorsConfiguration()
        self.vocab = vocab
        self.lookup_table = lookup_table
        self._sequence_source: Optional[Iterable[Sequence]] = None

    # -- builder ----------------------------------------------------------
    class Builder:
        _vectors_cls = None  # set below

        def __init__(self, configuration: Optional[VectorsConfiguration] = None):
            self.conf = configuration or VectorsConfiguration()
            self._source: Optional[Iterable[Sequence]] = None
            self._vocab: Optional[AbstractCache] = None

        def iterate(self, source: Iterable[Sequence]):
            self._source = source
            return self

        def vocab_cache(self, vocab: AbstractCache):
            self._vocab = vocab
            return self

        def layer_size(self, n):           self.conf.layer_size = n; return self
        def window_size(self, n):          self.conf.window = n; return self
        def epochs(self, n):               self.conf.epochs = n; return self
        def iterations(self, n):           self.conf.iterations = n; return self
        def learning_rate(self, lr):       self.conf.learning_rate = lr; return self
        def min_learning_rate(self, lr):   self.conf.min_learning_rate = lr; return self
        def negative_sample(self, k):      self.conf.negative = int(k); return self
        def sampling(self, s):             self.conf.sampling = s; return self
        def min_word_frequency(self, n):   self.conf.min_word_frequency = n; return self
        def use_hierarchic_softmax(self, b): self.conf.use_hierarchic_softmax = b; return self
        def batch_size(self, n):           self.conf.batch_size = n; return self
        def seed(self, n):                 self.conf.seed = n; return self

        def elements_learning_algorithm(self, name: str):
            self.conf.elements_learning_algorithm = name
            return self

        def sequence_learning_algorithm(self, name: str):
            self.conf.sequence_learning_algorithm = name
            return self

        def train_elements_representation(self, b: bool):
            self.conf.train_elements = b
            return self

        def train_sequences_representation(self, b: bool):
            self.conf.train_sequences = b
            return self

        def build(self) -> "SequenceVectors":
            sv = (self._vectors_cls or SequenceVectors)(self.conf)
            sv._sequence_source = self._source
            sv.vocab = self._vocab
            return sv

    # -- vocab + tables ----------------------------------------------------
    def build_vocab(self) -> None:
        if self.vocab is None:
            ctor = VocabConstructor(
                min_element_frequency=self.conf.min_word_frequency,
                build_huffman=True)
            ctor.add_source(self._sequence_source)
            self.vocab = ctor.build_joint_vocabulary()
        if self.lookup_table is None:
            self.lookup_table = InMemoryLookupTable(
                self.vocab, self.conf.layer_size, seed=self.conf.seed,
                use_hs=self.conf.use_hierarchic_softmax,
                negative=self.conf.negative)
        # Initialize only if absent — never wipe pretrained/deserialized
        # weights on a refit (reference resetModel(false) semantics).
        self.lookup_table.reset_weights(reset=self.lookup_table.syn0 is None)
        self._cached_code_matrices = None

    _cached_code_matrices = None

    def _code_matrices(self):
        if self._cached_code_matrices is not None:
            return self._cached_code_matrices
        words = self.vocab.vocab_words()
        V = len(words)
        C = max((w.code_length for w in words), default=1) or 1
        points = np.zeros((V, C), np.int32)
        codes = np.zeros((V, C), np.float32)
        mask = np.zeros((V, C), np.float32)
        for w in words:
            L = w.code_length
            points[w.index, :L] = w.points
            # kernel target = 1 - code (sigmoid should output 1 for code 0)
            codes[w.index, :L] = 1.0 - np.asarray(w.codes, np.float32)
            mask[w.index, :L] = 1.0
        self._cached_code_matrices = (points, codes, mask)
        return self._cached_code_matrices

    def _resolved_sequences(self):
        """Resolve raw elements/labels to the vocab's indexed instances.

        Sequence sources typically stream fresh elements with index -1
        (the vocab constructor stores its own copies); training needs the
        indexed instances, so every element is looked up by label and
        unknown/filtered elements are dropped."""
        vocab = self.vocab
        for seq in self._sequence_source:
            out = Sequence()
            for el in seq.elements:
                if el.index >= 0:
                    out.add_element(el)
                else:
                    known = vocab.word_for(el.label)
                    if known is not None:
                        out.add_element(known)
            for lbl in seq.labels:
                if lbl.index >= 0:
                    out.add_sequence_label(lbl)
                else:
                    known = vocab.word_for(lbl.label)
                    if known is not None:
                        out.add_sequence_label(known)
            if out.size() > 0 or out.labels:
                yield out

    # -- training ----------------------------------------------------------
    def fit(self) -> None:
        assert self._sequence_source is not None, "no sequence source set"
        self.build_vocab()
        conf = self.conf
        rng = np.random.default_rng(conf.seed)
        points_m, codes_m, cmask_m = self._code_matrices()
        window_width = 2 * conf.window + conf.max_labels_per_sequence
        buf = _BatchBuffer(self.lookup_table, conf, points_m, codes_m,
                           cmask_m, rng, window_width)

        total_words = max(self.vocab.total_word_count, 1.0)
        expected = total_words * conf.epochs * conf.iterations
        processed = 0.0

        # keep-probability per word index for subsampling
        keep = None
        if conf.sampling > 0:
            freqs = np.array([w.element_frequency
                              for w in self.vocab.vocab_words()])
            ratio = conf.sampling * total_words / np.maximum(freqs, 1.0)
            keep = np.minimum(1.0, np.sqrt(ratio) + ratio)

        for _epoch in range(conf.epochs):
            for seq in self._prefetch(self._resolved_sequences()):
                ids = np.array([e.index for e in seq.elements
                                if e.index >= 0 and not e.is_label],
                               np.int32)
                label_ids = [l.index for l in seq.labels
                             if l.index is not None and l.index >= 0]
                if ids.size == 0:
                    continue
                if keep is not None:
                    ids = ids[rng.random(ids.size) < keep[ids]]
                    if ids.size == 0:
                        continue
                for _it in range(conf.iterations):
                    alpha = max(conf.min_learning_rate,
                                conf.learning_rate *
                                (1.0 - processed / (expected + 1.0)))
                    if conf.train_elements:
                        self._learn_elements(ids, alpha, conf, rng, buf)
                    if conf.train_sequences and label_ids:
                        self._learn_sequence(ids, label_ids, alpha, conf,
                                             rng, buf)
                    processed += float(ids.size)
        buf.flush()

    def _prefetch(self, source, capacity: int = 256):
        """AsyncSequencer parity (ref: SequenceVectors.java:996) — a
        producer thread decouples sequence iteration/tokenization from
        device-batch assembly."""
        q: "queue.Queue" = queue.Queue(maxsize=capacity)
        SENTINEL = object()
        error: list = []

        def produce():
            try:
                for s in source:
                    q.put(s)
            except BaseException as exc:  # re-raised on the consumer side
                error.append(exc)
            finally:
                q.put(SENTINEL)

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is SENTINEL:
                break
            yield item
        if error:
            raise error[0]

    def _learn_elements(self, ids, alpha, conf, rng, buf: _BatchBuffer):
        n = ids.size
        algo = conf.elements_learning_algorithm.lower()
        # reduced-window per center, word2vec style
        bs = rng.integers(0, conf.window, size=n)
        if algo == "skipgram":
            # whole-sentence pair generation in native code (numpy
            # fallback) — the per-pair Python loop was the throughput
            # ceiling of the fit() path
            ctx, ctr = skipgram_pairs(ids, conf.window,
                                      bs.astype(np.int32))
            buf.add_pairs_bulk(ctx, ctr, alpha)
        elif algo == "cbow":
            for i in range(n):
                lo = max(0, i - conf.window + bs[i])
                hi = min(n, i + conf.window - bs[i] + 1)
                rows = [int(ids[c]) for c in range(lo, hi) if c != i]
                if rows:
                    buf.add_window(rows, int(ids[i]), alpha)
        else:
            raise ValueError(f"unknown elements algorithm {algo!r}")

    def _learn_sequence(self, ids, label_ids, alpha, conf, rng,
                        buf: _BatchBuffer):
        algo = conf.sequence_learning_algorithm.lower()
        if algo == "dbow":
            # ref: learning/impl/sequence/DBOW.java — label vector predicts
            # every word (skip-gram with the label as the input row).
            lbl_arr = np.asarray(label_ids, np.int32)
            buf.add_pairs_bulk(np.repeat(lbl_arr, ids.size),
                               np.tile(ids.astype(np.int32), lbl_arr.size),
                               alpha)
        elif algo == "dm":
            # ref: learning/impl/sequence/DM.java — CBOW windows with the
            # label vector(s) appended to the context.
            n = ids.size
            bs = rng.integers(0, conf.window, size=n)
            for i in range(n):
                lo = max(0, i - conf.window + bs[i])
                hi = min(n, i + conf.window - bs[i] + 1)
                rows = [int(ids[c]) for c in range(lo, hi) if c != i]
                rows += [int(l) for l in label_ids]
                if rows:
                    buf.add_window(rows, int(ids[i]), alpha)
        else:
            raise ValueError(f"unknown sequence algorithm {algo!r}")


SequenceVectors.Builder._vectors_cls = SequenceVectors
