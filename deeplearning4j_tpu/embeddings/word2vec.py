"""Word2Vec — SequenceVectors over tokenized sentences.

Mirrors the reference's builder surface (ref: models/word2vec/
Word2Vec.java:32 — Builder.iterate(SentenceIterator) + tokenizerFactory,
inherited SequenceVectors hyperparameters).  Sentences are tokenized
lazily into ``Sequence`` streams; vocab filtering/stopwords happen here.
"""

from __future__ import annotations

from typing import Iterable, List, Optional

from deeplearning4j_tpu.embeddings.sequencevectors import (
    SequenceVectors, VectorsConfiguration)
from deeplearning4j_tpu.text.sequence import Sequence, VocabWord
from deeplearning4j_tpu.text.sentence_iterators import SentenceIterator
from deeplearning4j_tpu.text.tokenization import (
    DefaultTokenizerFactory, TokenizerFactory)


class _SentenceSequenceSource:
    """Re-iterable sentence→Sequence adapter (ref: Word2Vec Builder wires a
    SentenceTransformer over the iterator)."""

    def __init__(self, sentences: SentenceIterator,
                 tokenizer_factory: TokenizerFactory,
                 stop_words: Optional[set] = None):
        self.sentences = sentences
        self.tf = tokenizer_factory
        self.stop_words = stop_words or set()

    def __iter__(self):
        self.sentences.reset()
        for sentence in self.sentences:
            tokens = self.tf.create(sentence).get_tokens()
            seq = Sequence()
            for tok in tokens:
                if tok and tok not in self.stop_words:
                    seq.add_element(VocabWord(tok))
            if seq.size() > 0:
                # indices resolve against the built vocab at training time
                yield seq


class Word2Vec(SequenceVectors):

    class Builder(SequenceVectors.Builder):
        def __init__(self, configuration: Optional[VectorsConfiguration] = None):
            super().__init__(configuration)
            self._sentences: Optional[SentenceIterator] = None
            self._tf: TokenizerFactory = DefaultTokenizerFactory()
            self._stop_words: set = set()

        def iterate(self, source):
            if isinstance(source, SentenceIterator):
                self._sentences = source
            else:
                self._source = source
            return self

        def tokenizer_factory(self, tf: TokenizerFactory):
            self._tf = tf
            return self

        def stop_words(self, words: Iterable[str]):
            self._stop_words = set(words)
            return self

        def build(self) -> "Word2Vec":
            w2v = Word2Vec(self.conf)
            if self._sentences is not None:
                w2v._sequence_source = _SentenceSequenceSource(
                    self._sentences, self._tf, self._stop_words)
            else:
                w2v._sequence_source = self._source
            w2v.vocab = self._vocab
            return w2v

Word2Vec.Builder._vectors_cls = Word2Vec
