"""GloVe — co-occurrence weighted least squares with AdaGrad.

Mirrors the reference (ref: models/glove/Glove.java:1-429 +
glove/count/* co-occurrence accumulation; GloVe objective
f(X_ij)·(w_i·w̃_j + b_i + b̃_j − log X_ij)² with per-weight AdaGrad).
TPU-first: the co-occurrence map is built on host, then shuffled into
fixed-size (i, j, X_ij) batches; ONE jitted XLA program per batch does
gather → residual → AdaGrad scatter-add on both vector tables.
"""

from __future__ import annotations

import functools
from collections import defaultdict
from typing import Dict, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from deeplearning4j_tpu.embeddings.lookup import InMemoryLookupTable
from deeplearning4j_tpu.embeddings.sequencevectors import VectorsConfiguration
from deeplearning4j_tpu.embeddings.word_vectors import WordVectorsMixin
from deeplearning4j_tpu.text.sequence import Sequence, VocabWord
from deeplearning4j_tpu.text.sentence_iterators import SentenceIterator
from deeplearning4j_tpu.text.tokenization import (
    DefaultTokenizerFactory, TokenizerFactory)
from deeplearning4j_tpu.text.vocab import VocabConstructor


@functools.partial(jax.jit, donate_argnums=(0, 1, 2, 3, 4, 5, 6, 7))
def _glove_step(w, wt, b, bt, hw, hwt, hb, hbt,
                rows, cols, logx, fx, lr, valid):
    """AdaGrad step on a batch of co-occurrence cells.

    w/wt: (V,D) main/context vectors; b/bt: (V,) biases;
    h*: AdaGrad accumulators (donated alongside).
    """
    wi = jnp.take(w, rows, axis=0)        # (B, D)
    wj = jnp.take(wt, cols, axis=0)
    diff = (jnp.einsum("bd,bd->b", wi, wj) + jnp.take(b, rows)
            + jnp.take(bt, cols) - logx)
    fdiff = fx * diff * valid             # (B,)

    gw = fdiff[:, None] * wj
    gwt = fdiff[:, None] * wi
    gb = fdiff
    # AdaGrad: accumulate squared grads, scale update
    hw_new = jnp.take(hw, rows, axis=0) + gw * gw
    hwt_new = jnp.take(hwt, cols, axis=0) + gwt * gwt
    hb_new = jnp.take(hb, rows) + gb * gb
    hbt_new = jnp.take(hbt, cols) + gb * gb

    w = w.at[rows].add(-lr * gw / jnp.sqrt(hw_new + 1e-8), mode="drop")
    wt = wt.at[cols].add(-lr * gwt / jnp.sqrt(hwt_new + 1e-8), mode="drop")
    b = b.at[rows].add(-lr * gb / jnp.sqrt(hb_new + 1e-8), mode="drop")
    bt = bt.at[cols].add(-lr * gb / jnp.sqrt(hbt_new + 1e-8), mode="drop")
    hw = hw.at[rows].add(gw * gw, mode="drop")
    hwt = hwt.at[cols].add(gwt * gwt, mode="drop")
    hb = hb.at[rows].add(gb * gb, mode="drop")
    hbt = hbt.at[cols].add(gb * gb, mode="drop")
    loss = 0.5 * jnp.sum(fdiff * diff)
    return w, wt, b, bt, hw, hwt, hb, hbt, loss


class Glove(WordVectorsMixin):

    def __init__(self, conf: Optional[VectorsConfiguration] = None,
                 x_max: float = 100.0, alpha: float = 0.75,
                 symmetric: bool = True, shuffle: bool = True):
        self.conf = conf or VectorsConfiguration(learning_rate=0.05)
        self.x_max = x_max
        self.alpha = alpha
        self.symmetric = symmetric
        self.shuffle = shuffle
        self.vocab = None
        self.lookup_table: Optional[InMemoryLookupTable] = None
        self._sentences: Optional[SentenceIterator] = None
        self._tf: TokenizerFactory = DefaultTokenizerFactory()

    class Builder:
        def __init__(self):
            self.conf = VectorsConfiguration(learning_rate=0.05)
            self._x_max = 100.0
            self._alpha = 0.75
            self._symmetric = True
            self._shuffle = True
            self._sentences = None
            self._tf = DefaultTokenizerFactory()

        def iterate(self, s):              self._sentences = s; return self
        def tokenizer_factory(self, tf):   self._tf = tf; return self
        def layer_size(self, n):           self.conf.layer_size = n; return self
        def learning_rate(self, lr):       self.conf.learning_rate = lr; return self
        def epochs(self, n):               self.conf.epochs = n; return self
        def window_size(self, n):          self.conf.window = n; return self
        def min_word_frequency(self, n):   self.conf.min_word_frequency = n; return self
        def batch_size(self, n):           self.conf.batch_size = n; return self
        def seed(self, n):                 self.conf.seed = n; return self
        def x_max(self, x):                self._x_max = x; return self
        def alpha(self, a):                self._alpha = a; return self
        def symmetric(self, b):            self._symmetric = b; return self
        def shuffle(self, b):              self._shuffle = b; return self

        def build(self) -> "Glove":
            g = Glove(self.conf, self._x_max, self._alpha, self._symmetric,
                      self._shuffle)
            g._sentences = self._sentences
            g._tf = self._tf
            return g

    # -- pipeline ----------------------------------------------------------
    def _token_stream(self):
        self._sentences.reset()
        for sentence in self._sentences:
            yield [t for t in self._tf.create(sentence).get_tokens() if t]

    def _build_vocab(self):
        def seqs():
            for toks in self._token_stream():
                s = Sequence()
                for t in toks:
                    s.add_element(VocabWord(t))
                yield s
        ctor = VocabConstructor(self.conf.min_word_frequency,
                                build_huffman=False)
        ctor.add_source(seqs())
        self.vocab = ctor.build_joint_vocabulary()
        self.lookup_table = InMemoryLookupTable(
            self.vocab, self.conf.layer_size, seed=self.conf.seed,
            use_hs=False, negative=0)
        self.lookup_table.reset_weights()

    def _cooccurrences(self) -> Dict[Tuple[int, int], float]:
        """Distance-weighted counts (ref: glove/count/* — 1/d weighting)."""
        co: Dict[Tuple[int, int], float] = defaultdict(float)
        win = self.conf.window
        for toks in self._token_stream():
            ids = [self.vocab.index_of(t) for t in toks]
            ids = [i for i in ids if i >= 0]
            for i, wi in enumerate(ids):
                for off in range(1, win + 1):
                    j = i + off
                    if j >= len(ids):
                        break
                    inc = 1.0 / off
                    co[(wi, ids[j])] += inc
                    if self.symmetric:
                        co[(ids[j], wi)] += inc
        return co

    def fit(self) -> float:
        assert self._sentences is not None
        self._build_vocab()
        co = self._cooccurrences()
        if not co:
            return 0.0
        entries = np.array([(i, j, x) for (i, j), x in co.items()],
                           np.float64)
        rows_all = entries[:, 0].astype(np.int32)
        cols_all = entries[:, 1].astype(np.int32)
        xs_all = entries[:, 2].astype(np.float32)

        V, D = self.vocab.num_words(), self.conf.layer_size
        rng = np.random.default_rng(self.conf.seed)
        w = self.lookup_table.syn0
        wt = jnp.asarray((rng.random((V, D), dtype=np.float32) - 0.5) / D)
        b = jnp.zeros((V,), jnp.float32)
        bt = jnp.zeros((V,), jnp.float32)
        hw = jnp.full((V, D), 1e-8, jnp.float32)
        hwt = jnp.full((V, D), 1e-8, jnp.float32)
        hb = jnp.full((V,), 1e-8, jnp.float32)
        hbt = jnp.full((V,), 1e-8, jnp.float32)

        B = min(self.conf.batch_size, max(len(xs_all), 1))
        lr = jnp.float32(self.conf.learning_rate)
        last_loss = 0.0
        for _epoch in range(self.conf.epochs):
            order = (rng.permutation(len(xs_all)) if self.shuffle
                     else np.arange(len(xs_all)))
            total, count = 0.0, 0
            for start in range(0, len(order), B):
                sel = order[start:start + B]
                n = len(sel)
                r = np.zeros(B, np.int32)
                c = np.zeros(B, np.int32)
                x = np.ones(B, np.float32)
                valid = np.zeros(B, np.float32)
                r[:n], c[:n], x[:n] = rows_all[sel], cols_all[sel], xs_all[sel]
                valid[:n] = 1.0
                fx = np.minimum((x / self.x_max) ** self.alpha, 1.0)
                (w, wt, b, bt, hw, hwt, hb, hbt, loss) = _glove_step(
                    w, wt, b, bt, hw, hwt, hb, hbt,
                    jnp.asarray(r), jnp.asarray(c),
                    jnp.asarray(np.log(x)), jnp.asarray(fx.astype(np.float32)),
                    lr, jnp.asarray(valid))
                total += float(loss)
                count += n
            last_loss = total / max(count, 1)
        # final embedding = w + wt (GloVe convention)
        self.lookup_table.syn0 = w + wt
        return last_loss
