"""Fused word2vec update kernels — chunked-scan XLA scatter/gather programs.

The reference's inner loop queues one ``AggregateSkipGram``/``AggregateCBOW``
native op per training pair and flushes batches of 4096 into libnd4j,
where they execute sequentially (ref: models/embeddings/learning/impl/
elements/SkipGram.java:224-272, CBOW.java).  The TPU-first equivalent:
the host assembles fixed-shape integer batches (context indices, Huffman
points/codes, negative samples, per-pair learning rates) and ONE jitted
XLA computation per batch runs a ``lax.scan`` over sub-chunks:

    per chunk: gather rows → batched dot (MXU) → sigmoid → weighted
    outer-product gradients → scatter-add into syn0/syn1/syn1neg

Chunking matters for fidelity: a fully-batched scatter-add would apply
every duplicate-row update from one stale snapshot (divergent on
Zipf-heavy rows); the scan re-reads fresh rows every ``CHUNK`` pairs,
approximating the reference's sequential hogwild dynamics while staying
a single compiled program.  Within a chunk, duplicate-row contributions
are averaged (not summed) for stability.  All three weight tables are
donated so XLA updates them in place.

This module is the portable XLA path and the reference semantics.

All gathers use mode="clip": placeholder tables (e.g. the 1-row syn1neg
when negative sampling is off) are indexed by masked-out entries, and the
default out-of-bounds fill is NaN, which survives multiplication by a
zero mask (0·NaN = NaN) and poisons the whole update.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

# Gradient clip matching word2vec's expTable domain [-6, 6]
# (ref: InMemoryLookupTable builds expTable over MAX_EXP=6).
MAX_EXP = 6.0

# Pairs per scan step.  Small enough that duplicate-row staleness is
# negligible even for tiny vocabs, large enough to keep the MXU busy.
# DL4J_W2V_CHUNK overrides for on-chip throughput tuning (the bench
# records the value used — BASELINE.md word2vec protocol).
import os as _os
try:
    CHUNK = max(1, int(_os.environ.get("DL4J_W2V_CHUNK", "64")))
except ValueError:
    CHUNK = 64


def _sigmoid_clipped(x):
    # Outside [-MAX_EXP, MAX_EXP] word2vec skips the update (sigmoid
    # saturates); clipping the input gives the same fixed endpoint values.
    return jax.nn.sigmoid(jnp.clip(x, -MAX_EXP, MAX_EXP))


def _inv_row_counts(n_rows, idx, weight):
    """1/count over rows touched in this chunk — duplicate contributions
    are averaged so a row's step never exceeds the sequential magnitude."""
    counts = jnp.zeros((n_rows,), weight.dtype).at[idx].add(
        weight, mode="drop")
    inv = 1.0 / jnp.maximum(counts, 1.0)
    return jnp.take(inv, idx, axis=0, mode="clip")


def _chunked(arr, chunk):
    b = arr.shape[0]
    pad = (-b) % chunk
    if pad:
        # padded tail rows carry zero masks/alpha, so they are no-ops
        arr = jnp.concatenate(
            [arr, jnp.zeros((pad,) + arr.shape[1:], arr.dtype)])
    return arr.reshape(((b + pad) // chunk, chunk) + arr.shape[1:])


def _hs_ns_grads(l1, syn1, syn1neg, points, code_targets, code_mask,
                 neg_idx, neg_label, neg_mask, alpha):
    """Shared HS + NS math: returns (neu1e, syn1', syn1neg')."""
    dt = l1.dtype
    neu1e = jnp.zeros_like(l1)

    l2 = jnp.take(syn1, points, axis=0, mode="clip")                     # (B, C, D)
    f = _sigmoid_clipped(jnp.einsum("bd,bcd->bc", l1, l2))
    g = ((code_targets - f) * code_mask * alpha[:, None]).astype(dt)
    neu1e = neu1e + jnp.einsum("bc,bcd->bd", g, l2)
    inv1 = _inv_row_counts(syn1.shape[0], points, code_mask).astype(dt)
    syn1 = syn1.at[points].add((g * inv1)[..., None] * l1[:, None, :],
                               mode="drop")

    l2n = jnp.take(syn1neg, neg_idx, axis=0, mode="clip")                # (B, K, D)
    fn = _sigmoid_clipped(jnp.einsum("bd,bkd->bk", l1, l2n))
    gn = ((neg_label - fn) * neg_mask * alpha[:, None]).astype(dt)
    neu1e = neu1e + jnp.einsum("bk,bkd->bd", gn, l2n)
    invn = _inv_row_counts(syn1neg.shape[0], neg_idx, neg_mask).astype(dt)
    syn1neg = syn1neg.at[neg_idx].add(
        (gn * invn)[..., None] * l1[:, None, :], mode="drop")
    return neu1e, syn1, syn1neg


@functools.partial(jax.jit, donate_argnums=(0, 1, 2))
def skipgram_step(syn0, syn1, syn1neg,
                  ctx_idx, points, code_targets, code_mask,
                  neg_idx, neg_label, neg_mask, alpha):
    """One batched skip-gram update.

    syn0:      (V, D) input vectors        — donated
    syn1:      (Vi, D) HS inner-node table — donated (Vi may be 1 if unused)
    syn1neg:   (Vn, D) NS output table     — donated (Vn may be 1 if unused)
    ctx_idx:   (B,)   int32 — row of syn0 being trained (the "lastWord")
    points:    (B, C) int32 — Huffman inner-node rows of the center word
    code_targets: (B, C) f32 — 1-code (what sigmoid should produce)
    code_mask: (B, C) f32 — 1 for valid code positions, 0 padding
    neg_idx:   (B, K) int32 — target + negative sample rows
    neg_label: (B, K) f32 — 1 for the true target column, 0 for negatives
    neg_mask:  (B, K) f32 — validity mask (0 also kills pad pairs)
    alpha:     (B,)   f32 — per-pair learning rate
    """
    chunk = min(CHUNK, ctx_idx.shape[0])

    def body(carry, xs):
        syn0, syn1, syn1neg = carry
        ctx, pts, ct, cm, ni, nl, nm, al = xs
        dt = syn0.dtype
        l1 = jnp.take(syn0, ctx, axis=0, mode="clip")
        valid = (al > 0).astype(jnp.float32)
        neu1e, syn1, syn1neg = _hs_ns_grads(
            l1, syn1, syn1neg, pts, ct, cm, ni, nl, nm, al)
        inv0 = _inv_row_counts(syn0.shape[0], ctx, valid).astype(dt)
        syn0 = syn0.at[ctx].add(neu1e * inv0[:, None], mode="drop")
        return (syn0, syn1, syn1neg), ()

    xs = tuple(_chunked(a, chunk) for a in
               (ctx_idx, points, code_targets, code_mask,
                neg_idx, neg_label, neg_mask, alpha))
    (syn0, syn1, syn1neg), _ = lax.scan(body, (syn0, syn1, syn1neg), xs)
    return syn0, syn1, syn1neg


@functools.partial(jax.jit, donate_argnums=(0, 1, 2))
def cbow_step(syn0, syn1, syn1neg,
              win_idx, win_mask, points, code_targets, code_mask,
              neg_idx, neg_label, neg_mask, alpha):
    """One batched CBOW update (ref: learning/impl/elements/CBOW.java).

    win_idx:  (B, W) int32 — context-window rows (incl. PV-DM labels)
    win_mask: (B, W) f32 — 1 for real context positions
    Other args as in :func:`skipgram_step`; l1 is the masked mean of the
    window vectors and the gradient is applied to every window row.
    """
    chunk = min(CHUNK, win_idx.shape[0])

    def body(carry, xs):
        syn0, syn1, syn1neg = carry
        win, wm, pts, ct, cm, ni, nl, nm, al = xs
        dt = syn0.dtype
        vecs = jnp.take(syn0, win, axis=0, mode="clip")                  # (b, W, D)
        counts = jnp.maximum(wm.sum(-1, keepdims=True), 1.0).astype(dt)
        l1 = (vecs * wm[..., None].astype(dt)).sum(1) / counts
        neu1e, syn1, syn1neg = _hs_ns_grads(
            l1, syn1, syn1neg, pts, ct, cm, ni, nl, nm, al)
        # Apply neu1e to every context row (word2vec convention:
        # undivided), averaging duplicate rows within the chunk.
        inv0 = _inv_row_counts(syn0.shape[0], win, wm).astype(dt)
        upd = neu1e[:, None, :] * (wm.astype(dt) * inv0)[..., None]
        syn0 = syn0.at[win].add(upd, mode="drop")
        return (syn0, syn1, syn1neg), ()

    xs = tuple(_chunked(a, chunk) for a in
               (win_idx, win_mask, points, code_targets, code_mask,
                neg_idx, neg_label, neg_mask, alpha))
    (syn0, syn1, syn1neg), _ = lax.scan(body, (syn0, syn1, syn1neg), xs)
    return syn0, syn1, syn1neg


@functools.partial(jax.jit, donate_argnums=(0,))
def infer_step(vec, syn1, syn1neg,
               points, code_targets, code_mask,
               neg_idx, neg_label, neg_mask, alpha):
    """PV inference: train ONLY a floating vector against frozen tables
    (ref: SkipGram.iterateSample isInference branch — updates the
    inferenceVector instead of syn0).

    vec: (B, D) — donated; one inference vector per row.
    """
    dt = vec.dtype
    l2 = jnp.take(syn1, points, axis=0, mode="clip")
    f = _sigmoid_clipped(jnp.einsum("bd,bcd->bc", vec, l2))
    g = ((code_targets - f) * code_mask * alpha[:, None]).astype(dt)
    neu1e = jnp.einsum("bc,bcd->bd", g, l2)

    l2n = jnp.take(syn1neg, neg_idx, axis=0, mode="clip")
    fn = _sigmoid_clipped(jnp.einsum("bd,bkd->bk", vec, l2n))
    gn = ((neg_label - fn) * neg_mask * alpha[:, None]).astype(dt)
    neu1e = neu1e + jnp.einsum("bk,bkd->bd", gn, l2n)
    return vec + neu1e
