"""Keras HDF5 → framework model import.

(ref: keras/KerasModelImport.java public API — importKerasSequentialModelAndWeights,
importKerasModelAndWeights, importKerasSequentialConfiguration;
KerasLayer.java:44 layer mapping; KerasModel.java:377-480 weight copying)

Supports Sequential models saved as .h5 (Keras 1/2 "layer_names" layout
and Keras 3 nested-group layout).  Layer coverage mirrors the reference's
keras/layers/Keras{Dense, Convolution, Pooling, Lstm, BatchNormalization,
Embedding, Dropout, Activation, Flatten}.java.

Weight layout conversions:
- Dense kernel: keras [in, out] == native [in, out] (no transpose)
- Conv2D kernel: keras HWIO [kh, kw, in, out] → native OIHW
- LSTM: keras [in, 4H] kernel / [H, 4H] recurrent, gate order i,f,c,o →
  native gate order i,f,o,c; peepholes zero (vanilla LSTM == Graves LSTM
  with zero peepholes)
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

_ACT_MAP = {
    "relu": "relu", "softmax": "softmax", "sigmoid": "sigmoid",
    "tanh": "tanh", "linear": "identity", "elu": "elu", "selu": "selu",
    "softplus": "softplus", "softsign": "softsign",
    "hard_sigmoid": "hardsigmoid", "swish": "swish", "silu": "swish",
    "gelu": "gelu", "leaky_relu": "leakyrelu", "relu6": "relu6",
}

_LOSS_MAP = {
    "categorical_crossentropy": "mcxent",
    "binary_crossentropy": "xent",
    "mean_squared_error": "mse", "mse": "mse",
    "mean_absolute_error": "mae", "mae": "mae",
    "mean_absolute_percentage_error": "mape",
    "mean_squared_logarithmic_error": "msle",
    "hinge": "hinge", "squared_hinge": "squared_hinge",
    "poisson": "poisson", "cosine_proximity": "cosine_proximity",
    "kullback_leibler_divergence": "kl_divergence",
}


def _act(cfg: dict) -> str:
    a = cfg.get("activation", "linear")
    if isinstance(a, dict):  # keras 3 serialized activation
        a = a.get("config", {}).get("name", a.get("class_name", "linear"))
    return _ACT_MAP.get(str(a).lower(), "identity")


def _pair(v) -> tuple:
    if isinstance(v, (list, tuple)):
        return (int(v[0]), int(v[1]))
    return (int(v), int(v))


class KerasLayerMapper:
    """Maps one Keras layer config dict → framework layer conf (or None for
    structural layers like Flatten/InputLayer)."""

    def map(self, class_name: str, cfg: dict, is_output: bool,
            loss: Optional[str]) -> Optional[L.Layer]:
        name = cfg.get("name")
        if class_name in ("InputLayer", "Flatten", "Reshape"):
            return None
        if class_name == "Dense":
            act = _act(cfg)
            if is_output:
                return L.OutputLayer(
                    name=name, n_out=cfg["units"], activation=act,
                    loss=loss or ("mcxent" if act == "softmax" else "mse"))
            return L.DenseLayer(name=name, n_out=cfg["units"], activation=act)
        if class_name in ("Conv2D", "Convolution2D"):
            pad = cfg.get("padding", cfg.get("border_mode", "valid"))
            return L.ConvolutionLayer(
                name=name, n_out=cfg["filters"] if "filters" in cfg else cfg["nb_filter"],
                kernel=_pair(cfg.get("kernel_size",
                                     (cfg.get("nb_row", 3), cfg.get("nb_col", 3)))),
                stride=_pair(cfg.get("strides", (1, 1))),
                convolution_mode="same" if pad == "same" else "truncate",
                activation=_act(cfg))
        if class_name in ("MaxPooling2D", "AveragePooling2D"):
            kind = "max" if class_name.startswith("Max") else "avg"
            pad = cfg.get("padding", cfg.get("border_mode", "valid"))
            pool = _pair(cfg.get("pool_size", (2, 2)))
            return L.SubsamplingLayer(
                name=name, pooling_type=kind, kernel=pool,
                stride=_pair(cfg.get("strides") or pool),
                convolution_mode="same" if pad == "same" else "truncate")
        if class_name in ("GlobalMaxPooling2D", "GlobalAveragePooling2D",
                          "GlobalMaxPooling1D", "GlobalAveragePooling1D"):
            kind = "max" if "Max" in class_name else "avg"
            return L.GlobalPoolingLayer(name=name, pooling_type=kind)
        if class_name == "Dropout":
            # keras rate = DROP probability; native dropout = RETAIN prob
            return L.DropoutLayer(name=name, dropout=1.0 - cfg.get("rate", 0.5))
        if class_name == "Activation":
            return L.ActivationLayer(name=name, activation=_act(cfg))
        if class_name == "BatchNormalization":
            return L.BatchNormalization(
                name=name, decay=cfg.get("momentum", 0.99),
                eps=cfg.get("epsilon", 1e-3))
        if class_name == "Embedding":
            return L.EmbeddingLayer(
                name=name, n_in=cfg.get("input_dim"),
                n_out=cfg.get("output_dim"), activation="identity")
        if class_name == "LSTM":
            return L.GravesLSTM(
                name=name, n_out=cfg["units"],
                activation=_ACT_MAP.get(str(cfg.get("activation", "tanh")), "tanh"),
                gate_activation=_ACT_MAP.get(
                    str(cfg.get("recurrent_activation", "sigmoid")), "sigmoid"),
                forget_gate_bias_init=1.0 if cfg.get("unit_forget_bias", True) else 0.0)
        if class_name == "ZeroPadding2D":
            p = cfg.get("padding", (1, 1))
            if isinstance(p, (list, tuple)) and isinstance(p[0], (list, tuple)):
                return L.ZeroPaddingLayer(name=name, pad=(p[0][0], p[0][1],
                                                          p[1][0], p[1][1]))
            ph, pw = _pair(p)
            return L.ZeroPaddingLayer(name=name, pad=(ph, ph, pw, pw))
        raise ValueError(
            f"Unsupported Keras layer type '{class_name}' "
            f"(ref parity: KerasLayer.java supported set)")


def _input_type_from_shape(shape) -> Optional[InputType]:
    dims = [d for d in shape if d is not None]
    if len(dims) == 3:
        # keras channels_last [H, W, C] → native NCHW InputType
        return InputType.convolutional(dims[0], dims[1], dims[2])
    if len(dims) == 2:
        return InputType.recurrent(dims[1], dims[0])
    if len(dims) == 1:
        return InputType.feed_forward(dims[0])
    return None


class KerasModelImport:
    """(ref: keras/KerasModelImport.java)"""

    @staticmethod
    def import_keras_sequential_model_and_weights(path, enforce_training_config=False
                                                  ) -> MultiLayerNetwork:
        import h5py
        with h5py.File(path, "r") as f:
            model_config = json.loads(f.attrs["model_config"])
            training_config = (json.loads(f.attrs["training_config"])
                               if "training_config" in f.attrs else {})
            net = KerasModelImport._build_sequential(model_config, training_config)
            KerasModelImport._load_weights(net, f)
        return net

    @staticmethod
    def import_keras_model_and_weights(path):
        """Functional-API model → ComputationGraph
        (ref: KerasModelImport.importKerasModelAndWeights → KerasModel)."""
        import h5py
        with h5py.File(path, "r") as f:
            model_config = json.loads(f.attrs["model_config"])
            training_config = (json.loads(f.attrs["training_config"])
                               if "training_config" in f.attrs else {})
            if model_config.get("class_name") == "Sequential":
                net = KerasModelImport._build_sequential(model_config,
                                                         training_config)
            else:
                net = KerasModelImport._build_functional(model_config,
                                                         training_config)
            KerasModelImport._load_weights(net, f)
        return net

    @staticmethod
    def import_keras_sequential_configuration(path_or_json) -> MultiLayerNetwork:
        if isinstance(path_or_json, str) and path_or_json.lstrip().startswith("{"):
            model_config = json.loads(path_or_json)
        else:
            with open(path_or_json) as fh:
                model_config = json.load(fh)
        return KerasModelImport._build_sequential(model_config, {})

    # ------------------------------------------------------------------
    @staticmethod
    def _build_sequential(model_config: dict, training_config: dict
                          ) -> MultiLayerNetwork:
        if model_config.get("class_name") != "Sequential":
            raise ValueError("Use import_keras_model_and_weights for functional models")
        cfg = model_config["config"]
        layer_dicts = cfg["layers"] if isinstance(cfg, dict) else cfg
        loss = training_config.get("loss")
        if isinstance(loss, dict):
            loss = next(iter(loss.values()), None)
        if isinstance(loss, dict):  # keras3 serialized loss object
            loss = loss.get("config", {}).get("name")
        loss = _LOSS_MAP.get(str(loss).lower()) if loss else None

        mapper = KerasLayerMapper()
        input_type = None
        mapped: List[L.Layer] = []
        keras_names: List[Optional[str]] = []  # keras layer name per mapped layer
        for i, ld in enumerate(layer_dicts):
            cls = ld["class_name"]
            lcfg = ld.get("config", {})
            if input_type is None:
                shape = (lcfg.get("batch_input_shape")
                         or lcfg.get("batch_shape") or lcfg.get("input_shape"))
                if shape:
                    it = _input_type_from_shape(shape[1:] if shape[0] is None
                                                else shape)
                    input_type = it
            is_output = (i == len(layer_dicts) - 1)
            layer = mapper.map(cls, lcfg, is_output, loss)
            if layer is not None:
                mapped.append(layer)
                keras_names.append(lcfg.get("name"))
                # Keras LSTM(return_sequences=False) — the default — keeps
                # only the last timestep (ref: KerasLstm last-step handling)
                if cls == "LSTM" and not lcfg.get("return_sequences", False):
                    mapped.append(L.LastTimeStepLayer())
                    keras_names.append(None)
        if not isinstance(mapped[-1], (L.OutputLayer, L.RnnOutputLayer, L.LossLayer)):
            # ensure trailing loss head for .fit parity: wrap as LossLayer
            mapped.append(L.LossLayer(loss=loss or "mse", activation="identity"))
            keras_names.append(None)

        b = NeuralNetConfiguration.builder().list()
        for layer in mapped:
            b.layer(layer)
        if input_type is not None:
            b.set_input_type(input_type)
        net = MultiLayerNetwork(b.build())
        net.keras_layer_names = keras_names
        return net

    # ------------------------------------------------------------------
    @staticmethod
    def _inbound_names(layer_dict: dict) -> List[str]:
        """Upstream layer names from inbound_nodes (keras 2 nested-list and
        keras 3 keras_history formats)."""
        names: List[str] = []

        def walk(obj):
            if isinstance(obj, dict):
                hist = obj.get("config", {}).get("keras_history") \
                    if obj.get("class_name") == "__keras_tensor__" else None
                if hist:
                    names.append(hist[0])
                    return
                for val in obj.values():
                    walk(val)
            elif isinstance(obj, (list, tuple)):
                if (len(obj) >= 3 and isinstance(obj[0], str)
                        and isinstance(obj[1], int) and isinstance(obj[2], int)):
                    names.append(obj[0])  # keras2 [name, node, tensor, {}]
                    return
                for val in obj:
                    walk(val)

        walk(layer_dict.get("inbound_nodes", []))
        return names

    @staticmethod
    def _build_functional(model_config: dict, training_config: dict):
        """Functional-API config → ComputationGraph (ref: KerasModel.java:59)."""
        from deeplearning4j_tpu.nn.conf.graph_conf import (
            ElementWiseVertex, GraphBuilder, MergeVertex)
        from deeplearning4j_tpu.nn.conf.network import GlobalConf
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        cfg = model_config["config"]
        layer_dicts = cfg["layers"]
        def _spec_names(specs) -> List[str]:
            # [["a",0,0],["b",0,0]] (multi) or ["a",0,0] (single, keras3)
            if specs and isinstance(specs[0], str):
                return [specs[0]]
            return [s[0] for s in specs]

        input_names = _spec_names(cfg.get("input_layers", []))
        output_names = _spec_names(cfg.get("output_layers", []))

        loss = training_config.get("loss")
        if isinstance(loss, dict):
            loss = next(iter(loss.values()), None)
        if isinstance(loss, dict):
            loss = loss.get("config", {}).get("name")
        loss = _LOSS_MAP.get(str(loss).lower()) if loss else None

        mapper = KerasLayerMapper()
        b = GraphBuilder(GlobalConf()).add_inputs(*input_names)
        alias: Dict[str, str] = {}  # keras name → effective vertex name
        input_types: Dict[str, InputType] = {}

        for ld in layer_dicts:
            cls = ld["class_name"]
            lcfg = ld.get("config", {})
            name = lcfg.get("name", ld.get("name"))
            ins = [alias.get(i, i) for i in KerasModelImport._inbound_names(ld)]
            if cls == "InputLayer":
                shape = lcfg.get("batch_shape") or lcfg.get("batch_input_shape")
                if shape:
                    it = _input_type_from_shape(shape[1:])
                    if it:
                        input_types[name] = it
                alias[name] = name
                continue
            if cls in ("Add", "Average", "Maximum", "Subtract", "Multiply"):
                op = {"Add": "add", "Average": "average", "Maximum": "max",
                      "Subtract": "subtract", "Multiply": "product"}[cls]
                b.add_vertex(name, ElementWiseVertex(op=op), *ins)
                alias[name] = name
                continue
            if cls in ("Concatenate", "Merge"):
                b.add_vertex(name, MergeVertex(), *ins)
                alias[name] = name
                continue
            if cls in ("Flatten", "Reshape"):
                # structural only: dense-after-cnn flattening is auto-inserted
                alias[name] = ins[0]
                continue
            is_output = name in output_names
            layer = mapper.map(cls, lcfg, is_output, loss)
            if layer is None:
                alias[name] = ins[0]
                continue
            b.add_layer(name, layer, *ins)
            alias[name] = name
            if cls == "LSTM" and not lcfg.get("return_sequences", False):
                from deeplearning4j_tpu.nn.conf.graph_conf import LastTimeStepVertex
                b.add_vertex(f"{name}-last", LastTimeStepVertex(), name)
                alias[name] = f"{name}-last"

        b.set_outputs(*[alias.get(n, n) for n in output_names])
        if input_types:
            b.set_input_types(*[input_types[n] for n in input_names])
        return ComputationGraph(b.build())

    @staticmethod
    def _find_weights(h5file, keras_name: str) -> Dict[str, np.ndarray]:
        """Locate a layer's weight datasets in keras2 or keras3 layouts."""
        import h5py
        root = h5file["model_weights"] if "model_weights" in h5file else h5file
        if keras_name not in root:
            return {}
        found: Dict[str, np.ndarray] = {}

        def walk(group):
            for k in group:
                item = group[k]
                if isinstance(item, h5py.Group):
                    walk(item)
                else:
                    base = k.split(":")[0]
                    found.setdefault(base, np.asarray(item))

        walk(root[keras_name])
        return found

    @staticmethod
    def _map_layer_weights(layer: L.Layer, w: Dict[str, np.ndarray],
                           p: dict, state: dict, flatten_proc=None):
        """Convert one keras layer's weight dict into native param/state
        dicts (layout conversions per the module docstring)."""
        p = dict(p)
        if isinstance(layer, L.ConvolutionLayer):
            kern = w.get("kernel", w.get("param_0"))
            p["W"] = np.transpose(kern, (3, 2, 0, 1))  # HWIO → OIHW
            if "bias" in w or "param_1" in w:
                p["b"] = w.get("bias", w.get("param_1"))
        elif isinstance(layer, L.BatchNormalization):
            if "gamma" in w:
                p["gamma"] = w["gamma"]
            if "beta" in w:
                p["beta"] = w["beta"]
            state = dict(state)
            if "moving_mean" in w:
                state["mean"] = np.asarray(w["moving_mean"])
            if "moving_variance" in w:
                state["var"] = np.asarray(w["moving_variance"])
        elif isinstance(layer, L.GravesLSTM):
            kern = w.get("kernel", w.get("param_0"))
            rec = w.get("recurrent_kernel", w.get("param_1"))
            bias = w.get("bias", w.get("param_2"))
            H = layer.n_out

            def reorder(m):  # keras gate order i,f,c,o → native i,f,o,c
                i, fgt, c, o = np.split(np.asarray(m), 4, axis=-1)
                return np.concatenate([i, fgt, o, c], axis=-1)

            p["W"] = reorder(kern)
            p["RW"] = reorder(rec)
            if bias is not None:
                p["b"] = reorder(bias.reshape(1, -1)).reshape(-1)
            p["pI"] = np.zeros(H, np.float32)
            p["pF"] = np.zeros(H, np.float32)
            p["pO"] = np.zeros(H, np.float32)
        elif isinstance(layer, (L.DenseLayer, L.EmbeddingLayer)):
            kern = np.asarray(w.get("kernel", w.get("embeddings",
                                                    w.get("param_0"))))
            # Dense directly after a conv flatten: keras flattened HWC, the
            # native CnnToFeedForward flattens CHW — permute kernel rows
            # (the reference permutes identically, KerasModel.java weight copy).
            from deeplearning4j_tpu.nn.conf.preprocessors import (
                CnnToFeedForwardPreProcessor)
            if (isinstance(layer, L.DenseLayer)
                    and isinstance(flatten_proc, CnnToFeedForwardPreProcessor)):
                H, W, C = (flatten_proc.height, flatten_proc.width,
                           flatten_proc.channels)
                if kern.shape[0] == H * W * C:
                    hwc = kern.reshape(H, W, C, -1)
                    kern = np.transpose(hwc, (2, 0, 1, 3)).reshape(H * W * C, -1)
            p["W"] = kern
            if "bias" in w or "param_1" in w:
                p["b"] = np.asarray(w.get("bias", w.get("param_1")))
        p = {k: jnp.asarray(np.asarray(v), jnp.float32) for k, v in p.items()}
        state = {k: jnp.asarray(np.asarray(v), jnp.float32)
                 for k, v in state.items()}
        return p, state

    @staticmethod
    def _load_weights(net, h5file) -> None:
        net.init()
        if isinstance(net, MultiLayerNetwork):
            for li, (layer, kname) in enumerate(zip(net.layers,
                                                    net.keras_layer_names)):
                if kname is None or not layer.has_params():
                    continue
                w = KerasModelImport._find_weights(h5file, kname)
                if not w:
                    continue
                p, s = KerasModelImport._map_layer_weights(
                    layer, w, net.net_params[li], net.net_state[li],
                    flatten_proc=net.conf.preprocessors.get(li))
                net.net_params[li] = p
                net.net_state[li] = s
            return
        # ComputationGraph: vertices are named by their keras layer names
        from deeplearning4j_tpu.nn.conf.graph_conf import LayerVertex
        from deeplearning4j_tpu.nn.conf.graph_conf import PreprocessorVertex
        from deeplearning4j_tpu.nn.conf.preprocessors import (
            CnnToFeedForwardPreProcessor, InputPreProcessor)
        for name in net.order:
            v = net.conf.vertices[name]
            if not isinstance(v, LayerVertex) or not v.has_params():
                continue
            w = KerasModelImport._find_weights(h5file, name)
            if not w:
                continue
            layer = v.layer_conf()
            # find upstream flatten (auto-inserted "-cnn2ff" or explicit)
            flatten_proc = None
            ups = net.conf.vertex_inputs[name]
            if ups:
                uv = net.conf.vertices.get(ups[0])
                if isinstance(uv, PreprocessorVertex):
                    proc = InputPreProcessor.from_dict(uv.preprocessor)
                    if isinstance(proc, CnnToFeedForwardPreProcessor):
                        flatten_proc = proc
            p, s = KerasModelImport._map_layer_weights(
                layer, w, net.net_params[name], net.net_state[name],
                flatten_proc=flatten_proc)
            net.net_params[name] = p
            net.net_state[name] = s
