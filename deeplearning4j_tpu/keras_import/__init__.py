"""Keras model import (ref: deeplearning4j-modelimport, 5.4k LoC:
keras/KerasModelImport.java:48-231, KerasModel.java:59,377-480,
KerasSequentialModel.java:143-222, per-type keras/layers/Keras*.java,
Hdf5Archive.java — JavaCPP-HDF5 replaced by h5py)."""

from deeplearning4j_tpu.keras_import.importer import KerasModelImport  # noqa: F401
