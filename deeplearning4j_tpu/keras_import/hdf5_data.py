"""HDF5 minibatch streaming for the gateway
(ref: keras/HDF5MiniBatchDataSetIterator.java:24-90 — minibatches dumped
as ``batch_%d.h5`` files, features and labels in SEPARATE directories,
each file holding one ndarray in its ``"data"`` dataset, read by
keras/NDArrayHDF5Reader.java:33).

Two layouts are accepted:

* reference layout — ``features_dir/batch_%d.h5`` + ``labels_dir/
  batch_%d.h5``, each with a ``"data"`` dataset;
* single-directory convenience — ``dir/batch_%d.h5`` where each file
  carries ``"features"`` and ``"labels"`` datasets.
"""

from __future__ import annotations

import re
from pathlib import Path
from typing import List, Optional, Union

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import DataSetIterator

_BATCH_RE = re.compile(r"^batch_(\d+)\.h5$")


def _batch_files(directory: Path) -> List[Path]:
    """``batch_%d.h5`` files in index order (the FILE_NAME_PATTERN
    contract, HDF5MiniBatchDataSetIterator.java:24)."""
    found = []
    for p in directory.iterdir():
        m = _BATCH_RE.match(p.name)
        if m:
            found.append((int(m.group(1)), p))
    return [p for _, p in sorted(found)]


def read_hdf5_ndarray(path: Union[str, Path], dataset: str = "data"):
    """One ndarray from an HDF5 file (ref: NDArrayHDF5Reader.java:33 —
    the array lives in the "data" dataset)."""
    import h5py
    with h5py.File(str(path), "r") as f:
        if dataset not in f:
            raise KeyError(f"{path}: no {dataset!r} dataset "
                           f"(has {list(f.keys())})")
        return np.asarray(f[dataset], np.float32)


class HDF5MiniBatchDataSetIterator(DataSetIterator):
    """Stream ``batch_%d.h5`` minibatches as DataSets."""

    def __init__(self, features_dir: Union[str, Path],
                 labels_dir: Optional[Union[str, Path]] = None):
        self.features_dir = Path(features_dir)
        self.labels_dir = Path(labels_dir) if labels_dir is not None else None
        self._files = _batch_files(self.features_dir)
        if not self._files:
            raise FileNotFoundError(
                f"no batch_%d.h5 files in {self.features_dir}")
        if self.labels_dir is not None:
            missing = [p.name for p in self._files
                       if not (self.labels_dir / p.name).exists()]
            if missing:
                raise FileNotFoundError(
                    f"labels dir {self.labels_dir} missing {missing}")
        self._i = 0

    def has_next(self) -> bool:
        return self._i < len(self._files)

    def next(self) -> DataSet:
        p = self._files[self._i]
        self._i += 1
        if self.labels_dir is not None:
            x = read_hdf5_ndarray(p)
            y = read_hdf5_ndarray(self.labels_dir / p.name)
        else:
            # one open for both datasets
            import h5py
            with h5py.File(str(p), "r") as f:
                for ds in ("features", "labels"):
                    if ds not in f:
                        raise KeyError(f"{p}: no {ds!r} dataset "
                                       f"(has {list(f.keys())})")
                x = np.asarray(f["features"], np.float32)
                y = np.asarray(f["labels"], np.float32)
        return DataSet(x, y)

    def reset(self) -> None:
        self._i = 0

    def __len__(self) -> int:
        return len(self._files)
