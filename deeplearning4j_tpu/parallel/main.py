"""ParallelWrapper CLI
(ref: parallelism/main/ParallelWrapperMain.java:136 — jcommander flags
--modelPath --dataSetIteratorFactoryClazz --workers --prefetchSize
--averagingFrequency --reportScore ... → argparse here).

Usage:
    python -m deeplearning4j_tpu.parallel.main \
        --model-path model.zip --data-dir ./batches \
        --workers-per-axis data=4 fsdp=2 --averaging-frequency 1 \
        --epochs 2 --output-path trained.zip
"""

from __future__ import annotations

import argparse
import json
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dl4j-tpu-parallel",
        description="Data-parallel training over the device mesh "
                    "(ParallelWrapperMain analog)")
    p.add_argument("--model-path", required=True,
                   help="checkpoint .zip (ModelSerializer format)")
    p.add_argument("--data-dir", required=True,
                   help="directory of exported .npz DataSet minibatches")
    p.add_argument("--output-path", default=None,
                   help="where to save the trained model (default: in place)")
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("--averaging-frequency", type=int, default=1,
                   help="1 = per-step gradient all-reduce (recommended); "
                        "N>1 = reference parameter-averaging compat")
    p.add_argument("--no-average-updaters", action="store_true")
    p.add_argument("--prefetch-size", type=int, default=4)
    p.add_argument("--fused-steps", type=int, default=1,
                   help="K>1 fuses K same-shape batches into one compiled "
                        "lax.scan launch (all-reduce mode only)")
    p.add_argument("--workers-per-axis", nargs="*", default=[],
                   metavar="AXIS=N",
                   help="mesh layout, e.g. data=4 fsdp=2 seq=1")
    p.add_argument("--report-score", action="store_true")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    from deeplearning4j_tpu.nn.serialization import load_model, write_model
    from deeplearning4j_tpu.parallel import (
        MeshConfig, ParallelWrapper, make_mesh)
    from deeplearning4j_tpu.scaleout.data import PathDataSetIterator

    axes = {}
    for spec in args.workers_per_axis:
        k, _, v = spec.partition("=")
        axes[k] = int(v)
    mesh = make_mesh(MeshConfig(**axes)) if axes else make_mesh()

    model = load_model(args.model_path)
    wrapper = ParallelWrapper(
        model, mesh,
        averaging_frequency=args.averaging_frequency,
        average_updaters=not args.no_average_updaters,
        prefetch_buffer=args.prefetch_size,
        fused_steps=args.fused_steps)
    it = PathDataSetIterator.from_dir(args.data_dir)
    wrapper.fit(it, epochs=args.epochs)

    out = args.output_path or args.model_path
    write_model(model, out)
    result = {"model_path": out, "score": float(model.score()),
              "iterations": int(model.iteration),
              "mesh": {k: int(v) for k, v in mesh.shape.items()}}
    if args.report_score:
        print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
