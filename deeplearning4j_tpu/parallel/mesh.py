"""Device mesh construction and sharding policy.

Replaces the reference's AffinityManager device placement (SURVEY.md
§2.10) with explicit ``jax.sharding.Mesh`` axes.  Axis names follow the
scaling-book convention: 'data' (dp), 'fsdp' (zero-style param sharding),
'model' (tp), 'seq' (sp), 'expert' (ep) — a config picks which are used;
unused axes have size 1 so one code path serves every layout.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXES = ("data", "fsdp", "model", "seq", "expert")


def shard_map_compat(f, *, mesh, in_specs, out_specs, check_vma=None):
    """``jax.shard_map`` across jax versions: the top-level export and
    its ``check_vma`` kwarg are recent; older jax ships it as
    ``jax.experimental.shard_map.shard_map`` with ``check_rep`` (same
    meaning: verify per-device replication of unmapped outputs)."""
    import inspect
    try:
        from jax import shard_map as sm
    except ImportError:
        from jax.experimental.shard_map import shard_map as sm
    kw = {}
    if check_vma is not None:
        params = inspect.signature(sm).parameters
        if "check_vma" in params:
            kw["check_vma"] = check_vma
        elif "check_rep" in params:
            kw["check_rep"] = check_vma
    return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """How many devices along each named axis (product must divide the
    device count; -1 on 'data' means 'all remaining')."""

    data: int = -1
    fsdp: int = 1
    model: int = 1
    seq: int = 1
    expert: int = 1

    def resolve(self, n_devices: int) -> Tuple[int, int, int, int, int]:
        fixed = self.fsdp * self.model * self.seq * self.expert
        data = self.data
        if data == -1:
            if n_devices % fixed:
                raise ValueError(f"{n_devices} devices not divisible by {fixed}")
            data = n_devices // fixed
        if data * fixed != n_devices:
            raise ValueError(
                f"mesh {data}x{fixed} != {n_devices} devices")
        return (data, self.fsdp, self.model, self.seq, self.expert)


def make_mesh(config: Optional[MeshConfig] = None,
              devices: Optional[Sequence] = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    config = config or MeshConfig()
    shape = config.resolve(len(devices))
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, AXES)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def data_sharded(mesh: Mesh) -> NamedSharding:
    """Batch-dim sharding over data(+fsdp) — the standard input layout."""
    return NamedSharding(mesh, P(("data", "fsdp")))


def param_sharding(mesh: Mesh, arr_shape: Tuple[int, ...],
                   replicate_below: int = 0) -> NamedSharding:
    """Parameter layout over the mesh:

    * arrays with fewer than ``replicate_below`` elements (biases, BN
      stats, LayerNorm scales) are REPLICATED outright: sharding a
      few-KB vector buys nothing and costs an all-gather per step
      (the ZeRO paper's small-tensor exemption, arXiv 2004.13336 §4).
    * 'model' (tensor parallelism): the LAST axis of ≥2-D params (a
      matmul's output features) shards over 'model' — GSPMD then
      partitions the matmuls and inserts the activation collectives
      (Megatron column-parallel layout, scaling-book recipe).
    * 'expert' (MoE): the FIRST axis of ≥3-D params shards over
      'expert' — expert weight stacks are [E, in, out]
      (MixtureOfExperts layer), and GSPMD turns the dispatch/combine
      einsums into expert-parallel all-to-alls.  The ndim≥3 gate keeps
      plain [in, out] matrices (whose fan-in merely happens to divide E)
      replicated.
    * 'fsdp' (ZeRO): the largest remaining divisible axis shards over
      'fsdp'.
    * 'data': always replicated.
    """
    if replicate_below and int(np.prod(arr_shape or (1,))) < replicate_below:
        return NamedSharding(mesh, P())
    fsdp = mesh.shape["fsdp"]
    model = mesh.shape["model"]
    expert = mesh.shape["expert"]
    spec = [None] * len(arr_shape)
    if expert > 1 and len(arr_shape) >= 3 and arr_shape[0] % expert == 0:
        spec[0] = "expert"
    if (model > 1 and len(arr_shape) >= 2 and spec[-1] is None
            and arr_shape[-1] % model == 0):
        spec[-1] = "model"
    if fsdp > 1:
        best = None
        for i, d in enumerate(arr_shape):
            if spec[i] is None and d % fsdp == 0 and (
                    best is None or d > arr_shape[best]):
                best = i
        if best is not None:
            spec[best] = "fsdp"
    return NamedSharding(mesh, P(*spec))
