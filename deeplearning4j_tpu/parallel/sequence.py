"""Sequence/context parallelism — long-context attention over the mesh.

The reference's only long-sequence mechanism is truncated BPTT
(ref: nn/multilayer/MultiLayerNetwork.java:1227); it predates ring
attention.  This module is the capability-parity *extension* SURVEY.md §5
prescribes: shard the time dimension over the mesh's 'seq' axis and keep
attention exact with ring / all-to-all communication over ICI.

Two strategies, both exact (bitwise-comparable to dense attention up to
float reassociation):

* **Ring attention** (``ring_attention``): K/V blocks rotate around the
  'seq' ring via ``lax.ppermute`` while each device streams them into a
  numerically-stable online softmax (flash-attention accumulation:
  running max / running sum / weighted accumulator).  Communication is
  neighbor-to-neighbor → rides ICI links; memory is O(T_local) per chip,
  so global context length scales linearly with the ring size.

* **Ulysses / all-to-all** (``ulysses_attention``): ``lax.all_to_all``
  re-shards [B, H, T/S, D] → [B, H/S, T, D] (heads scattered, sequence
  gathered), runs ordinary dense attention per head group, and transposes
  back.  Requires n_heads % seq_size == 0; two collectives instead of
  S-1 permutes.

Both run inside ``shard_map`` over just the attention core — projections
and the rest of the network stay plain GSPMD ops, so XLA still fuses and
partitions them automatically from the input shardings.
"""

from __future__ import annotations

import contextlib
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from deeplearning4j_tpu.parallel.mesh import shard_map_compat as shard_map
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30

# ---------------------------------------------------------------------------
# Active-mesh context: layers query this to decide whether their attention
# core should be sequence-parallel (the analog of the reference's implicit
# "which device am I on" AffinityManager state, made explicit and scoped).
_ACTIVE_MESH: Optional[Mesh] = None
_SEQ_AXIS = "seq"


@contextlib.contextmanager
def sequence_mesh(mesh: Optional[Mesh]):
    """Scope under which attention layers shard their time dimension over
    the mesh's 'seq' axis (no-op if mesh is None or seq size is 1)."""
    global _ACTIVE_MESH
    prev = _ACTIVE_MESH
    _ACTIVE_MESH = mesh
    try:
        yield mesh
    finally:
        _ACTIVE_MESH = prev


def active_seq_size() -> int:
    if _ACTIVE_MESH is None:
        return 1
    return int(_ACTIVE_MESH.shape.get(_SEQ_AXIS, 1))


def cache_token():
    """Identity of the active sequence-parallel regime.  Models key their
    cached jitted step/score/output functions on this: entering or
    leaving ``sequence_mesh`` (or switching meshes) must retrace, since
    the collectives are baked into the traced program."""
    if _ACTIVE_MESH is None or active_seq_size() == 1:
        return None
    return id(_ACTIVE_MESH)


# ---------------------------------------------------------------------------
# KV-cache decode scope: the engines' carried decode step (`_rnn_step_raw`,
# shared by rnn_time_step and the serving decode pool) traces its forward
# under this scope, which switches SelfAttentionLayer from "re-run the whole
# window" to the incremental ring-cached path (`attend_cached`).  Training,
# TBPTT and plain output() never enter the scope, so their numerics are
# untouched.  The flag is read at TRACE time — it is baked into the compiled
# step, exactly like `cache_token()` bakes the sequence-parallel regime.
_KV_DECODE = False


@contextlib.contextmanager
def kv_decode_scope(enabled: bool = True):
    """Scope under which attention layers decode incrementally against a
    per-stream KV ring carried in ``rnn_state`` (the compiled-carry
    contract: the ring is an explicit, relocatable carry leaf, so it
    rides the decode pool's device-resident slot buffer and the fleet
    tier's migration payload)."""
    global _KV_DECODE  # dl4j: noqa[DL4J103] trace-time regime flag like sequence_mesh: flipped once around a trace, never per step
    prev = _KV_DECODE
    _KV_DECODE = bool(enabled)  # dl4j: noqa[DL4J101] `enabled` is a host-side Python bool (a trace-time mode switch), never a tracer
    try:
        yield
    finally:
        _KV_DECODE = prev


def kv_decode_active() -> bool:
    return _KV_DECODE


def kv_ring_init(batch: int, n_heads: int, window: int, head_dim: int,
                 dtype=jnp.float32):
    """Zero KV ring for ``batch`` streams: ``k``/``v`` are ``[B, H, W,
    D]`` circular buffers, ``pos`` is the per-stream count of real
    tokens ever written (monotone; write index = ``pos % W``, valid
    length = ``min(pos, W)``) — so a freshly-zeroed ring (``pos == 0``)
    is self-describing as empty, which is what lets the decode pool
    reuse a slot by zeroing its gathered carry in-trace."""
    return {
        "k": jnp.zeros((batch, n_heads, window, head_dim), dtype),
        "v": jnp.zeros((batch, n_heads, window, head_dim), dtype),
        "pos": jnp.zeros((batch,), jnp.int32),
    }


# ---------------------------------------------------------------------------
# Paged KV arena (vLLM-style block tables — the serving tier's shared
# KV pool).  Instead of every stream owning a dense [H, W, D] ring, one
# pooled [num_blocks, H, block_size, D] arena per attention layer holds
# ALL streams' K/V pages; each stream carries only an int32 block table
# mapping its logical ring slots to physical blocks.  Effective decode
# capacity becomes total tokens RESIDENT across streams instead of the
# `max_streams x worst-case window` rectangle.
#
# The arena is shared state and therefore cannot ride the per-stream
# carry pytree the way the dense ring does — it is threaded through the
# compiled step as an explicit (donated) argument.  `PagedTape` is the
# trace-time conduit between the pool step (which owns the arena
# arguments) and the attention layers (which discover them mid-forward):
# the pool step activates a tape via `paged_scope`, each attention layer
# draws its arena + block-table input from it in encounter order and
# deposits the updated arena back.  Like `kv_decode_scope`, the tape is
# read at TRACE time only — it is baked into the compiled program and
# never consulted per step.
_PAGED_TAPE = None


def block_geometry(window: int, block_size: int):
    """Round a logical window up to whole blocks: returns ``(w_eff,
    n_blocks)`` with ``w_eff = n_blocks * block_size >= window``.  The
    ring arithmetic runs mod ``w_eff`` (every ring slot maps to a fixed
    offset of a fixed table entry); validity still masks to the logical
    ``window``."""
    bs = max(1, int(block_size))
    nbs = max(1, -(-int(window) // bs))
    return nbs * bs, nbs


class PagedTape:
    """Trace-time conduit handing attention layers their shared paged-KV
    arena.  Two modes:

    * **template** (``arenas is None``): active while the pool builds
      its carry template via ``eval_shape`` — records each layer's arena
      geometry in ``specs`` (encounter order == arena id) and hands back
      a dummy 1-block arena so the trace shapes resolve.
    * **run** (``arenas``/``tables`` given): hands layer ``i`` the real
      arena tracer ``arenas[i]`` and its block-table input
      ``tables[i]``; the layer deposits the written arena via
      :meth:`put` and the pool step collects them with :meth:`collect`.
    """

    def __init__(self, block_size: int = 16, arenas=None, tables=None,
                 dtype=None, record_undo: bool = False):
        self.block_size = max(1, int(block_size))
        self.dtype = dtype          # storage override (e.g. bf16 arena)
        self.arenas = None if arenas is None else tuple(arenas)
        self.tables = None if tables is None else tuple(tables)
        # speculative verify needs to roll REJECTED writes back out of
        # the shared arena (it cannot stack the whole arena per step the
        # way the per-stream carry is stacked) — when set, layers record
        # each token's overwritten slot contents via put_undo
        self.record_undo = bool(record_undo)
        self.specs = []
        self._out = {}
        self._undo = {}
        self._i = 0

    @property
    def template(self) -> bool:
        return self.arenas is None

    def next_layer(self, n_heads: int, head_dim: int, window: int,
                   ref_dtype):
        """Claim the next arena id (layer encounter order).  Returns
        ``(aid, arena, tbl)``; in template mode ``tbl`` is ``None`` (the
        layer zero-fills) and the arena is a dummy."""
        i = self._i
        self._i += 1
        w_eff, nbs = block_geometry(window, self.block_size)
        dt = self.dtype if self.dtype is not None else ref_dtype
        if self.template:
            self.specs.append({
                "heads": int(n_heads), "head_dim": int(head_dim),
                "window": int(window), "window_eff": int(w_eff),
                "blocks_per_slot": int(nbs),
                "dtype": str(jnp.zeros((), dt).dtype)})
            dummy = jnp.zeros((2, n_heads, self.block_size, head_dim), dt)
            return i, {"k": dummy, "v": dummy}, None
        return i, self.arenas[i], self.tables[i]

    def put(self, aid: int, arena) -> None:
        if not self.template:
            self._out[aid] = arena

    def put_undo(self, aid: int, undo) -> None:
        if not self.template:
            self._undo[aid] = undo

    def collect(self):
        """Updated arenas in arena-id order (the pool step's return)."""
        return tuple(self._out[i] for i in range(self._i))

    def collect_undo(self):
        """Per-layer undo journals in arena-id order (spec verify)."""
        return tuple(self._undo[i] for i in range(self._i))


@contextlib.contextmanager
def paged_scope(tape: PagedTape):
    """Activate ``tape`` for the duration of one trace (the paged
    analog of ``kv_decode_scope`` — a trace-time regime, never a
    per-step branch)."""
    global _PAGED_TAPE  # dl4j: noqa[DL4J103] trace-time regime flag like _KV_DECODE: flipped once around a trace, never per step
    prev = _PAGED_TAPE
    _PAGED_TAPE = tape
    try:
        yield tape
    finally:
        _PAGED_TAPE = prev


def paged_tape() -> Optional[PagedTape]:
    return _PAGED_TAPE


def attend_paged(q, k_new, v_new, pos, tbl, arena, *, window: int,
                 key_mask=None, scale: Optional[float] = None,
                 undo: bool = False):
    """Incremental sliding-window attention through a block table — the
    paged twin of :func:`attend_cached` (same streaming-causal
    semantics, same masked-pad exactness, same >= f32 accumulation).

    ``pos``: ``[B]`` int32 monotone token count per stream; ``tbl``:
    ``[B, n_blocks_per_slot]`` int32 physical block ids (entries beyond
    the allocated prefix point at the arena's scratch block — they are
    never valid-attendable); ``arena``: ``{"k","v"}`` of
    ``[num_blocks, H, block_size, D]``.  Token ``t`` writes its K/V at
    ring slot ``pos % w_eff`` → physical ``(tbl[slot // bs], slot %
    bs)``, then attends over the gathered ``[H, w_eff, D]`` view with
    validity masked to the logical ``window``.  Writes are
    delta-scatter-adds (``old + (new - old) * mask``): masked pad
    tokens write exactly nothing, and duplicate scratch-block rows
    (pad/warmup) stay bounded.  Returns ``(out, new_pos, new_arena)``;
    the arena is storage-dtype (bf16 arenas attend with f32
    accumulation via ``preferred_element_type``).

    With ``undo=True`` additionally returns a journal of every token's
    overwritten slot — ``{"pb","o": [Tc,B], "k","v": [Tc,B,H,D]}`` (the
    pre-write contents) — so speculative verify can restore the shared
    arena for rejected tokens after acceptance is known."""
    B, H, Tc, D = q.shape
    ak, av = arena["k"], arena["v"]
    bs = ak.shape[2]
    nbs = tbl.shape[1]
    w_eff = nbs * bs
    W = min(int(window), w_eff)
    scale = scale if scale is not None else 1.0 / (D ** 0.5)
    acc_dt = jnp.promote_types(q.dtype, jnp.float32)
    if key_mask is None:
        key_mask = jnp.ones((B, Tc), q.dtype)
    slots = jnp.arange(w_eff)
    rows = jnp.arange(B)

    def body(carry, inp):
        ka, va, p = carry
        q_t, k_t, v_t, m_t = inp          # [B,H,D] x3, [B]
        w = p % w_eff                      # [B] ring slot
        pb = tbl[rows, w // bs]            # [B] physical block
        o = w % bs                         # [B] offset within block
        m = m_t.astype(ka.dtype)[:, None, None]
        old_k = ka[pb, :, o, :]            # [B, H, D] pre-write contents
        old_v = va[pb, :, o, :]
        # masked delta-write: .add of (new - old) * m is a set for
        # unique (pb, o) pairs (live streams hold disjoint blocks), a
        # no-op for masked pads, and bounded for duplicated scratch
        # rows (whose contents are never valid-attendable)
        ka = ka.at[pb, :, o, :].add((k_t.astype(ka.dtype) - old_k) * m)
        va = va.at[pb, :, o, :].add((v_t.astype(va.dtype) - old_v) * m)
        count = p + m_t.astype(p.dtype)
        # gather AFTER the write: [B, nbs, H, bs, D] -> [B, H, w_eff, D]
        kg = jnp.moveaxis(ka[tbl], 2, 1).reshape(B, H, w_eff, D)
        vg = jnp.moveaxis(va[tbl], 2, 1).reshape(B, H, w_eff, D)
        # slot s holds logical position `last` = the largest p' < count
        # with p' ≡ s (mod w_eff); valid iff it exists and is within
        # the logical window (w_eff > window only pads to whole blocks)
        c1 = count[:, None] - 1
        last = c1 - ((c1 - slots[None, :]) % w_eff)       # [B, w_eff]
        valid = (last >= 0) & (last >= count[:, None] - W)
        # zero INVALID values before the weighted sum: invalid slots may
        # alias the scratch block (unallocated table tail entries) whose
        # contents are arbitrary — a 0-weight x garbage product must
        # never poison the output (0 * inf/nan is nan)
        vg = jnp.where(valid[:, None, :, None], vg, 0)
        scores = jnp.einsum("bhd,bhwd->bhw", q_t, kg,
                            preferred_element_type=acc_dt) * scale
        scores = jnp.where(valid[:, None, :], scores, NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1)
        o_t = jnp.einsum("bhw,bhwd->bhd", probs, vg,
                         preferred_element_type=acc_dt)
        u_t = {"pb": pb, "o": o, "k": old_k, "v": old_v}
        return (ka, va, count), (o_t.astype(q.dtype), u_t)

    xs = (jnp.moveaxis(q, 2, 0), jnp.moveaxis(k_new, 2, 0),
          jnp.moveaxis(v_new, 2, 0), jnp.moveaxis(key_mask, 1, 0))
    (ak, av, pos), (outs, journal) = lax.scan(body, (ak, av, pos), xs)
    if undo:
        return jnp.moveaxis(outs, 0, 2), pos, {"k": ak, "v": av}, journal
    return jnp.moveaxis(outs, 0, 2), pos, {"k": ak, "v": av}


def attend_cached(q, k_new, v_new, ring, *, key_mask=None,
                  scale: Optional[float] = None):
    """Incremental sliding-window attention over a per-stream KV ring —
    the O(window)/token decode path (vs ``dense_attention``'s
    O(T)/token re-run of the whole stream).

    ``q, k_new, v_new``: the NEW chunk's projections ``[B, H, Tc, D]``;
    ``ring``: ``kv_ring_init``-shaped pytree; ``key_mask``: ``[B, Tc]``
    with 1 = real token.  Semantics are streaming-causal: chunk token
    ``t`` first appends its K/V at ``pos % W`` (masked pad tokens write
    nothing and advance nothing — a bucketed pad chunk carries the ring
    through unchanged, exact), then attends over the ``min(pos+1, W)``
    valid entries; entries older than ``window`` are overwritten and
    masked out (ring wraparound).  For ``window >= stream length`` the
    step-by-step outputs match full causal ``dense_attention`` to float
    reassociation (the parity the tests pin at 1e-5).

    Cost per token is O(window) flat in stream length — the lax.scan
    over the chunk keeps the HLO O(1) in chunk length, and per-step
    statistics accumulate at >= f32 like the ring-attention core."""
    B, H, Tc, D = q.shape
    W = ring["k"].shape[2]
    scale = scale if scale is not None else 1.0 / (D ** 0.5)
    acc_dt = jnp.promote_types(q.dtype, jnp.float32)
    if key_mask is None:
        key_mask = jnp.ones((B, Tc), q.dtype)
    slots = jnp.arange(W)

    def body(carry, inp):
        kr, vr, pos = carry
        q_t, k_t, v_t, m_t = inp          # [B,H,D] x3, [B]
        m_t = m_t.astype(kr.dtype)
        # append: one-hot write at pos % W, gated by the token mask
        write = ((slots[None, :] == (pos % W)[:, None]).astype(kr.dtype)
                 * m_t[:, None])          # [B, W]
        wr = write[:, None, :, None]      # [B, 1, W, 1]
        kr = kr * (1.0 - wr) + k_t[:, :, None, :] * wr
        vr = vr * (1.0 - wr) + v_t[:, :, None, :] * wr
        count = pos + m_t.astype(pos.dtype)
        # ring wraparound masking: only the min(count, W) most-recent
        # entries are attendable (slot indices fill 0..W-1 then wrap,
        # so validity is a plain length test against the write count)
        valid = slots[None, :] < jnp.minimum(count, W)[:, None]   # [B, W]
        scores = jnp.einsum("bhd,bhwd->bhw", q_t, kr,
                            preferred_element_type=acc_dt) * scale
        scores = jnp.where(valid[:, None, :], scores, NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1)
        o_t = jnp.einsum("bhw,bhwd->bhd", probs, vr,
                         preferred_element_type=acc_dt)
        return (kr, vr, count), o_t.astype(q.dtype)

    xs = (jnp.moveaxis(q, 2, 0), jnp.moveaxis(k_new, 2, 0),
          jnp.moveaxis(v_new, 2, 0), jnp.moveaxis(key_mask, 1, 0))
    (kr, vr, pos), outs = lax.scan(
        body, (ring["k"], ring["v"], ring["pos"]), xs)
    return (jnp.moveaxis(outs, 0, 2),
            {"k": kr, "v": vr, "pos": pos})


# ---------------------------------------------------------------------------
# Dense reference core (single device / no 'seq' axis).


def dense_attention(q, k, v, *, causal: bool = False, key_mask=None,
                    scale: Optional[float] = None, allow_flash: bool = True):
    """Plain softmax attention.  q,k,v: [B, H, T, D]; key_mask: [B, Tk]
    with 1=keep (the reference's feedForwardMaskArray convention,
    ref: nn/api/Layer.java:309).  On TPU, tile-friendly shapes route to
    the Pallas flash-attention kernel (ops/pallas_kernels.py) — O(T·D)
    memory instead of the [T, T] score matrix in HBM."""
    D = q.shape[-1]
    scale = scale if scale is not None else 1.0 / (D ** 0.5)
    if allow_flash and q.shape[2] == k.shape[2]:
        # helper selection (ops/helpers.py): the attention tier routes
        # tile-friendly shapes to the flash kernel and meters the choice
        from deeplearning4j_tpu.ops import helpers
        from deeplearning4j_tpu.ops import pallas_kernels as pk
        if helpers.attention_wanted(q):
            km = (key_mask if key_mask is not None
                  else jnp.ones((q.shape[0], k.shape[2]), q.dtype))
            return pk.flash_attention(q, k, v, km.astype(q.dtype), causal,
                                      scale)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        Tq, Tk = scores.shape[-2], scores.shape[-1]
        qi = jnp.arange(Tq)[:, None]
        ki = jnp.arange(Tk)[None, :]
        scores = jnp.where(qi >= ki, scores, NEG_INF)
    if key_mask is not None:
        scores = jnp.where(key_mask[:, None, None, :].astype(bool),
                           scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


# ---------------------------------------------------------------------------
def _axis_size(axis_name: str):
    """lax.axis_size across jax versions (older jax has no such export;
    the size of a mapped axis is the psum of 1 over it)."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


# Ring attention (per-shard body; run under shard_map over 'seq').


def _ring_attention_sharded(q, k, v, key_mask, *, axis_name: str,
                            causal: bool, scale: Optional[float]):
    """Online-softmax ring scan.  Per-shard shapes: q,k,v [B, H, Tl, D],
    key_mask [B, Tl] or None.  The device's global block index comes from
    ``lax.axis_index`` so causal masking uses *global* positions."""
    S = _axis_size(axis_name)
    B, H, Tl, D = q.shape
    scale = scale if scale is not None else 1.0 / (D ** 0.5)
    idx = lax.axis_index(axis_name)
    q_pos = idx * Tl + jnp.arange(Tl)                      # global q positions

    # Online-softmax statistics accumulate at >=f32 regardless of the
    # compute dtype: bf16 running max/denominator drifts visibly vs the
    # dense/Pallas paths (which accumulate f32), and the f64 gradient-check
    # path keeps its width (advisor round-1 finding).
    acc_dt = jnp.promote_types(q.dtype, jnp.float32)
    m = jnp.full((B, H, Tl), NEG_INF, acc_dt)              # running row max
    l = jnp.zeros((B, H, Tl), acc_dt)                      # running denom
    o = jnp.zeros((B, H, Tl, D), acc_dt)                   # weighted accum
    if key_mask is None:
        key_mask = jnp.ones((B, Tl), q.dtype)

    # after s hops each device holds the block originally on (idx - s) % S
    perm = [(i, (i + 1) % S) for i in range(S)]

    # lax.scan (not a Python loop) so the HLO stays O(1) in ring size —
    # one block-update body compiled once, S trips; the extra ppermute on
    # the last trip completes the cycle (blocks return to their owners).
    def body(carry, s):
        m, l, o, k, v, mask = carry
        src = (idx - s) % S
        k_pos = src * Tl + jnp.arange(Tl)                  # global k positions

        def attend(m, l, o):
            scores = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                                preferred_element_type=acc_dt) * scale
            if causal:
                scores = jnp.where(q_pos[:, None] >= k_pos[None, :],
                                   scores, NEG_INF)
            scores = jnp.where(mask[:, None, None, :].astype(bool),
                               scores, NEG_INF)
            m_new = jnp.maximum(m, scores.max(axis=-1))
            # guard fully-masked rows: keep exp argument finite
            alpha = jnp.exp(jnp.maximum(m - m_new, NEG_INF * 0.5))
            p = jnp.exp(scores - m_new[..., None])
            l_new = l * alpha + p.sum(axis=-1)
            o_new = o * alpha[..., None] + jnp.einsum(
                "bhqk,bhkd->bhqd", p, v, preferred_element_type=acc_dt)
            return m_new, l_new, o_new

        # NB: a causal block-skip (cond on "all k in this shard's future")
        # cannot shorten the ring's critical path — every hop ends in a
        # ppermute all S devices must join, and the last shard attends on
        # every hop, so step time stays S x attend either way.  The real
        # causal win is zigzag/striped query partitioning (balance low+high
        # positions per shard); until that layout lands, unconditional
        # compute keeps the body simple and vmap-safe.
        m, l, o = attend(m, l, o)
        k = lax.ppermute(k, axis_name, perm)
        v = lax.ppermute(v, axis_name, perm)
        mask = lax.ppermute(mask, axis_name, perm)
        return (m, l, o, k, v, mask), None

    (m, l, o, _, _, _), _ = lax.scan(
        body, (m, l, o, k, v, key_mask), jnp.arange(S))
    return (o / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)


def ring_attention(q, k, v, *, mesh: Mesh, causal: bool = False,
                   key_mask=None, scale: Optional[float] = None,
                   axis_name: str = _SEQ_AXIS):
    """shard_map-wrapped exact ring attention; q,k,v are full arrays whose
    time dim is (to be) sharded over ``axis_name``."""
    spec = P(None, None, axis_name, None)
    mask_spec = P(None, axis_name)
    if key_mask is None:
        key_mask = jnp.ones((q.shape[0], q.shape[2]), q.dtype)
    fn = shard_map(
        partial(_ring_attention_sharded, axis_name=axis_name,
                causal=causal, scale=scale),
        mesh=mesh,
        in_specs=(spec, spec, spec, mask_spec),
        out_specs=spec,
        check_vma=False)
    return fn(q, k, v, key_mask)


# ---------------------------------------------------------------------------
# Ulysses (all-to-all) sequence parallelism.


def _ulysses_sharded(q, k, v, key_mask, *, axis_name: str, causal: bool,
                     scale: Optional[float]):
    """Per-shard: [B, H, Tl, D] → all_to_all → [B, H/S, T, D] → dense
    attention → all_to_all back."""
    S = _axis_size(axis_name)
    a2a = partial(lax.all_to_all, axis_name=axis_name, split_axis=1,
                  concat_axis=2, tiled=True)
    qg, kg, vg = a2a(q), a2a(k), a2a(v)                  # [B, H/S, T, D]
    mask_g = lax.all_gather(key_mask, axis_name, axis=1, tiled=True)  # [B, T]
    out = dense_attention(qg, kg, vg, causal=causal, key_mask=mask_g,
                          scale=scale)
    return lax.all_to_all(out, axis_name=axis_name, split_axis=2,
                          concat_axis=1, tiled=True)     # [B, H, Tl, D]


def ulysses_attention(q, k, v, *, mesh: Mesh, causal: bool = False,
                      key_mask=None, scale: Optional[float] = None,
                      axis_name: str = _SEQ_AXIS):
    S = int(mesh.shape[axis_name])
    if q.shape[1] % S:
        raise ValueError(f"n_heads={q.shape[1]} not divisible by seq={S}")
    spec = P(None, None, axis_name, None)
    mask_spec = P(None, axis_name)
    if key_mask is None:
        key_mask = jnp.ones((q.shape[0], q.shape[2]), q.dtype)
    fn = shard_map(
        partial(_ulysses_sharded, axis_name=axis_name, causal=causal,
                scale=scale),
        mesh=mesh,
        in_specs=(spec, spec, spec, mask_spec),
        out_specs=spec,
        check_vma=False)
    return fn(q, k, v, key_mask)


# ---------------------------------------------------------------------------
# Strategy dispatch used by SelfAttentionLayer.


def attention(q, k, v, *, causal: bool = False, key_mask=None,
              scale: Optional[float] = None, strategy: str = "auto"):
    """Attention core that is sequence-parallel whenever a mesh with a
    non-trivial 'seq' axis is active (see ``sequence_mesh``), dense
    otherwise.  strategy: 'auto' | 'ring' | 'ulysses' | 'dense'."""
    if strategy not in ("auto", "ring", "ulysses", "dense"):
        raise ValueError(f"unknown attention strategy {strategy!r} "
                         "(expected auto|ring|ulysses|dense)")
    mesh = _ACTIVE_MESH
    seq = active_seq_size()
    if strategy == "dense" or seq == 1 or mesh is None:
        return dense_attention(q, k, v, causal=causal, key_mask=key_mask,
                               scale=scale)
    if q.shape[2] % seq:
        raise ValueError(
            f"sequence length {q.shape[2]} not divisible by the mesh 'seq' "
            f"axis ({seq}); pad/bucket the time dimension to a multiple")
    if strategy == "ulysses":
        # explicit request: let ulysses_attention raise on head/seq mismatch
        return ulysses_attention(q, k, v, mesh=mesh, causal=causal,
                                 key_mask=key_mask, scale=scale)
    if strategy == "auto" and q.shape[1] % seq == 0 and seq <= 4:
        return ulysses_attention(q, k, v, mesh=mesh, causal=causal,
                                 key_mask=key_mask, scale=scale)
    return ring_attention(q, k, v, mesh=mesh, causal=causal,
                          key_mask=key_mask, scale=scale)
