"""ParallelWrapper — data-parallel training over the device mesh.

The reference replicates the model into per-device worker threads and
synchronously averages parameters every ``averagingFrequency`` iterations
through the host (ref: parallelism/ParallelWrapper.java:49-679,
``Nd4j.averageAndPropagate`` :218).  TPU-natively there are two modes:

* ``averaging_frequency=1`` (default, recommended): per-step gradient
  all-reduce — the batch is sharded over the 'data' axis, params are
  replicated, and XLA inserts the psum over ICI inside the one jitted
  step.  Mathematically stronger than parameter averaging (equivalent to
  large-batch SGD) and what BASELINE.json prescribes.

* ``averaging_frequency=N>1`` (reference-compat): each device runs N
  independent local steps on its own replica (params carry a leading
  device axis, sharded over 'data'), then replicas are averaged — the
  mean over the device axis is XLA's all-reduce.  Reproduces the
  reference's parameter-averaging semantics including optional updater
  state averaging (ref: ParallelWrapper.averageUpdatersState :239-257).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.ops import bucketing
from deeplearning4j_tpu.parallel import fsdp
from deeplearning4j_tpu.parallel import mesh as mesh_util


class ParallelWrapper:
    def __init__(self, model, mesh: Optional[Mesh] = None,
                 averaging_frequency: int = 1,
                 average_updaters: bool = True,
                 prefetch_buffer: int = 4,
                 fused_steps: int = 1):
        """``fused_steps=K>1`` (all-reduce mode only) fuses K same-shape
        sharded batches into ONE compiled lax.scan launch — the engine's
        fit(fused_steps=K) dispatch elimination, composed with the
        per-step gradient psum.  Same caveats: listeners fire once per
        launch, ragged tails fall back per-step."""
        self.model = model
        self.mesh = mesh if mesh is not None else mesh_util.make_mesh()
        self.averaging_frequency = averaging_frequency
        self.average_updaters = average_updaters
        self.prefetch_buffer = prefetch_buffer
        self.fused_steps = max(1, int(fused_steps))
        self._sharded_step = None
        self._sharded_fused = None
        self._local_step = None
        g = model.conf.global_conf
        # the wrapper predates conf.sharding(); its explicit mesh wins,
        # but the small-array replication threshold is honored when the
        # conf opted into sharding
        rb = (g.sharding_replicate_below
              if getattr(g, "sharding_enabled", False) else 0)
        self.plan = fsdp.plan_from_mesh(self.mesh, replicate_below=rb)
        self.n_data = self.plan.n_data

    # ------------------------------------------------------------------
    def _adopt_plan(self, plan):
        """Point the model's grad-constraint/sharding hooks at the
        wrapper's plan (or None in param-averaging mode, where the
        vmapped local step must not constrain) so the shared
        _apply_updates traces against THIS mesh, not a conf-derived
        one."""
        m = self.model
        if fsdp.plan_key(getattr(m, "_sharding_plan", None)) != \
                fsdp.plan_key(plan):
            m._sharding_plan = plan
            m._step_fn = None
            m._fused_fns = None

    def _build_sharded_step(self):
        """Mode 1: batch sharded over 'data', params replicated/FSDP;
        XLA inserts the gradient psum (reduce-scatter under fsdp — see
        parallel/fsdp.jit_sharded_step)."""
        m = self.model
        if m.net_params is None:
            m.init()
        return fsdp.jit_sharded_step(m._build_step_raw(), self.plan,
                                     m.net_params, m.opt_states)

    def _place(self):
        """Move model state onto the mesh with the right shardings."""
        fsdp.place_model(self.plan, self.model)

    # ------------------------------------------------------------------
    def fit(self, iterator, epochs: int = 1):
        if self.averaging_frequency <= 1:
            return self._fit_allreduce(iterator, epochs)
        return self._fit_param_averaging(iterator, epochs)

    # Pad/mask primitives now live in ops/bucketing.py (shared with the
    # engines' shape-bucketing paths); kept as aliases for callers/tests.
    _MASK_NONLINEAR_LOSSES = bucketing.MASK_NONLINEAR_LOSSES
    _cycle_rows = staticmethod(bucketing.cycle_rows)
    _scaled_mask = staticmethod(bucketing.scaled_mask)

    def _pad_supported(self):
        """See ops/bucketing.pad_supported — mean reduction, mask-linear
        losses, no batch-coupled aux losses."""
        return bucketing.pad_supported(self.model)

    def _normalize_batch(self, ds, is_graph):
        """Pad-or-trim one batch to the data degree — the shared
        implementation lives in parallel/fsdp.normalize_batch (the
        engines' conf.sharding() fit path uses the very same function).
        Returns (batch, n) with ``n`` the REAL example count, or None
        when everything would be dropped."""
        norm = fsdp.normalize_batch(self.model, ds, self.n_data, is_graph,
                                    owner=self)
        if norm is None:
            return None
        batch, n, bucket = norm
        if bucket is not None:
            tel = getattr(self.model, "compile_telemetry", None)
            if tel is not None:
                tel.record("sharded_step", batch, bucket=bucket)
        return batch, n

    _host_batch = staticmethod(fsdp.host_batch)

    def _run_sharded_step(self, batch, n):
        m = self.model
        batch_sh = mesh_util.data_sharded(self.mesh)
        x, y, fm, lm = jax.tree_util.tree_map(
            lambda a: self._put_batch(a, batch_sh), batch)
        m._key, sub = jax.random.split(m._key)
        (m.net_params, m.net_state, m.opt_states, score) = self._sharded_step(
            m.net_params, m.net_state, m.opt_states, x, y, fm, lm,
            jnp.asarray(m.iteration, jnp.int32), sub)
        m._strip_rnn_state()
        m._score = score
        m.last_batch_size = n
        m.iteration += 1
        for lst in m.listeners:
            lst.iteration_done(m, m.iteration)

    def _run_fused_group(self, group):
        m = self.model
        k = len(group)
        if self._sharded_fused is None:
            self._sharded_fused = {}
            # structure warmup (carried-state keys) through one per-step
            batch, n = group[0]
            self._run_sharded_step(batch, n)
            group = group[1:]
            k = len(group)
            if not k:
                return
        if k not in self._sharded_fused:
            # the engine's own fused builder (MultiLayerNetwork/
            # ComputationGraph._build_fused_step) IS the right program:
            # params/opt/state are committed with their mesh shardings by
            # _place() and the stacked batches carry the scan-axis
            # sharding, so the jit composes the per-step psum with the
            # scan without wrapper-side re-implementation
            self._sharded_fused[k] = self.model._build_fused_step(k)
        scan_sh = NamedSharding(self.mesh, P(None, ("data", "fsdp")))
        stacked = jax.tree_util.tree_map(
            lambda *leaves: self._put_batch(np.stack(leaves), scan_sh),
            *[b for b, _ in group])
        xs, ys, fms, lms = stacked
        m._key, sub = jax.random.split(m._key)
        (m.net_params, m.net_state, m.opt_states,
         score) = self._sharded_fused[k](
            m.net_params, m.net_state, m.opt_states, xs, ys, fms, lms,
            jnp.asarray(m.iteration, jnp.int32), sub)
        m._strip_rnn_state()
        m._score = score
        m.iteration += k
        m.last_batch_size = group[0][1] * k
        for lst in m.listeners:
            lst.iteration_done(m, m.iteration)

    @staticmethod
    def _batch_sig(batch):
        leaves, treedef = jax.tree_util.tree_flatten(batch)
        # dtype included: np.stack would silently promote a mixed-dtype
        # group and train it at the promoted precision
        return (treedef, tuple((a.shape, a.dtype) for a in leaves))

    def _fit_allreduce(self, iterator, epochs: int):
        from deeplearning4j_tpu.datasets.iterators import AsyncDataSetIterator
        m = self.model
        is_graph = type(m).__name__ == "ComputationGraph"
        if m.net_params is None:
            m.init()
        self._adopt_plan(self.plan)
        if self._sharded_step is None:
            self._sharded_step = self._build_sharded_step()
            self._place()
        it = AsyncDataSetIterator(iterator, queue_size=self.prefetch_buffer)
        fuse = self.fused_steps
        try:
            for _ in range(epochs):
                it.reset()
                pending = []
                while it.has_next():
                    norm = self._normalize_batch(it.next(), is_graph)
                    if norm is None:
                        continue
                    if fuse > 1:
                        if pending and self._batch_sig(pending[0][0]) != \
                                self._batch_sig(norm[0]):
                            for b, n in pending:   # mixed shapes: per-step
                                self._run_sharded_step(b, n)
                            pending = []
                        pending.append(norm)
                        if len(pending) == fuse:
                            self._run_fused_group(pending)
                            pending = []
                    else:
                        self._run_sharded_step(*norm)
                for b, n in pending:
                    self._run_sharded_step(b, n)
        finally:
            it.close()  # a producer blocked on a full queue must not leak
        return m

    # ------------------------------------------------------------------
    def _build_local_step(self):
        """Mode 2: per-replica independent step via vmap over a leading
        device axis, sharded over 'data' → no cross-device traffic during
        local steps; averaging afterwards is the collective."""
        m = self.model
        base_step = m._build_step_raw()

        def local(params, state, opts, x, y, fm, lm, it, rng):
            return base_step(params, state, opts, x, y, fm, lm, it, rng)

        vstep = jax.vmap(local, in_axes=(0, 0, 0, 0, 0, 0, 0, None, 0))
        dev_axis = NamedSharding(self.mesh, P(("data", "fsdp")))

        jit_step = jax.jit(vstep, donate_argnums=(0, 1, 2))

        def average(params, opts):
            avg_p = jax.tree_util.tree_map(
                lambda a: jnp.broadcast_to(jnp.mean(a, axis=0), a.shape), params)
            if self.average_updaters:
                opts = jax.tree_util.tree_map(
                    lambda a: jnp.broadcast_to(jnp.mean(a, axis=0), a.shape), opts)
            return avg_p, opts

        jit_avg = jax.jit(average, donate_argnums=(0, 1))
        return jit_step, jit_avg, dev_axis

    @staticmethod
    def _put_batch(arr, batch_sh):
        """Place one batch onto the mesh.  Multi-process (the cluster
        tier, scaleout/multislice.py): each host feeds its process-LOCAL
        rows and the global array is assembled across hosts — the Spark
        executors-feed-disjoint-partitions pattern
        (ref: spark/impl/paramavg/ParameterAveragingTrainingMaster.java
        executeTraining split semantics)."""
        arr = np.asarray(arr)
        if jax.process_count() > 1:
            return jax.make_array_from_process_local_data(batch_sh, arr)
        return jax.device_put(arr, batch_sh)

    def _fit_param_averaging(self, iterator, epochs: int):
        m = self.model
        # the vmapped local step must not carry sharding constraints —
        # params deliberately live replica-per-device here
        self._adopt_plan(None)
        if m.net_params is None:
            m.init()
        if self._local_step is None:
            self._local_step = self._build_local_step()
        jit_step, jit_avg, dev_axis = self._local_step
        D = self.n_data

        # replicate model state with a leading device axis
        stack = lambda t: jax.device_put(  # noqa: E731
            jax.tree_util.tree_map(
                lambda a: jnp.broadcast_to(a[None], (D,) + a.shape), t),
            jax.tree_util.tree_map(lambda a: dev_axis, t))
        params = stack(m.net_params)
        opts = stack(m.opt_states)
        state = stack(m.net_state)

        since_avg = 0
        for _ in range(epochs):
            iterator.reset()
            while iterator.has_next():
                # one remainder policy for both modes (pad+mask, or
                # trim+warn fallback) — see _normalize_batch
                norm = self._normalize_batch(iterator.next(), False)
                if norm is None:
                    continue
                (x, y, fm, lm), _ = norm
                n = len(x)   # padded/trimmed row count, divisible by D
                shard = lambda a: (  # noqa: E731
                    None if a is None else jax.device_put(
                        np.asarray(a).reshape((D, n // D) + a.shape[1:]),
                        dev_axis))
                m._key, sub = jax.random.split(m._key)
                rngs = jax.random.split(sub, D)
                params, state, opts, scores = jit_step(
                    params, state, opts, shard(x), shard(y),
                    shard(fm), shard(lm),
                    jnp.asarray(m.iteration, jnp.int32), rngs)
                m._score = jnp.mean(scores)  # lazy; score() converts
                m.iteration += 1
                since_avg += 1
                if since_avg >= self.averaging_frequency:
                    params, opts = jit_avg(params, opts)
                    since_avg = 0
                for lst in m.listeners:
                    lst.iteration_done(m, m.iteration)
        if since_avg:
            params, opts = jit_avg(params, opts)
        # collapse the device axis back
        m.net_params = jax.tree_util.tree_map(lambda a: a[0], params)
        m.opt_states = jax.tree_util.tree_map(lambda a: a[0], opts)
        m.net_state = jax.tree_util.tree_map(lambda a: a[0], state)
        return m
