"""ParallelWrapper — data-parallel training over the device mesh.

The reference replicates the model into per-device worker threads and
synchronously averages parameters every ``averagingFrequency`` iterations
through the host (ref: parallelism/ParallelWrapper.java:49-679,
``Nd4j.averageAndPropagate`` :218).  TPU-natively there are two modes:

* ``averaging_frequency=1`` (default, recommended): per-step gradient
  all-reduce — the batch is sharded over the 'data' axis, params are
  replicated, and XLA inserts the psum over ICI inside the one jitted
  step.  Mathematically stronger than parameter averaging (equivalent to
  large-batch SGD) and what BASELINE.json prescribes.

* ``averaging_frequency=N>1`` (reference-compat): each device runs N
  independent local steps on its own replica (params carry a leading
  device axis, sharded over 'data'), then replicas are averaged — the
  mean over the device axis is XLA's all-reduce.  Reproduces the
  reference's parameter-averaging semantics including optional updater
  state averaging (ref: ParallelWrapper.averageUpdatersState :239-257).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel import mesh as mesh_util


class ParallelWrapper:
    def __init__(self, model, mesh: Optional[Mesh] = None,
                 averaging_frequency: int = 1,
                 average_updaters: bool = True,
                 prefetch_buffer: int = 4):
        self.model = model
        self.mesh = mesh if mesh is not None else mesh_util.make_mesh()
        self.averaging_frequency = averaging_frequency
        self.average_updaters = average_updaters
        self.prefetch_buffer = prefetch_buffer
        self._sharded_step = None
        self._local_step = None
        self.n_data = self.mesh.shape["data"] * self.mesh.shape["fsdp"]

    # ------------------------------------------------------------------
    def _build_sharded_step(self):
        """Mode 1: batch sharded over 'data', params replicated/FSDP;
        XLA inserts the gradient psum."""
        m = self.model
        if m.net_params is None:
            m.init()
        base_step = m._build_step_raw()

        repl = mesh_util.replicated(self.mesh)
        batch_sh = mesh_util.data_sharded(self.mesh)
        param_sh = jax.tree_util.tree_map(
            lambda a: mesh_util.param_sharding(self.mesh, a.shape), m.net_params)
        opt_sh = jax.tree_util.tree_map(
            lambda a: mesh_util.param_sharding(self.mesh, a.shape), m.opt_states)
        state_sh = jax.tree_util.tree_map(lambda a: repl, m.net_state)

        step = jax.jit(
            base_step,
            in_shardings=(param_sh, state_sh, opt_sh, batch_sh, batch_sh,
                          None, None, None, None),
            out_shardings=(param_sh, state_sh, opt_sh, repl),
            donate_argnums=(0, 1, 2))
        return step

    def _place(self):
        """Move model state onto the mesh with the right shardings."""
        m = self.model
        repl = mesh_util.replicated(self.mesh)
        m.net_params = jax.device_put(
            m.net_params,
            jax.tree_util.tree_map(
                lambda a: mesh_util.param_sharding(self.mesh, a.shape), m.net_params))
        m.opt_states = jax.device_put(
            m.opt_states,
            jax.tree_util.tree_map(
                lambda a: mesh_util.param_sharding(self.mesh, a.shape), m.opt_states))
        m.net_state = jax.device_put(
            m.net_state, jax.tree_util.tree_map(lambda a: repl, m.net_state))

    # ------------------------------------------------------------------
    def fit(self, iterator, epochs: int = 1):
        if self.averaging_frequency <= 1:
            return self._fit_allreduce(iterator, epochs)
        return self._fit_param_averaging(iterator, epochs)

    def _fit_allreduce(self, iterator, epochs: int):
        from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet
        from deeplearning4j_tpu.datasets.iterators import AsyncDataSetIterator
        m = self.model
        is_graph = type(m).__name__ == "ComputationGraph"
        if m.net_params is None:
            m.init()
        if self._sharded_step is None:
            self._sharded_step = self._build_sharded_step()
            self._place()
        batch_sh = mesh_util.data_sharded(self.mesh)
        it = AsyncDataSetIterator(iterator, queue_size=self.prefetch_buffer)
        for _ in range(epochs):
            it.reset()
            while it.has_next():
                ds = it.next()
                # ComputationGraph steps take TUPLES of inputs/labels
                # (MultiDataSet); normalize DataSet→MultiDataSet for it
                if is_graph and isinstance(ds, DataSet):
                    ds = MultiDataSet([ds.features], [ds.labels],
                                      [ds.features_mask], [ds.labels_mask])
                n = ds.num_examples()
                if n % self.n_data:
                    n_new = (n // self.n_data) * self.n_data
                    self._warn_remainder(n - n_new, n)
                    n = n_new
                    if n == 0:
                        continue
                if isinstance(ds, MultiDataSet):
                    put_all = lambda arrs: (  # noqa: E731
                        None if arrs is None else tuple(
                            None if a is None else
                            self._put_batch(a[:n], batch_sh) for a in arrs))
                    x = put_all(ds.features)
                    y = put_all(ds.labels)
                    fm = put_all(ds.features_masks)
                    lm = put_all(ds.labels_masks)
                else:
                    x = self._put_batch(ds.features[:n], batch_sh)
                    y = self._put_batch(ds.labels[:n], batch_sh)
                    fm = (self._put_batch(ds.features_mask[:n], batch_sh)
                          if ds.features_mask is not None else None)
                    lm = (self._put_batch(ds.labels_mask[:n], batch_sh)
                          if ds.labels_mask is not None else None)
                m._key, sub = jax.random.split(m._key)
                (m.net_params, m.net_state, m.opt_states, score) = self._sharded_step(
                    m.net_params, m.net_state, m.opt_states, x, y, fm, lm,
                    jnp.asarray(m.iteration, jnp.int32), sub)
                m._strip_rnn_state()
                m._score = score
                m.last_batch_size = n
                m.iteration += 1
                for lst in m.listeners:
                    lst.iteration_done(m, m.iteration)
        return m

    # ------------------------------------------------------------------
    def _build_local_step(self):
        """Mode 2: per-replica independent step via vmap over a leading
        device axis, sharded over 'data' → no cross-device traffic during
        local steps; averaging afterwards is the collective."""
        m = self.model
        base_step = m._build_step_raw()

        def local(params, state, opts, x, y, fm, lm, it, rng):
            return base_step(params, state, opts, x, y, fm, lm, it, rng)

        vstep = jax.vmap(local, in_axes=(0, 0, 0, 0, 0, 0, 0, None, 0))
        dev_axis = NamedSharding(self.mesh, P(("data", "fsdp")))

        jit_step = jax.jit(vstep, donate_argnums=(0, 1, 2))

        def average(params, opts):
            avg_p = jax.tree_util.tree_map(
                lambda a: jnp.broadcast_to(jnp.mean(a, axis=0), a.shape), params)
            if self.average_updaters:
                opts = jax.tree_util.tree_map(
                    lambda a: jnp.broadcast_to(jnp.mean(a, axis=0), a.shape), opts)
            return avg_p, opts

        jit_avg = jax.jit(average, donate_argnums=(0, 1))
        return jit_step, jit_avg, dev_axis

    @staticmethod
    def _put_batch(arr, batch_sh):
        """Place one batch onto the mesh.  Multi-process (the cluster
        tier, scaleout/multislice.py): each host feeds its process-LOCAL
        rows and the global array is assembled across hosts — the Spark
        executors-feed-disjoint-partitions pattern
        (ref: spark/impl/paramavg/ParameterAveragingTrainingMaster.java
        executeTraining split semantics)."""
        arr = np.asarray(arr)
        if jax.process_count() > 1:
            return jax.make_array_from_process_local_data(batch_sh, arr)
        return jax.device_put(arr, batch_sh)

    def _warn_remainder(self, dropped: int, batch: int):
        """Round-2 advisor finding: remainder examples were dropped
        SILENTLY.  Dropping (the reference's round-robin feeding does the
        same) is still the policy, but it is now visible — resize batches
        to a multiple of the data-parallel degree to use every example."""
        import warnings
        if not getattr(self, "_remainder_warned", False):
            self._remainder_warned = True
            warnings.warn(
                f"ParallelWrapper: dropping {dropped} of {batch} examples "
                f"per batch (batch not divisible by data degree "
                f"{self.n_data}); pad or resize batches to avoid this",
                stacklevel=3)

    def _fit_param_averaging(self, iterator, epochs: int):
        m = self.model
        if m.net_params is None:
            m.init()
        if self._local_step is None:
            self._local_step = self._build_local_step()
        jit_step, jit_avg, dev_axis = self._local_step
        D = self.n_data

        # replicate model state with a leading device axis
        stack = lambda t: jax.device_put(  # noqa: E731
            jax.tree_util.tree_map(
                lambda a: jnp.broadcast_to(a[None], (D,) + a.shape), t),
            jax.tree_util.tree_map(lambda a: dev_axis, t))
        params = stack(m.net_params)
        opts = stack(m.opt_states)
        state = stack(m.net_state)

        since_avg = 0
        for _ in range(epochs):
            iterator.reset()
            while iterator.has_next():
                ds = iterator.next()
                n = (ds.num_examples() // D) * D
                if n != ds.num_examples():
                    self._warn_remainder(ds.num_examples() - n,
                                         ds.num_examples())
                if n == 0:
                    continue
                shard = lambda a: (  # noqa: E731
                    None if a is None else jax.device_put(
                        np.asarray(a[:n]).reshape((D, n // D) + a.shape[1:]),
                        dev_axis))
                m._key, sub = jax.random.split(m._key)
                rngs = jax.random.split(sub, D)
                params, state, opts, scores = jit_step(
                    params, state, opts, shard(ds.features), shard(ds.labels),
                    shard(ds.features_mask), shard(ds.labels_mask),
                    jnp.asarray(m.iteration, jnp.int32), rngs)
                m._score = jnp.mean(scores)  # lazy; score() converts
                m.iteration += 1
                since_avg += 1
                if since_avg >= self.averaging_frequency:
                    params, opts = jit_avg(params, opts)
                    since_avg = 0
                for lst in m.listeners:
                    lst.iteration_done(m, m.iteration)
        if since_avg:
            params, opts = jit_avg(params, opts)
        # collapse the device axis back
        m.net_params = jax.tree_util.tree_map(lambda a: a[0], params)
        m.opt_states = jax.tree_util.tree_map(lambda a: a[0], opts)
        m.net_state = jax.tree_util.tree_map(lambda a: a[0], state)
        return m
