"""ParallelWrapper — data-parallel training over the device mesh.

The reference replicates the model into per-device worker threads and
synchronously averages parameters every ``averagingFrequency`` iterations
through the host (ref: parallelism/ParallelWrapper.java:49-679,
``Nd4j.averageAndPropagate`` :218).  TPU-natively there are two modes:

* ``averaging_frequency=1`` (default, recommended): per-step gradient
  all-reduce — the batch is sharded over the 'data' axis, params are
  replicated, and XLA inserts the psum over ICI inside the one jitted
  step.  Mathematically stronger than parameter averaging (equivalent to
  large-batch SGD) and what BASELINE.json prescribes.

* ``averaging_frequency=N>1`` (reference-compat): each device runs N
  independent local steps on its own replica (params carry a leading
  device axis, sharded over 'data'), then replicas are averaged — the
  mean over the device axis is XLA's all-reduce.  Reproduces the
  reference's parameter-averaging semantics including optional updater
  state averaging (ref: ParallelWrapper.averageUpdatersState :239-257).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.ops import bucketing
from deeplearning4j_tpu.parallel import mesh as mesh_util


class ParallelWrapper:
    def __init__(self, model, mesh: Optional[Mesh] = None,
                 averaging_frequency: int = 1,
                 average_updaters: bool = True,
                 prefetch_buffer: int = 4,
                 fused_steps: int = 1):
        """``fused_steps=K>1`` (all-reduce mode only) fuses K same-shape
        sharded batches into ONE compiled lax.scan launch — the engine's
        fit(fused_steps=K) dispatch elimination, composed with the
        per-step gradient psum.  Same caveats: listeners fire once per
        launch, ragged tails fall back per-step."""
        self.model = model
        self.mesh = mesh if mesh is not None else mesh_util.make_mesh()
        self.averaging_frequency = averaging_frequency
        self.average_updaters = average_updaters
        self.prefetch_buffer = prefetch_buffer
        self.fused_steps = max(1, int(fused_steps))
        self._sharded_step = None
        self._sharded_fused = None
        self._local_step = None
        self.n_data = self.mesh.shape["data"] * self.mesh.shape["fsdp"]

    # ------------------------------------------------------------------
    def _build_sharded_step(self):
        """Mode 1: batch sharded over 'data', params replicated/FSDP;
        XLA inserts the gradient psum."""
        m = self.model
        if m.net_params is None:
            m.init()
        base_step = m._build_step_raw()

        repl = mesh_util.replicated(self.mesh)
        batch_sh = mesh_util.data_sharded(self.mesh)
        param_sh = jax.tree_util.tree_map(
            lambda a: mesh_util.param_sharding(self.mesh, a.shape), m.net_params)
        opt_sh = jax.tree_util.tree_map(
            lambda a: mesh_util.param_sharding(self.mesh, a.shape), m.opt_states)

        # net_state uses a PREFIX sharding (one sharding for every leaf):
        # an RNN step's output state gains carried keys (rnn_state) the
        # input structure doesn't have, so a full-tree spec would pin the
        # wrong structure for out_shardings
        step = jax.jit(
            base_step,
            in_shardings=(param_sh, repl, opt_sh, batch_sh, batch_sh,
                          None, None, None, None),
            out_shardings=(param_sh, repl, opt_sh, repl),
            donate_argnums=(0, 1, 2))
        return step

    def _place(self):
        """Move model state onto the mesh with the right shardings."""
        m = self.model
        repl = mesh_util.replicated(self.mesh)
        m.net_params = jax.device_put(
            m.net_params,
            jax.tree_util.tree_map(
                lambda a: mesh_util.param_sharding(self.mesh, a.shape), m.net_params))
        m.opt_states = jax.device_put(
            m.opt_states,
            jax.tree_util.tree_map(
                lambda a: mesh_util.param_sharding(self.mesh, a.shape), m.opt_states))
        m.net_state = jax.device_put(
            m.net_state, jax.tree_util.tree_map(lambda a: repl, m.net_state))

    # ------------------------------------------------------------------
    def fit(self, iterator, epochs: int = 1):
        if self.averaging_frequency <= 1:
            return self._fit_allreduce(iterator, epochs)
        return self._fit_param_averaging(iterator, epochs)

    # Pad/mask primitives now live in ops/bucketing.py (shared with the
    # engines' shape-bucketing paths); kept as aliases for callers/tests.
    _MASK_NONLINEAR_LOSSES = bucketing.MASK_NONLINEAR_LOSSES
    _cycle_rows = staticmethod(bucketing.cycle_rows)
    _scaled_mask = staticmethod(bucketing.scaled_mask)

    def _pad_supported(self):
        """See ops/bucketing.pad_supported — mean reduction, mask-linear
        losses, no batch-coupled aux losses."""
        return bucketing.pad_supported(self.model)

    def _normalize_batch(self, ds, is_graph):
        """(x, y, fm, lm) host pytrees at a data-degree multiple.  A
        non-divisible batch is PADDED with cycled real rows whose loss is
        masked out and the valid rows' mask rescaled, so every example
        trains and gradients equal the unsharded step exactly (the
        reference's round-robin feedDataSet trains on every example —
        ref: parallelism/ParallelWrapper.java:383).  Mask-nonlinear
        losses fall back to trimming (warned).  Returns (batch, n) with
        ``n`` the REAL example count, or None when everything would be
        dropped."""
        from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet
        if is_graph and isinstance(ds, DataSet):
            # ComputationGraph steps take TUPLES of inputs/labels
            ds = MultiDataSet([ds.features], [ds.labels],
                              [ds.features_mask], [ds.labels_mask])
        n = ds.num_examples()
        g = self.model.conf.global_conf
        if getattr(g, "shape_bucketing", False) and self._pad_supported():
            # shape bucketing subsumes the remainder policy: the batch
            # bucket is lifted to a data-degree multiple, rows are
            # cycled and the labels mask rescaled exactly as below —
            # every sharded launch is then bucket-shaped, so the jitted
            # sharded step (and the fused scan) compiles once per bucket
            fn = (bucketing.bucket_train_multidataset
                  if isinstance(ds, MultiDataSet)
                  else bucketing.bucket_train_dataset)
            ds_b, bucket = fn(ds, g, min_multiple=self.n_data)
            if bucket is not None:
                batch = self._host_batch(ds_b)
                tel = getattr(self.model, "compile_telemetry", None)
                if tel is not None:
                    tel.record("sharded_step", batch, bucket=bucket)
                return batch, n
        rem = n % self.n_data
        pad_ok = bool(rem) and self._pad_supported()
        lm_base = None
        if pad_ok:
            # The synthesized labels mask takes precedence over the
            # features-propagated time mask in the step's loss
            # (multilayer.py loss_fn lm resolution), so when a features
            # mask exists without a labels mask it must BECOME the base
            # of the scaled mask — and only when its shape provably
            # matches the labels' time layout; otherwise trim.
            if isinstance(ds, MultiDataSet):
                # container-level None checks are not enough: the
                # DataSet→MultiDataSet wrap above produces [None] lists,
                # so compare the ENTRIES
                def _all_none(t):
                    return t is None or all(m is None for m in t)
                if not _all_none(ds.features_masks) \
                        and _all_none(ds.labels_masks):
                    pad_ok = False  # multi-input→output mask routing is
                    # ambiguous; don't guess
            elif ds.labels_mask is not None:
                lm_base = np.asarray(ds.labels_mask)
            elif ds.features_mask is not None:
                fm_arr = np.asarray(ds.features_mask)
                y_arr = np.asarray(ds.labels)
                if fm_arr.ndim == y_arr.ndim - 1 \
                        and fm_arr.shape == y_arr.shape[:-1]:
                    lm_base = fm_arr
                else:
                    pad_ok = False
        if pad_ok:
            target = n + (self.n_data - rem)
            cyc = lambda a: (None if a is None  # noqa: E731
                             else self._cycle_rows(a, target))
            if isinstance(ds, MultiDataSet):
                lms = (ds.labels_masks
                       if ds.labels_masks is not None
                       else (None,) * len(ds.labels))
                return ((tuple(cyc(a) for a in ds.features),
                         tuple(cyc(a) for a in ds.labels),
                         None if ds.features_masks is None else
                         tuple(cyc(a) for a in ds.features_masks),
                         tuple(self._scaled_mask(lm, y, n, target)
                               for lm, y in zip(lms, ds.labels))), n)
            return ((cyc(ds.features), cyc(ds.labels),
                     cyc(ds.features_mask),
                     self._scaled_mask(lm_base, ds.labels,
                                       n, target)), n)
        if rem:
            n_new = (n // self.n_data) * self.n_data
            self._warn_remainder(n - n_new, n)
            n = n_new
            if n == 0:
                return None
        if isinstance(ds, MultiDataSet):
            trim = lambda arrs: (  # noqa: E731
                None if arrs is None else tuple(
                    None if a is None else np.asarray(a)[:n] for a in arrs))
            return (trim(ds.features), trim(ds.labels),
                    trim(ds.features_masks), trim(ds.labels_masks)), n
        return ((np.asarray(ds.features)[:n], np.asarray(ds.labels)[:n],
                 None if ds.features_mask is None
                 else np.asarray(ds.features_mask)[:n],
                 None if ds.labels_mask is None
                 else np.asarray(ds.labels_mask)[:n])), n

    @staticmethod
    def _host_batch(ds):
        """DataSet/MultiDataSet → the (x, y, fm, lm) host-pytree the
        sharded step consumes."""
        from deeplearning4j_tpu.datasets.dataset import MultiDataSet
        if isinstance(ds, MultiDataSet):
            tup = lambda arrs: (  # noqa: E731
                None if arrs is None else tuple(
                    None if a is None else np.asarray(a) for a in arrs))
            return (tuple(np.asarray(a) for a in ds.features),
                    tuple(np.asarray(a) for a in ds.labels),
                    tup(ds.features_masks), tup(ds.labels_masks))
        return (np.asarray(ds.features), np.asarray(ds.labels),
                None if ds.features_mask is None
                else np.asarray(ds.features_mask),
                None if ds.labels_mask is None
                else np.asarray(ds.labels_mask))

    def _run_sharded_step(self, batch, n):
        m = self.model
        batch_sh = mesh_util.data_sharded(self.mesh)
        x, y, fm, lm = jax.tree_util.tree_map(
            lambda a: self._put_batch(a, batch_sh), batch)
        m._key, sub = jax.random.split(m._key)
        (m.net_params, m.net_state, m.opt_states, score) = self._sharded_step(
            m.net_params, m.net_state, m.opt_states, x, y, fm, lm,
            jnp.asarray(m.iteration, jnp.int32), sub)
        m._strip_rnn_state()
        m._score = score
        m.last_batch_size = n
        m.iteration += 1
        for lst in m.listeners:
            lst.iteration_done(m, m.iteration)

    def _run_fused_group(self, group):
        m = self.model
        k = len(group)
        if self._sharded_fused is None:
            self._sharded_fused = {}
            # structure warmup (carried-state keys) through one per-step
            batch, n = group[0]
            self._run_sharded_step(batch, n)
            group = group[1:]
            k = len(group)
            if not k:
                return
        if k not in self._sharded_fused:
            # the engine's own fused builder (MultiLayerNetwork/
            # ComputationGraph._build_fused_step) IS the right program:
            # params/opt/state are committed with their mesh shardings by
            # _place() and the stacked batches carry the scan-axis
            # sharding, so the jit composes the per-step psum with the
            # scan without wrapper-side re-implementation
            self._sharded_fused[k] = self.model._build_fused_step(k)
        scan_sh = NamedSharding(self.mesh, P(None, ("data", "fsdp")))
        stacked = jax.tree_util.tree_map(
            lambda *leaves: self._put_batch(np.stack(leaves), scan_sh),
            *[b for b, _ in group])
        xs, ys, fms, lms = stacked
        m._key, sub = jax.random.split(m._key)
        (m.net_params, m.net_state, m.opt_states,
         score) = self._sharded_fused[k](
            m.net_params, m.net_state, m.opt_states, xs, ys, fms, lms,
            jnp.asarray(m.iteration, jnp.int32), sub)
        m._strip_rnn_state()
        m._score = score
        m.iteration += k
        m.last_batch_size = group[0][1] * k
        for lst in m.listeners:
            lst.iteration_done(m, m.iteration)

    @staticmethod
    def _batch_sig(batch):
        leaves, treedef = jax.tree_util.tree_flatten(batch)
        # dtype included: np.stack would silently promote a mixed-dtype
        # group and train it at the promoted precision
        return (treedef, tuple((a.shape, a.dtype) for a in leaves))

    def _fit_allreduce(self, iterator, epochs: int):
        from deeplearning4j_tpu.datasets.iterators import AsyncDataSetIterator
        m = self.model
        is_graph = type(m).__name__ == "ComputationGraph"
        if m.net_params is None:
            m.init()
        if self._sharded_step is None:
            self._sharded_step = self._build_sharded_step()
            self._place()
        it = AsyncDataSetIterator(iterator, queue_size=self.prefetch_buffer)
        fuse = self.fused_steps
        try:
            for _ in range(epochs):
                it.reset()
                pending = []
                while it.has_next():
                    norm = self._normalize_batch(it.next(), is_graph)
                    if norm is None:
                        continue
                    if fuse > 1:
                        if pending and self._batch_sig(pending[0][0]) != \
                                self._batch_sig(norm[0]):
                            for b, n in pending:   # mixed shapes: per-step
                                self._run_sharded_step(b, n)
                            pending = []
                        pending.append(norm)
                        if len(pending) == fuse:
                            self._run_fused_group(pending)
                            pending = []
                    else:
                        self._run_sharded_step(*norm)
                for b, n in pending:
                    self._run_sharded_step(b, n)
        finally:
            it.close()  # a producer blocked on a full queue must not leak
        return m

    # ------------------------------------------------------------------
    def _build_local_step(self):
        """Mode 2: per-replica independent step via vmap over a leading
        device axis, sharded over 'data' → no cross-device traffic during
        local steps; averaging afterwards is the collective."""
        m = self.model
        base_step = m._build_step_raw()

        def local(params, state, opts, x, y, fm, lm, it, rng):
            return base_step(params, state, opts, x, y, fm, lm, it, rng)

        vstep = jax.vmap(local, in_axes=(0, 0, 0, 0, 0, 0, 0, None, 0))
        dev_axis = NamedSharding(self.mesh, P(("data", "fsdp")))

        jit_step = jax.jit(vstep, donate_argnums=(0, 1, 2))

        def average(params, opts):
            avg_p = jax.tree_util.tree_map(
                lambda a: jnp.broadcast_to(jnp.mean(a, axis=0), a.shape), params)
            if self.average_updaters:
                opts = jax.tree_util.tree_map(
                    lambda a: jnp.broadcast_to(jnp.mean(a, axis=0), a.shape), opts)
            return avg_p, opts

        jit_avg = jax.jit(average, donate_argnums=(0, 1))
        return jit_step, jit_avg, dev_axis

    @staticmethod
    def _put_batch(arr, batch_sh):
        """Place one batch onto the mesh.  Multi-process (the cluster
        tier, scaleout/multislice.py): each host feeds its process-LOCAL
        rows and the global array is assembled across hosts — the Spark
        executors-feed-disjoint-partitions pattern
        (ref: spark/impl/paramavg/ParameterAveragingTrainingMaster.java
        executeTraining split semantics)."""
        arr = np.asarray(arr)
        if jax.process_count() > 1:
            return jax.make_array_from_process_local_data(batch_sh, arr)
        return jax.device_put(arr, batch_sh)

    def _warn_remainder(self, dropped: int, batch: int):
        """Non-divisible batches are normally padded+masked so every
        example trains (round-4 verdict weak #5); this warning only fires
        on the trim fallback for mask-nonlinear losses
        (_MASK_NONLINEAR_LOSSES / CenterLoss)."""
        import warnings
        if not getattr(self, "_remainder_warned", False):
            self._remainder_warned = True
            warnings.warn(
                f"ParallelWrapper: dropping {dropped} of {batch} examples "
                f"per batch (batch not divisible by data degree "
                f"{self.n_data}); pad or resize batches to avoid this",
                stacklevel=3)

    def _fit_param_averaging(self, iterator, epochs: int):
        m = self.model
        if m.net_params is None:
            m.init()
        if self._local_step is None:
            self._local_step = self._build_local_step()
        jit_step, jit_avg, dev_axis = self._local_step
        D = self.n_data

        # replicate model state with a leading device axis
        stack = lambda t: jax.device_put(  # noqa: E731
            jax.tree_util.tree_map(
                lambda a: jnp.broadcast_to(a[None], (D,) + a.shape), t),
            jax.tree_util.tree_map(lambda a: dev_axis, t))
        params = stack(m.net_params)
        opts = stack(m.opt_states)
        state = stack(m.net_state)

        since_avg = 0
        for _ in range(epochs):
            iterator.reset()
            while iterator.has_next():
                # one remainder policy for both modes (pad+mask, or
                # trim+warn fallback) — see _normalize_batch
                norm = self._normalize_batch(iterator.next(), False)
                if norm is None:
                    continue
                (x, y, fm, lm), _ = norm
                n = len(x)   # padded/trimmed row count, divisible by D
                shard = lambda a: (  # noqa: E731
                    None if a is None else jax.device_put(
                        np.asarray(a).reshape((D, n // D) + a.shape[1:]),
                        dev_axis))
                m._key, sub = jax.random.split(m._key)
                rngs = jax.random.split(sub, D)
                params, state, opts, scores = jit_step(
                    params, state, opts, shard(x), shard(y),
                    shard(fm), shard(lm),
                    jnp.asarray(m.iteration, jnp.int32), rngs)
                m._score = jnp.mean(scores)  # lazy; score() converts
                m.iteration += 1
                since_avg += 1
                if since_avg >= self.averaging_frequency:
                    params, opts = jit_avg(params, opts)
                    since_avg = 0
                for lst in m.listeners:
                    lst.iteration_done(m, m.iteration)
        if since_avg:
            params, opts = jit_avg(params, opts)
        # collapse the device axis back
        m.net_params = jax.tree_util.tree_map(lambda a: a[0], params)
        m.opt_states = jax.tree_util.tree_map(lambda a: a[0], opts)
        m.net_state = jax.tree_util.tree_map(lambda a: a[0], state)
        return m
