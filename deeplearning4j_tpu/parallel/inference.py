"""ParallelInference — a batching inference front-end.

(ref: parallelism/ParallelInference.java:32-370 — requests queue into a
BlockingQueue, BatchedInferenceObservable merges concurrent requests up
to ``batchLimit`` into a single ``output()`` call.)  One jitted forward
on the TPU serves all callers; dynamic batching amortizes dispatch.

Sharded serving (ROADMAP 3a): when the model's conf declares a
``sharding(...)`` plan, ``model.output()`` runs as a pjit'd program with
the plan's in/out shardings — params stay in their fsdp layout (a model
that only fits sharded never materializes whole on one device), the
merged batch shards over the mesh's data axis, and the output replicates
on device.  This front-end stays plan-agnostic except for two edges: the
merged batch is lifted to a multiple of the mesh's data degree (one
all-gather-free dispatch instead of a pad-per-request), and the ONLY
host transfer is the explicit ``jax.device_get`` on the final output —
the response edge.
"""

from __future__ import annotations

import queue
import threading
from typing import List, Optional

import jax
import numpy as np


class _Request:
    def __init__(self, x: np.ndarray):
        self.x = x
        self.event = threading.Event()
        self.result: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None


class ParallelInference:
    INFERENCE_MODE_BATCHED = "batched"
    INFERENCE_MODE_SEQUENTIAL = "sequential"

    def __init__(self, model, batch_limit: int = 32, queue_limit: int = 64,
                 inference_mode: str = "batched", workers: int = 1):
        self.model = model
        self.batch_limit = batch_limit
        n_data = self._plan_data_degree()
        if n_data > 1 and batch_limit % n_data:
            # merged batches divide the mesh's data axis: round the merge
            # target UP so a full batch dispatches without pad rows
            self.batch_limit = batch_limit + n_data - batch_limit % n_data
        self.inference_mode = inference_mode
        self._queue: "queue.Queue[_Request]" = queue.Queue(maxsize=queue_limit)
        self._shutdown = threading.Event()
        self._threads = [threading.Thread(target=self._worker, daemon=True)
                         for _ in range(max(1, workers))]
        for t in self._threads:
            t.start()

    def _plan_data_degree(self) -> int:
        """The mesh's batch degree under the model's sharding plan (1
        when serving unsharded) — resolved lazily so construction before
        ``init()`` still works."""
        try:
            self.model._ensure_sharding()
            plan = getattr(self.model, "_sharding_plan", None)
            return int(plan.n_data) if plan is not None else 1
        except Exception:
            return 1

    def _worker(self):
        while not self._shutdown.is_set():
            try:
                first = self._queue.get(timeout=0.1)
            except queue.Empty:
                continue
            batch: List[_Request] = [first]
            if self.inference_mode == self.INFERENCE_MODE_BATCHED:
                total = first.x.shape[0]
                while total < self.batch_limit:
                    try:
                        nxt = self._queue.get_nowait()
                    except queue.Empty:
                        break
                    batch.append(nxt)
                    total += nxt.x.shape[0]
            try:
                x = np.concatenate([r.x for r in batch]) if len(batch) > 1 else batch[0].x
                out = self.model.output(x)
                if isinstance(out, tuple):   # multi-output graph: first head
                    out = out[0]
                # the response edge: the one explicit device→host gather
                # (sharded outputs all-gathered on device by the pjit'd
                # program, so this is a single replicated pull)
                out = np.asarray(jax.device_get(out))
                off = 0
                for r in batch:
                    n = r.x.shape[0]
                    r.result = out[off:off + n]
                    off += n
            except BaseException as e:  # propagate to all waiters
                for r in batch:
                    r.error = e
            finally:
                for r in batch:
                    r.event.set()

    def output(self, x, timeout: Optional[float] = 60.0) -> np.ndarray:
        """Blocking call, safe from many threads; requests are batched."""
        req = _Request(np.asarray(x))
        self._queue.put(req)
        if not req.event.wait(timeout):
            raise TimeoutError("ParallelInference request timed out")
        if req.error is not None:
            raise req.error
        return req.result

    def shutdown(self):
        self._shutdown.set()
        for t in self._threads:
            t.join(timeout=2)
