"""ParallelInference — a batching inference front-end.

(ref: parallelism/ParallelInference.java:32-370 — requests queue into a
BlockingQueue, BatchedInferenceObservable merges concurrent requests up
to ``batchLimit`` into a single ``output()`` call.)  One jitted forward
on the TPU serves all callers; dynamic batching amortizes dispatch.
"""

from __future__ import annotations

import queue
import threading
from typing import List, Optional

import numpy as np


class _Request:
    def __init__(self, x: np.ndarray):
        self.x = x
        self.event = threading.Event()
        self.result: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None


class ParallelInference:
    INFERENCE_MODE_BATCHED = "batched"
    INFERENCE_MODE_SEQUENTIAL = "sequential"

    def __init__(self, model, batch_limit: int = 32, queue_limit: int = 64,
                 inference_mode: str = "batched", workers: int = 1):
        self.model = model
        self.batch_limit = batch_limit
        self.inference_mode = inference_mode
        self._queue: "queue.Queue[_Request]" = queue.Queue(maxsize=queue_limit)
        self._shutdown = threading.Event()
        self._threads = [threading.Thread(target=self._worker, daemon=True)
                         for _ in range(max(1, workers))]
        for t in self._threads:
            t.start()

    def _worker(self):
        while not self._shutdown.is_set():
            try:
                first = self._queue.get(timeout=0.1)
            except queue.Empty:
                continue
            batch: List[_Request] = [first]
            if self.inference_mode == self.INFERENCE_MODE_BATCHED:
                total = first.x.shape[0]
                while total < self.batch_limit:
                    try:
                        nxt = self._queue.get_nowait()
                    except queue.Empty:
                        break
                    batch.append(nxt)
                    total += nxt.x.shape[0]
            try:
                x = np.concatenate([r.x for r in batch]) if len(batch) > 1 else batch[0].x
                out = np.asarray(self.model.output(x))
                off = 0
                for r in batch:
                    n = r.x.shape[0]
                    r.result = out[off:off + n]
                    off += n
            except BaseException as e:  # propagate to all waiters
                for r in batch:
                    r.error = e
            finally:
                for r in batch:
                    r.event.set()

    def output(self, x, timeout: Optional[float] = 60.0) -> np.ndarray:
        """Blocking call, safe from many threads; requests are batched."""
        req = _Request(np.asarray(x))
        self._queue.put(req)
        if not req.event.wait(timeout):
            raise TimeoutError("ParallelInference request timed out")
        if req.error is not None:
            raise req.error
        return req.result

    def shutdown(self):
        self._shutdown.set()
        for t in self._threads:
            t.join(timeout=2)
