"""Pipeline parallelism — GPipe-style stage partitioning over a mesh
axis (scaling-book pipelining recipe; no reference analog — DL4J's
distribution tiers are data-parallel only, SURVEY.md §2.4-2.6 — this is
part of the TPU-native multi-chip story alongside dp/fsdp/tp/sp/ep).

The model is a stack of S *identical* blocks (the practical pipeline
case: repeated transformer/dense blocks).  Block parameters are stacked
on a leading stage dimension and sharded over the pipeline axis, so each
device holds exactly its stage's weights.  The schedule runs
``M + S - 1`` ticks; each tick every stage applies its block to its
current microbatch and ``lax.ppermute``s the activation to the next
stage (neighbor transfer → rides ICI).  Outputs are collected on the
last stage and broadcast with a ``psum``.  Bubble fraction is
``(S-1)/(M+S-1)`` — raise the microbatch count M to amortize.

Everything is differentiable (scan + ppermute + psum), so ``jax.grad``
through ``pipeline_apply`` gives pipeline-parallel training for free.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from deeplearning4j_tpu.parallel.mesh import shard_map_compat as shard_map
from jax.sharding import Mesh, PartitionSpec as P

PIPELINE_AXIS = "model"  # default: reuse the mesh's 'model' axis for stages


def stack_block_params(params_list):
    """[per-stage pytree, ...] → stacked pytree with leading stage dim
    (shard this dim over the pipeline axis)."""
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *params_list)


def _pipeline_sharded(params, xs, *, block_fn, axis: str, n_stages: int):
    """Per-shard body.  params: this stage's block params (leading stage
    dim of size 1, squeezed); xs: full microbatch stack [M, mb, ...]
    (replicated — only stage 0 reads it)."""
    params = jax.tree_util.tree_map(lambda a: a[0], params)
    idx = lax.axis_index(axis)
    S = n_stages
    M = xs.shape[0]
    mb_shape = xs.shape[1:]

    # one extra row absorbs not-yet-valid writes (t < S-1 → slot M)
    outs0 = jnp.zeros((M + 1,) + mb_shape, xs.dtype)
    buf0 = jnp.zeros(mb_shape, xs.dtype)
    perm = [(i, (i + 1) % S) for i in range(S)]

    def tick(carry, t):
        buf, outs = carry
        feed = jnp.where(t < M, t, 0)
        inp = jnp.where(idx == 0, xs[feed], buf)
        y = block_fn(params, inp)
        out_slot = jnp.where(t >= S - 1, t - (S - 1), M)
        outs = lax.dynamic_update_index_in_dim(
            outs, jnp.where(idx == S - 1, y, jnp.zeros_like(y)),
            out_slot, axis=0)
        buf = lax.ppermute(y, axis, perm)
        return (buf, outs), None

    (_, outs), _ = lax.scan(tick, (buf0, outs0), jnp.arange(M + S - 1))
    # last stage holds the real outputs; everyone else contributed zeros
    return lax.psum(outs[:M], axis)


def pipeline_apply(block_fn: Callable, stacked_params, microbatches,
                   *, mesh: Mesh, axis: str = PIPELINE_AXIS):
    """Run the pipeline.  stacked_params: pytree with leading stage dim
    S == mesh.shape[axis]; microbatches: [M, mb, ...] array."""
    S = int(mesh.shape[axis])
    leading = {a.shape[0] for a in jax.tree_util.tree_leaves(stacked_params)}
    if leading != {S}:
        raise ValueError(
            f"stacked params leading dim {leading} != pipeline axis size {S}")
    param_specs = jax.tree_util.tree_map(
        lambda a: P(axis, *([None] * (a.ndim - 1))), stacked_params)
    fn = shard_map(
        partial(_pipeline_sharded, block_fn=block_fn, axis=axis,
                n_stages=S),
        mesh=mesh,
        in_specs=(param_specs, P()),
        out_specs=P(),
        check_vma=False)
    return fn(stacked_params, microbatches)


def pipeline_loss_fn(block_fn: Callable, loss_fn: Callable, *, mesh: Mesh,
                     axis: str = PIPELINE_AXIS):
    """Convenience: (stacked_params, microbatches, labels) → scalar loss
    through the pipeline — differentiate with jax.grad for
    pipeline-parallel training."""

    def f(stacked_params, microbatches, labels):
        outs = pipeline_apply(block_fn, stacked_params, microbatches,
                              mesh=mesh, axis=axis)
        return loss_fn(outs, labels)

    return f
