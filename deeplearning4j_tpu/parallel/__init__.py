"""Multi-device / multi-host training & inference on the TPU mesh.

The reference's three distribution tiers — ParallelWrapper threads with
host-staged parameter averaging (ref: parallelism/ParallelWrapper.java:218),
the Aeron parameter server (ref: ParameterServerTrainer.java), and Spark
parameter averaging (ref: ParameterAveragingTrainingMaster.java) — all
collapse into ONE TPU-native answer here: shardings over a
``jax.sharding.Mesh`` with XLA collectives (psum over ICI; multi-slice
GSPMD over DCN), inside the single jitted train step.
"""

from deeplearning4j_tpu.parallel.mesh import MeshConfig, make_mesh  # noqa: F401
from deeplearning4j_tpu.parallel import fsdp  # noqa: F401
from deeplearning4j_tpu.parallel.fsdp import ShardingPlan  # noqa: F401
from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper  # noqa: F401
from deeplearning4j_tpu.parallel.inference import ParallelInference  # noqa: F401
