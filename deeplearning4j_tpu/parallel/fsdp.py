"""Production FSDP: ZeRO-style sharded weight update in the default
fit path.

The replica-style fit loop keeps full params AND full updater state on
every device, so memory — not FLOPs — caps model size.  This module
promotes the 5-axis mesh (parallel/mesh.py) into ``MultiLayerNetwork.fit``
and ``ComputationGraph.fit`` behind ``conf.sharding(data=..., fsdp=...)``:

* params and updater state are laid out by a :class:`ShardingPlan` —
  large weight matrices shard over the ``fsdp`` axis, small arrays
  (biases, BN stats) under ``replicate_below`` elements stay replicated;
* the fused train step is jitted with ``in_shardings``/``out_shardings``
  and ``donate_argnums`` on params+updater so the step is in-place on
  device, and gradients carry an explicit ``with_sharding_constraint``
  to the param layout — XLA lowers that to reduce-scatter(grads) →
  per-shard updater update → all-gather(params), the weight-update
  sharding of "Automatic Cross-Replica Sharding of Weight Update in
  Data-Parallel Training" (arXiv 2004.13336);
* checkpoints stay mesh-shape-tolerant: the canonical flat host vector
  (nn/serialization.py) is the portable redistribution format (the
  single-host analog of arXiv 2112.01075's collective-based resharding),
  and :func:`sharding_manifest` records the mesh + per-param specs so
  ``resume_from_checkpoint`` can reshard host-side onto ANY mesh.

Degrades gracefully: no ``conf.sharding()`` / a single visible device /
an indivisible mesh → :func:`plan_from_conf` returns None and the fit
path is byte-identical to the replica-style one.
"""

from __future__ import annotations

import dataclasses
import logging
import warnings
from typing import Dict, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu import monitor
from deeplearning4j_tpu.ops import bucketing
from deeplearning4j_tpu.parallel import mesh as mesh_util

log = logging.getLogger(__name__)

tree_map = jax.tree_util.tree_map

# Mesh construction touches every device — cache per (devices, shape).
_MESH_CACHE: Dict[Tuple, Mesh] = {}


@dataclasses.dataclass(frozen=True)
class ShardingPlan:
    """Resolved sharding layout for one model: the mesh plus the policy
    mapping each array shape to a :class:`NamedSharding`."""

    mesh: Mesh
    replicate_below: int
    key: Tuple  # identity for trace-token / rebuild decisions

    @property
    def n_data(self) -> int:
        """Batch-axis degree — the data(+fsdp) product every global
        batch must divide into."""
        return self.mesh.shape["data"] * self.mesh.shape["fsdp"]

    def param_sharding(self, shape) -> NamedSharding:
        return mesh_util.param_sharding(
            self.mesh, tuple(shape), replicate_below=self.replicate_below)

    def batch_sharding(self) -> NamedSharding:
        return mesh_util.data_sharded(self.mesh)

    def replicated(self) -> NamedSharding:
        return mesh_util.replicated(self.mesh)

    def tree_shardings(self, tree):
        return tree_map(lambda a: self.param_sharding(a.shape), tree)

    def constrain_grads(self, tree):
        """The explicit ZeRO reduce-scatter point: pin each gradient to
        its param's fsdp layout right after backward, so XLA lowers the
        data-parallel gradient reduction as reduce-scatter into shards
        instead of a full all-reduce, and the updater math that follows
        runs per-shard."""
        return tree_map(
            lambda g: jax.lax.with_sharding_constraint(
                g, self.param_sharding(g.shape)), tree)


def conf_key(g) -> Optional[Tuple]:
    """Trace-token component for the conf's sharding request (None when
    sharding is off) — cheap, no device enumeration."""
    if not getattr(g, "sharding_enabled", False):
        return None
    return (g.sharding_data, g.sharding_fsdp, g.sharding_model,
            g.sharding_replicate_below)


def plan_key(plan: Optional[ShardingPlan]) -> Optional[Tuple]:
    return None if plan is None else plan.key


def plan_from_conf(g, devices=None) -> Optional[ShardingPlan]:
    """Build the active plan for a conf, or None when sharding should
    stay off: not enabled, a single visible device (replica-style is
    already optimal — the graceful-degrade contract), or a mesh request
    the device count cannot satisfy (warned once, never fatal)."""
    if not getattr(g, "sharding_enabled", False):
        return None
    devices = list(devices if devices is not None else jax.devices())
    if len(devices) < 2:
        return None
    cfg = mesh_util.MeshConfig(
        data=int(g.sharding_data), fsdp=int(g.sharding_fsdp),
        model=int(g.sharding_model))
    try:
        shape = cfg.resolve(len(devices))
    except ValueError as e:
        warnings.warn(f"conf.sharding() disabled: {e} — training "
                      f"replica-style", stacklevel=2)
        return None
    cache_key = (tuple(id(d) for d in devices), shape)
    mesh = _MESH_CACHE.get(cache_key)
    if mesh is None:
        mesh = Mesh(np.asarray(devices).reshape(shape), mesh_util.AXES)
        _MESH_CACHE[cache_key] = mesh
    rb = max(0, int(getattr(g, "sharding_replicate_below", 0)))
    return ShardingPlan(mesh=mesh, replicate_below=rb,
                        key=(shape, rb, cache_key[0]))


def plan_from_mesh(mesh: Mesh, replicate_below: int = 0) -> ShardingPlan:
    """Wrap an explicit mesh (ParallelWrapper's constructor argument)
    in the same plan machinery the conf-driven path uses."""
    shape = tuple(mesh.shape[a] for a in mesh_util.AXES)
    devs = tuple(id(d) for d in mesh.devices.flat)
    return ShardingPlan(mesh=mesh, replicate_below=int(replicate_below),
                        key=(shape, int(replicate_below), devs))


# --------------------------------------------------------------------------
# The sharded step
# --------------------------------------------------------------------------

def jit_sharded_step(raw_step, plan: ShardingPlan, params, opts):
    """pjit the engines' raw train step with the plan's layouts:
    params/updater sharded (fsdp/model/expert), carried state and score
    replicated, the batch sharded over data(+fsdp), and params+state+
    updater donated so the step updates buffers in place on device.

    net_state uses a PREFIX sharding (one spec for the whole subtree):
    an RNN step's output state gains carried keys the input structure
    doesn't have, so a full-tree spec would pin the wrong structure for
    out_shardings."""
    param_sh = plan.tree_shardings(params)
    opt_sh = plan.tree_shardings(opts)
    repl = plan.replicated()
    batch_sh = plan.batch_sharding()
    return jax.jit(
        raw_step,
        in_shardings=(param_sh, repl, opt_sh, batch_sh, batch_sh,
                      None, None, None, None),
        out_shardings=(param_sh, repl, opt_sh, repl),
        donate_argnums=(0, 1, 2))


def jit_sharded_output(raw_out, plan: ShardingPlan, params):
    """pjit the engines' raw inference fn for sharded SERVING (ROADMAP
    3a): params keep the plan's fsdp/model layout (a model that only
    fits sharded never materializes whole on one device), carried state
    stays replicated, the batch shards over data(+fsdp), and the output
    is replicated — XLA all-gathers the result over ICI inside the
    program, so the response edge does exactly ONE explicit host gather
    (``jax.device_get``) instead of pulling per-device shards."""
    param_sh = plan.tree_shardings(params)
    repl = plan.replicated()
    batch_sh = plan.batch_sharding()
    return jax.jit(raw_out,
                   in_shardings=(param_sh, repl, batch_sh, batch_sh),
                   out_shardings=repl)


def pad_inference_rows(x, mask, n_data: int):
    """Zero-pad a host inference batch (rows plus its optional mask) up
    to a multiple of the mesh's batch degree so the data-sharded layout
    divides evenly.  Inference rows are independent — no batch
    statistics — so zero rows are exact and the caller just slices the
    output back to ``n``.  Returns ``(x, mask, n)`` with ``n`` the real
    row count (``None`` when no padding was needed)."""
    x = np.asarray(x)
    n = int(x.shape[0])
    rem = n % max(1, int(n_data))
    if rem == 0:
        return x, mask, None
    pad = [(0, n_data - rem)] + [(0, 0)] * (x.ndim - 1)
    x = np.pad(x, pad)
    if mask is not None:
        m = np.asarray(mask)
        m = np.pad(m, [(0, n_data - rem)] + [(0, 0)] * (m.ndim - 1))
        mask = m
    return x, mask, n


def place_model(plan: ShardingPlan, model) -> None:
    """Move a model's param/updater/state pytrees onto the mesh with the
    plan's layouts (host→device scatter; re-placing already-placed
    arrays is a no-op per leaf).  Also refreshes the sharding gauges."""
    with monitor.span("sharding/place", phase="device_put"):
        if model.net_params is not None:
            model.net_params = jax.device_put(
                model.net_params, plan.tree_shardings(model.net_params))
        if model.opt_states is not None:
            model.opt_states = jax.device_put(
                model.opt_states, plan.tree_shardings(model.opt_states))
        if model.net_state is not None:
            repl = plan.replicated()
            model.net_state = jax.device_put(
                model.net_state,
                tree_map(lambda a: repl, model.net_state))
    record_gauges(plan, model)


def shard_put(plan: ShardingPlan, host_batch):
    """Place one normalized host batch (any pytree of arrays; None
    leaves pass through) onto the mesh, batch-dim sharded.  Multi-process
    (scaleout tier): each host contributes its process-local rows."""
    batch_sh = plan.batch_sharding()

    def put(a):
        arr = np.asarray(a)
        if jax.process_count() > 1:
            return jax.make_array_from_process_local_data(batch_sh, arr)
        return jax.device_put(arr, batch_sh)

    return tree_map(put, host_batch)


def stack_for_scan(plan: ShardingPlan, host_batches):
    """Stack K same-shape host batches along a leading scan axis and
    place them with the scan-aware sharding P(None, ('data','fsdp')) —
    the fused-steps (lax.scan) input layout."""
    scan_sh = NamedSharding(plan.mesh, P(None, ("data", "fsdp")))

    def put(*leaves):
        arr = np.stack([np.asarray(l) for l in leaves])
        if jax.process_count() > 1:
            return jax.make_array_from_process_local_data(scan_sh, arr)
        return jax.device_put(arr, scan_sh)

    return tree_map(put, *host_batches)


# --------------------------------------------------------------------------
# Batch normalization (pad-or-trim to the data degree) — shared by the
# engines' sharded fit path and ParallelWrapper
# --------------------------------------------------------------------------

def normalize_batch(model, ds, n_data: int, is_graph: bool, owner=None):
    """(x, y, fm, lm) host pytrees at a data-degree multiple, or None
    when everything would be dropped.  A non-divisible batch is PADDED
    with cycled real rows whose loss is masked out and the valid rows'
    mask rescaled, so every example trains and gradients equal the
    unsharded step exactly (the reference's round-robin feedDataSet
    trains on every example — ref: parallelism/ParallelWrapper.java:383).
    Mask-nonlinear losses fall back to trimming (warned once on
    ``owner``).  Returns ``(batch, n, bucket)`` with ``n`` the REAL
    example count and ``bucket`` the shape bucket when the conf's shape
    bucketing subsumed the remainder policy."""
    from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet
    owner = owner if owner is not None else model
    if is_graph and isinstance(ds, DataSet):
        # ComputationGraph steps take TUPLES of inputs/labels
        ds = MultiDataSet([ds.features], [ds.labels],
                          [ds.features_mask], [ds.labels_mask])
    n = ds.num_examples()
    g = model.conf.global_conf
    pad_supported = bucketing.pad_supported(model)
    if getattr(g, "shape_bucketing", False) and pad_supported:
        # shape bucketing subsumes the remainder policy: the batch
        # bucket is lifted to a data-degree multiple, rows are cycled
        # and the labels mask rescaled exactly as below — every sharded
        # launch is then bucket-shaped, so the jitted sharded step (and
        # the fused scan) compiles once per bucket
        fn = (bucketing.bucket_train_multidataset
              if isinstance(ds, MultiDataSet)
              else bucketing.bucket_train_dataset)
        ds_b, bucket = fn(ds, g, min_multiple=n_data)
        if bucket is not None:
            return host_batch(ds_b), n, bucket
    rem = n % n_data
    pad_ok = bool(rem) and pad_supported
    lm_base = None
    if pad_ok:
        # The synthesized labels mask takes precedence over the
        # features-propagated time mask in the step's loss (the engines'
        # loss_fn lm resolution), so when a features mask exists without
        # a labels mask it must BECOME the base of the scaled mask — and
        # only when its shape provably matches the labels' time layout;
        # otherwise trim.
        if isinstance(ds, MultiDataSet):
            # container-level None checks are not enough: the
            # DataSet→MultiDataSet wrap above produces [None] lists, so
            # compare the ENTRIES
            def _all_none(t):
                return t is None or all(m is None for m in t)
            if not _all_none(ds.features_masks) \
                    and _all_none(ds.labels_masks):
                pad_ok = False  # multi-input→output mask routing is
                # ambiguous; don't guess
        elif ds.labels_mask is not None:
            lm_base = np.asarray(ds.labels_mask)
        elif ds.features_mask is not None:
            fm_arr = np.asarray(ds.features_mask)
            y_arr = np.asarray(ds.labels)
            if fm_arr.ndim == y_arr.ndim - 1 \
                    and fm_arr.shape == y_arr.shape[:-1]:
                lm_base = fm_arr
            else:
                pad_ok = False
    if pad_ok:
        target = n + (n_data - rem)
        cyc = lambda a: (None if a is None  # noqa: E731
                         else bucketing.cycle_rows(a, target))
        if isinstance(ds, MultiDataSet):
            lms = (ds.labels_masks
                   if ds.labels_masks is not None
                   else (None,) * len(ds.labels))
            return ((tuple(cyc(a) for a in ds.features),
                     tuple(cyc(a) for a in ds.labels),
                     None if ds.features_masks is None else
                     tuple(cyc(a) for a in ds.features_masks),
                     tuple(bucketing.scaled_mask(lm, y, n, target)
                           for lm, y in zip(lms, ds.labels))), n, None)
        return ((cyc(ds.features), cyc(ds.labels),
                 cyc(ds.features_mask),
                 bucketing.scaled_mask(lm_base, ds.labels,
                                       n, target)), n, None)
    if rem:
        n_new = (n // n_data) * n_data
        _warn_remainder(owner, n - n_new, n, n_data)
        n = n_new
        if n == 0:
            return None
    if isinstance(ds, MultiDataSet):
        trim = lambda arrs: (  # noqa: E731
            None if arrs is None else tuple(
                None if a is None else np.asarray(a)[:n] for a in arrs))
        return ((trim(ds.features), trim(ds.labels),
                 trim(ds.features_masks), trim(ds.labels_masks)), n, None)
    return ((np.asarray(ds.features)[:n], np.asarray(ds.labels)[:n],
             None if ds.features_mask is None
             else np.asarray(ds.features_mask)[:n],
             None if ds.labels_mask is None
             else np.asarray(ds.labels_mask)[:n]), n, None)


def host_batch(ds):
    """DataSet/MultiDataSet → the (x, y, fm, lm) host-pytree the sharded
    step consumes."""
    from deeplearning4j_tpu.datasets.dataset import MultiDataSet
    if isinstance(ds, MultiDataSet):
        tup = lambda arrs: (  # noqa: E731
            None if arrs is None else tuple(
                None if a is None else np.asarray(a) for a in arrs))
        return (tuple(np.asarray(a) for a in ds.features),
                tuple(np.asarray(a) for a in ds.labels),
                tup(ds.features_masks), tup(ds.labels_masks))
    return (np.asarray(ds.features), np.asarray(ds.labels),
            None if ds.features_mask is None
            else np.asarray(ds.features_mask),
            None if ds.labels_mask is None
            else np.asarray(ds.labels_mask))


def _warn_remainder(owner, dropped: int, batch: int, n_data: int) -> None:
    """Non-divisible batches are normally padded+masked so every example
    trains; this warning only fires on the trim fallback for
    mask-nonlinear losses (bucketing.MASK_NONLINEAR_LOSSES /
    CenterLoss)."""
    if not getattr(owner, "_remainder_warned", False):
        owner._remainder_warned = True
        warnings.warn(
            f"sharded fit: dropping {dropped} of {batch} examples per "
            f"batch (batch not divisible by data degree {n_data}); pad "
            f"or resize batches to avoid this",
            stacklevel=4)


# --------------------------------------------------------------------------
# Observability: dl4j_sharding_* gauges (docs/OBSERVABILITY.md)
# --------------------------------------------------------------------------

def _tree_bytes(tree, plan: Optional[ShardingPlan]):
    """(total_bytes, per_device_bytes, n_sharded, n_replicated) for one
    pytree under ``plan`` (per-device = replica bytes when plan None).
    Uses each ARRAY's actual committed sharding when available so the
    gauges report reality, not intent."""
    total = per_dev = 0
    sharded = replicated = 0
    for a in jax.tree_util.tree_leaves(tree):
        shape = tuple(a.shape)
        nbytes = int(np.prod(shape) or 1) * np.dtype(a.dtype).itemsize
        total += nbytes
        sh = getattr(a, "sharding", None)
        if sh is None and plan is not None:
            sh = plan.param_sharding(shape)
        if sh is None:
            per_dev += nbytes
            replicated += 1
            continue
        try:
            shard_shape = sh.shard_shape(shape)
        except Exception:
            shard_shape = shape
        shard_bytes = int(np.prod(shard_shape) or 1) * \
            np.dtype(a.dtype).itemsize
        per_dev += shard_bytes
        if shard_bytes < nbytes:
            sharded += 1
        else:
            replicated += 1
    return total, per_dev, sharded, replicated


def record_gauges(plan: ShardingPlan, model) -> None:
    """Publish the sharding family: mesh shape per axis, params/updater
    bytes total and per device, sharded/replicated param counts, and the
    per-step collective-traffic estimates (all-gather = full bytes of
    every fsdp-sharded param gathered for the forward; reduce-scatter =
    the same bytes of gradients scattered into shards)."""
    reg = monitor.get_registry()
    for ax in mesh_util.AXES:
        reg.gauge("dl4j_sharding_mesh_devices",
                  "active sharding mesh size along each named axis",
                  labels=("axis",)).labels(axis=ax).set(plan.mesh.shape[ax])
    p_total, p_dev, p_sh, p_rep = _tree_bytes(model.net_params, plan)
    o_total, o_dev, _, _ = _tree_bytes(model.opt_states, plan)
    reg.gauge("dl4j_sharding_param_bytes_total",
              "model parameter bytes (unsharded logical size)").set(p_total)
    reg.gauge("dl4j_sharding_param_bytes_per_device",
              "model parameter bytes resident per device").set(p_dev)
    reg.gauge("dl4j_sharding_updater_bytes_total",
              "updater-state bytes (unsharded logical size)").set(o_total)
    reg.gauge("dl4j_sharding_updater_bytes_per_device",
              "updater-state bytes resident per device").set(o_dev)
    reg.gauge("dl4j_sharding_params_sharded",
              "param arrays sharded over the mesh").set(p_sh)
    reg.gauge("dl4j_sharding_params_replicated",
              "param arrays replicated (below the size threshold or "
              "indivisible)").set(p_rep)
    # per-step collective traffic estimate: every byte a param is short
    # of its full size must be all-gathered for the forward, and the
    # matching gradient bytes reduce-scattered after backward
    collective = max(0, p_total - p_dev)
    reg.gauge("dl4j_sharding_allgather_bytes_per_step",
              "estimated param bytes all-gathered per train step").set(
                  collective)
    reg.gauge("dl4j_sharding_reducescatter_bytes_per_step",
              "estimated gradient bytes reduce-scattered per train "
              "step").set(collective)


# --------------------------------------------------------------------------
# Mesh-reshape-tolerant checkpoints (manifest metadata + reshard logging)
# --------------------------------------------------------------------------

def sharding_manifest(model) -> Optional[dict]:
    """Serializable description of a model's active mesh + per-param
    shardings for the checkpoint manifest — None for replica-style
    models (the serde-compatible default: absent/None means
    'replicated everywhere', which is exactly what PR-5-era manifests
    implied)."""
    plan = getattr(model, "_sharding_plan", None)
    if plan is None:
        return None
    mesh_axes = {ax: int(plan.mesh.shape[ax]) for ax in mesh_util.AXES}
    specs = {}
    try:
        for key, arr in model.param_table().items():
            sh = getattr(arr, "sharding", None)
            spec = getattr(sh, "spec", None)
            if spec is None:
                spec = plan.param_sharding(arr.shape).spec
            specs[key] = [list(p) if isinstance(p, tuple) else p
                          for p in tuple(spec)]
    except Exception:  # never let metadata break a checkpoint save
        specs = {}
    return {"mesh": mesh_axes, "replicate_below": plan.replicate_below,
            "n_devices": int(np.prod(list(mesh_axes.values()))),
            "params": specs}


def note_reshard(model, saved_sharding: Optional[dict]) -> None:
    """Called by resume when a checkpoint's recorded mesh differs from
    the restoring model's: the flat host vector was already
    redistributed by ``set_params`` (host-side gather → scatter, the
    portable-collectives analog on one host); here we log and count it
    so cross-mesh restores are visible in /metrics."""
    cur = sharding_manifest(model)
    saved_mesh = (saved_sharding or {}).get("mesh")
    cur_mesh = (cur or {}).get("mesh")
    if saved_mesh == cur_mesh:
        return
    monitor.get_registry().counter(
        "dl4j_sharding_reshard_total",
        "checkpoint restores that redistributed params across a "
        "different mesh than they were saved on").inc()
    log.info("resharded checkpoint: saved mesh %s -> restored mesh %s",
             saved_mesh or "replicated", cur_mesh or "replicated")
