"""Early stopping composed with mesh data-parallel training.

(ref: deeplearning4j-scaleout/deeplearning4j-scaleout-parallelwrapper/
src/main/java/org/deeplearning4j/parallelism/EarlyStoppingParallelTrainer.java:1-372
— the reference wraps a ParallelWrapper, installs an
AveragingIterationListener to watch per-iteration scores, and drives the
standard early-stopping epoch loop around parallel fit passes.)

Here one "epoch" is one ParallelWrapper.fit pass — the compiled
mesh-sharded step with its gradient psum over ICI — and scoring between
epochs runs on the (replicated) driver-side params, so the score the
termination conditions see is the post-all-reduce model exactly as the
reference's post-averaging model.
"""

from __future__ import annotations

import math
from typing import Optional

from deeplearning4j_tpu.nn.earlystopping import (
    EarlyStoppingConfiguration, EarlyStoppingResult,
    check_score_free_epoch_conditions, validate_termination_conditions)
from deeplearning4j_tpu.nn.listeners import IterationListener
from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper


class _Terminate(Exception):
    """Control-flow signal: abort the current parallel fit pass NOW (a
    NaN score must not keep training for the rest of the epoch)."""


class _IterationWatcher(IterationListener):
    """Per-iteration hook inside the parallel fit pass — the analog of
    the reference's AveragingIterationListener (EarlyStoppingParallelTrainer.java:303):
    checks iteration termination conditions on every mesh step and
    aborts the wrapper loop mid-pass by raising."""

    def __init__(self, conditions):
        self.conditions = conditions
        self.fired = None

    def iteration_done(self, model, iteration):
        # no conditions → never force the device→host score sync (it
        # would serialize async dispatch against execution every step)
        if not self.conditions or self.fired is not None:
            return
        s = float(model.score())
        for cond in self.conditions:
            if cond.terminate(iteration, s):
                self.fired = cond
                raise _Terminate()


class EarlyStoppingParallelTrainer:
    """(ref: parallelism/EarlyStoppingParallelTrainer.java)"""

    def __init__(self, config: EarlyStoppingConfiguration, model,
                 train_data, wrapper: Optional[ParallelWrapper] = None,
                 mesh=None, averaging_frequency: int = 1):
        self.config = config
        self.model = model
        self.train_data = train_data
        self.wrapper = wrapper if wrapper is not None else ParallelWrapper(
            model, mesh=mesh, averaging_frequency=averaging_frequency)

    def fit(self) -> EarlyStoppingResult:
        cfg = self.config
        validate_termination_conditions(cfg)
        net = self.model
        watcher = _IterationWatcher(cfg.iteration_termination_conditions)
        saved_listeners = list(net.listeners)
        net.listeners = saved_listeners + [watcher]
        best_score, best_epoch = math.inf, -1
        score_vs_epoch = {}
        epoch = 0
        reason, details = "MaxEpochs", ""
        try:
            while True:
                try:
                    self.wrapper.fit(self.train_data, epochs=1)
                except _Terminate:
                    pass
                if watcher.fired is not None:
                    reason = "IterationTerminationCondition"
                    details = repr(watcher.fired)
                    break
                if epoch % cfg.evaluate_every_n_epochs == 0:
                    score = cfg.score_calculator.calculate_score(net)
                    score_vs_epoch[epoch] = score
                    if score < best_score:
                        best_score, best_epoch = score, epoch
                        cfg.model_saver.save_best(net)
                    if cfg.save_last_model:
                        cfg.model_saver.save_latest(net)
                    stop = False
                    for cond in cfg.epoch_termination_conditions:
                        if cond.terminate(epoch, score):
                            reason, details = ("EpochTerminationCondition",
                                               repr(cond))
                            stop = True
                            break
                    if stop:
                        break
                else:
                    fired = check_score_free_epoch_conditions(cfg, epoch)
                    if fired is not None:
                        reason = "EpochTerminationCondition"
                        details = repr(fired)
                        break
                epoch += 1
        finally:
            net.listeners = saved_listeners
        best = cfg.model_saver.get_best()
        return EarlyStoppingResult(
            termination_reason=reason, termination_details=details,
            total_epochs=epoch + 1, best_model_epoch=best_epoch,
            best_model_score=best_score, score_vs_epoch=score_vs_epoch,
            best_model=best if best is not None else net)
