"""Text pipeline: tokenizers, sentence iterators, vocab construction.

TPU-native re-realization of the reference's text stack
(ref: deeplearning4j-nlp-parent/deeplearning4j-nlp/.../text/ — sentence
iterators, tokenization factories, stopwords — and
models/word2vec/wordstore/ — vocab cache + Huffman coding).  All of this
is host-side CPU work feeding integer batches to the device kernels in
``deeplearning4j_tpu.embeddings``.
"""

from deeplearning4j_tpu.text.sequence import Sequence, SequenceElement, VocabWord  # noqa: F401
from deeplearning4j_tpu.text.tokenization import (  # noqa: F401
    CommonPreprocessor,
    DefaultTokenizer,
    DefaultTokenizerFactory,
    EndingPreProcessor,
    LowCasePreProcessor,
    NGramTokenizerFactory,
    TokenizerFactory,
)
from deeplearning4j_tpu.text.sentence_iterators import (  # noqa: F401
    BasicLineIterator,
    CollectionSentenceIterator,
    FileSentenceIterator,
    LabelAwareListSentenceIterator,
    LabelsSource,
    SentenceIterator,
)
from deeplearning4j_tpu.text.stopwords import StopWords  # noqa: F401
from deeplearning4j_tpu.text.vocab import AbstractCache, Huffman, VocabConstructor  # noqa: F401
